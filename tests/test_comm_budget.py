"""Gradient-exchange budget gate (ISSUE 5: the comm structure can't rot).

Mirrors tests/test_flash_budget.py: tools/comm_budgets.json commits the
DP step's collective structure and this gate holds every future PR to
it.  Two layers:

* STRUCTURE (backend-neutral, checked here on the simulated CPU mesh):
  a jaxpr census of the REAL compiled step per exchange config —
  per-leaf/flat/bucketed psum counts, the reduce-scatter step's
  reduce_scatter+all_gather replacing the full-gradient allreduce, and
  the exchanged-bytes accounting (gradient bytes exactly halved).
  ISSUE 6 adds the hierarchical (ici × dcn) configs on a simulated
  2-host split: per-hop collective counts resolved from eqn axis
  names, the DCN gradient payload pinned at exactly 1/intra_size, the
  slow-hop-first emission order, and per-hop dtype compression.
  Verified against the traced program, not against documentation.
* NUMBERS (measured on chip by the recovery queue's bucket sweep /
  exposed-comm A/B): dormant while ``sweep.status`` is
  ``pending_on_chip``; arms when rows are stamped ``measured``.

The census traces all five committed configs over ONE shared vertical
(model built once per process — see comm_census._Vertical), so the
whole gate costs seconds, not minutes, of tier-1 time.
"""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))

import comm_census  # noqa: E402


@pytest.fixture(scope="module")
def budgets():
    with open(comm_census.BUDGETS_PATH) as f:
        return json.load(f)


@pytest.fixture(scope="module")
def live(budgets):
    """The live census of every committed config, traced once."""
    import jax
    assert len(jax.devices()) == budgets["vertical"]["n_devices"], \
        "census devices != committed vertical (conftest pins 8)"
    return {name: comm_census.config_row(name)
            for name in comm_census.CONFIGS}


def test_budget_schema(budgets):
    assert set(budgets["structure"]) == set(comm_census.CONFIGS)
    assert budgets["grad_elems_floor"] == comm_census.GRAD_ELEMS_FLOOR
    v = budgets["vertical"]
    assert {k: v[k] for k in comm_census.VERTICAL} == comm_census.VERTICAL
    assert budgets["sweep"]["status"] in ("pending_on_chip", "measured")
    # ISSUE 12: the MoE dispatch census is a sibling section
    assert set(budgets["moe"]["structure"]) == set(comm_census.MOE_CONFIGS)
    mv = budgets["moe"]["vertical"]
    assert {k: mv[k] for k in comm_census.MOE_VERTICAL} == \
        comm_census.MOE_VERTICAL


def test_structure_census_matches_committed(budgets, live):
    """The machine check: the committed structure is what the step
    TRACES today, config by config.  A PR that changes bucketing, the
    packing, or the reduce-scatter wiring must regenerate the budgets
    (tools/comm_census.py --write-budgets) and own the diff."""
    for name, row in live.items():
        committed = dict(budgets["structure"][name])
        committed.pop("config", None)
        assert row == committed, (
            f"{name}: exchange structure drifted.\n traced    {row}\n "
            f"committed {committed}\nRegenerate tools/comm_budgets.json "
            "via `python tools/comm_census.py --write-budgets` if the "
            "change is intentional.")


def test_flat_is_one_collective(live):
    assert live["flat"]["grad_collectives"] == {"psum": 1}


def test_per_leaf_is_one_collective_per_param(live):
    vert = comm_census._Vertical.get()
    assert live["per_leaf"]["grad_collectives"]["psum"] == \
        sum(1 for _ in vert.model.params())


def test_bucketed_emits_multiple_bounded_buckets(budgets, live):
    """The acceptance bar: K>1 collectives at the DEFAULT bucket size on
    the transformer vertical, every bucket within the bound (a single
    oversize leaf may exceed it alone — the embed/head matrices here
    do, by design of the plan)."""
    from chainermn_tpu.communicators._memory_utility import DEFAULT_BUCKET_MB
    row = live["bucketed"]
    k = row["grad_collectives"]["psum"]
    assert k > 1, "bucketed exchange collapsed to one collective"
    import jax.numpy as jnp
    import numpy as np
    bound = DEFAULT_BUCKET_MB * 2 ** 20
    itemsize = jnp.dtype(row["grad_dtype"] or "float32").itemsize
    sizes = [e * itemsize for e in row["grad_collective_elems"]["psum"]]
    vert = comm_census._Vertical.get()
    max_leaf = max(itemsize * int(np.prod(p.shape))
                   for p in vert.model.params())
    for s in sizes:
        assert s <= max(bound, max_leaf)
    # all leaves land in buckets: bucket elems sum to the param count
    assert sum(row["grad_collective_elems"]["psum"]) == vert.n_params


def test_compression_composes_with_bucketing(live):
    """bf16 buckets carry bf16 payloads: exchanged gradient bytes halve
    vs the f32 bucketed config."""
    assert live["bucketed_bf16"]["exchanged_gradient_bytes_per_replica"] \
        * 2 == live["bucketed"]["exchanged_gradient_bytes_per_replica"]


def test_reduce_scatter_replaces_allreduce_and_halves_gradient_bytes(live):
    """The tentpole relation, machine-checked: the reduce-scatter DP
    step's census shows NO full-gradient psum — one reduce_scatter (the
    gradient's single wire crossing) + one all_gather (the params
    rebuild) — and per-replica exchanged GRADIENT bytes are exactly
    half the flat allreduce's."""
    rs = live["reduce_scatter"]
    assert rs["grad_collectives"] == {"reduce_scatter": 1, "all_gather": 1}
    flat = live["flat"]
    assert rs["exchanged_gradient_bytes_per_replica"] * 2 == \
        flat["exchanged_gradient_bytes_per_replica"]
    # the params all-gather is accounted separately, never hidden
    assert rs["exchanged_param_bytes_per_replica"] > 0


def test_hierarchical_per_hop_structure(live):
    """The ISSUE 6 tentpole, machine-checked: the hierarchical step is
    intra-host reduce_scatter over ICI → chunk allreduce over DCN →
    intra-host all_gather over ICI — per-hop counts resolved from the
    eqns' own axis names, never a full-axis gradient collective."""
    row = live["hierarchical"]
    assert row["topology"] == "hierarchical"
    assert row["intra_size"] == 4 and row["inter_size"] == 2
    assert row["per_hop"]["ici"]["collectives"] == \
        {"reduce_scatter": 1, "all_gather": 1}
    assert row["per_hop"]["dcn"]["collectives"] == {"psum": 1}
    # no hop label beyond ici/dcn: a residual full-axis collective
    # would surface as a "both"/"world" key here
    assert set(row["per_hop"]) == {"ici", "dcn"}


def test_hierarchical_dcn_payload_ratio_pinned(budgets, live):
    """Acceptance bar: DCN only ever carries 1/intra_size of the
    gradient — pinned from the traced operand sizes on every
    hierarchical config."""
    for name, row in live.items():
        if row.get("topology") != "hierarchical":
            continue
        assert row["dcn_grad_payload_ratio"] == \
            pytest.approx(1.0 / row["intra_size"], abs=0), name
        assert budgets["structure"][name]["dcn_grad_payload_ratio"] == \
            row["dcn_grad_payload_ratio"]


def test_hierarchical_slow_hop_first_schedule(live):
    """hop_schedule's ordering promise survives tracing: every DCN
    collective is emitted before ANY fast-hop all_gather (the slow hop
    starts first; ICI rebuilds overlap the remaining DCN traffic)."""
    for name, row in live.items():
        if row.get("topology") == "hierarchical":
            assert row["hop_ordered"], name


def test_hierarchical_buckets_compose_with_topology(live):
    """PR 5's bucket planner composes with the two-level exchange: K
    buckets at the default bound → K reduce_scatters, K DCN allreduces,
    K all_gathers — same K as the flat-topology bucketed config."""
    k = live["bucketed"]["grad_collectives"]["psum"]
    row = live["hierarchical_bucketed"]
    assert row["grad_collectives"] == \
        {"reduce_scatter": k, "psum": k, "all_gather": k}


def test_hierarchical_total_bytes_match_flat_ring(live):
    """The ring identity: the hierarchy relocates bytes onto the fast
    wires without adding any — hop totals sum to the flat allreduce's
    per-replica figure (2n(N-1)/N over N = intra × inter)."""
    assert live["hierarchical"]["exchanged_gradient_bytes_per_replica"] \
        == live["flat"]["exchanged_gradient_bytes_per_replica"]


def test_per_hop_dtype_halves_only_dcn(live):
    """allreduce_grad_dtype={'dcn': 'bfloat16'}: the DCN crossing
    halves, ICI stays lossless byte-for-byte."""
    f32 = live["hierarchical"]["per_hop"]
    bf16 = live["hierarchical_dcn_bf16"]["per_hop"]
    assert bf16["ici"]["exchanged_grad_bytes"] == \
        f32["ici"]["exchanged_grad_bytes"]
    assert bf16["dcn"]["exchanged_grad_bytes"] * 2 == \
        f32["dcn"]["exchanged_grad_bytes"]


def test_hierarchical_rs_shards_both_hops(live):
    """exchange='reduce_scatter' × hierarchical: the gradient crosses
    each hop ONCE (rs over ici on the full buffer, rs over dcn on the
    1/intra chunk), the params rebuild all-gathers both hops, and the
    gradient bytes match the flat reduce-scatter exchange (half the
    allreduce) while the DCN share is 1/intra of that."""
    row = live["hierarchical_rs"]
    assert row["per_hop"]["ici"]["collectives"] == \
        {"reduce_scatter": 1, "all_gather": 1}
    assert row["per_hop"]["dcn"]["collectives"] == \
        {"reduce_scatter": 1, "all_gather": 1}
    assert row["exchanged_gradient_bytes_per_replica"] == \
        live["reduce_scatter"]["exchanged_gradient_bytes_per_replica"]
    assert row["exchanged_param_bytes_per_replica"] == \
        live["reduce_scatter"]["exchanged_param_bytes_per_replica"]


def test_quantized_dcn_crossing_at_wire_dtype(live):
    """ISSUE 8 acceptance, machine-checked from the trace: the quantized
    configs' DCN gradient crossing rides the QUANTIZED wire dtype (the
    packed buffer's itemsize, never the gradient dtype), via
    quantize → all_gather (allreduce exchange) / all_to_all (sharded
    update) → dequantize-sum — no full-precision gradient psum ever
    touches DCN — while ICI stays lossless byte-for-byte."""
    f32 = live["hierarchical"]["per_hop"]
    for name, wire in (("hierarchical_int8", "int8"),
                       ("hierarchical_fp8", "float8_e4m3fn")):
        row = live[name]
        assert row["quantized_wire"] == wire, name
        assert row["per_hop"]["dcn"]["collectives"] == {"all_gather": 1}
        assert row["per_hop"]["dcn"]["wire_dtypes"] == [wire], name
        # ICI hop untouched: same collectives, same lossless bytes
        assert row["per_hop"]["ici"] == f32["ici"], name
    rs = live["hierarchical_rs_int8"]
    assert rs["per_hop"]["dcn"]["collectives"] == \
        {"all_to_all": 1, "all_gather": 1}
    # the all_to_all gradient segments are int8; the f32 entry is the
    # params-rebuild all_gather, accounted as param bytes
    assert rs["per_hop"]["dcn"]["wire_dtypes"] == ["float32", "int8"]
    assert rs["per_hop"]["ici"] == live["hierarchical_rs"]["per_hop"]["ici"]


def test_quantized_dcn_payload_pinned_at_quantized_fraction(budgets, live):
    """The acceptance bar: the DCN gradient-payload BYTE ratio of every
    quantized config is the quantized fraction of the lossless one —
    int8/fp8 are 1-byte wires, so exactly 1/4 of the f32 crossing
    (and 1/(4·ici) of the full gradient)."""
    lossless = live["hierarchical"]["dcn_payload_bytes_ratio"]
    for name in ("hierarchical_int8", "hierarchical_fp8",
                 "hierarchical_rs_int8"):
        row = live[name]
        # element payload unchanged (still the 1/ici chunk) ...
        assert row["dcn_grad_payload_ratio"] == \
            pytest.approx(1.0 / row["intra_size"], abs=0), name
        # ... byte payload at the quantized fraction: 1/4 of f32
        assert row["dcn_payload_bytes_ratio"] == \
            pytest.approx(lossless / 4, abs=0), name
        assert row["dcn_payload_bytes_ratio"] <= lossless / 4, name
        assert budgets["structure"][name]["dcn_payload_bytes_ratio"] == \
            row["dcn_payload_bytes_ratio"], name


def test_quantized_keeps_slow_hop_first_order(live):
    """The quantized DCN ops (all_gather of codewords / all_to_all of
    segments) keep hop_schedule's promise: every DCN collective is
    emitted before ANY fast-hop all_gather."""
    for name in ("hierarchical_int8", "hierarchical_fp8",
                 "hierarchical_rs_int8"):
        assert live[name]["hop_ordered"], name


def test_quantized_wire_halves_dcn_bytes_vs_bf16(live):
    """The headline relation at the committed 2-host split: int8 DCN
    grad bytes are half the bf16 crossing and a quarter of the f32 one
    (all_gather of 1-byte codewords at inter=2 == psum of 1-byte
    payload would-be bytes)."""
    f32 = live["hierarchical"]["per_hop"]["dcn"]["exchanged_grad_bytes"]
    bf16 = live["hierarchical_dcn_bf16"]["per_hop"]["dcn"][
        "exchanged_grad_bytes"]
    int8 = live["hierarchical_int8"]["per_hop"]["dcn"][
        "exchanged_grad_bytes"]
    assert bf16 * 2 == f32
    assert int8 * 4 == f32
    assert int8 * 2 == bf16


def test_striped_both_fabrics_carry_bulk(live):
    """The ISSUE 11 tentpole, machine-checked: the striped exchange
    puts a bulk reduce_scatter AND a bulk all_gather on BOTH fabrics
    in one step — the ICI path's rs/ag over ici with its chunk psum
    over dcn, and the transposed DCN path's rs/ag over dcn with its
    chunk psum over ici.  The strict hierarchy's idle-slow-fabric
    window is structurally gone."""
    row = live["striped"]
    assert row["topology"] == "striped"
    assert row["stripe_ratio"] == comm_census.STRIPE_RATIO
    for hop in ("ici", "dcn"):
        assert row["per_hop"][hop]["collectives"] == \
            {"reduce_scatter": 1, "psum": 1, "all_gather": 1}, hop
    assert set(row["per_hop"]) == {"ici", "dcn"}


def test_striped_byte_conservation_identity(budgets, live):
    """Acceptance bar: ici_path + dcn_path bytes of a striped bucket ==
    the flat allreduce bytes of the same payload — striping relocates
    bytes across fabrics, it adds NONE.  Pinned EXACT: the committed
    ratio splits the vertical into slices that divide both rings, so
    no pad slack hides a regression."""
    flat = live["flat"]["exchanged_gradient_bytes_per_replica"]
    for name in ("striped", "striped_bucketed"):
        per_path = live[name]["per_path_bytes"]
        assert set(per_path) == {"ici", "dcn"}, name
        assert per_path["ici"] + per_path["dcn"] == flat, name
        assert budgets["structure"][name]["per_path_bytes"] == per_path


def test_striped_dcn_share_is_committed_ratio(live):
    """Acceptance bar: the DCN path's byte share IS the committed split
    ratio, exactly — per-path totals are proportional to slice sizes
    under the ring identity, so the wire division the schedule promises
    falls out of the traced operand sizes."""
    for name in ("striped", "striped_bucketed"):
        row = live[name]
        per_path = row["per_path_bytes"]
        total = per_path["ici"] + per_path["dcn"]
        assert per_path["dcn"] / total == row["stripe_ratio"], name


def test_striped_buckets_compose_with_striping(live):
    """PR 5's bucket planner composes with the multi-path schedule: K
    buckets → K collectives per (path, op) — same K as the flat-
    topology bucketed config — with the per-path byte identities
    holding across the whole plan."""
    k = live["bucketed"]["grad_collectives"]["psum"]
    row = live["striped_bucketed"]
    for hop in ("ici", "dcn"):
        assert row["per_hop"][hop]["collectives"] == \
            {"reduce_scatter": k, "psum": k, "all_gather": k}


def test_striped_concurrent_eligible_order(live):
    """The generalized hop_ordered gate (ISSUE 11 satellite): every
    scatter/crossing op of BOTH paths precedes every rebuild
    all_gather — the striped configs are budget-gated, not exempted,
    and the old single-path slow-hop-first property still holds for
    the hierarchical configs under the same generalized check."""
    for name, row in live.items():
        if row.get("topology") in ("hierarchical", "striped"):
            assert row["hop_ordered"], name


def test_striped_dcn_bf16_compresses_only_dcn_fabric(live):
    """Per-hop dtype × striping: the DCN FABRIC's crossings (the ICI
    path's chunk psum, the DCN path's bulk rs + ag) halve; the ICI
    fabric is byte-identical — the DCN path's chunk upcasts to f32
    before its fast-hop allreduce, so lossless-over-ICI survives the
    transposed schedule."""
    f32 = live["striped"]["per_hop"]
    bf16 = live["striped_dcn_bf16"]["per_hop"]
    assert bf16["ici"]["exchanged_grad_bytes"] == \
        f32["ici"]["exchanged_grad_bytes"]
    assert bf16["dcn"]["exchanged_grad_bytes"] * 2 == \
        f32["dcn"]["exchanged_grad_bytes"]


def test_striped_rs_shards_both_paths(live):
    """exchange='reduce_scatter' × striped: each path's slice chains
    psum_scatter over BOTH axes (2 rs per hop) and the params rebuild
    all-gathers both chains in reverse (2 ag per hop); gradient bytes
    equal the flat reduce-scatter exchange (half the allreduce — the
    conservation identity's rs form) and the params rebuild matches
    it byte for byte."""
    row = live["striped_rs"]
    for hop in ("ici", "dcn"):
        assert row["per_hop"][hop]["collectives"] == \
            {"reduce_scatter": 2, "all_gather": 2}, hop
    assert row["exchanged_gradient_bytes_per_replica"] == \
        live["reduce_scatter"]["exchanged_gradient_bytes_per_replica"]
    assert row["exchanged_param_bytes_per_replica"] == \
        live["reduce_scatter"]["exchanged_param_bytes_per_replica"]


def test_unknown_collective_prim_is_hard_census_error():
    """A collective the pricing does not understand must raise, never
    silently skip or misprice (the satellite's contract)."""
    import chainermn_tpu as ct
    comm = ct.create_communicator("jax_ici")
    with pytest.raises(ValueError, match="cannot price"):
        comm_census.row_wire_bytes(
            {"prim": "ppermute", "elems": 1024, "dtype": "float32",
             "axes": ["mn_world"]}, comm)


# -- MoE dispatch census (ISSUE 12) ------------------------------------------

@pytest.fixture(scope="module")
def moe_live():
    """The live MoE dispatch census of every committed config."""
    return {name: comm_census.moe_config_row(name)
            for name in comm_census.MOE_CONFIGS}


def test_moe_structure_census_matches_committed(budgets, moe_live):
    """The machine check for the MoE section: what `parallel.moe`
    traces today is what tools/comm_budgets.json commits, config by
    config — a PR that changes the dispatch shape must regenerate the
    budgets and own the diff."""
    for name, row in moe_live.items():
        committed = dict(budgets["moe"]["structure"][name])
        committed.pop("config", None)
        assert row == committed, (
            f"{name}: MoE dispatch structure drifted.\n traced    {row}\n"
            f" committed {committed}\nRegenerate tools/comm_budgets.json "
            "via `python tools/comm_census.py --write-budgets` if the "
            "change is intentional.")


def test_moe_two_stage_per_hop_structure(moe_live):
    """The ISSUE 12 tentpole, machine-checked: the two-stage dispatch
    is an all_to_all over ICI and an all_to_all over DCN (each hop
    crossed once per direction — 2 with the combine return trip), hop
    labels resolved from the eqns' own axis names; the flat reference
    is ONE joint-axis collective each way; and no config emits any
    other dispatch-sized collective."""
    for name, row in moe_live.items():
        assert row["intra_size"] == 4 and row["inter_size"] == 2, name
        assert row["non_dispatch_collectives"] == 0, name
    two = moe_live["moe_two_stage"]
    assert set(two["per_hop"]) == {"ici", "dcn"}
    for hop in ("ici", "dcn"):
        assert two["per_hop"][hop]["collectives"] == {"all_to_all": 2}
    flat = moe_live["moe_flat"]
    assert set(flat["per_hop"]) == {"dcn+ici"}
    assert flat["per_hop"]["dcn+ici"]["collectives"] == {"all_to_all": 2}


def test_moe_off_host_dispatch_ratio_pinned(budgets, moe_live):
    """Acceptance bar: `off_host_dispatch_ratio` is pinned EXACT per
    committed config — (inter-1)/inter of the capacity buffer belongs
    to off-host experts on the 2-host split — and the two-stage
    configs' DCN dispatch bytes, pinned FROM THE TRACE at wire dtype,
    carry exactly that share of the f32 round trip when lossless, half
    under bf16, a quarter under int8."""
    for name, row in moe_live.items():
        assert row["off_host_dispatch_ratio"] == 0.5, name
        assert budgets["moe"]["structure"][name][
            "off_host_dispatch_ratio"] == 0.5, name
    assert moe_live["moe_two_stage"]["dcn_dispatch_bytes_ratio"] == 0.5
    assert moe_live["moe_two_stage_bf16"]["dcn_dispatch_bytes_ratio"] \
        == 0.25
    assert moe_live["moe_two_stage_int8"]["dcn_dispatch_bytes_ratio"] \
        == 0.125


def test_moe_dcn_crossing_at_wire_dtype(moe_live):
    """The compressed DCN crossing rides the WIRE dtype (the packed
    buffer that actually crosses — int8 codewords with the per-segment
    scale all_to_all below the census floor), while ICI stays lossless
    byte-for-byte across every two-stage config."""
    lossless = moe_live["moe_two_stage"]["per_hop"]
    for name, wire in (("moe_two_stage_bf16", "bfloat16"),
                       ("moe_two_stage_int8", "int8")):
        row = moe_live[name]
        assert row["dcn_wire_dtype"] == wire, name
        assert row["per_hop"]["dcn"]["wire_dtypes"] == [wire], name
        assert row["per_hop"]["ici"] == lossless["ici"], name
    f32 = lossless["dcn"]["exchanged_dispatch_bytes"]
    bf16 = moe_live["moe_two_stage_bf16"]["per_hop"]["dcn"][
        "exchanged_dispatch_bytes"]
    int8 = moe_live["moe_two_stage_int8"]["per_hop"]["dcn"][
        "exchanged_dispatch_bytes"]
    assert bf16 * 2 == f32 and int8 * 4 == f32


def test_moe_pricing_surface_matches_census(moe_live):
    """`_memory_utility.moe_dispatch_exchanged_bytes` — the pricing
    surface bench.py's MoE rows use — agrees with the traced census
    byte-for-byte, so the bench columns and the committed budgets
    cannot drift apart."""
    from chainermn_tpu.communicators._memory_utility import \
        moe_dispatch_exchanged_bytes
    row = moe_live["moe_two_stage"]
    n_bytes = row["dispatch_elems"] * 4
    hops = moe_dispatch_exchanged_bytes(n_bytes, row["intra_size"],
                                        row["inter_size"])
    assert hops["ici"] == \
        row["per_hop"]["ici"]["exchanged_dispatch_bytes"]
    assert hops["dcn"] == \
        row["per_hop"]["dcn"]["exchanged_dispatch_bytes"]
    int8 = moe_live["moe_two_stage_int8"]
    hops8 = moe_dispatch_exchanged_bytes(
        n_bytes, row["intra_size"], row["inter_size"],
        dcn_n_bytes=int8["dispatch_elems"])
    assert hops8["dcn"] == \
        int8["per_hop"]["dcn"]["exchanged_dispatch_bytes"]
    flat = moe_live["moe_flat"]
    world = moe_dispatch_exchanged_bytes(n_bytes, row["intra_size"],
                                         row["inter_size"],
                                         two_stage=False)
    assert world["world"] == \
        flat["per_hop"]["dcn+ici"]["exchanged_dispatch_bytes"]


def test_measured_sweep_meets_tolerance_when_present(budgets):
    sweep = budgets["sweep"]
    if sweep["status"] != "measured":
        return  # pending_on_chip: the numeric half is dormant
    rows = sweep.get("rows", [])
    flat = [r for r in rows if r.get("exchange") == "flat"]
    bucketed = [r for r in rows if r.get("exchange") == "bucketed"]
    assert flat and bucketed, "measured sweep lacks flat/bucketed rows"
    tol = 1.0 - sweep.get("regression_tolerance_pct", 2.0) / 100.0
    best_flat = max(r["value"] for r in flat)
    best_bucketed = max(r["value"] for r in bucketed)
    assert best_bucketed >= tol * best_flat, (
        f"bucketed flagship {best_bucketed} fell more than the "
        f"tolerated margin below flat {best_flat} — record the "
        "refutation in BENCH_NOTES before re-committing")
