"""Serving budget gate (ISSUE 9: the serving structure can't rot).

Mirrors tests/test_flash_budget.py: tools/serving_budgets.json commits
the serving engine's compiled-program contract and this gate holds
every future PR to it.  Two layers:

* STRUCTURE (backend-neutral, checked here on CPU): the decode step
  reads the KV cache through the block table — exactly one gather per
  pool per layer, NO full-T attention (zero dot_generals carrying a
  [T, T] score matrix — a dense re-prefill per token is the regression
  this exists to catch), zero backward kernels; prefill reuses the
  fused flash FORWARD (one Pallas kernel per layer, zero bwd kernels).
  Verified against the traced programs, not documentation.
* TARGETS (measured on chip by the recovery queue's BENCH_MODEL=serving
  rows): dormant while ``status`` is ``pending_on_chip``; once measured,
  the committed tokens/sec + p99 latency arm.
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))

import serving_census  # noqa: E402


def _budgets():
    return serving_census.load_budgets()


def test_budget_schema():
    b = _budgets()
    assert set(b["structure"]) == {"decode", "prefill", "prefix_prefill",
                                   "disagg_decode_slice",
                                   "transfer_insert", "spec_verify",
                                   "chunked_prefill"}
    g = b["geometry"]
    # the full-T detector's soundness precondition: T strictly exceeds
    # every feature dimension of the census vertical, so two T-sized
    # output dims can only be a score matrix
    assert g["prefill_T"] > max(4 * g["d_model"], g["n_vocab"])
    assert b["targets"]["status"] in ("pending_on_chip", "measured")


def test_decode_structure_gate():
    """The decode hot loop's contract, machine-checked: gather-backed
    cache reads (one per pool per layer), page-scatter writes, NO
    full-T attention, no Pallas bwd kernels.  A PR that reshapes the
    decode step fails here and must either fix it or consciously
    re-commit the structure (python tools/serving_census.py
    --write-budgets)."""
    b = _budgets()
    census = serving_census.decode_census("paged")
    assert census == b["structure"]["decode"], (
        f"decode structure drifted: traced {census}, committed "
        f"{b['structure']['decode']}")
    L = b["geometry"]["n_layers"]
    assert census["pool_gathers"] == 2 * L      # one per pool per layer
    assert census["pool_scatters"] == 2 * L     # one page write per pool
    assert census["full_t_score_dots"] == 0     # no dense re-prefill
    assert census["bwd_kernels"] == 0


def test_prefill_structure_gate():
    """Prefill must keep riding the PR 4 flash forward: one Pallas
    forward kernel per layer, zero backward kernels (no grad is ever
    traced on the serving path), zero [T, T] score dots at the XLA
    level."""
    b = _budgets()
    census = serving_census.prefill_census()
    assert census == b["structure"]["prefill"], (
        f"prefill structure drifted: traced {census}, committed "
        f"{b['structure']['prefill']}")
    L = b["geometry"]["n_layers"]
    assert census["flash_fwd_kernels"] == L
    assert census["bwd_kernels"] == 0
    assert census["full_t_score_dots"] == 0


def test_dense_hatch_structure():
    """The CHAINERMN_TPU_PAGED_ATTN=dense escape hatch still reads the
    cache through the block table (same gather count) and still never
    forms a [T, T] score — it differs in softmax shape only, so the
    trajectory-equality contract (tests/serving_tests) is structural
    too."""
    census = serving_census.decode_census("dense")
    b = _budgets()
    L = b["geometry"]["n_layers"]
    assert census["pool_gathers"] == 2 * L
    assert census["full_t_score_dots"] == 0
    assert census["attn_mode"] == "dense"


def test_full_t_detector_is_alive():
    """The no-full-T gate is only as good as its detector: a dense
    (non-flash) prefill of the same vertical MUST trip it — if this
    fails, the detector has gone blind and the decode/prefill zeros
    above are vacuous."""
    import jax
    import jax.numpy as jnp

    from chainermn_tpu.serving import prefill_program

    model, state, (k_pool, v_pool), N, _ = serving_census._vertical()
    g = serving_census.GEOMETRY
    tokens = jnp.zeros((1, g["prefill_T"]), jnp.int32)
    # NO interpret forcing: the CPU fallback materializes dense scores
    jaxpr = jax.make_jaxpr(
        lambda s, k, v, t, tl, b: prefill_program(
            model, s, k, v, t, tl, b))(
        state, k_pool, v_pool, tokens, jnp.int32(g["prefill_T"]),
        jnp.zeros(N, jnp.int32))
    facts = serving_census._census_facts(
        jaxpr.jaxpr, tuple(k_pool.shape[1:]), g["prefill_T"])
    assert facts["full_t_score_dots"] >= g["n_layers"]


def test_prefix_prefill_structure_gate():
    """The round-14 prefix-hit contract, machine-checked: the suffix
    prefill reads the shared prefix THROUGH the block table (one gather
    per pool per layer), scatters only the suffix (one offset write per
    pool per layer), and runs ZERO flash kernels — recomputing the
    matched prefix with a full flash pass is the regression this gate
    exists to catch.  No [T, T] score dot either: the score is
    suffix-bucket × context, which is the FLOP saving itself."""
    b = _budgets()
    census = serving_census.prefix_prefill_census()
    assert census == b["structure"]["prefix_prefill"], (
        f"prefix_prefill structure drifted: traced {census}, committed "
        f"{b['structure']['prefix_prefill']}")
    L = b["geometry"]["n_layers"]
    assert census["flash_fwd_kernels"] == 0   # ZERO flash over shared pages
    assert census["pool_gathers"] == 2 * L    # prefix read via the table
    assert census["pool_scatters"] == 2 * L   # suffix written, offset
    assert census["full_t_score_dots"] == 0
    assert census["bwd_kernels"] == 0
    # detector soundness for the suffix score: one dim (context) may
    # reach T, the suffix bucket must stay strictly below it
    g = b["geometry"]
    assert g["prefix_suffix_T"] < g["max_context"]


def test_disagg_decode_slice_gate():
    """Disaggregation's decode-slice contract: the only compute program
    on the HBM-bound slice is the decode step — zero prefill (flash)
    kernels, zero full-T dots, zero bwd kernels.  Pinned against the
    live decode trace so it cannot drift from the single-mesh decode
    either (the trajectory-identity hatch is structural too)."""
    b = _budgets()
    census = serving_census.disagg_decode_slice_census()
    assert census == b["structure"]["disagg_decode_slice"]
    assert census == b["structure"]["decode"]   # same program, one mesh
    assert census["flash_fwd_kernels"] == 0     # no prefill on the slice
    assert census["full_t_score_dots"] == 0
    assert census["bwd_kernels"] == 0


def test_transfer_insert_gate():
    """The page ship lands as ONE drop-fenced full-pool scatter — data
    movement only: no gathers, no kernels, no score dots.  A transfer
    that recomputes (or reads back) on arrival fails here."""
    b = _budgets()
    census = serving_census.transfer_insert_census()
    assert census == b["structure"]["transfer_insert"]
    assert census["pool_scatters"] == 1
    assert census["pool_gathers"] == 0
    assert census["flash_fwd_kernels"] == 0
    assert census["bwd_kernels"] == 0


def test_spec_verify_gate():
    """The round-20 speculative-verify contract, machine-checked: ONE
    dispatch scores spec_k + 1 positions per lane
    (``queries_per_dispatch`` — the dispatch-per-token reduction is
    structural, not a tuning claim), the K extra queries ride the SAME
    one-gather-per-pool-per-layer cache reads the single-query step
    pays, K/V land as one drop-fenced span scatter per pool per layer,
    and NO [T, T] score dot forms — a verify that degenerates into a
    per-token dense re-prefill is the regression this gate exists to
    catch."""
    b = _budgets()
    census = serving_census.spec_verify_census()
    assert census == b["structure"]["spec_verify"], (
        f"spec_verify structure drifted: traced {census}, committed "
        f"{b['structure']['spec_verify']}")
    g = b["geometry"]
    L = g["n_layers"]
    assert census["queries_per_dispatch"] == g["spec_k"] + 1
    assert census["pool_gathers"] == 2 * L    # same reads as decode
    assert census["pool_scatters"] == 2 * L   # one span write per pool
    assert census["full_t_score_dots"] == 0   # never a dense re-prefill
    assert census["flash_fwd_kernels"] == 0
    assert census["bwd_kernels"] == 0
    # detector soundness for the [B, H, K1, ctx] score: the span stays
    # a small constant, strictly below the context dimension
    assert g["spec_k"] + 1 < g["max_context"]


def test_chunked_prefill_gate():
    """The round-20 chunk contract: one mid-prompt chunk is an offset
    suffix-prefill — one gather per pool per layer (written context
    read through the block table), one offset scatter per pool per
    layer, zero flash kernels over already-written pages, and zero
    [T, T] dots: chunking a long prompt never re-materializes the
    monolithic score matrix, so per-chunk cost is budget-bounded by
    construction."""
    b = _budgets()
    census = serving_census.chunked_prefill_census()
    assert census == b["structure"]["chunked_prefill"], (
        f"chunked_prefill structure drifted: traced {census}, committed "
        f"{b['structure']['chunked_prefill']}")
    g = b["geometry"]
    L = g["n_layers"]
    assert census["pool_gathers"] == 2 * L
    assert census["pool_scatters"] == 2 * L
    assert census["full_t_score_dots"] == 0
    assert census["flash_fwd_kernels"] == 0
    assert census["bwd_kernels"] == 0
    # chunk geometry soundness: page-multiple (the admission contract)
    # and strictly below the full-T threshold (detector stays sound)
    assert g["chunk_T"] % g["page_size"] == 0
    assert g["chunk_T"] < g["max_context"]


def test_targets_armed_when_measured():
    b = _budgets()
    t = b["targets"]
    if t["status"] != "measured":
        # dormant: the numeric half waits for the recovery queue's
        # serving rows; the schema relation is still enforced
        assert t["tokens_per_sec"] is None
        return
    assert t["tokens_per_sec"] > 0
    assert t["p99_token_latency_ms"] > 0


def test_census_tool_cli_smoke():
    """One-command reproducibility: the census CLI prints one row per
    phase and --write-budgets round-trips the committed structure
    (trace property — allowed off-chip, unlike flash/hbm numbers)."""
    import subprocess
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "serving_census.py")],
        env=env, capture_output=True, text=True, timeout=600, cwd=root)
    assert out.returncode == 0, out.stderr[-2000:]
    rows = [json.loads(l) for l in out.stdout.strip().splitlines()]
    assert {r["phase"] for r in rows} == {
        "decode", "prefill", "prefix_prefill", "disagg_decode_slice",
        "transfer_insert", "spec_verify", "chunked_prefill"}
    committed = _budgets()["structure"]
    for r in rows:
        facts = {k: v for k, v in r.items() if k not in ("probe", "phase")}
        assert facts == committed[r["phase"]]
