"""Transformer LM: training + sequence-parallel equivalence (golden rule)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

import chainermn_tpu as ct
from chainermn_tpu.core.link import apply_state, extract_state
from chainermn_tpu.core.optimizer import Adam
from chainermn_tpu.models.transformer import TransformerLM

COMM = None


def setup_module(module):
    global COMM
    COMM = ct.create_communicator("jax_ici", axis_name="lm_seq")


def _lm_data(B=4, T=None, V=50, seed=0):
    T = T or 4 * COMM.size
    rng = np.random.RandomState(seed)
    x = rng.randint(0, V, (B, T)).astype(np.int32)
    t = np.roll(x, -1, axis=1).astype(np.int32)
    t[:, -1] = -1
    return jnp.asarray(x), jnp.asarray(t)


def test_transformer_lm_trains():
    x, t = _lm_data(T=16)
    model = TransformerLM(50, d_model=32, n_heads=2, n_layers=2, seed=0)
    opt = Adam(alpha=3e-3).setup(model)
    l0 = float(opt.update(model, x, t))
    for _ in range(15):
        l = float(opt.update(model, x, t))
    assert l < l0


def test_sequence_parallel_matches_single_device():
    """Ring and Ulysses sequence-parallel hidden states equal the
    single-device forward with the same weights."""
    x, _ = _lm_data(B=2, seed=3)
    for mode in ("ring", "ulysses"):
        heads = 8 if mode == "ulysses" else 2
        sp = TransformerLM(50, d_model=32, n_heads=heads, n_layers=2,
                           seed=7, sp_comm=COMM, sp_mode=mode)
        single = TransformerLM(50, d_model=32, n_heads=heads, n_layers=2,
                               seed=7)
        state = extract_state(sp)

        def body(params, pstate, x):
            out, _ = apply_state(sp, {"params": params, "state": pstate}, x)
            return out

        # shard the sequence (dim 1) over the axis
        out_sp = jax.jit(jax.shard_map(
            lambda p, s, x: sp_hidden(sp, p, s, x),
            mesh=COMM.mesh,
            in_specs=(P(), P(), P(None, "lm_seq")),
            out_specs=P(None, "lm_seq"),
            check_vma=False))(state["params"], state["state"], x)

        ref = single.logits(x)
        np.testing.assert_allclose(np.asarray(out_sp), np.asarray(ref),
                                   rtol=5e-4, atol=5e-4,
                                   err_msg=f"mode={mode}")


def sp_hidden(model, params, pstate, x_local):
    from chainermn_tpu.core.link import bind_state
    with bind_state(model, {"params": params, "state": pstate}):
        return model.logits(x_local)


def test_sequence_parallel_zigzag_matches_single_device():
    """The balanced zigzag SP schedule produces the same logits as the
    single-device forward: zigzag-shard tokens, run, unshard."""
    from chainermn_tpu.parallel import zigzag_shard, zigzag_unshard
    x, _ = _lm_data(B=2, seed=5)
    n = COMM.size
    sp = TransformerLM(50, d_model=32, n_heads=2, n_layers=2, seed=11,
                       sp_comm=COMM, sp_mode="zigzag")
    single = TransformerLM(50, d_model=32, n_heads=2, n_layers=2, seed=11)
    state = extract_state(sp)
    xz = zigzag_shard(x, n, axis=1)
    out_sp = jax.jit(jax.shard_map(
        lambda p, s, x: sp_hidden(sp, p, s, x),
        mesh=COMM.mesh,
        in_specs=(P(), P(), P(None, "lm_seq")),
        out_specs=P(None, "lm_seq"),
        check_vma=False))(state["params"], state["state"], xz)
    out = zigzag_unshard(out_sp, n, axis=1)
    ref = single.logits(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=5e-4, atol=5e-4)


def test_sequence_parallel_remat_policy_matches():
    """remat × SP composition: jax.checkpoint (incl. the dots policy)
    wrapped around blocks whose attention carries ppermute collectives
    must not change the sharded forward."""
    x, _ = _lm_data(B=2, seed=3)
    single = TransformerLM(50, d_model=32, n_heads=2, n_layers=2, seed=7)
    ref = single.logits(x)
    for remat in (True, "dots"):
        sp = TransformerLM(50, d_model=32, n_heads=2, n_layers=2,
                           seed=7, sp_comm=COMM, sp_mode="ring",
                           remat=remat)
        state = extract_state(sp)
        out_sp = jax.jit(jax.shard_map(
            lambda p, s, x: sp_hidden(sp, p, s, x),
            mesh=COMM.mesh,
            in_specs=(P(), P(), P(None, "lm_seq")),
            out_specs=P(None, "lm_seq"),
            check_vma=False))(state["params"], state["state"], x)
        np.testing.assert_allclose(np.asarray(out_sp), np.asarray(ref),
                                   rtol=5e-4, atol=5e-4,
                                   err_msg=f"remat={remat!r}")


def test_sequence_parallel_gradients_match(subtests=None):
    x, _ = _lm_data(B=2, seed=4)
    # equal valid-token count per shard: pmean of per-shard mean losses
    # then equals the global mean (unequal counts would need
    # count-weighted averaging — same caveat as the reference's equal-
    # shard invariant, SURVEY §3.4)
    t = jnp.asarray(np.roll(np.asarray(x), -1, axis=1))
    sp = TransformerLM(50, d_model=32, n_heads=2, n_layers=1, seed=9,
                       sp_comm=COMM, sp_mode="ring")
    single = TransformerLM(50, d_model=32, n_heads=2, n_layers=1, seed=9)
    state = extract_state(sp)

    def body(params, pstate, x, t):
        from chainermn_tpu.core.link import bind_state

        def loss(p):
            with bind_state(sp, {"params": p, "state": pstate}):
                return sp(x, t)
        g = jax.grad(loss)(params)
        # per-token losses are sequence-local; sum grads across shards
        return jax.tree.map(
            lambda a: jax.lax.pmean(a, COMM.axis_name), g)

    g_sp = jax.jit(jax.shard_map(
        body, mesh=COMM.mesh,
        in_specs=(P(), P(), P(None, "lm_seq"), P(None, "lm_seq")),
        out_specs=P(), check_vma=False))(state["params"], state["state"],
                                         x, t)

    s_single = extract_state(single)

    def ref_loss(p):
        from chainermn_tpu.core.link import bind_state
        with bind_state(single, {"params": p, "state": s_single["state"]}):
            return single(x, t)

    g_ref = jax.grad(ref_loss)(s_single["params"])
    # same seeds → same param paths; compare the attention/mlp weights
    for key in g_ref:
        np.testing.assert_allclose(
            np.asarray(g_sp[key]), np.asarray(g_ref[key]),
            rtol=5e-3, atol=5e-4, err_msg=key)


def test_moe_transformer_dense_vs_expert_parallel():
    """MoE LM loss matches between dense fallback and EP execution."""
    from chainermn_tpu.models import MoETransformerLM
    ep = ct.create_communicator("jax_ici", axis_name="lm_ep")
    x, _ = _lm_data(B=2, T=16, seed=6)
    t = jnp.asarray(np.roll(np.asarray(x), -1, axis=1))
    model = MoETransformerLM(50, ep, d_model=16, n_heads=2, n_layers=1,
                             seed=11, capacity_factor=float(ep.size))
    loss_dense = model(x, t)  # no axis bound → dense fallback

    from chainermn_tpu.core.link import bind_state, extract_state
    state = extract_state(model)

    def body(params, pstate, x, t):
        with bind_state(model, {"params": params, "state": pstate}):
            return model(x, t).reshape(1)

    loss_ep = jax.jit(jax.shard_map(
        body, mesh=ep.mesh,
        in_specs=(P(), P(), P(), P()),
        out_specs=P("lm_ep"), check_vma=False))(
            state["params"], state["state"], x, t)
    # replicated tokens on every rank: each rank routes the full batch;
    # dense vs EP should agree at generous capacity
    np.testing.assert_allclose(float(np.asarray(loss_ep)[0]),
                               float(loss_dense), rtol=1e-3)


def test_moe_transformer_trains():
    from chainermn_tpu.models import MoETransformerLM
    from chainermn_tpu.core.optimizer import Adam
    ep = ct.create_communicator("jax_ici", axis_name="lm_ep2")
    x, _ = _lm_data(B=2, T=16, seed=8)
    t = jnp.asarray(np.roll(np.asarray(x), -1, axis=1))
    model = MoETransformerLM(50, ep, d_model=16, n_heads=2, n_layers=1,
                             seed=12)
    opt = Adam(alpha=3e-3).setup(model)
    l0 = float(opt.update(model, x, t))
    for _ in range(10):
        l = float(opt.update(model, x, t))
    assert l < l0


def test_moe_transformer_remat_and_bf16():
    """MoE LM grows the same knobs as TransformerLM: remat (incl.
    policies — aux losses cross the checkpoint boundary as explicit
    outputs) must not change the trajectory; bf16 compute stays close
    and trains."""
    from chainermn_tpu.core.optimizer import Adam
    from chainermn_tpu.models import MoETransformerLM
    ep = ct.create_communicator("jax_ici", axis_name="lm_ep3")
    x, _ = _lm_data(B=2, T=16, seed=9)
    t = jnp.asarray(np.roll(np.asarray(x), -1, axis=1))

    losses = {}
    for remat in (False, True, "dots"):
        m = MoETransformerLM(50, ep, d_model=16, n_heads=2, n_layers=2,
                             seed=12, remat=remat)
        opt = Adam(alpha=3e-3).setup(m)
        losses[remat] = [float(opt.update(m, x, t)) for _ in range(3)]
    np.testing.assert_allclose(losses[True], losses[False], rtol=1e-5)
    np.testing.assert_allclose(losses["dots"], losses[False], rtol=1e-5)

    mb = MoETransformerLM(50, ep, d_model=16, n_heads=2, n_layers=2,
                          seed=12, compute_dtype=jnp.bfloat16, remat=True)
    opt = Adam(alpha=3e-3).setup(mb)
    lb = [float(opt.update(mb, x, t)) for _ in range(8)]
    assert np.isfinite(lb).all()
    np.testing.assert_allclose(lb[0], losses[False][0], rtol=5e-2)
    assert lb[-1] < lb[0]  # bf16+remat actually TRAINS, not just runs


def test_transformer_remat_matches():
    from chainermn_tpu.core.optimizer import SGD
    x, t = _lm_data(B=2, T=16, seed=10)
    losses = {}
    for remat in (False, True, "dots", "everything_saveable"):
        m = TransformerLM(50, d_model=32, n_heads=2, n_layers=2, seed=13,
                          remat=remat)
        opt = SGD(lr=0.1).setup(m)
        losses[remat] = [float(opt.update(m, x, t)) for _ in range(3)]
    for variant in (True, "dots", "everything_saveable"):
        np.testing.assert_allclose(losses[variant], losses[False],
                                   rtol=1e-5,
                                   err_msg=f"remat={variant!r} diverged")


def test_transformer_remat_rejects_unknown_policy():
    import pytest
    m = TransformerLM(50, d_model=32, n_heads=2, n_layers=1, seed=13,
                      remat="not_a_policy")
    x, t = _lm_data(B=1, T=8, seed=1)
    with pytest.raises(ValueError, match="remat policy"):
        m(x, t)


def test_generate_kv_cache_matches_full_forward():
    """Greedy generation with KV caches emits exactly the argmax of the
    full-forward logits at each position."""
    m = TransformerLM(31, d_model=32, n_heads=2, n_layers=2, max_len=64,
                      seed=0)
    prompt = jnp.asarray(np.random.RandomState(0)
                         .randint(0, 31, (2, 5)).astype(np.int32))
    out = m.generate(prompt, 6)
    assert out.shape == (2, 6)
    full = jnp.concatenate([prompt, out], axis=1)
    logits = m.logits(full)
    for i in range(6):
        expect = np.argmax(np.asarray(logits[:, 5 + i - 1]), -1)
        np.testing.assert_array_equal(np.asarray(out[:, i]), expect)


def test_generate_sampling_reproducible():
    m = TransformerLM(31, d_model=16, n_heads=2, n_layers=1, max_len=32,
                      seed=1)
    prompt = jnp.zeros((1, 3), jnp.int32)
    a = m.generate(prompt, 5, temperature=1.0, key=jax.random.PRNGKey(7))
    b = m.generate(prompt, 5, temperature=1.0, key=jax.random.PRNGKey(7))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_transformer_lm_bf16_compute():
    """compute_dtype=bfloat16: params stay fp32, loss tracks the fp32
    model's (fp32 statistics inside LN/softmax keep numerics sane)."""
    import jax.numpy as jnp
    import numpy as np
    from chainermn_tpu.models import TransformerLM

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randint(0, 64, (2, 16)).astype(np.int32))
    t = jnp.asarray(np.roll(np.asarray(x), -1, axis=1))

    m32 = TransformerLM(n_vocab=64, d_model=32, n_heads=2, n_layers=2,
                        max_len=32, seed=0)
    m16 = TransformerLM(n_vocab=64, d_model=32, n_heads=2, n_layers=2,
                        max_len=32, seed=0, compute_dtype=jnp.bfloat16)
    l32 = float(m32(x, t))
    l16 = float(m16(x, t))
    assert abs(l32 - l16) / abs(l32) < 0.02
    for _, p in m16.namedparams():
        assert p.array.dtype == jnp.float32
