"""Model zoo tests: shapes, training steps, model-parallel equivalence."""

import numpy as np
import pytest

# multi-minute compile-heavy suite (ResNets, model-parallel seq2seq):
# slow-marked so tier-1 stays inside its wall-clock budget
pytestmark = pytest.mark.slow

import jax
import jax.numpy as jnp

import chainermn_tpu as ct
from chainermn_tpu import F
from chainermn_tpu.core.optimizer import Adam, SGD
from chainermn_tpu.models import (Classifier, DCGANUpdater, Discriminator,
                                  Generator, MLP, ModelParallelSeq2seq,
                                  ResNet18, ResNet50, Seq2seq,
                                  make_synthetic_translation_data)


def test_mlp_classifier_trains():
    model = Classifier(MLP(n_units=32, n_out=5, seed=0))
    opt = Adam().setup(model)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.normal(0, 1, (16, 20)).astype(np.float32))
    t = jnp.asarray(rng.randint(0, 5, 16).astype(np.int32))
    losses = [float(opt.update(model, x, t)) for _ in range(10)]
    assert losses[-1] < losses[0]


def test_resnet50_forward_shape():
    model = ResNet50(n_classes=10)
    x = jnp.zeros((2, 3, 64, 64), jnp.float32)
    y = model(x)
    assert y.shape == (2, 10)
    assert model.count_params() > 23_000_000  # ResNet-50 scale


def test_resnet50_bf16_compute():
    model = ResNet50(n_classes=10, compute_dtype=jnp.bfloat16)
    x = jnp.zeros((2, 3, 64, 64), jnp.float32)
    y = model(x)
    assert y.dtype == jnp.float32  # logits back in f32
    assert np.isfinite(np.asarray(y)).all()


def test_resnet50_uint8_input_norm_matches_host_normalized():
    """input_norm='imagenet' over raw uint8 pixels must equal the same
    weights fed host-normalized float32 ((x/255 - mean)/std) — the
    in-graph path exists so the pipeline can ship uint8 and cast on
    device (BENCH_NOTES r5 input-pipeline probe)."""
    from chainermn_tpu.models.resnet import IMAGENET_MEAN, IMAGENET_STD

    rng = np.random.RandomState(0)
    x8 = rng.randint(0, 256, (2, 3, 64, 64)).astype(np.uint8)
    mean = np.asarray(IMAGENET_MEAN, np.float32).reshape(1, 3, 1, 1)
    std = np.asarray(IMAGENET_STD, np.float32).reshape(1, 3, 1, 1)
    xf = (x8.astype(np.float32) / 255.0 - mean) / std

    m_u8 = ResNet50(n_classes=10, seed=0, input_norm="imagenet")
    m_f = ResNet50(n_classes=10, seed=0)
    y_u8 = np.asarray(m_u8(jnp.asarray(x8)))
    y_f = np.asarray(m_f(jnp.asarray(xf)))
    np.testing.assert_allclose(y_u8, y_f, rtol=2e-4, atol=2e-4)
    # NHWC layout flavor keeps the same math
    m_u8n = ResNet50(n_classes=10, seed=0, input_norm="imagenet",
                     layout="NHWC")
    y_u8n = np.asarray(m_u8n(jnp.asarray(
        np.transpose(x8, (0, 2, 3, 1)))))
    np.testing.assert_allclose(y_u8n, y_f, rtol=2e-4, atol=2e-4)
    # bf16 flavor: the in-graph normalize runs in f32 and casts only the
    # result, so it must track the host-normalized bf16 model within
    # bf16 rounding (not merely stay finite)
    m_b = ResNet50(n_classes=10, seed=0, input_norm="imagenet",
                   compute_dtype=jnp.bfloat16)
    m_bf = ResNet50(n_classes=10, seed=0, compute_dtype=jnp.bfloat16)
    y_b = np.asarray(m_b(jnp.asarray(x8)))
    y_bf = np.asarray(m_bf(jnp.asarray(xf)))
    np.testing.assert_allclose(y_b, y_bf, rtol=5e-2, atol=5e-2)
    # misspelled preset fails loudly at construction
    with pytest.raises(ValueError, match="input_norm preset"):
        ResNet50(n_classes=10, input_norm="ImageNet")


def test_classic_convnets_input_norm_matches_host_normalized():
    """input_norm='imagenet' on the classic ImageNet archs equals the
    same weights fed host-normalized float32 (NIN: deterministic
    forward, no dropout on the conv path)."""
    from chainermn_tpu.models import NIN
    from chainermn_tpu.models.resnet import IMAGENET_MEAN, IMAGENET_STD

    rng = np.random.RandomState(0)
    x8 = rng.randint(0, 256, (2, 3, 64, 64)).astype(np.uint8)
    mean = np.asarray(IMAGENET_MEAN, np.float32).reshape(1, 3, 1, 1)
    std = np.asarray(IMAGENET_STD, np.float32).reshape(1, 3, 1, 1)
    xf = (x8.astype(np.float32) / 255.0 - mean) / std
    m_u8 = NIN(n_classes=10, seed=0, input_norm="imagenet")
    m_f = NIN(n_classes=10, seed=0)
    np.testing.assert_allclose(np.asarray(m_u8(jnp.asarray(x8))),
                               np.asarray(m_f(jnp.asarray(xf))),
                               rtol=2e-4, atol=2e-4)


def test_resnet18_trains_on_synthetic_cifar():
    model = Classifier(ResNet18(n_classes=10, seed=0))
    opt = Adam().setup(model)
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.normal(0, 1, (8, 3, 32, 32)).astype(np.float32))
    t = jnp.asarray(rng.randint(0, 10, 8).astype(np.int32))
    l0 = float(opt.update(model, x, t))
    for _ in range(5):
        l = float(opt.update(model, x, t))
    assert l < l0


def test_seq2seq_loss_and_translate():
    xs, ys_in, ys_out = make_synthetic_translation_data(n=32, max_len=8)
    model = Seq2seq(40, 40, 32, seed=0)
    opt = Adam().setup(model)
    l0 = float(opt.update(model, jnp.asarray(xs), jnp.asarray(ys_in),
                          jnp.asarray(ys_out)))
    for _ in range(15):
        l = float(opt.update(model, jnp.asarray(xs), jnp.asarray(ys_in),
                             jnp.asarray(ys_out)))
    assert l < l0
    out = model.translate(jnp.asarray(xs[:4]), bos_id=0, eos_id=1,
                          max_length=8)
    assert out.shape == (4, 8)


def test_model_parallel_seq2seq_matches_single_process():
    """Enc/dec split across stage ranks == single-process seq2seq (golden
    rule, BASELINE config #4)."""
    comm = ct.create_communicator("jax_ici", axis_name="s2s_stage")
    xs, ys_in, ys_out = make_synthetic_translation_data(n=8, max_len=6)
    xs, ys_in, ys_out = (jnp.asarray(xs), jnp.asarray(ys_in),
                        jnp.asarray(ys_out))
    mp = ModelParallelSeq2seq(comm, 40, 40, 16, seed=5)
    ref = Seq2seq(40, 40, 16, seed=5)
    loss_mp = mp(xs, ys_in, ys_out)
    loss_ref = ref(xs, ys_in, ys_out)
    np.testing.assert_allclose(float(loss_mp), float(loss_ref),
                               rtol=1e-4)


def test_model_parallel_seq2seq_trains():
    comm = ct.create_communicator("jax_ici", axis_name="s2s_stage2")
    xs, ys_in, ys_out = make_synthetic_translation_data(n=16, max_len=6)
    xs, ys_in, ys_out = (jnp.asarray(xs), jnp.asarray(ys_in),
                        jnp.asarray(ys_out))
    model = ModelParallelSeq2seq(comm, 40, 40, 16, seed=3)
    opt = SGD(lr=0.5).setup(model)
    l0 = float(opt.update(model, xs, ys_in, ys_out))
    for _ in range(10):
        l = float(opt.update(model, xs, ys_in, ys_out))
    assert l < l0


def test_dcgan_updater_steps():
    gen, dis = Generator(n_hidden=16, ch=32, seed=0), Discriminator(ch=32,
                                                                    seed=1)
    opt_gen = Adam(alpha=1e-3).setup(gen)
    opt_dis = Adam(alpha=1e-3).setup(dis)
    rng = np.random.RandomState(0)
    data = rng.normal(0, 0.5, (16, 3, 32, 32)).astype(np.float32)
    from chainermn_tpu.dataset import SerialIterator
    it = SerialIterator(data, 8, shuffle=False)
    updater = DCGANUpdater(it, opt_gen, opt_dis)
    w_gen0 = np.asarray(gen.l0.W.array).copy()
    w_dis0 = np.asarray(dis.l4.W.array).copy()
    updater.update()
    updater.update()
    assert not np.allclose(np.asarray(gen.l0.W.array), w_gen0)
    assert not np.allclose(np.asarray(dis.l4.W.array), w_dis0)


def test_dcgan_data_parallel():
    comm = ct.create_communicator("jax_ici")
    gen, dis = Generator(n_hidden=16, ch=32, seed=0), Discriminator(ch=32,
                                                                    seed=1)
    opt_gen = ct.create_multi_node_optimizer(Adam(alpha=1e-3), comm).setup(gen)
    opt_dis = ct.create_multi_node_optimizer(Adam(alpha=1e-3), comm).setup(dis)
    rng = np.random.RandomState(0)
    data = rng.normal(0, 0.5, (32, 3, 32, 32)).astype(np.float32)
    from chainermn_tpu.dataset import SerialIterator
    it = SerialIterator(data, 16, shuffle=False)
    updater = DCGANUpdater(it, opt_gen, opt_dis)
    updater.update()
    assert np.isfinite(np.asarray(gen.l0.W.array)).all()


def test_resnet_remat_matches_no_remat():
    """jax.checkpoint stages: identical loss/grads, lower activation
    memory; BN stats thread through the remat boundary."""
    from chainermn_tpu.core.optimizer import SGD
    rng = np.random.RandomState(7)
    x = jnp.asarray(rng.normal(0, 1, (4, 3, 64, 64)).astype(np.float32))
    t = jnp.asarray(rng.randint(0, 10, 4).astype(np.int32))
    losses = {}
    stats = {}
    for remat in (False, True):
        m = Classifier(ResNet50(n_classes=10, remat=remat, seed=0))
        opt = SGD(lr=0.01).setup(m)
        losses[remat] = [float(opt.update(m, x, t)) for _ in range(2)]
        stats[remat] = np.asarray(m.predictor.res2[0].a.bn.avg_mean)
    np.testing.assert_allclose(losses[True], losses[False], rtol=1e-5)
    np.testing.assert_allclose(stats[True], stats[False], rtol=1e-5)
    assert np.abs(stats[True]).sum() > 0  # BN stats actually updated


def test_classic_convnets_forward_and_train():
    from chainermn_tpu.models import AlexNet, NIN, VGG16, GoogLeNet
    rng = np.random.RandomState(0)
    # small spatial input keeps CPU time sane; archs handle any size ≥ their
    # stride pyramid via lazy/GAP heads (VGG/Alex use lazy fc6)
    for cls, size in ((NIN, 67), (GoogLeNet, 64)):
        m = cls(n_classes=7, seed=0)
        x = jnp.asarray(rng.normal(0, 1, (2, 3, size, size))
                        .astype(np.float32))
        y = m(x)
        assert y.shape == (2, 7), cls.__name__
        assert np.isfinite(np.asarray(y)).all()
    # AlexNet/VGG16 train one step on tiny inputs
    from chainermn_tpu.core.optimizer import SGD
    for cls, size in ((AlexNet, 67), (VGG16, 64)):
        m = Classifier(cls(n_classes=5, seed=0))
        opt = SGD(lr=0.01).setup(m)
        x = jnp.asarray(rng.normal(0, 1, (2, 3, size, size))
                        .astype(np.float32))
        t = jnp.asarray(rng.randint(0, 5, 2).astype(np.int32))
        loss = opt.update(m, x, t)
        assert np.isfinite(float(loss)), cls.__name__


def test_googlenet_aux_heads():
    from chainermn_tpu.models import GoogLeNet
    from chainermn_tpu.core.optimizer import SGD
    m = GoogLeNet(n_classes=7, seed=0)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.normal(0, 1, (2, 3, 64, 64)).astype(np.float32))
    t = jnp.asarray(rng.randint(0, 7, 2).astype(np.int32))
    main, a1, a2 = m.forward_with_aux(x)
    assert main.shape == a1.shape == a2.shape == (2, 7)
    opt = SGD(lr=0.01).setup(m)
    loss = opt.update(m.loss, x, t)
    assert np.isfinite(float(loss))
    # eval mode: loss excludes aux terms
    with ct.using_config("train", False):
        eval_loss = m.loss(x, t)
        main_only = F.softmax_cross_entropy(m(x), t)
    np.testing.assert_allclose(float(eval_loss), float(main_only),
                               rtol=1e-5)
