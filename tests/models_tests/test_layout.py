"""NHWC (TPU-native) vs NCHW activation-layout equivalence.

The kernel layout is OIHW in both cases, so the same seed yields the
same parameters — the two layouts must compute the same function
(VERDICT r2 Missing #2: the bench's NHWC path needs a correctness
anchor before any MFU claim built on it counts).
"""

import numpy as np
import pytest

import jax.numpy as jnp

import chainermn_tpu as ct
from chainermn_tpu import F
from chainermn_tpu.core.optimizer import SGD
from chainermn_tpu.models import Classifier, ResNet50

# ResNet50 forward/backward compiles for minutes on the simulated CPU
# mesh: slow-marked so tier-1 stays inside its wall-clock budget
pytestmark = pytest.mark.slow


def _nhwc(x):
    return jnp.transpose(x, (0, 2, 3, 1))


def test_convolution_2d_nhwc_matches_nchw():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.normal(0, 1, (2, 5, 9, 9)).astype(np.float32))
    W = jnp.asarray(rng.normal(0, 1, (7, 5, 3, 3)).astype(np.float32))
    b = jnp.asarray(rng.normal(0, 1, (7,)).astype(np.float32))
    ref = F.convolution_2d(x, W, b, stride=2, pad=1)
    out = F.convolution_2d(_nhwc(x), W, b, stride=2, pad=1, layout="NHWC")
    np.testing.assert_allclose(np.asarray(_nhwc(ref)), np.asarray(out),
                               rtol=1e-5, atol=1e-5)


def test_pooling_nhwc_matches_nchw():
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.normal(0, 1, (2, 4, 11, 11)).astype(np.float32))
    for fn, kwargs in ((F.max_pooling_2d, dict(cover_all=True)),
                       (F.max_pooling_2d, dict(cover_all=False)),
                       (F.average_pooling_2d, {})):
        ref = fn(x, 3, stride=2, pad=1, **kwargs)
        out = fn(_nhwc(x), 3, stride=2, pad=1, layout="NHWC", **kwargs)
        np.testing.assert_allclose(np.asarray(_nhwc(ref)), np.asarray(out),
                                   rtol=1e-6, atol=1e-6)
    ref = F.global_average_pooling_2d(x)
    out = F.global_average_pooling_2d(_nhwc(x), layout="NHWC")
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), rtol=1e-6)


def test_resnet50_nhwc_matches_nchw_train_step():
    """Full train step (fwd + bwd + BN stats + update) agrees between
    layouts — the NHWC bench path computes the same model."""
    rng = np.random.RandomState(7)
    x = jnp.asarray(rng.normal(0, 1, (4, 3, 64, 64)).astype(np.float32))
    t = jnp.asarray(rng.randint(0, 10, 4).astype(np.int32))
    losses, stats, fc = {}, {}, {}
    for layout in ("NCHW", "NHWC"):
        m = Classifier(ResNet50(n_classes=10, seed=0, layout=layout))
        opt = SGD(lr=0.01).setup(m)
        xin = x if layout == "NCHW" else _nhwc(x)
        losses[layout] = [float(opt.update(m, xin, t)) for _ in range(2)]
        stats[layout] = np.asarray(m.predictor.res2[0].a.bn.avg_mean)
        fc[layout] = np.asarray(m.predictor.fc.W.array)
    np.testing.assert_allclose(losses["NHWC"], losses["NCHW"], rtol=1e-4)
    np.testing.assert_allclose(stats["NHWC"], stats["NCHW"],
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(fc["NHWC"], fc["NCHW"], rtol=1e-3, atol=1e-6)


def test_resnet50_nhwc_bf16_remat():
    """The exact bench configuration (NHWC + bf16 + remat) runs and is
    finite."""
    m = Classifier(ResNet50(n_classes=10, seed=0, layout="NHWC",
                            compute_dtype=jnp.bfloat16, remat=True))
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.normal(0, 1, (2, 64, 64, 3)).astype(np.float32))
    t = jnp.asarray(rng.randint(0, 10, 2).astype(np.int32))
    opt = SGD(lr=0.01).setup(m)
    loss = opt.update(m, x, t)
    assert np.isfinite(float(loss))


def test_mnbn_preserves_axis():
    """create_mnbn_model keeps the NHWC BN axis on the rewritten links."""
    comm = ct.create_communicator("jax_ici")
    m = ResNet50(n_classes=10, seed=0, layout="NHWC")
    mn = ct.links.create_mnbn_model(m, comm)
    assert mn.conv1.bn.axis == (0, 1, 2)
