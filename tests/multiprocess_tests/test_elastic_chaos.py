"""End-to-end elastic chaos over REAL 2-process gloo transport (the
ISSUE 10 acceptance gate, see docs/resilience.md §7).

One training run: a seeded rank-targeted ``preempt`` fault hard-stops
rank 1 mid-run → rank 0 detects through a typed channel timeout, the
membership protocol shrinks the world to {0}, and training continues
solo (global batch preserved) → rank 1 parks, announces ``join``, is
re-admitted, adopts the survivors' newest snapshot, and the world grows
back to {0, 1} → the run finishes at the full iteration count with the
final loss inside the committed ±5% convergence-parity band of the
uninterrupted baseline, bit-identical params across the re-grown world,
and a world-size-1 snapshot proven to resume bit-exact into a
2-process-shaped trainer (params/opt-state; re-seeded elastic buffers
excluded by contract)."""

import pytest

from .test_two_process import _launch

pytestmark = pytest.mark.chaos


def test_two_process_elastic_preempt_and_rejoin(tmp_path):
    outs = _launch("elastic", 2, tmp_path, timeout=420)
    for rc, out in outs:
        assert rc == 0, f"worker failed (rc={rc}):\n{out[-6000:]}"
        assert "ALL_OK" in out, out[-6000:]
    for name in ("elastic_baseline", "elastic_shrink_and_regrow",
                 "elastic_world_consistent", "elastic_convergence_parity",
                 "elastic_cross_size_resume_bit_exact"):
        for rc, out in outs:
            assert f"PASS {name}" in out, (name, out[-6000:])
