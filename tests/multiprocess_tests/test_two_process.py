"""Real two-process ``jax.distributed`` transport tests.

The reference's CI discipline was REAL ``mpiexec -n 2`` processes
(SURVEY.md §4) — no mock transport.  The TPU analog: two CPU-backend
controller processes bootstrapped through a localhost coordinator, gloo
cross-process collectives, and the coordination-service KV object
channel (VERDICT r1 "Next round" items 3 and 4).
"""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = os.path.join(os.path.dirname(__file__), "_worker.py")
_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _free_port():
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _worker_env(local_devices=1):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # the pytest process's conftest forces an 8-device CPU host; workers
    # control their own device count
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={local_devices}")
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["CHAINERMN_TPU_FORCE_ABORT_ON_EXCEPTION"] = "0"  # scenario installs
    return env


def _launch(scenario, nprocs, tmpdir, local_devices=1, timeout=240):
    port = _free_port()
    env = _worker_env(local_devices)
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, scenario, str(pid), str(nprocs),
             str(port), str(tmpdir)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        for pid in range(nprocs)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append((p.returncode, out))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    return outs


@pytest.mark.slow
def test_two_process_transport_suite(tmp_path):
    outs = _launch("transport", 2, tmp_path)
    for rc, out in outs:
        assert rc == 0, f"worker failed (rc={rc}):\n{out[-4000:]}"
        assert "ALL_OK" in out, out[-4000:]
    # every sub-scenario passed on every worker
    for name in ("topology", "allgather_pickled", "bcast_obj",
                 "allgather_obj", "send_recv_obj", "chunked_payload",
                 "send_recv_ndarray", "evaluator", "multi_node_iterator",
                 "synchronized_iterator", "checkpointer_consensus",
                 "scatter_dataset"):
        for rc, out in outs:
            assert f"PASS {name}" in out, (name, out[-4000:])


@pytest.mark.slow
def test_two_process_compiled_dp_step(tmp_path):
    """The compiled data plane spans real processes: a jitted shard_map
    DP step (gradient pmean over a 2-process gloo CPU mesh) matches the
    single-process full-batch golden, and split() returns the caller's
    group (VERDICT r2 Missing #3 / Weak #5)."""
    outs = _launch("dp_step", 2, tmp_path)
    for rc, out in outs:
        assert rc == 0, f"worker failed (rc={rc}):\n{out[-4000:]}"
        assert "ALL_OK" in out, out[-4000:]
    for name in ("mesh_spans_processes", "dp_step_runs",
                 "dp_loss_matches_golden", "dp_grads_match_golden",
                 "dp_params_consistent", "split_returns_caller_group"):
        for rc, out in outs:
            assert f"PASS {name}" in out, (name, out[-4000:])


def test_two_process_zero_step(tmp_path):
    """ZeRO-1 across real process boundaries: psum_scatter/all_gather
    over the 2-process gloo mesh, per-process 1/n optimizer-state
    chunks, sharded global-norm clipping, golden-equal trajectory."""
    outs = _launch("zero_step", 2, tmp_path)
    for rc, out in outs:
        assert rc == 0, f"worker failed (rc={rc}):\n{out[-4000:]}"
        assert "ALL_OK" in out, out[-4000:]
    for name in ("zero_step_runs", "zero_state_sharded_across_processes",
                 "zero_loss_matches_golden", "zero_params_consistent"):
        for rc, out in outs:
            assert f"PASS {name}" in out, (name, out[-4000:])


def test_two_process_zero_save_resume(tmp_path):
    """ZeRO-1 save/resume with REAL multi-controller sharded state
    (ADVICE r4): the npz writer host-gathers each process's flat chunk
    over the object channel, the reader re-commits to the sharded
    layout, and the resumed trajectory is bit-exact."""
    outs = _launch("zero_save_resume", 2, tmp_path)
    for rc, out in outs:
        assert rc == 0, f"worker failed (rc={rc}):\n{out[-4000:]}"
        assert "ALL_OK" in out, out[-4000:]
    for name in ("zero_save_multiprocess",
                 "zero_state_still_sharded_after_save",
                 "zero_resume_state_sharded", "zero_resume_bit_exact",
                 "zero_resume_consistent"):
        for rc, out in outs:
            assert f"PASS {name}" in out, (name, out[-4000:])


@pytest.mark.slow
def test_four_process_split_groups(tmp_path):
    """MPI_Comm_Split across REAL process boundaries: 4 gloo processes,
    colors [0,0,1,1] → two live 2-process sub-communicators, each
    running its own compiled DP step with group-isolated collectives."""
    outs = _launch("split_groups", 4, tmp_path, timeout=360)
    for rc, out in outs:
        assert rc == 0, f"worker failed (rc={rc}):\n{out[-4000:]}"
        assert "ALL_OK" in out, out[-4000:]
    for name in ("split_two_process_subgroups", "subgroup_dp_step_runs",
                 "subgroup_matches_own_golden", "split_groups_isolated"):
        for rc, out in outs:
            assert f"PASS {name}" in out, (name, out[-4000:])


@pytest.mark.slow
def test_two_process_multidevice_topology(tmp_path):
    """2 controllers × 4 devices each: intra/inter topology and
    device-rank-weighted object collectives on a host layout the
    single-process suite cannot produce."""
    outs = _launch("transport", 2, tmp_path, local_devices=4)
    for rc, out in outs:
        assert rc == 0, f"worker failed (rc={rc}):\n{out[-4000:]}"
        assert "ALL_OK" in out, out[-4000:]


@pytest.mark.slow
def test_crash_fail_stop(tmp_path):
    """One rank raises → except hook shuts the job down: the surviving
    rank must exit (not hang in its blocking recv) and both exit
    non-zero."""
    outs = _launch("crash", 2, tmp_path, timeout=120)
    assert all(rc != 0 for rc, _ in outs), [rc for rc, _ in outs]
    assert not any("UNEXPECTED" in out for _, out in outs)
    assert any("deliberate crash" in out for _, out in outs)
