"""End-to-end chaos: inject → detect → recover → converge over REAL
2-process gloo transport (the acceptance test of the resilience
subsystem — see docs/resilience.md).

Faults exercised in one training run: an injected collective fault (both
ranks, same seeded call site), a transient host-channel transport fault
(absorbed by bounded retry), and a torn checkpoint write — recovered via
the checkpointer's consensus resume to the exact fault-free trajectory.
Finally, a deliberately corrupted snapshot is proven excluded from a
fresh consensus vote on both ranks."""

import pytest

from .test_two_process import _launch

pytestmark = pytest.mark.chaos


def test_two_process_chaos_recovery(tmp_path):
    outs = _launch("chaos_recovery", 2, tmp_path, timeout=300)
    for rc, out in outs:
        assert rc == 0, f"worker failed (rc={rc}):\n{out[-4000:]}"
        assert "ALL_OK" in out, out[-4000:]
    for name in ("chaos_baseline", "chaos_recovered_twice",
                 "chaos_transient_retry_absorbed",
                 "chaos_final_matches_baseline", "chaos_corrupt_excluded"):
        for rc, out in outs:
            assert f"PASS {name}" in out, (name, out[-4000:])
