"""Worker for real multi-process transport tests.

Launched N times (subprocess per controller) by ``test_two_process.py``
with a localhost coordinator — the TPU analog of the reference's
``mpiexec -n 2`` CI discipline (SURVEY.md §4): the REAL bootstrap and
transport are exercised, no in-memory fakes.

Usage: python _worker.py <scenario> <pid> <nprocs> <port> <tmpdir>
Prints ``PASS <name>`` per sub-scenario; exits non-zero on any failure.
"""

import os
import sys


def main():
    scenario, pid, nprocs, port, tmpdir = sys.argv[1:6]
    pid, nprocs = int(pid), int(nprocs)

    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

    # the real bootstrap under test (VERDICT r1 missing #2)
    from chainermn_tpu.communicators._communication_utility import (
        initialize_distributed)
    assert initialize_distributed(f"localhost:{port}",
                                  num_processes=nprocs, process_id=pid)

    if scenario == "transport":
        run_transport_suite(pid, nprocs, tmpdir)
    elif scenario == "dp_step":
        run_dp_step(pid, nprocs)
    elif scenario == "zero_step":
        run_zero_step(pid, nprocs)
    elif scenario == "zero_save_resume":
        run_zero_save_resume(pid, nprocs, tmpdir)
    elif scenario == "split_groups":
        run_split_groups(pid, nprocs)
    elif scenario == "crash":
        run_crash(pid, nprocs)
    elif scenario == "chaos_recovery":
        run_chaos_recovery(pid, nprocs, tmpdir)
    elif scenario == "elastic":
        run_elastic(pid, nprocs, tmpdir)
    elif scenario == "fleet":
        run_fleet(pid, nprocs, tmpdir)
    elif scenario == "capacity":
        run_capacity(pid, nprocs, tmpdir)
    else:
        raise SystemExit(f"unknown scenario {scenario}")


def _ok(name):
    print(f"PASS {name}", flush=True)


def run_transport_suite(pid, nprocs, tmpdir):
    import numpy as np
    import jax
    import jax.numpy as jnp

    import chainermn_tpu as ct

    # -- topology ----------------------------------------------------------
    assert jax.process_count() == nprocs, jax.process_count()
    assert jax.process_index() == pid
    comm = ct.create_communicator("jax_ici")
    assert comm.inter_size == nprocs
    assert comm.inter_rank == pid
    assert comm.size == jax.device_count()
    from chainermn_tpu.communicators._communication_utility import init_ranks
    quintuple = init_ranks()
    assert len(quintuple) == jax.device_count()
    assert all(n == nprocs for (_, _, _, _, n) in quintuple)
    _ok("topology")

    # -- object allgather / bcast over the KV channel ----------------------
    mine = {"rank": pid, "arr": np.arange(3) + pid, "s": "x" * (pid + 1)}
    gathered = comm._process_allgather_pickled(mine)
    assert len(gathered) == nprocs
    for i, d in enumerate(gathered):
        assert d["rank"] == i and len(d["s"]) == i + 1
        np.testing.assert_array_equal(d["arr"], np.arange(3) + i)
    _ok("allgather_pickled")

    for root in range(nprocs):
        out = comm.bcast_obj({"from": pid} if pid == root else None,
                             root=root)
        assert out == {"from": root}
    _ok("bcast_obj")

    # allgather_obj: one entry per device rank
    per_rank = comm.allgather_obj(pid * 100)
    assert len(per_rank) == comm.size
    _ok("allgather_obj")

    # -- cross-process p2p, both directions, tags, ordering, chunking ------
    peer = (pid + 1) % nprocs
    comm.send_obj(("hello", pid), dest=peer, tag=7)
    comm.send_obj(("second", pid), dest=peer, tag=7)
    comm.send_obj({"tagged": 9}, dest=peer, tag=9)
    src = (pid - 1) % nprocs
    assert comm.recv_obj(source=src, tag=9) == {"tagged": 9}
    assert comm.recv_obj(source=src, tag=7) == ("hello", src)
    assert comm.recv_obj(source=src, tag=7) == ("second", src)
    _ok("send_recv_obj")

    # payload spanning many KV chunks (3.5 MiB > 1 MiB chunk size)
    big = np.random.RandomState(pid).bytes(3_500_000)
    comm.send_obj(big, dest=peer, tag=11)
    got = comm.recv_obj(source=src, tag=11)
    assert got == np.random.RandomState(src).bytes(3_500_000)
    _ok("chunked_payload")

    # eager ndarray p2p across processes
    comm.send(jnp.arange(5, dtype=jnp.float32) * (pid + 1), dest=peer)
    nd = comm.recv(source=src)
    np.testing.assert_allclose(np.asarray(nd),
                               np.arange(5, dtype=np.float32) * (src + 1))
    _ok("send_recv_ndarray")

    # -- multi-node evaluator ---------------------------------------------
    class _FakeEval:
        def evaluate(self):
            return {"main/loss": 1.0 + pid, "main/acc": 0.5}

    ev = ct.create_multi_node_evaluator(_FakeEval(), comm)
    metrics = ev.evaluate()
    # device-rank-weighted mean of per-host dicts
    expect_loss = float(np.mean(
        [1.0 + r for r in range(nprocs)
         for _ in range(jax.device_count() // nprocs)]))
    assert abs(metrics["main/loss"] - expect_loss) < 1e-9, metrics
    assert abs(metrics["main/acc"] - 0.5) < 1e-9
    _ok("evaluator")

    # -- multi-node iterator (master broadcasts batches) -------------------
    from chainermn_tpu.dataset.iterators import SerialIterator
    base = SerialIterator(np.arange(8), 4, shuffle=True,
                          seed=pid * 13 + 1)  # different seeds per host!
    it = ct.create_multi_node_iterator(base, comm, rank_master=0)
    batches = [sorted(it.next()) for _ in range(2)]
    agreed = comm._process_allgather_pickled(batches)
    assert all(b == agreed[0] for b in agreed[1:]), agreed
    _ok("multi_node_iterator")

    # -- synchronized iterator preserves master seed -----------------------
    base2 = SerialIterator(np.arange(16), 4, shuffle=True, seed=42)
    sync = ct.create_synchronized_iterator(base2, comm)
    orders = comm._process_allgather_pickled(list(sync._order))
    assert all(o == orders[0] for o in orders[1:])
    # user's seed preserved: the order is the next draw from the MASTER's
    # seed-42 stream (construction drew one permutation, reset the next)
    rs = np.random.RandomState(42)
    rs.permutation(16)
    assert orders[0] == list(rs.permutation(16))
    _ok("synchronized_iterator")

    # -- checkpointer consensus resume ------------------------------------
    from chainermn_tpu import Chain, Parameter
    from chainermn_tpu.extensions import create_multi_node_checkpointer

    class _M(Chain):
        def __init__(self):
            super().__init__()
            with self.init_scope():
                self.w = Parameter(jnp.zeros(2))

    cp = create_multi_node_checkpointer(comm, name="cons", path=tmpdir)

    class _T:  # minimal trainer stand-in for save/load
        def __init__(self, model):
            self.model = model

        def serialize(self, s):
            self.model.serialize(s["model"])

    m = _M()
    t = _T(m)
    m.w.array = jnp.full(2, 10.0)
    cp.save(t, 100)
    if pid == 0:  # only proc 0 reaches iteration 200: no consensus there
        m.w.array = jnp.full(2, 20.0)
        cp.save(t, 200)
    comm._host_channel().barrier()
    m2 = _M()
    cp2 = create_multi_node_checkpointer(comm, name="cons", path=tmpdir)
    it_resumed = cp2.maybe_load(_T(m2), path=tmpdir)
    assert it_resumed == 100, it_resumed  # newest COMMON iteration
    np.testing.assert_allclose(np.asarray(m2.w.array), 10.0)
    _ok("checkpointer_consensus")

    # -- scatter_dataset across real processes -----------------------------
    if pid == 0:
        shard = ct.scatter_dataset(list(range(20)), comm, shuffle=True,
                                   seed=5)
    else:
        shard = ct.scatter_dataset(None, comm, shuffle=True, seed=5)
    lengths = comm._process_allgather_pickled(len(shard))
    assert len(set(lengths)) == 1  # equal shards: lock-step invariant
    union = comm._process_allgather_pickled(list(shard))
    seen = set()
    for chunk in union:
        seen.update(chunk)
    assert seen == set(range(20))
    _ok("scatter_dataset")

    print("ALL_OK", flush=True)


def run_dp_step(pid, nprocs):
    """The compiled cross-process data plane (VERDICT r2 Missing #3):
    a jitted ``create_multi_node_optimizer`` DP step whose shard_mapped
    gradient pmean executes over a mesh SPANNING the real processes (1
    gloo CPU device per process), checked against the single-process
    full-batch golden.  This is the reference's core product — gradient
    allreduce across process boundaries (SURVEY §2.7 tensor channel,
    §3.2 hot path) — executing, not simulated."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    import chainermn_tpu as ct
    from chainermn_tpu.core.optimizer import MomentumSGD
    from chainermn_tpu.models import MLP, Classifier

    comm = ct.create_communicator("jax_ici")
    assert comm.size == nprocs == jax.device_count()
    # the mesh really spans both processes
    mesh_pidx = {getattr(d, "process_index", 0)
                 for d in comm.mesh.devices.flat}
    assert mesh_pidx == set(range(nprocs)), mesh_pidx
    _ok("mesh_spans_processes")

    # identical global batch on every process (the multi-controller SPMD
    # contract: numpy inputs are the global value; the jit's in_spec
    # shards them so each process computes only its own half)
    rng = np.random.RandomState(0)
    x = rng.normal(0, 1, (8, 12)).astype(np.float32)
    t = rng.randint(0, 3, 8).astype(np.int32)

    model = Classifier(MLP(n_units=16, n_out=3, seed=0))
    comm.bcast_data(model)
    opt = ct.create_multi_node_optimizer(
        MomentumSGD(lr=0.1, momentum=0.9), comm).setup(model)
    losses = [float(opt.update(model, x, t)) for _ in range(3)]
    _ok("dp_step_runs")

    # golden: plain single-process optimizer on the FULL batch (mean
    # loss ⇒ full-batch step == pmean of half-batch steps)
    golden = Classifier(MLP(n_units=16, n_out=3, seed=0))
    gopt = MomentumSGD(lr=0.1, momentum=0.9).setup(golden)
    glosses = [float(gopt.update(golden, x, t)) for _ in range(3)]
    np.testing.assert_allclose(losses, glosses, rtol=1e-5, atol=1e-6)
    _ok("dp_loss_matches_golden")

    # cross-process mean gradient == full-batch golden gradient
    for p, gp in zip(model.params(), golden.params()):
        np.testing.assert_allclose(np.asarray(p.grad), np.asarray(gp.grad),
                                   rtol=1e-4, atol=1e-6)
    _ok("dp_grads_match_golden")

    # updated params agree with the golden AND bit-agree across processes
    for p, gp in zip(model.params(), golden.params()):
        np.testing.assert_allclose(np.asarray(p.array),
                                   np.asarray(gp.array),
                                   rtol=1e-4, atol=1e-6)
    digest = [np.asarray(p.array).tobytes() for p in model.params()]
    agreed = comm._process_allgather_pickled(digest)
    assert all(d == agreed[0] for d in agreed[1:])
    _ok("dp_params_consistent")

    # split() under process_count > 1 returns the CALLER's group
    subs_seen = comm.split(list(range(nprocs)), 0)
    my_dev = [d for d in comm._devices
              if getattr(d, "process_index", 0) == pid]
    assert list(subs_seen._devices) == my_dev, (pid, subs_seen._devices)
    assert subs_seen.axis_name.endswith(f"_s{pid}")
    _ok("split_returns_caller_group")

    print("ALL_OK", flush=True)


def _dp_golden_check(comm, seed=0, steps=3, lr=0.1, momentum=0.9,
                     hooks=(), zero_sharding=False):
    """Shared DP-step scaffold: train a Classifier(MLP) under ``comm``
    (optionally with ZeRO-1 sharded optimizer state), assert losses AND
    params match the single-process full-batch golden, and return
    (model, opt, losses, per-param digests) for scenario asserts."""
    import numpy as np

    import chainermn_tpu as ct
    from chainermn_tpu.core.optimizer import MomentumSGD
    from chainermn_tpu.models import MLP, Classifier

    rng = np.random.RandomState(seed)
    x = rng.normal(0, 1, (8, 12)).astype(np.float32)
    t = rng.randint(0, 3, 8).astype(np.int32)

    def build(comm_):
        model = Classifier(MLP(n_units=16, n_out=3, seed=0))
        if comm_ is None:
            opt = MomentumSGD(lr=lr, momentum=momentum).setup(model)
        else:
            comm_.bcast_data(model)
            opt = ct.create_multi_node_optimizer(
                MomentumSGD(lr=lr, momentum=momentum), comm_,
                zero_sharding=zero_sharding).setup(model)
        for hook in hooks:
            opt.add_hook(hook)
        return model, opt

    model, opt = build(comm)
    losses = [float(opt.update(model, x, t)) for _ in range(steps)]
    golden, gopt = build(None)
    glosses = [float(gopt.update(golden, x, t)) for _ in range(steps)]
    np.testing.assert_allclose(losses, glosses, rtol=1e-5, atol=1e-6)
    for p, gp in zip(model.params(), golden.params()):
        np.testing.assert_allclose(np.asarray(p.array),
                                   np.asarray(gp.array),
                                   rtol=1e-4, atol=1e-6)
    digest = [np.asarray(p.array).tobytes() for p in model.params()]
    return model, opt, losses, digest


def run_zero_step(pid, nprocs):
    """ZeRO-1 across REAL process boundaries: psum_scatter + all_gather
    span the gloo processes; each process's optimizer state is only its
    own 1/n chunk; trajectory matches the single-process full-batch
    golden — the same `_dp_golden_check` scaffold run_dp_step and
    run_split_groups certify with, plus the sharded global-norm
    clipping hook."""
    import jax

    import chainermn_tpu as ct
    from chainermn_tpu.core.optimizer import GradientClipping

    comm = ct.create_communicator("jax_ici")
    assert comm.size == nprocs == jax.device_count()

    model, opt, losses, digest = _dp_golden_check(
        comm, hooks=(GradientClipping(0.05),), zero_sharding=True)
    _ok("zero_step_runs")
    _ok("zero_loss_matches_golden")

    # state is sharded: this process holds exactly 1/n of the flat vector
    flat = [l for l in jax.tree.leaves(opt.actual_optimizer._opt_state)
            if getattr(l, "ndim", 0) == 1 and l.shape[0] > 1]
    assert flat
    for leaf in flat:
        assert len(leaf.addressable_shards) == 1  # one local device
        assert leaf.addressable_shards[0].data.shape[0] \
            == leaf.shape[0] // nprocs
    _ok("zero_state_sharded_across_processes")

    agreed = comm._process_allgather_pickled(digest)
    assert all(d == agreed[0] for d in agreed[1:])
    _ok("zero_params_consistent")

    print("ALL_OK", flush=True)


def run_zero_save_resume(pid, nprocs, tmpdir):
    """ZeRO-1 checkpointing across REAL process boundaries (ADVICE r4):
    on save, each non-fully-addressable flat opt_state leaf is assembled
    on every host over the object channel, so each per-host npz carries
    the FULL vector; on load, restored leaves are re-committed to the
    sharded layout.  Certified bit-exact: 3 steps → save → 2 steps must
    equal load(snapshot) → 2 steps, with state sharded again after both
    the save and the load."""
    import os

    import numpy as np
    import jax
    import jax.numpy as jnp

    import chainermn_tpu as ct
    from chainermn_tpu.core.optimizer import Adam
    from chainermn_tpu.models import MLP, Classifier
    from chainermn_tpu.serializers import load_npz, save_npz

    comm = ct.create_communicator("jax_ici")
    assert comm.size == nprocs == jax.device_count()

    rng = np.random.RandomState(7)
    x = rng.normal(0, 1, (8, 12)).astype(np.float32)
    t = rng.randint(0, 3, 8).astype(np.int32)

    def build():
        model = Classifier(MLP(n_units=16, n_out=3, seed=0))
        comm.bcast_data(model)
        opt = ct.create_multi_node_optimizer(
            Adam(alpha=1e-2), comm, zero_sharding=True).setup(model)
        return model, opt

    def sharded_leaves(opt):
        return [l for l in jax.tree.leaves(opt.actual_optimizer._opt_state)
                if getattr(l, "ndim", 0) == 1 and l.shape[0] > 1]

    model, opt = build()
    for _ in range(3):
        opt.update(model, x, t)
    snap = os.path.join(str(tmpdir), f"zero_snap_{pid}.npz")
    save_npz(snap, opt)
    _ok("zero_save_multiprocess")

    # the writer-side host-gather swap must have RESTORED the sharded
    # device state afterwards (not left host copies behind)
    flat = sharded_leaves(opt)
    assert flat and all(isinstance(l, jax.Array)
                        and not l.is_fully_addressable for l in flat)
    _ok("zero_state_still_sharded_after_save")

    for _ in range(2):
        opt.update(model, x, t)
    digest = [np.asarray(p.array).tobytes() for p in model.params()]

    model2, opt2 = build()
    load_npz(snap, opt2)
    # restored flat leaves are committed back to the mesh-sharded layout
    flat2 = sharded_leaves(opt2)
    assert flat2 and all(isinstance(l, jax.Array)
                         and not l.is_fully_addressable for l in flat2)
    for leaf in flat2:
        assert len(leaf.addressable_shards) == 1
        assert leaf.addressable_shards[0].data.shape[0] \
            == leaf.shape[0] // nprocs
    _ok("zero_resume_state_sharded")

    for _ in range(2):
        opt2.update(model2, x, t)
    digest2 = [np.asarray(p.array).tobytes() for p in model2.params()]
    assert digest == digest2, "resumed ZeRO trajectory diverged"
    _ok("zero_resume_bit_exact")

    # and the resumed run still agrees across processes
    agreed = comm._process_allgather_pickled(digest2)
    assert all(d == agreed[0] for d in agreed[1:])
    _ok("zero_resume_consistent")

    print("ALL_OK", flush=True)


def run_split_groups(pid, nprocs):
    """4-process split: colors [0,0,1,1] yield two REAL 2-process
    sub-communicators.  Each group runs its own compiled DP step on its
    own data — collectives stay inside the group (different data ⇒
    different params ACROSS groups; bit-identical params WITHIN a
    group; each group matches its single-process golden).  This is the
    reference's MPI_Comm_Split product actually exercised across
    process boundaries, not just the caller-group bookkeeping."""
    import jax

    import chainermn_tpu as ct

    assert nprocs == 4
    comm = ct.create_communicator("jax_ici")
    assert comm.size == 4 == jax.device_count()
    group_id = pid // 2
    sub = comm.split([0, 0, 1, 1], 0)
    assert sub.size == 2
    assert {getattr(d, "process_index", 0) for d in sub._devices} \
        == {2 * group_id, 2 * group_id + 1}
    _ok("split_two_process_subgroups")

    # group-specific data (seed differs by group): the two groups must
    # NOT mix gradients
    _, _, _, digest = _dp_golden_check(sub, seed=100 + group_id, steps=2)
    _ok("subgroup_dp_step_runs")
    _ok("subgroup_matches_own_golden")
    # within-group agreement AND across-group divergence, checked over
    # the FULL communicator's object channel
    all_digests = comm._process_allgather_pickled((group_id, digest))
    mine = [d for g, d in all_digests if g == group_id]
    other = [d for g, d in all_digests if g != group_id]
    assert len(mine) == 2 and len(other) == 2
    assert mine[0] == mine[1], "params diverged WITHIN a split group"
    assert mine[0] != other[0], \
        "groups share params: split leaked collectives across groups"
    _ok("split_groups_isolated")

    print("ALL_OK", flush=True)


def run_chaos_recovery(pid, nprocs, tmpdir):
    """End-to-end chaos over REAL 2-process gloo transport: faults at a
    collective (shared seeded schedule → both ranks raise at the same
    call site), at a host-channel op (transient, absorbed by bounded
    retry), and mid-checkpoint-write (both ranks) — each recovered via
    the consensus resume, with the run converging to the fault-free
    baseline's final iteration and loss.  A deliberately corrupted
    snapshot is then proven excluded from a fresh consensus vote on BOTH
    ranks."""
    import os

    import numpy as np

    import chainermn_tpu as ct
    from chainermn_tpu.communicators import (FaultInjectionCommunicator,
                                             FaultSchedule,
                                             bind_host_channel)
    from chainermn_tpu.communicators.fault_schedule import InjectedFault
    from chainermn_tpu.core.optimizer import MomentumSGD
    from chainermn_tpu.dataset import SerialIterator, TupleDataset
    from chainermn_tpu.extensions import FailureRecovery
    from chainermn_tpu.models import MLP, Classifier
    from chainermn_tpu.training import StandardUpdater, Trainer
    from chainermn_tpu.training.trainer import Extension

    # identical global batch stream on every process (multi-controller
    # SPMD contract; see run_dp_step)
    rng = np.random.RandomState(11)
    x = rng.normal(0, 1, (64, 12)).astype(np.float32)
    t = rng.randint(0, 3, 64).astype(np.int32)

    class _Beacon(Extension):
        """Per-iteration control-plane bcast over the REAL KV channel —
        the injection site for the collective fault."""
        trigger = (1, "iteration")
        priority = 400

        def __init__(self, comm):
            self.comm = comm

        def __call__(self, trainer):
            out = self.comm.bcast_obj(
                {"iteration": trainer.updater.iteration}, root=0)
            assert out["iteration"] == trainer.updater.iteration

    def run_training(out, schedule=None, hc_specs=None, write_fault=None):
        comm = ct.create_communicator("jax_ici")
        if hc_specs is not None:
            bind_host_channel(comm._host_channel(),
                              FaultSchedule(hc_specs, seed=1))
        if schedule is not None:
            comm = FaultInjectionCommunicator(comm, schedule)
        model = Classifier(MLP(n_units=8, n_out=3, seed=0))
        comm.bcast_data(model)
        opt = ct.create_multi_node_optimizer(
            MomentumSGD(lr=0.05, momentum=0.9), comm).setup(model)
        it = SerialIterator(TupleDataset(x, t), 8, shuffle=False)
        trainer = Trainer(StandardUpdater(it, opt), (10, "iteration"),
                          out=out)
        trainer.extend(_Beacon(comm))
        cp = ct.create_multi_node_checkpointer(comm, name="cz", path=out)
        trainer.extend(cp, trigger=(3, "iteration"))
        recovery = FailureRecovery(checkpointer=cp, verbose=False)
        trainer.extend(recovery)
        if write_fault is not None:
            cp._write_fault_hook = write_fault
        trainer.run()
        # bit-identical params ⇒ identical loss; the digest is the
        # strictest form of the "same final loss" acceptance check
        digest = [np.asarray(p.array).tobytes() for p in model.params()]
        # uninstall: the channel outlives this run
        comm._host_channel().set_fault_hook(None)
        return trainer, cp, recovery, model, digest

    # -- fault-free baseline ------------------------------------------------
    base_out = os.path.join(tmpdir, "base")
    b_trainer, b_cp, b_rec, b_model, b_digest = run_training(base_out)
    assert b_trainer.updater.iteration == 10
    assert b_rec.stats["recoveries"] == 0
    _ok("chaos_baseline")

    # -- faulted run --------------------------------------------------------
    # shared seeded schedule: BOTH ranks raise at bcast_obj call #5
    sched = FaultSchedule([dict(op="bcast_obj", nth=5)], seed=1234)
    # transient host-channel fault on the non-root reader only: absorbed
    # by the bounded retry, training never notices
    hc_specs = [dict(op="hc.get", nth=3)] if pid == 1 else []
    fired = []

    def write_fault(tmp, fname):
        # both ranks tear checkpoint generation 9 (same call site)
        if ".9." in fname and not fired:
            fired.append(fname)
            raise InjectedFault("checkpoint.save", 1, "torn write")

    chaos_out = os.path.join(tmpdir, "chaos")
    trainer, cp, recovery, model, digest = run_training(
        chaos_out, schedule=sched, hc_specs=hc_specs,
        write_fault=write_fault)

    assert recovery.stats["recoveries"] == 2, recovery.stats
    assert recovery.stats["resumed_iterations"] == [3, 6], recovery.stats
    assert fired, "checkpoint write fault never fired"
    _ok("chaos_recovered_twice")

    # NOTE: communicator construction is a collective (hostname
    # allgather) — read the channel singleton directly so this check
    # stays one-sided-safe
    from chainermn_tpu.communicators._host_channel import get_host_channel
    if pid == 1:
        assert get_host_channel().stats["retries"] >= 1, \
            get_host_channel().stats
    _ok("chaos_transient_retry_absorbed")

    # -- convergence: same final iteration count and state as baseline -----
    assert trainer.updater.iteration == b_trainer.updater.iteration == 10
    for a, b in zip(digest, b_digest):
        assert a == b, "faulted run diverged from the fault-free baseline"
    _ok("chaos_final_matches_baseline")

    # -- corrupted snapshot provably excluded from the consensus vote -------
    if pid == 0:  # tear rank 0's newest snapshot only
        newest = os.path.join(chaos_out, "cz.9.0")
        with open(newest, "r+b") as f:
            f.seek(12)
            f.write(b"\xde\xad\xbe\xef")
    comm2 = ct.create_communicator("jax_ici")
    comm2._host_channel().barrier()  # corruption durable before the vote
    model2 = Classifier(MLP(n_units=8, n_out=3, seed=0))
    opt2 = ct.create_multi_node_optimizer(
        MomentumSGD(lr=0.05, momentum=0.9), comm2).setup(model2)
    it2 = SerialIterator(TupleDataset(x, t), 8, shuffle=False)
    trainer2 = Trainer(StandardUpdater(it2, opt2), (10, "iteration"),
                       out=os.path.join(tmpdir, f"resume{pid}"))
    cp2 = ct.create_multi_node_checkpointer(comm2, name="cz",
                                            path=chaos_out)
    resumed = cp2.maybe_load(trainer2, path=chaos_out)
    # rank 0's iteration 9 failed verification → excluded GLOBALLY: every
    # rank falls back to the newest intact common generation
    assert resumed == 6, (pid, resumed)
    assert trainer2.updater.iteration == 6
    if pid == 0:
        assert cp2.stats["verify_failures"] == 1
    _ok("chaos_corrupt_excluded")

    print("ALL_OK", flush=True)


def run_elastic(pid, nprocs, tmpdir):
    """Elastic preempt-and-rejoin over REAL 2-process gloo transport
    (ISSUE 10 acceptance): a seeded ``preempt`` fault hard-stops rank 1
    mid-run; rank 0 detects it through a typed channel timeout, the
    membership protocol shrinks the world to {0}, and training
    CONTINUES at world size 1 (global batch preserved — the full batch
    now rides one rank).  Rank 1 parks, announces ``join``, is
    re-admitted, adopts the survivors' newest snapshot over the new
    members-only channel, and the world grows back to {0, 1} — the run
    finishes at the full iteration count with the final loss inside
    the committed ±5% convergence-parity band of the uninterrupted
    baseline and bit-identical params across the grown world.  A
    world-size-1 snapshot from the solo phase is then loaded into a
    2-process-shaped trainer and proven bit-exact for params/opt-state
    (the cross-world-size resume brick, exercised on REAL transport).
    """
    import os
    import time

    import numpy as np
    import jax.numpy as jnp

    import chainermn_tpu as ct
    from chainermn_tpu.communicators import (FaultInjectionCommunicator,
                                             FaultSchedule)
    from chainermn_tpu.core.optimizer import MomentumSGD
    from chainermn_tpu.dataset import SerialIterator, TupleDataset
    from chainermn_tpu.extensions import ElasticRecovery
    from chainermn_tpu.models import MLP, Classifier
    from chainermn_tpu.serializers import load_npz
    from chainermn_tpu.training import StandardUpdater, Trainer
    from chainermn_tpu.training.trainer import Extension

    # identical global batch stream on every process (multi-controller
    # SPMD contract): the SAME global batch at any world size is what
    # makes the resized gradient the full-batch mean — the parity basis
    rng = np.random.RandomState(11)
    x = rng.normal(0, 1, (64, 12)).astype(np.float32)
    t = rng.randint(0, 3, 64).astype(np.int32)
    ITERS = 24

    class _Beacon(Extension):
        """Per-iteration control-plane op through the CURRENT world's
        channel (recovery.comm follows every resize) — the detection
        site: the survivor's matched bcast times out TYPED when the
        peer is preempted mid-iteration."""
        trigger = (1, "iteration")
        priority = 400

        def __init__(self, recovery):
            self.recovery = recovery

        def __call__(self, trainer):
            self.recovery.comm.bcast_obj(
                {"it": trainer.updater.iteration}, root=0)

    class _Pacer(Extension):
        """Slows the loop so the parked rank's rejoin lands MID-run —
        without it the survivor finishes its solo phase in milliseconds
        and nothing is left to grow back into."""
        trigger = (1, "iteration")
        priority = 350

        def __init__(self, dwell_s):
            self.dwell_s = dwell_s

        def __call__(self, trainer):
            if self.dwell_s:
                time.sleep(self.dwell_s)

    def run_training(out, schedule=None, pace_s=0.0):
        comm = ct.create_communicator("jax_ici")
        ch = comm._host_channel()
        ch._timeout_ms = 6000  # typed detection in seconds, not 600 s
        if schedule is not None:
            comm = FaultInjectionCommunicator(comm, schedule)
        model = Classifier(MLP(n_units=8, n_out=3, seed=0))
        comm.bcast_data(model)
        opt = ct.create_multi_node_optimizer(
            MomentumSGD(lr=0.05, momentum=0.9), comm).setup(model)
        it = SerialIterator(TupleDataset(x, t), 8, shuffle=False)
        trainer = Trainer(StandardUpdater(it, opt), (ITERS, "iteration"),
                          out=out)
        cp = ct.create_multi_node_checkpointer(comm, name="el", path=out)
        recovery = ElasticRecovery(
            checkpointer=cp, comm=comm, rejoin_after_s=2.0,
            resolve_timeout_ms=90_000, verbose=True)
        trainer.extend(_Beacon(recovery))
        trainer.extend(_Pacer(pace_s))
        trainer.extend(cp, trigger=(3, "iteration"))
        trainer.extend(recovery)
        trainer.run()
        digest = [_host_value(p.array).tobytes()
                  for p in model.params()]
        return trainer, recovery, model, opt, digest

    def _host_value(arr):
        if hasattr(arr, "is_fully_addressable") \
                and not arr.is_fully_addressable:
            return np.asarray(arr.addressable_shards[0].data)
        return np.asarray(arr)

    def local_eval_loss(model):
        """Full-batch loss of the trained params, computed on a LOCAL
        replica (the final world's mesh spans processes, so eager eval
        on its arrays cannot run one-sided)."""
        m = Classifier(MLP(n_units=8, n_out=3, seed=0))
        for dst, src in zip(m.params(), model.params()):
            dst.array = jnp.asarray(_host_value(src.array))
        return float(m(jnp.asarray(x), jnp.asarray(t)))

    # -- uninterrupted baseline --------------------------------------------
    base_out = os.path.join(tmpdir, "base")
    b_trainer, b_rec, b_model, _, _ = run_training(base_out)
    assert b_trainer.updater.iteration == ITERS
    assert b_rec.stats["resizes"] == 0, b_rec.stats
    base_loss = local_eval_loss(b_model)
    _ok("elastic_baseline")

    # -- preempt → shrink → rejoin → grow ----------------------------------
    # shared seeded schedule, rank-targeted: only rank 1 is preempted
    # (call #7 = iteration 4's beacon — beacon + join-poll make two
    # bcast_obj calls per iteration on every rank)
    sched = FaultSchedule([dict(op="bcast_obj", nth=7, action="preempt",
                                rank=1)], seed=99)
    el_out = os.path.join(tmpdir, "elastic")
    trainer, recovery, model, opt, digest = run_training(
        el_out, schedule=sched, pace_s=0.25)

    stats = recovery.stats
    assert trainer.updater.iteration == ITERS
    if pid == 0:
        # the survivor saw both resizes: shrink 2->1, then grow 1->2
        assert stats["resizes"] == 2, stats
        assert stats["ranks_lost"] == 1, stats
    else:
        # the preempted rank was ABSENT for the shrink; from its view
        # there was one resize (its own re-admission, {0} -> {0, 1})
        assert stats["resizes"] == 1, stats
        assert stats["ranks_lost"] == 0, stats
    assert stats["ranks_joined"] == 1, stats
    assert stats["recoveries"] == 1, stats
    assert recovery.view.members == (0, 1), recovery.view
    assert recovery.view.epoch == 2, recovery.view
    assert recovery.comm.size == nprocs
    _ok("elastic_shrink_and_regrow")

    # bit-identical params across the re-grown world: the joiner's
    # adopted state really converged with the survivor's
    agreed = recovery.comm._process_allgather_pickled(digest)
    assert all(d == agreed[0] for d in agreed[1:]), \
        "params diverged across the re-grown world"
    _ok("elastic_world_consistent")

    # committed convergence-parity band vs the uninterrupted baseline
    el_loss = local_eval_loss(model)
    assert abs(el_loss - base_loss) <= 0.05 * abs(base_loss) + 1e-6, \
        (el_loss, base_loss)
    _ok("elastic_convergence_parity")

    # -- checkpoint resume ACROSS world sizes ------------------------------
    # the solo phase wrote WORLD-SIZE-1 snapshots on rank 0 only; prove
    # one loads bit-exact (params AND opt-state) into a fresh
    # 2-PROCESS-shaped multi-node trainer.  Communicator + optimizer
    # construction is collective (both ranks), the load itself is local
    # (one-sided by design — rank 1 has no solo-generation files).
    import jax
    comm2 = ct.create_communicator("jax_ici")
    m2 = Classifier(MLP(n_units=8, n_out=3, seed=0))
    comm2.bcast_data(m2)
    opt2 = ct.create_multi_node_optimizer(
        MomentumSGD(lr=0.05, momentum=0.9), comm2).setup(m2)
    it2 = SerialIterator(TupleDataset(x, t), 8, shuffle=False)
    t2 = Trainer(StandardUpdater(it2, opt2), (ITERS, "iteration"),
                 out=os.path.join(tmpdir, f"xsize{pid}"))
    if pid == 0:
        solo = sorted(
            int(f.split(".")[1]) for f in os.listdir(el_out)
            if f.startswith("el.") and f.endswith(".0")
            and not os.path.exists(
                os.path.join(el_out, f"el.{f.split('.')[1]}.1")))
        assert solo, os.listdir(el_out)
        pick = solo[-1]
        load_npz(os.path.join(el_out, f"el.{pick}.0"), t2, strict=False)
        assert t2.updater.iteration == pick
        # reference: the SAME world-1 snapshot in a world-1-shaped
        # (plain single-process) trainer
        m1 = Classifier(MLP(n_units=8, n_out=3, seed=0))
        opt1 = MomentumSGD(lr=0.05, momentum=0.9).setup(m1)
        it1 = SerialIterator(TupleDataset(x, t), 8, shuffle=False)
        t1 = Trainer(StandardUpdater(it1, opt1), (ITERS, "iteration"),
                     out=os.path.join(tmpdir, "xsize1p"))
        load_npz(os.path.join(el_out, f"el.{pick}.0"), t1, strict=False)
        # params and optimizer state bit-equal regardless of the world
        # shape the snapshot is loaded into (re-seeded elastic buffers
        # — stale grads / EF residual — are excluded by contract: this
        # DP run carries none, and the tier-1 suite pins their
        # re-seed-zeros path explicitly)
        for a, b in zip(m2.params(), m1.params()):
            assert _host_value(a.array).tobytes() \
                == _host_value(b.array).tobytes()
        sa = jax.tree.leaves(opt2.actual_optimizer._opt_state)
        sb = jax.tree.leaves(opt1._opt_state)
        assert sa and len(sa) == len(sb)
        for a, b in zip(sa, sb):
            assert _host_value(a).tobytes() == _host_value(b).tobytes()
    _ok("elastic_cross_size_resume_bit_exact")

    print("ALL_OK", flush=True)


def run_fleet(pid, nprocs, tmpdir):
    """Serving-fleet chaos over REAL 2-process gloo transport (the
    ISSUE 15 acceptance gate): process 0 runs the router + replica 0,
    process 1 one FleetWorker replica.  A seeded kill preempts replica
    1 at decode step 2 under open-loop load — the worker announces its
    fleet-role leave and goes silent, the router detects through the
    TYPED channel timeout (the committed detection bound), resolves the
    fleet membership down to {0}, and replays every request replica 1
    held from its ORIGINAL prompt on the survivor: zero dropped
    requests, every trajectory equal to its solo run.  Replica 1 then
    parks, re-joins through the membership protocol, PERTURBS its
    weights, and adopts the root's over the multicast-tree sync —
    bit-identical restoration proven on the worker — and the router
    spreads new admissions to the re-joined replica."""
    import time

    import numpy as np
    import jax

    import chainermn_tpu as ct
    from chainermn_tpu.communicators import ElasticMembership
    from chainermn_tpu.models import TransformerLM
    from chainermn_tpu.serving import (FleetWorker, RemoteReplica,
                                       ReplicaFleet, Request,
                                       ServingEngine)

    DETECT_S = 6.0          # the committed typed detection bound
    KILL_AT = 2
    N_REQS = 8

    comm = ct.create_communicator("jax_ici")
    ch = comm._host_channel()
    ch._timeout_ms = int(DETECT_S * 1000)
    membership = ElasticMembership(ch._client, rank=pid, world=nprocs,
                                   role="fleet", settle_s=0.5,
                                   poll_s=0.02, timeout_ms=90_000)
    model = TransformerLM(n_vocab=127, d_model=32, n_heads=1,
                          n_layers=1, max_len=32, seed=0)
    engine = ServingEngine(model, num_pages=32, page_size=16,
                           max_batch=2, max_context=32,
                           prefix_cache=False)

    def leaves(e):
        return [np.asarray(x) for x in jax.tree.leaves(e.state)]

    if pid == 1:
        worker = FleetWorker(engine, ch, membership=membership,
                             router_process=0)
        outcome = worker.serve(kill_at=KILL_AT)
        assert outcome == "preempted", outcome
        before = leaves(engine)
        # park until the survivors' shrink decision lands, then rejoin
        epoch_at_leave = membership.current_epoch()
        deadline = time.monotonic() + 60
        while membership.current_epoch() == epoch_at_leave \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        assert membership.current_view().members == (0,)
        _ok("fleet_shrunk_to_survivor")
        time.sleep(0.5)
        # perturb the weights: the tree sync must RESTORE them
        # bit-identically from the root (a cold joiner's weights are
        # whatever its factory seeded — here, provably wrong ones)
        import jax.numpy as jnp
        ls, treedef = jax.tree.flatten(engine.state)
        engine.state = jax.tree.unflatten(
            treedef, [jnp.asarray(np.asarray(x) + 1.0) for x in ls])
        membership.announce_join(note="rejoin after preemption")
        view = membership.resolve(expect={0, 1}, require={0})
        assert 1 in view and view.role == "fleet"
        rounds = worker.sync_weights(view, joiners=(1,))
        assert rounds == 1, rounds   # 1 joiner: ceil(log2 2) rounds
        after = leaves(engine)
        assert all((a == b).all() for a, b in zip(after, before)), \
            "tree sync did not restore bit-identical weights"
        _ok("fleet_weight_sync_bit_identical")
        worker.serve()   # back in rotation until the router stops us
        print("ALL_OK", flush=True)
        return

    # -- process 0: router + local replica 0 --------------------------------
    remote = RemoteReplica(1, ch, 1)
    fleet = ReplicaFleet(engines={0: engine, 1: remote},
                         membership=membership)
    rng = np.random.RandomState(3)
    prompts = [rng.randint(1, 127, rng.randint(4, 9)).astype(np.int32)
               for _ in range(N_REQS)]
    reqs = [Request(p, 4, tenant=f"t{i % 2}", arrival_time=0.0,
                    request_id=i) for i, p in enumerate(prompts)]
    placements = [fleet.submit(r) for r in reqs]
    assert set(placements) == {0, 1}, placements
    rejoined = False
    detect_dt = None
    deadline = time.monotonic() + 120
    while (fleet.pending() or not rejoined) \
            and time.monotonic() < deadline:
        if fleet.pending():
            sheds_before = fleet.sheds
            t0 = time.monotonic()
            fleet.step()
            if fleet.sheds > sheds_before:
                detect_dt = time.monotonic() - t0
        if not rejoined and fleet.sheds:
            joins = membership.pending_joins(fleet.view)
            if joins:
                fleet.join(engines={1: RemoteReplica(1, ch, 1)})
                rejoined = True
            else:
                time.sleep(0.05)
    assert rejoined, "replica 1 never re-joined"

    # zero dropped requests: every submitted id completed exactly once
    done_ids = sorted(r.request_id for r in fleet.completed)
    assert done_ids == list(range(N_REQS)), done_ids
    assert fleet.sheds == 1 and fleet.reroutes >= 1, fleet.stats()
    _ok("fleet_zero_drop")

    # detection bounded: the shed step paid at most the typed channel
    # deadline (plus resolve/replay slack), never an unbounded hang
    assert detect_dt is not None and detect_dt <= DETECT_S + 8.0, \
        detect_dt
    _ok("fleet_detection_bounded")

    # solo-run trajectory parity (rerouted sequences replay from their
    # prompts; greedy decode regenerates identical tokens)
    golden = ServingEngine(TransformerLM(n_vocab=127, d_model=32,
                                         n_heads=1, n_layers=1,
                                         max_len=32, seed=0),
                           num_pages=32, page_size=16, max_batch=2,
                           max_context=32, prefix_cache=False)
    for req in sorted(fleet.completed, key=lambda r: r.request_id):
        if req.request_id >= N_REQS:
            continue
        generated = list(req.prompt[len(prompts[req.request_id]):]) \
            + list(req.tokens)
        g = Request(prompts[req.request_id], 4, tenant="g",
                    arrival_time=0.0)
        golden.submit(g)
        golden.drain(now=1.0)
        assert generated == golden.completed[-1].tokens, req.request_id
    _ok("fleet_replay_parity")

    # the router spreads new admissions onto the re-joined replica
    more = [Request(rng.randint(1, 127, 5).astype(np.int32), 2,
                    tenant="t0", arrival_time=0.0,
                    request_id=100 + i) for i in range(3)]
    new_placements = [fleet.submit(r) for r in more]
    assert 1 in new_placements, new_placements
    fleet.drain()
    assert sorted(r.request_id for r in fleet.completed
                  if r.request_id >= 100) == [100, 101, 102]
    _ok("fleet_router_spreads_to_joiner")

    for rep in fleet.replicas.values():
        if rep.remote and rep.live:
            rep.stop()
    print("ALL_OK", flush=True)


def run_capacity(pid, nprocs, tmpdir):
    """Capacity transfer over REAL 2-process gloo transport (the
    ISSUE 16 chaos gate).  Process 0 is the router + replica 0 + the
    :class:`CapacityBroker`; process 1 is the convertible rank.

    Leg A (chaos): a seeded ``FaultSpec(op="capacity.convert",
    action="preempt", step="CONVERTING")`` kills the conversion AFTER
    rank 1's training leave landed but BEFORE its fleet admission.  The
    survivor's ``recover_orphans`` sweep detects the frozen journal
    beat through the REAL KV store, aborts the orphan (rank 1 ends in
    NEITHER role group, journal scrubbed), and rank 1 re-enters
    training through the ordinary elastic join — a consistent two-role
    world after a mid-conversion death.

    Leg B (clean arc): queue pressure on replica 0 trips the hysteresis
    policy's +1, ``broker.apply`` converts rank 1 (training shrinks to
    {0}, the fleet grows to {0, 1}, the joiner's deliberately-wrong
    seed-1 weights are overwritten BIT-IDENTICALLY over the multicast
    tree), the fleet serves the backlog across both replicas with zero
    drops, the drained queues trip the -1, and ``apply`` retires rank 1
    back into training — journal cleared, both role groups whole."""
    import time

    import numpy as np
    import jax

    import chainermn_tpu as ct
    from chainermn_tpu.communicators import (ElasticMembership,
                                             FaultSchedule)
    from chainermn_tpu.communicators.fault_schedule import RankPreempted
    from chainermn_tpu.elastic import CapacityBroker
    from chainermn_tpu.models import TransformerLM
    from chainermn_tpu.serving import (FleetWorker, RemoteReplica,
                                       ReplicaFleet, Request,
                                       ServingEngine)
    from chainermn_tpu.serving.fleet import QueueDepthScalePolicy

    CAP_TAG = 7003
    comm = ct.create_communicator("jax_ici")
    ch = comm._host_channel()
    ch._timeout_ms = 8000
    kv = ch._client
    train = ElasticMembership(kv, rank=pid, world=nprocs, role="elastic",
                              settle_s=2.0 if pid == 0 else 0.5,
                              poll_s=0.02, timeout_ms=90_000)

    def digest(engine):
        return [np.asarray(x).tobytes()
                for x in jax.tree.leaves(engine.state)]

    # the joiner seeds DIFFERENT weights: the tree sync must overwrite
    # them bit-identically from replica 0
    engine = ServingEngine(TransformerLM(n_vocab=127, d_model=32,
                                         n_heads=1, n_layers=1,
                                         max_len=32, seed=pid),
                           num_pages=32, page_size=16, max_batch=2,
                           max_context=32, prefix_cache=False)
    fleet_member = ElasticMembership(kv, rank=pid, world=nprocs,
                                     role="fleet",
                                     settle_s=2.0 if pid == 0 else 0.5,
                                     poll_s=0.02, timeout_ms=90_000)

    if pid == 1:
        worker = FleetWorker(engine, ch, membership=fleet_member,
                             router_process=0)
        # -- leg A: the broker's conversion died mid-flight; after the
        # survivor's abort this rank is in NEITHER group and comes back
        # through the ordinary elastic join
        msg = ch.recv_obj(0, tag=CAP_TAG)
        assert msg == ("rejoin_training",), msg
        train.announce_join(note="back after aborted conversion")
        view = train.resolve(expect={0, 1}, require={0})
        assert view.members == (0, 1), view
        _ok("capacity_abort_rank_rejoined")
        # -- leg B: become a fleet replica, adopt weights over the tree
        msg = ch.recv_obj(0, tag=CAP_TAG)
        assert msg == ("convert",), msg
        fleet_member.announce_join(note="capacity transfer")
        fview = fleet_member.resolve(expect={0, 1}, require={0})
        assert 1 in fview and fview.role == "fleet", fview
        rounds = worker.sync_weights(fview, joiners=(1,))
        assert rounds == 1, rounds
        ch.send_obj(digest(engine), 0, tag=CAP_TAG)
        outcome = worker.serve()
        assert outcome == "stopped", outcome
        _ok("capacity_worker_served_and_stopped")
        # the retire landed: rejoin training through the grow path
        train.announce_join(note="capacity transfer: rejoin")
        view = train.resolve(expect={0, 1}, require={0})
        assert view.members == (0, 1), view
        _ok("capacity_retire_rank_rejoined")
        print("ALL_OK", flush=True)
        return

    # -- process 0: router + replica 0 + the broker --------------------------
    policy = QueueDepthScalePolicy(scale_up_depth=2, scale_down_depth=0,
                                   min_replicas=1, max_replicas=2)
    fleet = ReplicaFleet(engines={0: engine}, membership=fleet_member,
                         min_replicas=1, scale_policy=policy)
    sched = FaultSchedule([dict(op="capacity.convert", action="preempt",
                                prob=1.0, step="CONVERTING", rank=1,
                                count=1)], seed=1234).bind_rank(1)
    broker = CapacityBroker(train, fleet,
                            engine_factory=lambda r: RemoteReplica(
                                r, ch, r),
                            min_world=1, stale_s=1.0, schedule=sched)

    # -- leg A: seeded preempt mid-conversion --------------------------------
    try:
        broker.convert_to_serving(rank=1)
        raise AssertionError("seeded mid-conversion preempt never fired")
    except RankPreempted:
        pass
    entry = train.read_conversion(1)
    assert entry is not None and entry[0] == "CONVERTING", entry
    _ok("capacity_kill_mid_conversion")
    broker.schedule = None
    deadline = time.monotonic() + 30
    actions = ()
    while not actions and time.monotonic() < deadline:
        actions = broker.recover_orphans()
        time.sleep(0.25)
    assert actions == ((1, "CONVERTING", "abort"),), actions
    assert train.scan_conversions() == {}
    # the world rolled forward consistent: training {0} (the announced
    # leave landed), fleet {0}, the dead conversion in NEITHER role
    tview = train.resolve(expect={0})
    assert tview.members == (0,), tview
    assert [r.rid for r in fleet.live_replicas()] == [0]
    assert 1 not in fleet.replicas and 1 not in broker.converted
    _ok("capacity_orphan_aborted")
    ch.send_obj(("rejoin_training",), 1, tag=CAP_TAG)
    deadline = time.monotonic() + 60
    while not train.pending_joins() and time.monotonic() < deadline:
        time.sleep(0.05)
    assert train.pending_joins() == (1,), "rank 1 never announced rejoin"
    tview = train.resolve(expect={0, 1})
    assert tview.members == (0, 1), tview
    _ok("capacity_abort_rank_rejoined")

    # -- leg B: pressure -> convert -> serve -> drain -> retire --------------
    rng = np.random.RandomState(3)
    N_REQS = 8
    prompts = [rng.randint(1, 127, rng.randint(4, 9)).astype(np.int32)
               for _ in range(N_REQS)]
    reqs = [Request(p, 4, tenant=f"t{i % 2}", arrival_time=0.0,
                    request_id=i) for i, p in enumerate(prompts)]
    for r in reqs:
        fleet.submit(r)
    st = fleet.step()
    assert st["scale_decision"] == 1, st
    ch.send_obj(("convert",), 1, tag=CAP_TAG)
    # wait for the worker's fleet join intent so the admission resolve
    # can never settle without it
    deadline = time.monotonic() + 60
    while fleet_member._try_get(f"{fleet_member._base}/join/1") is None \
            and time.monotonic() < deadline:
        time.sleep(0.02)
    res = broker.apply(st["scale_decision"])
    assert res == ("convert", 1), res
    assert train.read_conversion(1)[0] == "SERVING"
    # one pool, two roles: training shrank to the survivor while the
    # fleet grew
    tview = train.resolve(expect={0})
    assert tview.members == (0,), tview
    assert sorted(r.rid for r in fleet.live_replicas()) == [0, 1]
    _ok("capacity_auto_converted")
    joiner_digest = ch.recv_obj(1, tag=CAP_TAG)
    assert joiner_digest == digest(engine), \
        "tree sync did not land bit-identical weights on the joiner"
    _ok("capacity_sync_bit_identical")
    # serve the backlog across BOTH replicas, zero drops
    more = [Request(rng.randint(1, 127, 5).astype(np.int32), 3,
                    tenant=f"t{i % 2}", arrival_time=0.0,
                    request_id=100 + i) for i in range(6)]
    placements = [fleet.submit(r) for r in more]
    assert 1 in placements, placements
    decision = 0
    steps = 0
    while fleet.pending() and steps < 10000:
        st = fleet.step()
        if st["scale_decision"]:
            decision = st["scale_decision"]
        steps += 1
    done = sorted(r.request_id for r in fleet.completed)
    assert done == sorted([r.request_id for r in reqs]
                          + [r.request_id for r in more]), done
    _ok("capacity_zero_drop")
    # drained queues tripped the policy's -1: auto-applied retire
    assert decision == -1, decision
    res = broker.apply(decision)
    assert res == ("retire", 1), res
    assert train.read_conversion(1) is None
    assert [r.rid for r in fleet.live_replicas()] == [0]
    assert broker.stats["conversions"] == 1 \
        and broker.stats["retires"] == 1 \
        and broker.stats["role_transfers"] == 2, broker.stats
    # re-admit the returning rank into training
    deadline = time.monotonic() + 60
    while not train.pending_joins() and time.monotonic() < deadline:
        time.sleep(0.05)
    assert train.pending_joins() == (1,), "retired rank never rejoined"
    tview = train.resolve(expect={0, 1})
    assert tview.members == (0, 1), tview
    _ok("capacity_retired_to_training")
    print("ALL_OK", flush=True)


def run_crash(pid, nprocs):
    """Except-hook fail-stop: rank 1 raises; rank 0 blocks on a matched
    recv that will never arrive.  The hook's distributed shutdown must
    take rank 0 down with an error instead of letting it hang."""
    import chainermn_tpu as ct
    from chainermn_tpu import global_except_hook
    global_except_hook.add_hook()
    comm = ct.create_communicator("jax_ici")
    comm._host_channel().barrier()  # both up before the crash
    if pid == 1:
        raise RuntimeError("deliberate crash on rank 1")
    comm.recv_obj(source=1, tag=99)  # never sent
    print("UNEXPECTED: recv returned", flush=True)


if __name__ == "__main__":
    main()
