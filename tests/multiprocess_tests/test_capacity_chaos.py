"""Capacity transfer chaos over REAL 2-process gloo transport (the
ISSUE 16 acceptance gate, see docs/resilience.md §8 "Capacity
transfer").

One run, two legs.  Leg A: a seeded
``FaultSpec(op="capacity.convert", action="preempt",
step="CONVERTING")`` kills the conversion AFTER rank 1's training
leave landed but BEFORE its fleet admission — the survivor's
``recover_orphans`` sweep detects the frozen journal beat through the
real KV store, aborts the orphan (the rank ends in NEITHER role group,
journal scrubbed), and the rank re-enters training through the
ordinary elastic join.  Leg B: queue pressure trips the hysteresis
policy's +1, ``CapacityBroker.apply`` converts rank 1 (training
shrinks to {0}, the fleet grows to {0, 1}, the joiner's
deliberately-wrong weights overwritten BIT-IDENTICALLY over the
multicast tree), the fleet serves the backlog across both replicas
with zero drops, the drained queues trip the -1, and the broker
retires the rank back into training — both role groups whole."""

import pytest

from .test_two_process import _launch

pytestmark = pytest.mark.chaos


def test_two_process_capacity_transfer_chaos(tmp_path):
    outs = _launch("capacity", 2, tmp_path, timeout=420)
    for rc, out in outs:
        assert rc == 0, f"worker failed (rc={rc}):\n{out[-6000:]}"
        assert "ALL_OK" in out, out[-6000:]
    combined = "\n".join(out for _, out in outs)
    for name in ("capacity_kill_mid_conversion", "capacity_orphan_aborted",
                 "capacity_abort_rank_rejoined", "capacity_auto_converted",
                 "capacity_sync_bit_identical", "capacity_zero_drop",
                 "capacity_worker_served_and_stopped",
                 "capacity_retired_to_training"):
        assert f"PASS {name}" in combined, (name, combined[-6000:])
