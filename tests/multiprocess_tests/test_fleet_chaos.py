"""Serving-fleet chaos over REAL 2-process gloo transport (the
ISSUE 15 acceptance gate, see docs/serving.md §"Elastic serving
fleet").

One run: a seeded kill preempts the worker-process replica at decode
step 2 under open-loop load → the worker announces its FLEET-role
leave and goes silent, the router detects through the typed channel
timeout (bounded by the committed detection deadline), the fleet
membership shrinks to {0}, and every request the dead replica held
replays from its ORIGINAL prompt on the survivor — zero dropped
requests, every trajectory equal to its solo run → the replica parks,
re-joins through the membership protocol, perturbs its weights, and
adopts the root's BIT-IDENTICALLY over the multicast-tree sync → the
router spreads new admissions to the re-joined replica."""

import pytest

from .test_two_process import _launch

pytestmark = pytest.mark.chaos


def test_two_process_fleet_kill_reroute_and_rejoin(tmp_path):
    outs = _launch("fleet", 2, tmp_path, timeout=420)
    for rc, out in outs:
        assert rc == 0, f"worker failed (rc={rc}):\n{out[-6000:]}"
        assert "ALL_OK" in out, out[-6000:]
    combined = "\n".join(out for _, out in outs)
    for name in ("fleet_zero_drop", "fleet_detection_bounded",
                 "fleet_replay_parity", "fleet_router_spreads_to_joiner",
                 "fleet_shrunk_to_survivor",
                 "fleet_weight_sync_bit_identical"):
        assert f"PASS {name}" in combined, (name, combined[-6000:])
