"""Public API surface guard.

Asserts the documented export surface (docs/api.md, PARITY.md) resolves —
a cheap tripwire against accidental API breaks in later rounds.
"""

import importlib

import chainermn_tpu as ct


TOP_LEVEL = [
    "create_communicator", "CommunicatorBase", "MeshCommunicator",
    "DummyCommunicator", "create_multi_node_optimizer",
    "create_multi_node_evaluator", "scatter_dataset", "create_empty_dataset",
    "scatter_index", "create_multi_node_iterator",
    "create_synchronized_iterator", "create_multi_node_checkpointer",
    "rescatter_dataset",
    "Parameter", "Link", "Chain", "ChainList", "Sequential",
    "report", "using_config", "F", "L",
]

MODULES = {
    "chainermn_tpu.functions": [
        "send", "recv", "pseudo_connect", "point_to_point", "allgather",
        "alltoall", "bcast", "gather", "scatter", "allreduce",
        "psum_gradient"],
    "chainermn_tpu.links": [
        "MultiNodeChainList", "MultiNodeBatchNormalization",
        "create_mnbn_model", "ParallelConvolution2D"],
    "chainermn_tpu.extensions": [
        "create_multi_node_checkpointer", "ObservationAggregator",
        "OrbaxCheckpointer",
        # round 11 (elastic, docs/resilience.md §7)
        "FailureRecovery", "RecoveryGivingUp", "ElasticRecovery",
        "ElasticConfigError", "create_elastic_membership",
        "global_batch_plan"],
    "chainermn_tpu.communicators": [
        "ElasticMembership", "MembershipView", "ElasticMeshCommunicator",
        "RankPreempted", "FaultSchedule", "FaultSpec",
        "FaultInjectionCommunicator", "multicast_tree_plan"],
    "chainermn_tpu.parallel": [
        "ring_self_attention", "ring_attention", "ulysses_attention",
        "gpipe_apply", "one_f_one_b", "make_pipeline_train_step",
        "switch_moe", "moe_dispatch_combine", "make_mesh",
        "axis_communicators", "split_microbatches", "merge_microbatches"],
    "chainermn_tpu.ops": ["attention", "flash_attention", "xla_attention",
                          "paged_decode_attention", "paged_attn_mode"],
    "chainermn_tpu.serving": [
        "ServingEngine", "Request", "RequestScheduler", "BlockAllocator",
        "PagedKVCache", "prefill_program", "decode_program",
        "write_prompt_kv", "write_token_kv", "ServingError",
        "PagePoolExhaustedError", "QueueSaturatedError",
        # round 16 (elastic serving fleet, docs/serving.md)
        "ReplicaFleet", "FleetRouter", "FleetWorker", "RemoteReplica",
        "QueueDepthScalePolicy", "fleet_mode", "NoLiveReplicaError"],
    # round 16: the fleet module itself is a documented import surface
    "chainermn_tpu.serving.fleet": [
        "ReplicaFleet", "LocalReplica", "RemoteReplica", "FleetWorker",
        "QueueDepthScalePolicy", "fleet_mode", "serialize_state",
        "deserialize_state", "FLEET_ENV", "FLEET_ROLE"],
    "chainermn_tpu.models": [
        "MLP", "Classifier", "ResNet18", "ResNet50", "ResNet101",
        "AlexNet", "NIN", "VGG16", "GoogLeNet", "Seq2seq",
        "ModelParallelSeq2seq", "Generator", "Discriminator",
        "DCGANUpdater", "TransformerLM", "MoETransformerLM"],
    "chainermn_tpu.core.optimizer": [
        "SGD", "MomentumSGD", "NesterovAG", "Adam", "AdamW", "RMSprop",
        "AdaGrad", "AdaDelta", "WeightDecay", "GradientClipping"],
    "chainermn_tpu.training.extensions": [
        "LogReport", "PrintReport", "ProgressBar", "snapshot",
        "snapshot_object", "Evaluator", "ExponentialShift", "LinearShift",
        "observe_lr", "FailOnNonNumber", "ParameterStatistics"],
    "chainermn_tpu.dataset": [
        "TupleDataset", "DictDataset", "SubDataset", "TransformDataset",
        "SerialIterator", "MultiprocessIterator", "MultithreadIterator",
        "concat_examples", "identity_converter", "get_mnist", "get_cifar10"],
    "chainermn_tpu.serializers": ["save_npz", "load_npz"],
    "chainermn_tpu.utils": ["use_platform", "simulate_devices", "trace",
                            "annotate", "Profile"],
    # round 15 (observability, docs/observability.md)
    "chainermn_tpu.observability": [
        "span", "instant", "tracer", "SpanTracer", "validate_events",
        "set_mode", "enabled", "MetricsRegistry", "Counter", "Gauge",
        "Histogram", "registry"],
}

F_FUNCTIONS = [
    "relu", "sigmoid", "tanh", "gelu", "softmax", "log_softmax",
    "softmax_cross_entropy", "sigmoid_cross_entropy", "mean_squared_error",
    "accuracy", "dropout", "linear", "embed_id", "convolution_2d",
    "deconvolution_2d", "max_pooling_2d", "average_pooling_2d",
    "unpooling_2d", "batch_normalization", "layer_normalization", "concat",
    "reshape", "select_item", "normalize", "einsum", "logsumexp"]

L_LINKS = [
    "Linear", "Convolution2D", "Deconvolution2D", "BatchNormalization",
    "GroupNormalization", "LayerNormalization", "EmbedID", "LSTM",
    "StatelessLSTM", "GRU", "StatelessGRU", "NStepLSTM", "NStepGRU",
    "Highway", "Maxout", "Scale", "Classifier"]


def test_top_level_exports():
    missing = [n for n in TOP_LEVEL if not hasattr(ct, n)]
    assert not missing, missing


def test_module_exports():
    problems = []
    for mod_name, names in MODULES.items():
        mod = importlib.import_module(mod_name)
        for n in names:
            if getattr(mod, n, None) is None:
                problems.append(f"{mod_name}.{n}")
    assert not problems, problems


def test_F_and_L_surfaces():
    missing = [n for n in F_FUNCTIONS if not hasattr(ct.F, n)]
    missing += [f"L.{n}" for n in L_LINKS if getattr(ct.L, n, None) is None]
    assert not missing, missing


def test_communicator_names_accepted():
    for name in ("naive", "flat", "hierarchical", "two_dimensional",
                 "single_node", "non_cuda_aware", "pure_nccl", "jax_ici",
                 "dummy", "debug"):
        assert ct.create_communicator(name) is not None
