"""Distributed model components (reference: ``chainermn.links``)."""

from .multi_node_chain_list import MultiNodeChainList
from .batch_normalization import MultiNodeBatchNormalization
from .create_mnbn_model import create_mnbn_model
from .parallel_convolution import ParallelConvolution2D

__all__ = ["MultiNodeChainList", "MultiNodeBatchNormalization",
           "create_mnbn_model", "ParallelConvolution2D"]
