"""Channel-parallel (tensor-parallel) convolution.

Reference: ``examples/parallel_convolution/`` (SURVEY.md §2.6 TP row) —
the reference's by-hand tensor parallelism: each rank owns a filter
slice, computes its output-channel block, and the blocks are stitched
with the differentiable ``allgather``.  Promoted from example to a
first-class link here (the TPU mapping notes TP is "nearly free" — this
link is the explicit-collective form; ``pjit`` sharding annotations on a
plain ``Convolution2D`` are the automatic form).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.link import Link, Parameter
from ..nn import functions as F
from ..nn import initializers as I
from .. import functions as mnfn

__all__ = ["ParallelConvolution2D"]


class ParallelConvolution2D(Link):
    """Filter-split conv: rank r computes out-channel block r.

    Inside a compiled step over ``comm``'s axis, each rank holds
    ``out_channels // size`` filters (selected by ``axis_index``) and the
    full output is assembled with the differentiable allgather; gradients
    flow back to each rank's slice through the allgather transpose —
    exactly the reference example's construction.

    Eagerly (host mode) the full filter bank is applied directly
    (single-controller: the controller owns all slices).
    """

    def __init__(self, comm, in_channels, out_channels, ksize, stride=1,
                 pad=0, nobias=False, initialW=None, initial_bias=None,
                 seed=None):
        super().__init__()
        if out_channels % comm.size != 0:
            raise ValueError(
                f"out_channels {out_channels} not divisible by "
                f"comm.size {comm.size}")
        self.comm = comm
        self.out_channels = out_channels
        self.stride = stride
        self.pad = pad
        self.nobias = nobias
        rng = np.random.RandomState(seed) if seed is not None else np.random
        initW = I._get_initializer(initialW, I.HeNormal())
        initb = I._get_initializer(initial_bias, I.Zero())
        kh, kw = (ksize, ksize) if np.isscalar(ksize) else ksize
        with self.init_scope():
            self.W = Parameter(initW((out_channels, in_channels, kh, kw),
                                     np.float32, rng))
            if not nobias:
                self.b = Parameter(initb((out_channels,), np.float32, rng))

    def forward(self, x):
        comm = self.comm
        from jax._src.core import get_axis_env
        in_axis = comm.axis_name is not None and \
            get_axis_env().axis_exists(comm.axis_name)
        W = self.W.array
        b = None if self.nobias else self.b.array
        if not in_axis:
            return F.convolution_2d(x, W, b, self.stride, self.pad)
        # rank-local filter slice; psum_gradient reassembles the full
        # replicated weight gradient from the per-rank slice cotangents
        size = comm.size
        block = self.out_channels // size
        idx = jax.lax.axis_index(comm.axis_name)
        W = mnfn.psum_gradient(comm, W)
        if b is not None:
            b = mnfn.psum_gradient(comm, b)
        W_local = jax.lax.dynamic_slice_in_dim(W, idx * block, block, 0)
        b_local = None if b is None else \
            jax.lax.dynamic_slice_in_dim(b, idx * block, block, 0)
        y_local = F.convolution_2d(x, W_local, b_local, self.stride,
                                   self.pad)
        blocks = mnfn.allgather(comm, y_local)
        return jnp.concatenate(blocks, axis=1)
