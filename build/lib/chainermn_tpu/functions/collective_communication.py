"""Differentiable collective communication.

Reference: ``chainermn/functions/collective_communication.py · AllGather,
AllToAll, Bcast, Gather, Scatter, Allreduce`` (SURVEY.md §2.2) — each a
FunctionNode whose backward performs the transposed communication
(allgather ↔ reduce-scatter-sum, bcast ↔ gather+sum-to-root,
alltoall ↔ alltoall).

Here each op is a plain function over ``lax`` collectives used inside a
``shard_map``ped program; JAX's AD transposition inserts exactly the
reference's backward collectives, so no hand-written backward exists to
get wrong.  These are the building blocks for tensor/hybrid parallelism
(reference ``examples/parallel_convolution``) and the long-context layers
(``parallel/ring_attention.py``, ``parallel/ulysses.py``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["allgather", "alltoall", "bcast", "gather", "scatter",
           "allreduce", "psum_gradient"]


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _psum_grad(x, axis_name):
    return x


def _psum_grad_fwd(x, axis_name):
    return x, None


def _psum_grad_bwd(axis_name, _, g):
    return (lax.pmean(g, axis_name),)


_psum_grad.defvjp(_psum_grad_fwd, _psum_grad_bwd)


def psum_gradient(communicator, x):
    """Identity forward, gradient allreduce backward.

    The "copy into tensor-parallel region" primitive: a replicated tensor
    consumed shard-wise by different ranks (each slicing its block) has
    per-rank cotangents covering only that rank's slice; the backward
    allreduce reassembles the full replicated gradient.

    Scaling contract: this framework's SPMD convention is that the loss is
    computed *redundantly on every rank* (MultiNodeChainList broadcasts
    the terminal output; DP losses are per-shard means).  Under that
    convention collective transposes already multiply cotangents by the
    rank count, so the reassembly here is a ``pmean`` — the result equals
    the single-process gradient exactly.
    """
    return _psum_grad(x, communicator.axis_name)


def allgather(communicator, x):
    """Every rank's ``x`` as a tuple (reference returns a list of size
    variables).  Backward: each rank receives the summed shard gradients —
    JAX's all_gather transpose (dynamic-slice + reduce-scatter-sum)."""
    gathered = lax.all_gather(x, communicator.axis_name)
    return tuple(gathered[i] for i in range(communicator.size))


def alltoall(communicator, xs):
    """Scatter a per-destination tuple, gather per-source (reference
    AllToAll).  Backward is the reverse alltoall."""
    if isinstance(xs, (tuple, list)):
        if len(xs) != communicator.size:
            raise ValueError(
                f"alltoall expects {communicator.size} slices, got {len(xs)}")
        xs = jnp.stack(list(xs))
    out = lax.all_to_all(xs, communicator.axis_name,
                         split_axis=0, concat_axis=0, tiled=False)
    return tuple(out[i] for i in range(communicator.size))


def bcast(communicator, x, root=0):
    """Root's ``x`` on every rank.  Backward: gradients gather-summed to
    root (transpose of the masked psum)."""
    idx = lax.axis_index(communicator.axis_name)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    return lax.psum(masked, communicator.axis_name)


def gather(communicator, x, root=0):
    """All ranks' values as a tuple (meaningful on root; SPMD computes it
    everywhere — the compiler drops unused results on other ranks)."""
    gathered = lax.all_gather(x, communicator.axis_name)
    return tuple(gathered[i] for i in range(communicator.size))


def scatter(communicator, xs, root=0):
    """Rank ``root`` holds a per-destination tuple; each rank gets its
    slice.  Backward: gradients gathered back to root."""
    if isinstance(xs, (tuple, list)):
        xs = jnp.stack(list(xs))
    from_root = bcast(communicator, xs, root)
    idx = lax.axis_index(communicator.axis_name)
    return jnp.take(from_root, idx, axis=0)


def allreduce(communicator, x, op="sum"):
    """Elementwise reduction across ranks on every rank.

    Backward: the gradient is itself allreduced (reference Allreduce
    backward) — automatic via psum's self-transpose.
    """
    if op == "sum":
        return lax.psum(x, communicator.axis_name)
    if op == "mean":
        return lax.pmean(x, communicator.axis_name)
    raise ValueError(f"unsupported op {op!r}")
