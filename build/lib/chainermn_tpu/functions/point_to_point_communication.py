"""Differentiable point-to-point communication.

Reference: ``chainermn/functions/point_to_point_communication.py · Send,
Recv, send, recv, pseudo_connect`` (SURVEY.md §2.2, call stack §3.3).

The reference's machinery exists because its backward pass must *trigger*
communication imperatively: ``Send.forward`` posts an MPI send and returns
a zero-size **delegate variable** whose ``backward`` blocks on a recv of
the gradient; delegates thread the per-process graphs together so
``loss.backward()`` on the last pipeline stage transitively drives every
stage (MPMD).

The TPU rebuild is SPMD: every rank traces the *same* program, and a
transfer is one ``lax.ppermute`` with a statically-known ``(src, dst)``
edge.  JAX's AD transposes ``ppermute`` automatically (cotangents flow
along the reversed edge), so the reference's hard part — "backward
triggers a recv" (SURVEY §7) — dissolves: gradient communication is just
the transposed collective XLA inserts.  ``send``/``recv``/delegate
variables are kept as the user-facing vocabulary: ``send`` performs the
transfer and stashes the in-flight traced value on the communicator
(keyed by ``(tag, src, dst)``), ``recv`` claims it, and the delegate keeps
reference code shapes working (including ``pseudo_connect`` fan-in).

SPMD deviation from the reference, by design: both endpoints appear in the
one traced program, so ``send``/``recv`` take the static pair (``dst`` and
``src``); inside ``MultiNodeChainList`` these come from the registered
``rank_in``/``rank_out`` topology exactly as the reference's do.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

__all__ = ["point_to_point", "send", "recv", "pseudo_connect"]


def point_to_point(x, communicator, src, dst):
    """One transfer edge: rank ``src``'s ``x`` arrives on rank ``dst``.

    Other ranks receive zeros (they still participate in the collective —
    SPMD lock-step).  Differentiable: the transpose is the reversed edge.
    """
    perm = [(int(src), int(dst))]
    return lax.ppermute(x, communicator.axis_name, perm)


def send(x, communicator, rank, *, self_rank, tag=0):
    """Send ``x`` to ``rank``; returns a zero-size delegate variable.

    ``self_rank`` is the static rank of the sending stage (the reference
    learns it from the process; SPMD needs it stated — MultiNodeChainList
    supplies it from its topology table).
    """
    y = point_to_point(x, communicator, self_rank, rank)
    stash = _stash(communicator)
    stash.setdefault((tag, int(self_rank), int(rank)), []).append(y)
    # zero-size delegate: carries graph connectivity, no payload
    flat = jnp.ravel(y)
    return jnp.sum(flat) * 0.0


def recv(communicator, rank, delegate_variable=None, *, self_rank, tag=0,
         force_tuple=False):
    """Receive the value sent from ``rank`` to ``self_rank``.

    If ``delegate_variable`` is given, it is fused into the result so the
    local graph stays connected through prior sends (reference Recv
    semantics with ``delegate_variable=``).
    """
    stash = _stash(communicator)
    key = (tag, int(rank), int(self_rank))
    queue = stash.get(key)
    if not queue:
        raise RuntimeError(
            f"recv from rank {rank} to {self_rank} (tag {tag}) with no "
            f"matching send in this traced program; SPMD send/recv pairs "
            f"must both appear in one compiled step")
    y = queue.pop(0)
    if delegate_variable is not None:
        y = pseudo_connect(delegate_variable, y)
    return (y,) if force_tuple else y


def pseudo_connect(delegate_variable, *actual_variables):
    """Fuse a delegate into actual variables (reference: ``pseudo_connect``).

    Adds a zero-valued dependency on the delegate so backward traverses the
    send edge even when the sender's output is not otherwise used locally.
    """
    if not actual_variables:
        return delegate_variable
    zero = jnp.sum(jnp.ravel(delegate_variable)) * 0.0
    connected = tuple(v + zero.astype(v.dtype) for v in actual_variables)
    return connected[0] if len(connected) == 1 else connected


def _stash(communicator):
    # trace-scoped in-flight transfers; cleared per compiled call by the
    # launching wrapper (run_spmd / MultiNodeChainList)
    stash = getattr(communicator, "_p2p_stash", None)
    if stash is None:
        stash = {}
        communicator._p2p_stash = stash
    return stash


def clear_stash(communicator):
    communicator._p2p_stash = {}
