"""Differentiable communication ops (reference: ``chainermn.functions``)."""

from .point_to_point_communication import (point_to_point, send, recv,
                                           pseudo_connect)
from .collective_communication import (allgather, alltoall, bcast, gather,
                                       scatter, allreduce, psum_gradient)

__all__ = ["point_to_point", "send", "recv", "pseudo_connect",
           "allgather", "alltoall", "bcast", "gather", "scatter",
           "allreduce", "psum_gradient"]
