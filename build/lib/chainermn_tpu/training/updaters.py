"""Updaters (consumed-Chainer surface: ``chainer.training.updaters``).

Reference: ``chainer/training/updaters/standard_updater.py ·
StandardUpdater`` (SURVEY.md §3.2 call stack — ``trainer.run →
StandardUpdater.update → optimizer.update``).  The updater stays thin: the
whole compute step is inside ``Optimizer.update``'s jitted program.
"""

from __future__ import annotations

from ..dataset.convert import concat_examples

__all__ = ["Updater", "StandardUpdater"]


class Updater:
    def connect_trainer(self, trainer):
        pass

    def finalize(self):
        pass

    def get_optimizer(self, name):
        raise NotImplementedError

    def get_all_optimizers(self):
        raise NotImplementedError

    def update(self):
        raise NotImplementedError

    def serialize(self, serializer):
        raise NotImplementedError


class StandardUpdater(Updater):
    def __init__(self, iterator, optimizer, converter=concat_examples,
                 device=None, loss_func=None, loss_scale=None):
        if not isinstance(iterator, dict):
            iterator = {"main": iterator}
        self._iterators = iterator
        if not isinstance(optimizer, dict):
            optimizer = {"main": optimizer}
        self._optimizers = optimizer
        self.converter = converter
        self.device = device
        self.loss_func = loss_func
        self.iteration = 0

    @property
    def epoch(self):
        return self._iterators["main"].epoch

    @property
    def epoch_detail(self):
        return self._iterators["main"].epoch_detail

    @property
    def previous_epoch_detail(self):
        return self._iterators["main"].previous_epoch_detail

    @property
    def is_new_epoch(self):
        return self._iterators["main"].is_new_epoch

    def get_optimizer(self, name="main"):
        return self._optimizers[name]

    def get_all_optimizers(self):
        return dict(self._optimizers)

    def get_iterator(self, name="main"):
        return self._iterators[name]

    def update(self):
        self.update_core()
        self.iteration += 1

    def update_core(self):
        iterator = self._iterators["main"]
        optimizer = self._optimizers["main"]
        batch = iterator.next()
        in_arrays = self.converter(batch, self.device)
        loss_func = self.loss_func or optimizer.target
        if isinstance(in_arrays, tuple):
            optimizer.update(loss_func, *in_arrays)
        elif isinstance(in_arrays, dict):
            optimizer.update(loss_func, **in_arrays)
        else:
            optimizer.update(loss_func, in_arrays)
        if self.is_new_epoch:
            optimizer.new_epoch()

    def finalize(self):
        for iterator in self._iterators.values():
            iterator.finalize()

    def serialize(self, serializer):
        self.iteration = int(serializer("iteration", self.iteration))
        for name, iterator in self._iterators.items():
            iterator.serialize(serializer["iterator:" + name])
        for name, optimizer in self._optimizers.items():
            optimizer.serialize(serializer["optimizer:" + name])
