"""Observation reporting (consumed-Chainer surface: ``chainer.Reporter``).

Reference: ``chainer/reporter.py · Reporter/report/report_scope`` (SURVEY.md
§2.8, §5 metrics).  Extensions (LogReport/PrintReport) and the multi-node
evaluator consume the observation dict this module builds.  Values may be
``jax.Array`` scalars; ``Summary``/``DictSummary`` accumulate in float64 on
host to keep aggregation out of compiled programs.
"""

from __future__ import annotations

import contextlib
import threading

import numpy as np

__all__ = ["Reporter", "report", "report_scope", "get_current_reporter",
           "Summary", "DictSummary"]

_thread_local = threading.local()


def _reporter_stack():
    if not hasattr(_thread_local, "stack"):
        _thread_local.stack = []
    return _thread_local.stack


class Reporter:
    """Collects named observations from registered observers."""

    def __init__(self):
        self._observer_names = {}
        self.observation = {}

    def add_observer(self, name, observer):
        self._observer_names[id(observer)] = name

    def add_observers(self, prefix, observers):
        for name, observer in observers:
            self._observer_names[id(observer)] = prefix + name

    @contextlib.contextmanager
    def scope(self, observation):
        stack = _reporter_stack()
        stack.append(self)
        old = self.observation
        self.observation = observation
        try:
            yield
        finally:
            self.observation = old
            stack.pop()

    def __enter__(self):
        _reporter_stack().append(self)
        return self

    def __exit__(self, *exc):
        _reporter_stack().pop()

    def report(self, values, observer=None):
        if observer is not None:
            observer_name = self._observer_names.get(id(observer))
            if observer_name is None:
                raise KeyError("observer is not registered: %r" % observer)
            for key, value in values.items():
                self.observation[f"{observer_name}/{key}"] = value
        else:
            self.observation.update(values)


def get_current_reporter() -> Reporter:
    stack = _reporter_stack()
    if not stack:
        stack.append(Reporter())
    return stack[-1]


def report(values, observer=None):
    stack = _reporter_stack()
    if stack:
        stack[-1].report(values, observer)


@contextlib.contextmanager
def report_scope(observation):
    with get_current_reporter().scope(observation):
        yield


class Summary:
    """Online mean/std accumulator (reference: ``chainer.reporter.Summary``)."""

    def __init__(self):
        self._x = 0.0
        self._x2 = 0.0
        self._n = 0.0

    def add(self, value, weight=1.0):
        value = float(np.asarray(value))
        self._x += weight * value
        self._x2 += weight * value * value
        self._n += weight

    def compute_mean(self):
        return self._x / self._n

    def make_statistics(self):
        mean = self._x / self._n
        var = self._x2 / self._n - mean * mean
        return mean, float(np.sqrt(max(var, 0.0)))

    def serialize(self, serializer):
        self._x = float(serializer("x", self._x))
        self._x2 = float(serializer("x2", self._x2))
        self._n = float(serializer("n", self._n))


class DictSummary:
    """Per-key ``Summary`` over observation dicts."""

    def __init__(self):
        self._summaries = {}

    def add(self, d):
        for key, value in d.items():
            try:
                arr = np.asarray(value)
            except Exception:
                continue
            if arr.size != 1:
                continue
            self._summaries.setdefault(key, Summary()).add(arr)

    def compute_mean(self):
        return {k: s.compute_mean() for k, s in self._summaries.items()}
