"""Per-step randomness for stochastic layers under jit.

The reference's ``F.dropout`` consumes a hidden global RNG — a new mask
every call.  Under jit a naively-drawn key becomes a trace-time constant
(same mask every step).  This module is the bridge: the compiled train
step receives a fresh key as a *traced argument* each call and pushes it
here; stochastic functions (``F.dropout``) draw deterministic subkeys via
``fold_in`` on a per-trace counter — fresh randomness every step, zero
recompilation, reproducible given the optimizer's seed.
"""

from __future__ import annotations

import threading

__all__ = ["push_key", "pop_key", "next_key", "key_scope"]

_tl = threading.local()


def _stack():
    if not hasattr(_tl, "stack"):
        _tl.stack = []
    return _tl.stack


class _KeyCtx:
    __slots__ = ("key", "counter")

    def __init__(self, key):
        self.key = key
        self.counter = 0


def push_key(key):
    _stack().append(_KeyCtx(key))


def pop_key():
    _stack().pop()


class key_scope:
    def __init__(self, key):
        self.key = key

    def __enter__(self):
        if self.key is not None:
            push_key(self.key)
        return self

    def __exit__(self, *exc):
        if self.key is not None:
            pop_key()
        return False


def next_key():
    """A fresh subkey from the innermost scope, or None outside any."""
    stack = _stack()
    if not stack:
        return None
    import jax
    ctx = stack[-1]
    ctx.counter += 1
    return jax.random.fold_in(ctx.key, ctx.counter)
