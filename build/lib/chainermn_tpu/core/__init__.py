from .link import (Parameter, Link, Chain, ChainList, Sequential,
                   extract_state, bind_state, apply_state, param_tree,
                   grad_tree, set_grads, load_param_tree)
from .optimizer import (Optimizer, GradientMethod, SGD, MomentumSGD, Adam,
                        AdamW, RMSprop, AdaGrad, AdaDelta, NesterovAG,
                        WeightDecay, GradientClipping, GradientHardClipping,
                        Lasso, GradientScaling)
from .reporter import (Reporter, report, report_scope, get_current_reporter,
                       Summary, DictSummary)
from .config import global_config, config, using_config
