"""Thread-local global configuration (consumed-Chainer surface).

Reference: ``chainer/configuration.py · global_config/config/using_config``
(SURVEY.md §5 config note: train/test mode, dtype flags).  Only the flags this
framework consults are declared, but arbitrary attributes are allowed for
user code parity.
"""

from __future__ import annotations

import contextlib
import threading

__all__ = ["global_config", "config", "using_config"]


class _GlobalConfig:
    def __init__(self):
        self.train = True
        self.enable_backprop = True
        self.dtype = "float32"
        self.debug = False


global_config = _GlobalConfig()


class _LocalConfig(threading.local):
    def __getattr__(self, name):  # fall through to global defaults
        return getattr(global_config, name)


config = _LocalConfig()


@contextlib.contextmanager
def using_config(name, value, cfg=config):
    if name in cfg.__dict__:
        old = cfg.__dict__[name]
        setattr(cfg, name, value)
        try:
            yield
        finally:
            setattr(cfg, name, old)
    else:
        setattr(cfg, name, value)
        try:
            yield
        finally:
            delattr(cfg, name)
