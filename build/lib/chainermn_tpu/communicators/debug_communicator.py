"""Debug communicator — cross-host signature checking.

SURVEY.md §5 (race detection): the reference's worst failure mode is rank
divergence → collective deadlock, mitigated only structurally.  The
recommended rebuild addition is a communicator that checksums collective
inputs' shapes/dtypes across ranks *before* executing.

Single-controller SPMD makes intra-host divergence impossible by
construction (all local ranks share one traced program); the remaining
hazard is *across hosts*: processes tracing different shapes compile
different programs and hang in the first DCN/ICI collective.  This
communicator agrees on a step-signature over the object channel before
each compiled launch and fails fast with a readable diff instead of
hanging — at one small host allgather per *compilation* signature (cached
afterward), so steady-state cost is a dict lookup.
"""

from __future__ import annotations

import hashlib

import jax
import numpy as np

from .mesh_communicator import MeshCommunicator

__all__ = ["DebugCommunicator", "SignatureMismatchError"]


class SignatureMismatchError(RuntimeError):
    pass


def _signature(tree):
    parts = []
    for leaf in jax.tree.leaves(tree):
        shape = tuple(np.shape(leaf))
        dtype = str(getattr(leaf, "dtype", type(leaf).__name__))
        parts.append(f"{shape}:{dtype}")
    return ";".join(parts)


class DebugCommunicator(MeshCommunicator):
    def __init__(self, *args, **kwargs):
        kwargs.setdefault("name", "debug")
        super().__init__(*args, **kwargs)
        self._verified_signatures = set()
        self.signature_checks = 0

    def verify_step_signature(self, tree, what="train step"):
        """Raise if any host would launch this step with different
        shapes/dtypes.  Cached per signature — one object-channel
        round per new compilation."""
        sig = _signature(tree)
        if sig in self._verified_signatures:
            return
        self.signature_checks += 1
        digest = hashlib.sha1(sig.encode()).hexdigest()[:16]
        gathered = self.allgather_obj((self.inter_rank, digest, sig))
        digests = {d for _, d, _ in gathered}
        if len(digests) > 1:
            lines = [f"  host {r}: {s}" for r, _, s in gathered]
            raise SignatureMismatchError(
                f"hosts disagree on the {what} signature — the compiled "
                f"collectives would deadlock (reference failure mode: "
                f"rank divergence).  Per-host signatures:\n"
                + "\n".join(lines))
        self._verified_signatures.add(sig)

    def run_spmd(self, fn, *args, **kwargs):
        self.verify_step_signature(args, what="run_spmd")
        return super().run_spmd(fn, *args, **kwargs)
