"""Rank/topology utilities.

Reference: ``chainermn/communicators/_communication_utility.py ·
init_ranks/init_comms`` (SURVEY.md §2.1) — there, topology is derived by
allgathering hostnames over MPI and NCCL ids are broadcast.  On TPU the
runtime already knows the topology: ``jax.devices()`` carries process
ownership and ICI coordinates, and ``jax.distributed.initialize`` is the
bootstrap (N4 in SURVEY §2.5).  These helpers expose the same vocabulary.
"""

from __future__ import annotations

import os

import jax

__all__ = ["init_ranks", "initialize_distributed", "device_topology"]


def init_ranks(devices=None):
    """Per-device ``(global_rank, intra_rank, intra_size, inter_rank,
    inter_size)`` — the reference's quintuple, with host standing in for
    node (one controlling process per TPU host)."""
    devices = list(devices) if devices is not None else list(jax.devices())
    n_hosts = jax.process_count()
    ranks = []
    per_host = {}
    for gr, d in enumerate(devices):
        host = getattr(d, "process_index", 0)
        intra = per_host.setdefault(host, 0)
        per_host[host] += 1
        ranks.append((gr, intra, None, host, n_hosts))
    intra_sizes = dict(per_host)
    return [(gr, ir, intra_sizes[h], h, n)
            for (gr, ir, _, h, n) in ranks]


def initialize_distributed(coordinator_address=None, num_processes=None,
                           process_id=None):
    """Multi-host bootstrap (reference: ``mpiexec`` + ``init_ranks``).

    Wraps ``jax.distributed.initialize``: the coordinator's gRPC/DCN store
    takes MPI's role for process launch agreement.  No-op when already
    initialized or running single-process.
    """
    if num_processes in (None, 1) and coordinator_address is None \
            and "JAX_COORDINATOR_ADDRESS" not in os.environ:
        return False
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id)
        return True
    except RuntimeError:
        return False  # already initialized


def device_topology(devices=None):
    """Best-effort ICI coordinates per device (for mesh layout choices)."""
    devices = list(devices) if devices is not None else list(jax.devices())
    coords = []
    for d in devices:
        coords.append(getattr(d, "coords", None))
    return coords
