"""Parameter packing utilities.

Reference: ``chainermn/communicators/_memory_utility.py · DeviceMemory,
pack_params, unpack_params`` (SURVEY.md §2.1, N2 in §2.5) — there, CUDA
arenas and batched-copy kernels gather scattered grads into one buffer.
On TPU, packing is a ``concatenate`` *inside* the compiled step (XLA fuses
the copies); no arena management exists because XLA owns HBM.  These
helpers provide the same pack/unpack contract for the ``flat``-flavor
communicator and for flat-buffer checkpointing.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

__all__ = ["pack_params", "unpack_params", "tree_pack", "tree_unpack"]


def tree_pack(tree, dtype=None):
    """Flatten a pytree of arrays into (flat_vector, spec)."""
    leaves, treedef = jax.tree.flatten(tree)
    shapes = [l.shape for l in leaves]
    dtypes = [l.dtype for l in leaves]
    flat = jnp.concatenate(
        [l.reshape(-1).astype(dtype or l.dtype) for l in leaves]) \
        if leaves else jnp.zeros((0,), dtype or jnp.float32)
    return flat, (treedef, shapes, dtypes)


def tree_unpack(flat, spec):
    treedef, shapes, dtypes = spec
    leaves = []
    offset = 0
    for shape, dt in zip(shapes, dtypes):
        n = int(np.prod(shape))
        leaves.append(flat[offset:offset + n].reshape(shape).astype(dt))
        offset += n
    return jax.tree.unflatten(treedef, leaves)


def pack_params(params, attr="grad", dtype=None):
    """Pack ``param.<attr>`` of a parameter list into one flat vector.

    Reference-shaped API (``pack_params(params, 'grad', buffer)``); returns
    (flat, spec) instead of filling a caller-owned arena.
    """
    arrays = [getattr(p, attr) for p in params]
    return tree_pack(arrays, dtype=dtype)


def unpack_params(params, flat, spec, attr="grad"):
    arrays = tree_unpack(flat, spec)
    for p, a in zip(params, arrays):
        setattr(p, attr, a)
