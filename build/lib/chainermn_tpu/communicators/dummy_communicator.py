"""No-op communicator.

Reference: ``chainermn/communicators/dummy_communicator.py ·
DummyCommunicator`` (SURVEY.md §2.1) — used to measure the
non-communication fraction of a run and in API-shape tests.  All
collectives are size-1 identities; ``grad_transform`` is the identity, so
a training loop built for a real communicator runs unchanged with zero
communication cost.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .communicator_base import CommunicatorBase

__all__ = ["DummyCommunicator"]


class DummyCommunicator(CommunicatorBase):
    def __init__(self):
        self.name = "dummy"
        self.axis_name = None
        self._mailbox = []
        self._obj_mailbox = []

    rank = property(lambda self: 0)
    size = property(lambda self: 1)
    intra_rank = property(lambda self: 0)
    intra_size = property(lambda self: 1)
    inter_rank = property(lambda self: 0)
    inter_size = property(lambda self: 1)

    def send(self, data, dest, tag=0):
        self._mailbox.append(jnp.asarray(data))

    def recv(self, source, tag=0):
        return self._mailbox.pop(0)

    def bcast(self, data, root=0):
        return jnp.asarray(data)

    def gather(self, data, root=0):
        return (jnp.asarray(data),)

    def allgather(self, x):
        return (jnp.asarray(x),)

    def alltoall(self, xs):
        return xs

    def scatter(self, xs, root=0):
        return jnp.asarray(xs)

    def allreduce(self, data, op="sum"):
        return jnp.asarray(data)

    def multi_node_mean(self, data):
        return jnp.asarray(data)

    def send_obj(self, obj, dest, tag=0):
        self._obj_mailbox.append(obj)

    def recv_obj(self, source, tag=0):
        return self._obj_mailbox.pop(0)

    def bcast_obj(self, obj, root=0):
        return obj

    def gather_obj(self, obj, root=0):
        return [obj]

    def allgather_obj(self, obj):
        return [obj]

    def allreduce_obj(self, obj):
        return obj

    def bcast_data(self, model):
        return model

    def multi_node_mean_grad(self, model, zero_fill=False):
        pass

    def grad_transform(self):
        return lambda grads: grads

    def run_spmd(self, fn, *args, **kwargs):
        return jax.jit(fn)(*args)

    def split(self, color, key):
        return self
