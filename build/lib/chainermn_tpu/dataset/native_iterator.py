"""Iterator backed by the native C++ gather engine.

Drop-in for ``SerialIterator`` when the dataset is numpy arrays (or a
``TupleDataset`` of them): batch assembly (the per-example gather into a
contiguous buffer) runs in C++ worker threads with ring-buffer
backpressure, and the next batch is always being prepared while the
device computes — the TPU-host counterpart of the reference's
``MultiprocessIterator`` (SURVEY.md §2.8) without fork/pickle overhead.
"""

from __future__ import annotations

import numpy as np

from .datasets import TupleDataset
from .iterators import Iterator

__all__ = ["NativeBatchIterator"]


class NativeBatchIterator(Iterator):
    def __init__(self, dataset, batch_size, repeat=True, shuffle=True,
                 seed=None, n_prefetch=2, n_threads=4):
        arrays = self._extract_arrays(dataset)
        if arrays is None:
            raise TypeError(
                "NativeBatchIterator needs numpy arrays or a TupleDataset "
                "of numpy arrays; use SerialIterator for generic datasets")
        from ..utils.native import NativeLoader
        self._loaders = [NativeLoader(a, batch_size,
                                      n_buffers=n_prefetch + 1,
                                      n_threads=n_threads)
                         for a in arrays]
        self._n = len(arrays[0])
        self.batch_size = batch_size
        self._repeat = repeat
        self._shuffle = shuffle
        self._rng = np.random.RandomState(seed)
        self._n_prefetch = n_prefetch
        self._tuple = len(arrays) > 1
        self.reset()

    @staticmethod
    def _extract_arrays(dataset):
        if isinstance(dataset, np.ndarray):
            return [dataset]
        if isinstance(dataset, TupleDataset) and all(
                isinstance(d, np.ndarray) for d in dataset._datasets):
            return list(dataset._datasets)
        if isinstance(dataset, (list, tuple)) and all(
                isinstance(d, np.ndarray) for d in dataset):
            return list(dataset)
        return None

    # -- schedule ----------------------------------------------------------
    def reset(self):
        self.epoch = 0
        self.is_new_epoch = False
        self.current_position = 0
        self._previous_epoch_detail = -1.0
        self._order = (self._rng.permutation(self._n) if self._shuffle
                       else np.arange(self._n))
        self._in_flight = []
        self._exhausted = False
        for _ in range(self._n_prefetch):
            self._submit_next()

    def _next_indices(self):
        """Advance the schedule; returns (indices, epoch, is_new_epoch)."""
        i = self.current_position
        i_end = i + self.batch_size
        idx = self._order[i:i_end]
        epoch, new_epoch = self.epoch, False
        if i_end >= self._n:
            if self._repeat:
                rest = i_end - self._n
                order = (self._rng.permutation(self._n) if self._shuffle
                         else np.arange(self._n))
                if rest > 0:
                    idx = np.concatenate([idx, order[:rest]])
                self._order = order
                self.current_position = rest
            else:
                self.current_position = self._n
            epoch += 1
            new_epoch = True
        else:
            self.current_position = i_end
        self.epoch_after = epoch
        return idx, epoch, new_epoch

    def _submit_next(self):
        if self._exhausted:
            return
        if not self._repeat and self.current_position >= self._n:
            self._exhausted = True
            return
        idx, epoch, new_epoch = self._next_indices()
        if idx.size == 0:
            self._exhausted = True
            return
        for loader in self._loaders:
            loader.submit(idx)
        self._in_flight.append((epoch, new_epoch,
                                (self.current_position, self._n)))

    def __next__(self):
        if not self._in_flight:
            raise StopIteration
        self._previous_epoch_detail = self.epoch_detail
        epoch, new_epoch, (pos, n) = self._in_flight.pop(0)
        batches = [loader.next() for loader in self._loaders]
        self._submit_next()
        self.epoch = epoch if new_epoch else self.epoch
        self.is_new_epoch = new_epoch
        self._detail_pos = pos
        return tuple(batches) if self._tuple else batches[0]

    next = __next__

    @property
    def epoch_detail(self):
        return self.epoch + getattr(self, "_detail_pos", 0) / self._n \
            if not self.is_new_epoch else float(self.epoch)

    @property
    def previous_epoch_detail(self):
        return self._previous_epoch_detail

    def finalize(self):
        for loader in self._loaders:
            loader.close()
