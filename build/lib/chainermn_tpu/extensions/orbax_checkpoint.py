"""Orbax-backed checkpointing for sharded pod-scale state.

SURVEY.md §5 checkpoint note: "orbax-style sharded checkpoint of the
jitted train state; keep the consensus-resume semantic".  The npz
checkpointer (``extensions.checkpoint``) is the reference-parity path
(per-host files, host-gathered arrays); this wrapper writes device-
sharded pytrees directly — each host persists only its shards, restore
re-places them — which is the right mechanics once models outgrow one
host's memory.
"""

from __future__ import annotations

import os

from ..core.link import extract_state, load_param_tree, _persistent_slots

__all__ = ["OrbaxCheckpointer"]


class OrbaxCheckpointer:
    def __init__(self, directory, max_to_keep=3):
        import orbax.checkpoint as ocp
        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep))

    # -- raw pytrees -------------------------------------------------------
    def save(self, step, pytree):
        self._manager.save(step, args=self._ocp.args.StandardSave(pytree))
        self._manager.wait_until_finished()

    def restore(self, step=None, template=None):
        step = self.latest_step() if step is None else step
        if step is None:
            return None
        if template is not None:
            return self._manager.restore(
                step, args=self._ocp.args.StandardRestore(template))
        return self._manager.restore(step)

    def latest_step(self):
        return self._manager.latest_step()

    def all_steps(self):
        return list(self._manager.all_steps())

    # -- links -------------------------------------------------------------
    def save_link(self, step, link):
        self.save(step, extract_state(link))

    def restore_link(self, link, step=None):
        state = self.restore(step, template=extract_state(link))
        if state is None:
            return False
        load_param_tree(link, state["params"])
        slots = {full: (sublink, name)
                 for sublink, name, full in _persistent_slots(link)}
        for path, value in state.get("state", {}).items():
            if path in slots:
                sublink, name = slots[path]
                object.__setattr__(sublink, name, value)
                sublink._persistent[name] = value
        return True

    def close(self):
        self._manager.close()
