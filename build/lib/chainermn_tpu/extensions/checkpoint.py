"""Distributed checkpointing with consensus resume.

Reference: ``chainermn/extensions/checkpoint.py ·
create_multi_node_checkpointer, _MultiNodeCheckpointer`` (SURVEY.md §2.4,
call stack §3.5): every rank snapshots its own trainer state
(``<name>.<iteration>.<rank>``) on a trigger, old generations are
garbage-collected, and ``maybe_load`` allgathers each rank's available
snapshot iterations, picks the newest iteration present on *all* ranks,
and resumes everyone consistently — the fail-stop recovery contract
(crash → relaunch → converge on the newest common checkpoint).

Single-controller translation: one snapshot per *host* (``comm.inter_rank``
— this process drives all its devices' state); the consensus allgather
runs over the object channel (DCN multi-host, loopback single-host).
Device-sharded arrays are pulled to host by the npz serializer; for
pod-scale sharded state see ``chainermn_tpu.extensions.orbax_checkpoint``.
"""

from __future__ import annotations

import os
import re
import tempfile
import time

from ..serializers.npz import load_npz, save_npz
from ..training.trainer import Extension

__all__ = ["create_multi_node_checkpointer", "_MultiNodeCheckpointer"]


def create_multi_node_checkpointer(comm, name="", cp_interval=5,
                                   gc_interval=5, path=None):
    """Reference-shaped factory.

    ``cp_interval``: number of snapshot generations kept.  ``gc_interval``:
    collection cadence — stale generations are removed once they number at
    least ``gc_interval`` (batching deletes instead of one unlink per save).
    """
    return _MultiNodeCheckpointer(comm, name, cp_interval, gc_interval, path)


class _MultiNodeCheckpointer(Extension):
    trigger = (1, "epoch")
    priority = -100  # after everything else mutated state this iteration

    def __init__(self, comm, name, cp_interval, gc_interval, path):
        self.comm = comm
        self.name = name
        self.cp_interval = cp_interval
        self.gc_interval = gc_interval
        self.path = path
        self.stats = {"snapshots": 0, "gc": 0, "save_time": 0.0}
        self._files = []

    @property
    def rank(self):
        return self.comm.inter_rank

    def _dir(self, trainer=None):
        if self.path is not None:
            return self.path
        assert trainer is not None
        return trainer.out

    def _filename(self, iteration):
        return f"{self.name}.{iteration}.{self.rank}"

    _pattern = property(lambda self: re.compile(
        re.escape(self.name) + r"\.(\d+)\.(\d+)$"))

    # -- save -------------------------------------------------------------
    def __call__(self, trainer):
        self.save(trainer, trainer.updater.iteration)

    def save(self, trainer, iteration):
        start = time.time()
        out = self._dir(trainer)
        os.makedirs(out, exist_ok=True)
        fname = self._filename(iteration)
        fd, tmp = tempfile.mkstemp(prefix=fname, dir=out)
        os.close(fd)
        try:
            save_npz(tmp, trainer)
        except Exception:
            os.remove(tmp)
            raise
        os.replace(tmp, os.path.join(out, fname))
        self._files.append(fname)
        self.stats["snapshots"] += 1
        self.stats["save_time"] += time.time() - start
        if len(self._files) >= self.cp_interval + self.gc_interval:
            self._gc(out)

    def _gc(self, out):
        keep = sorted(self._files,
                      key=lambda f: int(self._pattern.match(f).group(1)))
        stale, keep = keep[: -self.cp_interval], keep[-self.cp_interval:]
        for fname in stale:
            try:
                os.remove(os.path.join(out, fname))
                self.stats["gc"] += 1
            except OSError:
                pass
        self._files = keep

    # -- consensus resume ---------------------------------------------------
    def maybe_load(self, trainer, optimizer=None, path=None):
        """Resume from the newest iteration *every* rank has a snapshot of.

        Reference semantics: local scan → allgather of iteration sets →
        max of the intersection → ``load_npz`` on each rank's own file.
        Returns the resumed iteration or None.
        """
        out = path or self._dir(trainer)
        local = self._scan(out)
        all_sets = self.comm.allgather_obj(sorted(local))
        common = set(all_sets[0])
        for s in all_sets[1:]:
            common &= set(s)
        if not common:
            return None
        iteration = max(common)
        load_npz(os.path.join(out, self._filename(iteration)), trainer,
                 strict=False)
        self._files = [self._filename(i) for i in sorted(local)]
        return iteration

    def _scan(self, out):
        iterations = set()
        if not os.path.isdir(out):
            return iterations
        for fname in os.listdir(out):
            m = self._pattern.match(fname)
            if m and int(m.group(2)) == self.rank:
                iterations.add(int(m.group(1)))
        return iterations

    def finalize(self):
        pass

    def serialize(self, serializer):
        pass
