"""Observation aggregation across ranks.

Reference: ``chainermn/extensions/observation_aggregator.py ·
ObservationAggregator`` (SURVEY.md §5 metrics note; chainer ≥ v6):
allreduce-averages chosen training observations each interval so rank-0
logs reflect the whole job.

Here the compiled multi-node train step already pmeans in-forward
observations across devices; this extension covers the *host* level
(multi-host metric agreement) and arbitrary host-computed observations.
"""

from __future__ import annotations

import numpy as np

from ..training.trainer import Extension, PRIORITY_EDITOR

__all__ = ["ObservationAggregator"]


class ObservationAggregator(Extension):
    trigger = (1, "iteration")
    priority = PRIORITY_EDITOR  # after writers, before readers (LogReport)

    def __init__(self, comm, original_key, aggregated_key=None,
                 aggregator=None):
        self.comm = comm
        self.original_key = original_key
        self.aggregated_key = aggregated_key or original_key
        self.aggregator = aggregator or (lambda xs: float(np.mean(xs)))

    def __call__(self, trainer):
        obs = trainer.observation
        if self.original_key not in obs:
            return
        value = float(np.asarray(obs[self.original_key]))
        gathered = self.comm.allgather_obj(value)
        obs[self.aggregated_key] = self.aggregator(gathered)
