"""Multi-node evaluator.

Reference: ``chainermn/evaluators.py · create_multi_node_evaluator``
(SURVEY.md §2.4): patches an ``Evaluator`` so every rank's local metric
dict is allreduce-averaged, making report/trigger logic behave identically
everywhere.

Single-controller translation: evaluation runs once per *host* over the
host's data shard; the average is taken across hosts (``allreduce_obj``
over DCN when multi-host; identity on one host, where local metrics
already cover all local devices' data).
"""

from __future__ import annotations

import numpy as np

__all__ = ["create_multi_node_evaluator"]


def create_multi_node_evaluator(actual_evaluator, communicator):
    """Patch ``actual_evaluator.evaluate`` in place (reference behavior:
    returns the same object with a wrapped ``evaluate``)."""

    actual_evaluator._mn_original_evaluate = actual_evaluator.evaluate
    actual_evaluator._mn_communicator = communicator

    def evaluate():
        local = actual_evaluator._mn_original_evaluate()
        comm = actual_evaluator._mn_communicator
        gathered = comm.allgather_obj({k: float(np.asarray(v))
                                       for k, v in local.items()})
        keys = set()
        for d in gathered:
            keys.update(d)
        return {k: float(np.mean([d[k] for d in gathered if k in d]))
                for k in keys}

    actual_evaluator.evaluate = evaluate
    return actual_evaluator
