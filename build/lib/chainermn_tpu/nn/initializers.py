"""Weight initializers (consumed-Chainer surface: ``chainer.initializers``).

Reference anchors: ``chainer/initializers/ · LeCunNormal/GlorotUniform/
HeNormal/Normal/Uniform/Constant/Zero/One`` (SURVEY.md §2.8).  Implemented as
plain callables ``(shape, dtype, rng) -> np.ndarray`` evaluated eagerly on
host at link construction; the resulting arrays become ``jax.Array`` leaves.
"""

from __future__ import annotations

import numpy as np

__all__ = ["Initializer", "Normal", "Uniform", "Constant", "Zero", "One",
           "LeCunNormal", "GlorotNormal", "GlorotUniform", "HeNormal",
           "HeUniform", "Orthogonal", "Identity", "_get_initializer"]


def _fans(shape):
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[1], shape[0]
    # conv kernels (out_ch, in_ch, kh, kw)
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    dtype = None

    def __call__(self, shape, dtype=np.float32, rng=None):
        raise NotImplementedError


class Normal(Initializer):
    def __init__(self, scale=0.05):
        self.scale = scale

    def __call__(self, shape, dtype=np.float32, rng=None):
        rng = rng or np.random
        return rng.normal(0.0, self.scale, size=shape).astype(dtype)


class Uniform(Initializer):
    def __init__(self, scale=0.05):
        self.scale = scale

    def __call__(self, shape, dtype=np.float32, rng=None):
        rng = rng or np.random
        return rng.uniform(-self.scale, self.scale, size=shape).astype(dtype)


class Constant(Initializer):
    def __init__(self, fill_value=0.0):
        self.fill_value = fill_value

    def __call__(self, shape, dtype=np.float32, rng=None):
        return np.full(shape, self.fill_value, dtype=dtype)


class Zero(Constant):
    def __init__(self):
        super().__init__(0.0)


class One(Constant):
    def __init__(self):
        super().__init__(1.0)


class LeCunNormal(Initializer):
    def __init__(self, scale=1.0):
        self.scale = scale

    def __call__(self, shape, dtype=np.float32, rng=None):
        rng = rng or np.random
        fan_in, _ = _fans(shape)
        s = self.scale * np.sqrt(1.0 / fan_in)
        return rng.normal(0.0, s, size=shape).astype(dtype)


class GlorotNormal(Initializer):
    def __init__(self, scale=1.0):
        self.scale = scale

    def __call__(self, shape, dtype=np.float32, rng=None):
        rng = rng or np.random
        fan_in, fan_out = _fans(shape)
        s = self.scale * np.sqrt(2.0 / (fan_in + fan_out))
        return rng.normal(0.0, s, size=shape).astype(dtype)


class GlorotUniform(Initializer):
    def __init__(self, scale=1.0):
        self.scale = scale

    def __call__(self, shape, dtype=np.float32, rng=None):
        rng = rng or np.random
        fan_in, fan_out = _fans(shape)
        s = self.scale * np.sqrt(6.0 / (fan_in + fan_out))
        return rng.uniform(-s, s, size=shape).astype(dtype)


class HeNormal(Initializer):
    def __init__(self, scale=1.0):
        self.scale = scale

    def __call__(self, shape, dtype=np.float32, rng=None):
        rng = rng or np.random
        fan_in, _ = _fans(shape)
        s = self.scale * np.sqrt(2.0 / fan_in)
        return rng.normal(0.0, s, size=shape).astype(dtype)


class HeUniform(Initializer):
    def __init__(self, scale=1.0):
        self.scale = scale

    def __call__(self, shape, dtype=np.float32, rng=None):
        rng = rng or np.random
        fan_in, _ = _fans(shape)
        s = self.scale * np.sqrt(6.0 / fan_in)
        return rng.uniform(-s, s, size=shape).astype(dtype)


class Orthogonal(Initializer):
    def __init__(self, scale=1.0):
        self.scale = scale

    def __call__(self, shape, dtype=np.float32, rng=None):
        rng = rng or np.random
        flat = (shape[0], int(np.prod(shape[1:])) if len(shape) > 1 else 1)
        a = rng.normal(0.0, 1.0, size=flat)
        q, r = np.linalg.qr(a if flat[0] >= flat[1] else a.T)
        q = q * np.sign(np.diag(r))
        if flat[0] < flat[1]:
            q = q.T
        return (self.scale * q.reshape(shape)).astype(dtype)


class Identity(Initializer):
    def __init__(self, scale=1.0):
        self.scale = scale

    def __call__(self, shape, dtype=np.float32, rng=None):
        assert len(shape) == 2 and shape[0] == shape[1]
        return (self.scale * np.eye(shape[0])).astype(dtype)


def _get_initializer(initializer, default=None):
    if initializer is None:
        return default or LeCunNormal()
    if isinstance(initializer, Initializer) or callable(initializer):
        return initializer
    if np.isscalar(initializer):
        return Constant(initializer)
    arr = np.asarray(initializer)
    return lambda shape, dtype=np.float32, rng=None: arr.astype(dtype)
