"""Recurrent links (consumed-Chainer surface: ``chainer.links`` RNNs).

Reference anchors: ``chainer/links/connection/n_step_lstm.py ·
NStepLSTM``, ``n_step_gru.py · NStepGRU``, ``gru.py · GRU/StatelessGRU``
(SURVEY.md §2.8 — the seq2seq example family consumes these).

TPU-first formulation: every cell packs its gates into one GEMM; whole
sequences run as a single ``lax.scan`` (batch-major [B, T, D] API, the
scan is time-major internally).  Unlike the reference's cuDNN-backed
NStep links which take ragged per-example lists, these take padded
batches with an optional length mask — the static-shape contract XLA
needs; ``chainermn_tpu.models.seq2seq`` shows the padding convention.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ..core.link import Chain, ChainList
from . import functions as F
from .links import Linear, StatelessLSTM

__all__ = ["StatelessGRU", "GRU", "NStepLSTM", "NStepGRU"]


class StatelessGRU(Chain):
    """One GRU step: (h, x) -> h  (reference: ``L.StatelessGRU``)."""

    def __init__(self, in_size, out_size, seed=None):
        super().__init__()
        self.out_size = out_size
        s = (lambda k: None if seed is None else seed + k)
        with self.init_scope():
            # [reset, update] gates fused; candidate separate (its lateral
            # term is gated by r before the matmul)
            self.w_rz = Linear(in_size, 2 * out_size, seed=s(0))
            self.u_rz = Linear(out_size, 2 * out_size, nobias=True,
                               seed=s(1))
            self.w_h = Linear(in_size, out_size, seed=s(2))
            self.u_h = Linear(out_size, out_size, nobias=True, seed=s(3))

    def forward(self, h, x):
        if h is None:
            h = jnp.zeros((x.shape[0], self.out_size), x.dtype)
        rz = F.sigmoid(self.w_rz(x) + self.u_rz(h))
        r, z = jnp.split(rz, 2, axis=1)
        h_bar = F.tanh(self.w_h(x) + self.u_h(r * h))
        return (1 - z) * h + z * h_bar


class GRU(StatelessGRU):
    """Stateful GRU (reference: ``L.GRU``)."""

    _volatile_attrs = ("h",)

    def __init__(self, in_size, out_size, seed=None):
        super().__init__(in_size, out_size, seed=seed)
        self.h = None

    def reset_state(self):
        self.h = None

    def set_state(self, h):
        self.h = h

    def forward(self, x):
        self.h = super().forward(self.h, x)
        return self.h


def _mask_step(new, old, mask_t):
    return jnp.where(mask_t[:, None], new, old)


class _NStepRNNBase(ChainList):
    def __init__(self, n_layers, in_size, out_size, cell_factory, seed=0):
        cells = []
        for i in range(n_layers):
            cells.append(cell_factory(in_size if i == 0 else out_size,
                                      out_size, seed + 10 * i))
        super().__init__(*cells)
        self.n_layers = n_layers
        self.out_size = out_size


class NStepLSTM(_NStepRNNBase):
    """Multi-layer LSTM over padded sequences.

    ``forward(hx, cx, xs, mask=None)``: xs [B, T, D]; hx/cx [L, B, H] or
    None; mask [B, T] bool (True = valid).  Returns (hy, cy, ys) with ys
    [B, T, H] — the reference's (hy, cy, ys) contract on padded batches.
    """

    def __init__(self, n_layers, in_size, out_size, dropout=0.0, seed=0):
        super().__init__(n_layers, in_size, out_size,
                         lambda i, o, s: StatelessLSTM(i, o, seed=s), seed)
        self.dropout = dropout

    def forward(self, hx, cx, xs, mask=None):
        B, T, _ = xs.shape
        L, H = self.n_layers, self.out_size
        hx = jnp.zeros((L, B, H), xs.dtype) if hx is None else hx
        cx = jnp.zeros((L, B, H), xs.dtype) if cx is None else cx
        mask_t = (jnp.ones((B, T), bool) if mask is None else mask)
        h_seq = xs
        hy, cy = [], []
        for layer, cell in enumerate(self):
            if layer > 0 and self.dropout:
                # reference semantics: inter-layer dropout during training
                h_seq = F.dropout(h_seq, self.dropout)
            def step(carry, inp):
                c, h = carry
                x_t, m_t = inp
                c_new, h_new = cell(c, h, x_t)
                c = _mask_step(c_new, c, m_t)
                h = _mask_step(h_new, h, m_t)
                return (c, h), h
            (c_f, h_f), ys = lax.scan(
                step, (cx[layer], hx[layer]),
                (jnp.swapaxes(h_seq, 0, 1), jnp.swapaxes(mask_t, 0, 1)))
            h_seq = jnp.swapaxes(ys, 0, 1)
            hy.append(h_f)
            cy.append(c_f)
        return jnp.stack(hy), jnp.stack(cy), h_seq


class NStepGRU(_NStepRNNBase):
    """Multi-layer GRU over padded sequences: ``forward(hx, xs, mask)`` →
    (hy, ys)."""

    def __init__(self, n_layers, in_size, out_size, dropout=0.0, seed=0):
        super().__init__(n_layers, in_size, out_size,
                         lambda i, o, s: StatelessGRU(i, o, seed=s), seed)
        self.dropout = dropout

    def forward(self, hx, xs, mask=None):
        B, T, _ = xs.shape
        L, H = self.n_layers, self.out_size
        hx = jnp.zeros((L, B, H), xs.dtype) if hx is None else hx
        mask_t = (jnp.ones((B, T), bool) if mask is None else mask)
        h_seq = xs
        hy = []
        for layer, cell in enumerate(self):
            if layer > 0 and self.dropout:
                h_seq = F.dropout(h_seq, self.dropout)
            def step(h, inp):
                x_t, m_t = inp
                h_new = cell(h, x_t)
                h = _mask_step(h_new, h, m_t)
                return h, h
            h_f, ys = lax.scan(
                step, hx[layer],
                (jnp.swapaxes(h_seq, 0, 1), jnp.swapaxes(mask_t, 0, 1)))
            h_seq = jnp.swapaxes(ys, 0, 1)
            hy.append(h_f)
        return jnp.stack(hy), h_seq
