from . import functions
from . import initializers
from . import links
