from .platform import use_platform, simulate_devices
from .profiling import trace, annotate, Profile
