"""Platform selection helpers.

This machine's interpreter boots with the axon TPU plugin registered by a
``sitecustomize`` (JAX_PLATFORMS=axon baked in before any user code), so
ordinary ``JAX_PLATFORMS=cpu`` env overrides are ineffective —
``jax.config.update`` after import is the reliable lever.  Used by the
examples' ``--platform`` flags and the test conftest.
"""

from __future__ import annotations

import os

import jax

__all__ = ["use_platform", "simulate_devices"]


def use_platform(name: str | None):
    """Pin the JAX platform ('cpu'/'tpu'/'axon'); None keeps the default."""
    if name:
        jax.config.update("jax_platforms", name)


def simulate_devices(n: int):
    """Request n simulated host devices (effective only before the CPU
    backend first initializes — call early)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={n} " + flags)
