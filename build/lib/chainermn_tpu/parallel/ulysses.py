"""Ulysses-style sequence parallelism — all_to_all head exchange.

Reference status: **absent** in ChainerMN (SURVEY.md §2.6); SURVEY §5
names the differentiable ``alltoall`` as the Ulysses-shaped primitive.

The sequence axis is sharded across ranks; for attention, an
``all_to_all`` re-shards from sequence-split [B, H, T/n, D] to head-split
[B, H/n, T, D], full attention runs per local head group over the whole
sequence, and a reverse ``all_to_all`` restores sequence sharding.  Two
collectives per attention layer, each moving activations once — the
bandwidth-optimal exchange when H ≥ n.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

__all__ = ["ulysses_attention", "seq_to_head_shard", "head_to_seq_shard"]


def seq_to_head_shard(comm, x):
    """[B, H, T_local, D] (sequence-sharded) → [B, H/n, T, D] (head-sharded)."""
    size = comm.size
    B, H, Tl, D = x.shape
    if H % size != 0:
        raise ValueError(f"head count {H} not divisible by axis size {size}")
    return lax.all_to_all(x, comm.axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def head_to_seq_shard(comm, x):
    """[B, H/n, T, D] (head-sharded) → [B, H, T_local, D] (sequence-sharded)."""
    return lax.all_to_all(x, comm.axis_name, split_axis=2, concat_axis=1,
                          tiled=True)


def _full_attention(q, k, v, causal, scale):
    scores = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32),
                        preferred_element_type=jnp.float32) * scale
    if causal:
        T = scores.shape[-1]
        qpos = lax.broadcasted_iota(jnp.int32, (T, T), 0)
        kpos = lax.broadcasted_iota(jnp.int32, (T, T), 1)
        scores = jnp.where((qpos >= kpos)[None, None], scores, -jnp.inf)
    p = jnp.exp(scores - jnp.max(scores, axis=-1, keepdims=True))
    p = p / jnp.sum(p, axis=-1, keepdims=True)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32),
                      preferred_element_type=jnp.float32)


def ulysses_attention(comm, q, k, v, causal=False, scale=None):
    """Exact attention with Ulysses sequence parallelism.

    Inputs rank-local [B, H, T_local, D] sequence shards; output the same.
    Identical math to full attention on the gathered sequence.
    """
    D = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    qh = seq_to_head_shard(comm, q)
    kh = seq_to_head_shard(comm, k)
    vh = seq_to_head_shard(comm, v)
    out = _full_attention(qh, kh, vh, causal, scale).astype(q.dtype)
    return head_to_seq_shard(comm, out)
