"""Expert parallelism — Switch-style mixture-of-experts over all_to_all.

Reference status: **absent** in ChainerMN (SURVEY.md §2.6 EP row: "not
required for parity; all_to_all primitive should still be first-class").
This module is the beyond-parity realization: experts are sharded one (or
more) per rank along the communicator axis; tokens are routed top-1
(Switch Transformer) with fixed per-expert capacity, exchanged with one
``all_to_all``, transformed by the local expert's fused GEMMs, and
returned by the reverse ``all_to_all`` — two collectives per MoE layer,
the canonical EP pattern.

Static shapes throughout (capacity-bounded dispatch with drop/pad), so
XLA compiles one program regardless of routing decisions; gradients flow
through the combine weights (straight-through on the router probability).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["switch_moe", "moe_dispatch_combine"]


def _one_hot_capacity(expert_idx, n_experts, capacity):
    """Position-in-expert assignment with capacity truncation.

    Returns (dispatch_mask [T, E, C] bool, position [T]) — token t goes to
    slot ``position[t]`` of its expert's buffer unless over capacity
    (dropped: contributes zero output, gradient flows only via the
    router's load-balancing loss).
    """
    T = expert_idx.shape[0]
    onehot = jax.nn.one_hot(expert_idx, n_experts, dtype=jnp.int32)  # [T,E]
    # position of each token within its expert's queue
    position = jnp.cumsum(onehot, axis=0) * onehot  # [T, E]
    position = position.sum(axis=1) - 1             # [T]
    keep = position < capacity
    pos_cap = jnp.clip(position, 0, capacity - 1)
    dispatch = (jax.nn.one_hot(expert_idx, n_experts, dtype=jnp.bool_)
                [:, :, None]
                & jax.nn.one_hot(pos_cap, capacity, dtype=jnp.bool_)
                [:, None, :]
                & keep[:, None, None])
    return dispatch, keep


def moe_dispatch_combine(comm, x, gate_logits, expert_fn,
                         capacity_factor=1.25):
    """Route rank-local tokens through rank-sharded experts.

    ``x``: [T_local, D] tokens on this rank; ``gate_logits``: [T_local, E]
    with E == comm.size (one expert per rank); ``expert_fn(h)`` applies
    this rank's expert to [E*C', D]... returns same shape.  Returns
    ([T_local, D] combined output, aux dict with load-balancing stats).
    """
    axis = comm.axis_name
    E = comm.size
    T, D = x.shape
    capacity = max(1, int(capacity_factor * T / E))

    probs = jax.nn.softmax(gate_logits, axis=-1)            # [T, E]
    expert_idx = jnp.argmax(probs, axis=-1)                  # [T]
    gate = jnp.take_along_axis(probs, expert_idx[:, None], 1)[:, 0]  # [T]

    dispatch, keep = _one_hot_capacity(expert_idx, E, capacity)

    # [E, C, D] buffer of tokens headed to each expert
    send = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), x)
    # exchange: slot e of every rank converges on rank e
    recv = lax.all_to_all(send, axis, split_axis=0, concat_axis=0,
                          tiled=False)                      # [E, C, D]
    # local expert processes all ranks' contributions
    h = expert_fn(recv.reshape(E * capacity, D)).reshape(E, capacity, D)
    # return trip
    back = lax.all_to_all(h, axis, split_axis=0, concat_axis=0,
                          tiled=False)                      # [E, C, D]
    combined = jnp.einsum("tec,ecd->td", dispatch.astype(x.dtype), back)
    combined = combined * gate[:, None]

    # Switch load-balancing loss: E * sum_e fraction_e * mean_prob_e
    frac = jnp.mean(dispatch.any(axis=2).astype(jnp.float32), axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux_loss = E * jnp.sum(frac * mean_prob)
    return combined, {"aux_loss": aux_loss,
                      "dropped": 1.0 - jnp.mean(keep.astype(jnp.float32)),
                      "capacity": capacity}


def switch_moe(comm, x, router_w, w_in, b_in, w_out, b_out,
               capacity_factor=1.25, activation=jax.nn.gelu):
    """Complete Switch-MoE layer: router + rank-local expert MLP.

    ``x``: [T_local, D].  ``router_w``: [D, E] (replicated).  ``w_in``:
    this rank's expert weights [D, H]; ``w_out``: [H, D] (shard the
    stacked [E, ...] expert bank with ``P(axis)``).  Returns
    ([T_local, D], aux).
    """
    gate_logits = x @ router_w

    def expert_fn(h):
        return activation(h @ w_in + b_in) @ w_out + b_out

    return moe_dispatch_combine(comm, x, gate_logits, expert_fn,
                                capacity_factor=capacity_factor)
