"""Ring attention — sequence/context parallelism over a mesh axis.

Reference status: **absent** in ChainerMN (SURVEY.md §2.6: SP/CP row —
"rebuild extension"); SURVEY §5 long-context note prescribes ring
attention via ppermute KV rotation built on the L3 primitives.

Design (blockwise ring attention, Liu et al.-style): the sequence is
sharded over the communicator axis ([B, H, T/n, D] per rank).  Each rank
keeps its query block resident and rotates K/V blocks around the ring
with ``lax.ppermute`` (ICI neighbor exchanges); partial attention is
accumulated with the numerically-stable online-softmax recurrence
(running max ``m``, normalizer ``l``, weighted accumulator) so the result
is exact — identical to full attention on the gathered sequence — while
no rank ever materializes more than one remote KV block.  Peak memory is
O(T/n), and XLA overlaps each step's ppermute with the previous block's
matmuls.

Causal masking is chunk-aware: a KV block strictly in the future is
skipped-by-masking, the diagonal block gets the triangular mask, past
blocks attend fully.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

__all__ = ["ring_self_attention", "ring_attention"]


def _block_attention(q, k, v, m, l, acc, mask, scale):
    """One online-softmax accumulation step for a KV block."""
    # q: [B, H, Tq, D]; k/v: [B, H, Tk, D]
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    scores = jnp.where(mask, scores, -jnp.inf)
    m_block = jnp.max(scores, axis=-1, keepdims=True)     # [B,H,Tq,1]
    m_new = jnp.maximum(m, m_block)
    # all-masked blocks produce -inf maxima; keep the recurrence finite
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(scores - m_safe)
    p = jnp.where(mask, p, 0.0)
    correction = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
    l_new = l * correction + jnp.sum(p, axis=-1, keepdims=True)
    acc_new = acc * correction + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(jnp.float32),
        preferred_element_type=jnp.float32)
    return m_new, l_new, acc_new


def ring_self_attention(comm, q, k, v, causal=False, scale=None):
    """Exact self-attention over a sequence sharded on ``comm``'s axis.

    ``q``/``k``/``v``: rank-local [B, H, T_local, D] (call inside a
    ``shard_map`` over the axis, e.g. via ``comm.run_spmd`` with specs
    splitting the T dimension).  Returns the local [B, H, T_local, D]
    output block.
    """
    axis = comm.axis_name
    size = comm.size
    B, H, Tq, D = q.shape
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    my_chunk = lax.axis_index(axis)
    perm = [(i, (i + 1) % size) for i in range(size)]

    q32 = q.astype(jnp.float32)
    m = jnp.full((B, H, Tq, 1), -jnp.inf, jnp.float32)
    l = jnp.zeros((B, H, Tq, 1), jnp.float32)
    acc = jnp.zeros((B, H, Tq, D), jnp.float32)

    q_pos = my_chunk * Tq + lax.broadcasted_iota(jnp.int32, (Tq, 1), 0)

    def step(carry, step_idx):
        k_cur, v_cur, m, l, acc = carry
        # KV block currently held arrived from rank (me - step) mod size
        kv_chunk = (my_chunk - step_idx) % size
        Tk = k_cur.shape[2]
        if causal:
            kv_pos = kv_chunk * Tk + lax.broadcasted_iota(
                jnp.int32, (1, Tk), 1)
            mask = (q_pos >= kv_pos)[None, None]          # [1,1,Tq,Tk]
        else:
            mask = jnp.ones((1, 1, Tq, Tk), bool)
        m, l, acc = _block_attention(q32, k_cur.astype(jnp.float32),
                                     v_cur, m, l, acc, mask, scale)
        # rotate KV to the next rank (no-op effect on the last step's
        # carry, but keeps the loop uniform; XLA overlaps it with compute)
        k_next = lax.ppermute(k_cur, axis, perm)
        v_next = lax.ppermute(v_cur, axis, perm)
        return (k_next, v_next, m, l, acc), None

    (k_f, v_f, m, l, acc), _ = lax.scan(
        step, (k, v, m, l, acc), jnp.arange(size))
    out = acc / jnp.maximum(l, 1e-30)
    return out.astype(q.dtype)


def ring_attention(comm, q, k, v, causal=False, scale=None):
    """Cross-attention variant: same rotation, ``q`` and KV may have
    different local lengths."""
    return ring_self_attention(comm, q, k, v, causal=causal, scale=scale)
