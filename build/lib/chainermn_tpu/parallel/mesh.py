"""Mesh construction helpers for hybrid parallelism.

Reference: hybrid DP×MP via ``CommunicatorBase.split`` + two communicators
(SURVEY.md §2.6).  The TPU idiom is one N-D mesh with named axes; these
helpers build it and hand back per-axis communicators so reference-shaped
code keeps working.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..communicators.mesh_communicator import MeshCommunicator

__all__ = ["make_mesh", "axis_communicators", "shard_batch", "replicate"]


def make_mesh(axis_sizes: dict, devices=None) -> Mesh:
    """``make_mesh({'data': 4, 'model': 2})`` over the device list.

    One axis size may be -1 (inferred).  Device order follows
    ``jax.devices()`` — on real pods, order devices so the fastest-moving
    axis rides ICI neighbors.
    """
    devices = list(devices) if devices is not None else list(jax.devices())
    names = list(axis_sizes)
    sizes = [axis_sizes[n] for n in names]
    unknown = [i for i, s in enumerate(sizes) if s == -1]
    if len(unknown) > 1:
        raise ValueError("at most one axis may be -1")
    known = int(np.prod([s for s in sizes if s != -1]))
    if unknown:
        if len(devices) % known:
            raise ValueError(
                f"{len(devices)} devices not divisible by {known}")
        sizes[unknown[0]] = len(devices) // known
    if int(np.prod(sizes)) != len(devices):
        raise ValueError(
            f"mesh {dict(zip(names, sizes))} != {len(devices)} devices")
    return Mesh(np.asarray(devices).reshape(sizes), tuple(names))


def axis_communicators(mesh: Mesh, **kwargs) -> dict:
    """One communicator per mesh axis (hybrid DP×MP×SP wiring)."""
    return {name: MeshCommunicator.from_mesh_axis(mesh, name, **kwargs)
            for name in mesh.axis_names}


def shard_batch(x, mesh: Mesh, axis: str):
    """Place a host batch sharded along ``axis`` on its leading dim."""
    spec = P(axis)
    return jax.device_put(x, NamedSharding(mesh, spec))


def replicate(x, mesh: Mesh):
    return jax.device_put(x, NamedSharding(mesh, P()))
