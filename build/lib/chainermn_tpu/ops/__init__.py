"""Pallas TPU kernels for hot ops (the rebuild's N2/N3 escape hatch)."""

from .flash_attention import attention, flash_attention, xla_attention

__all__ = ["attention", "flash_attention", "xla_attention"]
