"""Sequence-to-sequence NMT model (BASELINE configs #3/#4).

Reference capability: ChainerMN ``examples/seq2seq/seq2seq.py`` (encoder/
decoder LSTM NMT on WMT) and its model-parallel enc/dec split via
``MultiNodeChainList`` (SURVEY.md §2.3, §3.3).  TPU-first design: the
recurrence is a ``lax.scan`` over a packed-gate LSTM cell (one MXU GEMM
per step), batch-major static shapes, teacher forcing in a single
compiled program — no per-token Python.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

from ..core.link import Chain
from ..nn import functions as F
from ..nn import links as L
from ..links import MultiNodeChainList

__all__ = ["Seq2seq", "Encoder", "Decoder", "create_model_parallel_seq2seq"]

PAD = -1


def _scan_lstm(cell, xs, c0=None, h0=None, reverse=False):
    """Run a StatelessLSTM over [B, T, D] with lax.scan (time-major scan)."""
    B = xs.shape[0]
    H = cell.out_size
    c0 = jnp.zeros((B, H), xs.dtype) if c0 is None else c0
    h0 = jnp.zeros((B, H), xs.dtype) if h0 is None else h0
    xs_t = jnp.swapaxes(xs, 0, 1)  # [T, B, D]

    def step(carry, x_t):
        c, h = carry
        c, h = cell(c, h, x_t)
        return (c, h), h

    (c, h), hs = lax.scan(step, (c0, h0), xs_t, reverse=reverse)
    return c, h, jnp.swapaxes(hs, 0, 1)  # [B, T, H]


class Encoder(Chain):
    """n-layer LSTM encoder (reference example: 3-layer NStepLSTM).

    PAD positions freeze the recurrent state (length masking), so the
    final state reflects each sequence's true last token.
    """

    def __init__(self, n_vocab, n_units, n_layers=1, seed=0):
        super().__init__()
        with self.init_scope():
            self.embed = L.EmbedID(n_vocab, n_units, ignore_label=PAD,
                                   seed=seed)
            self.lstm = L.NStepLSTM(n_layers, n_units, n_units,
                                    seed=seed + 1)

    def forward(self, xs):
        """xs: int [B, T] (PAD-padded) → state stacked [2, L, B, H]."""
        emb = self.embed(xs)
        hy, cy, _ = self.lstm(None, None, emb, mask=(xs != PAD))
        return jnp.stack([cy, hy])


class Decoder(Chain):
    def __init__(self, n_vocab, n_units, n_layers=1, seed=10):
        super().__init__()
        self.n_units = n_units
        with self.init_scope():
            self.embed = L.EmbedID(n_vocab, n_units, ignore_label=PAD,
                                   seed=seed)
            self.lstm = L.NStepLSTM(n_layers, n_units, n_units,
                                    seed=seed + 1)
            self.out = L.Linear(n_units, n_vocab, seed=seed + 2)

    def forward(self, state, ys_in, ys_out):
        """Teacher-forced loss.  state: [2, L, B, H] from the encoder."""
        cx, hx = state[0], state[1]
        emb = self.embed(ys_in)
        _, _, hs = self.lstm(hx, cx, emb)
        logits = self.out(hs.reshape(-1, self.n_units))
        loss = F.softmax_cross_entropy(logits, ys_out.reshape(-1),
                                       ignore_label=PAD)
        return loss

    def step_tokens(self, c, h, tok):
        """One greedy-decoding step through all layers: (c, h [L,B,H],
        tok [B]) → (c, h, next_tok)."""
        inp = self.embed(tok)
        new_c, new_h = [], []
        for layer, cell in enumerate(self.lstm):
            c_l, h_l = cell(c[layer], h[layer], inp)
            new_c.append(c_l)
            new_h.append(h_l)
            inp = h_l
        logits = self.out(inp)
        tok = jnp.argmax(logits, axis=1).astype(jnp.int32)
        return jnp.stack(new_c), jnp.stack(new_h), tok


class Seq2seq(Chain):
    """Single-process encoder-decoder (reference example model shape)."""

    def __init__(self, n_source_vocab, n_target_vocab, n_units,
                 n_layers=1, seed=0):
        super().__init__()
        with self.init_scope():
            self.encoder = Encoder(n_source_vocab, n_units,
                                   n_layers=n_layers, seed=seed)
            self.decoder = Decoder(n_target_vocab, n_units,
                                   n_layers=n_layers, seed=seed + 100)

    def forward(self, xs, ys_in, ys_out):
        from ..core import reporter
        state = self.encoder(xs)
        loss = self.decoder(state, ys_in, ys_out)
        reporter.report({"loss": loss}, self)
        return loss

    def translate(self, xs, bos_id, eos_id, max_length=32):
        """Greedy decoding as one compiled scan (inference path)."""
        state = self.encoder(xs)
        c, h = state[0], state[1]
        B = xs.shape[0]
        tok0 = jnp.full((B,), bos_id, jnp.int32)

        def step(carry, _):
            c, h, tok = carry
            c, h, tok = self.decoder.step_tokens(c, h, tok)
            return (c, h, tok), tok

        _, toks = lax.scan(step, (c, h, tok0), None, length=max_length)
        return jnp.swapaxes(toks, 0, 1)  # [B, max_length]


class _EncoderComponent(Chain):
    def __init__(self, encoder):
        super().__init__()
        with self.init_scope():
            self.encoder = encoder

    def forward(self, xs, ys_in, ys_out):
        return self.encoder(xs)


class _DecoderWrapper(Chain):
    def __init__(self, decoder):
        super().__init__()
        with self.init_scope():
            self.decoder = decoder

    def forward(self, state, xs, ys_in, ys_out):
        # receives the encoder state over the stage edge plus the original
        # call inputs (pass_inputs=True); xs is the encoder's input, unused
        return self.decoder(state, ys_in, ys_out)


class ModelParallelSeq2seq(MultiNodeChainList):
    """Enc/dec split across two stage ranks (reference: the seq2seq
    model-parallel example; BASELINE config #4).

    The encoder's [2, B, H] state crosses the stage edge via the
    differentiable send/recv pair; the decoder's loss is the terminal
    output, broadcast to all ranks.
    """

    def __init__(self, comm, n_source_vocab, n_target_vocab, n_units,
                 rank_encoder=0, rank_decoder=1, n_layers=1, seed=0):
        super().__init__(comm)
        enc = Encoder(n_source_vocab, n_units, n_layers=n_layers, seed=seed)
        dec = Decoder(n_target_vocab, n_units, n_layers=n_layers,
                      seed=seed + 100)
        self._enc_component = _EncoderComponent(enc)
        self._dec_component = _DecoderWrapper(dec)
        self.add_link(self._enc_component, rank_in=None,
                      rank_out=rank_decoder, rank=rank_encoder)
        self.add_link(self._dec_component, rank_in=rank_encoder,
                      rank_out=None, rank=rank_decoder, pass_inputs=True)

    def forward(self, xs, ys_in, ys_out):
        from ..core import reporter
        loss = super().forward(xs, ys_in, ys_out)
        reporter.report({"loss": loss}, self)
        return loss


def create_model_parallel_seq2seq(comm, n_source_vocab, n_target_vocab,
                                  n_units, **kwargs):
    return ModelParallelSeq2seq(comm, n_source_vocab, n_target_vocab,
                                n_units, **kwargs)


def make_synthetic_translation_data(n=256, src_vocab=40, tgt_vocab=40,
                                    max_len=12, seed=0):
    """Deterministic toy translation task: target = reversed source mapped
    through a fixed permutation (learnable; no network access)."""
    rng = np.random.RandomState(seed)
    perm = rng.permutation(tgt_vocab - 3) + 3  # reserve 0=bos,1=eos,2=unk
    xs = np.full((n, max_len), PAD, np.int32)
    ys_in = np.full((n, max_len + 1), PAD, np.int32)
    ys_out = np.full((n, max_len + 1), PAD, np.int32)
    for i in range(n):
        length = rng.randint(3, max_len + 1)
        src = rng.randint(3, src_vocab, size=length)
        tgt = perm[(src[::-1] - 3) % (tgt_vocab - 3)]
        xs[i, :length] = src
        ys_in[i, 0] = 0
        ys_in[i, 1:length + 1] = tgt
        ys_out[i, :length] = tgt
        ys_out[i, length] = 1
    return xs, ys_in, ys_out
