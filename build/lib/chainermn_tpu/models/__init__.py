"""Model zoo (reference example model families, TPU-first designs)."""

from .mlp import MLP, Classifier
from .resnet import (ResNet, ResNet18, ResNet50, ResNet101,
                     BottleneckBlock, BasicBlock)
from .seq2seq import (Seq2seq, Encoder, Decoder, ModelParallelSeq2seq,
                      create_model_parallel_seq2seq,
                      make_synthetic_translation_data)
from .dcgan import Generator, Discriminator, DCGANUpdater
from .transformer import TransformerLM, TransformerBlock, MultiHeadAttention
from .moe_transformer import (MoETransformerLM, MoETransformerBlock,
                              MoEFeedForward)
from .convnets import AlexNet, NIN, VGG16, GoogLeNet

__all__ = ["MLP", "Classifier", "ResNet", "ResNet18", "ResNet50",
           "ResNet101", "BottleneckBlock", "BasicBlock", "Seq2seq",
           "Encoder", "Decoder", "ModelParallelSeq2seq",
           "create_model_parallel_seq2seq",
           "make_synthetic_translation_data", "Generator", "Discriminator",
           "DCGANUpdater", "TransformerLM", "TransformerBlock",
           "MultiHeadAttention", "MoETransformerLM", "MoETransformerBlock",
           "MoEFeedForward", "AlexNet", "NIN", "VGG16", "GoogLeNet"]
