"""MLP + Classifier (BASELINE config #1 model; reference: the mnist
example MLP and ``chainer.links.Classifier``)."""

from __future__ import annotations

from ..core.link import Chain
from ..core import reporter
from ..nn import functions as F
from ..nn import links as L

__all__ = ["MLP", "Classifier"]


class MLP(Chain):
    def __init__(self, n_units=1000, n_out=10, seed=0):
        super().__init__()
        with self.init_scope():
            self.l1 = L.Linear(None, n_units, seed=seed)
            self.l2 = L.Linear(None, n_units,
                               seed=None if seed is None else seed + 1)
            self.l3 = L.Linear(None, n_out,
                               seed=None if seed is None else seed + 2)

    def forward(self, x):
        h = F.relu(self.l1(x))
        h = F.relu(self.l2(h))
        return self.l3(h)


class Classifier(Chain):
    """Loss head (reference: ``L.Classifier``): wraps a predictor,
    reports loss/accuracy."""

    def __init__(self, predictor, lossfun=F.softmax_cross_entropy,
                 accfun=F.accuracy):
        super().__init__()
        self.lossfun = lossfun
        self.accfun = accfun
        with self.init_scope():
            self.predictor = predictor

    def forward(self, *args):
        *inputs, t = args
        y = self.predictor(*inputs)
        loss = self.lossfun(y, t)
        if self.accfun is not None:
            reporter.report({"loss": loss,
                             "accuracy": self.accfun(y, t)}, self)
        else:
            reporter.report({"loss": loss}, self)
        return loss
