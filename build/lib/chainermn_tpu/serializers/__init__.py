from .npz import (DictionarySerializer, NpzDeserializer, save_npz, load_npz)
