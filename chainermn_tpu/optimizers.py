"""Multi-node optimizer wrappers.

Reference: ``chainermn/optimizers.py · _MultiNodeOptimizer,
_DoubleBufferingOptimizer, create_multi_node_optimizer`` (SURVEY.md §2.4,
call stack §3.2).

The reference interposes ``communicator.allreduce_grad(target)`` between
``loss.backward()`` and ``optimizer.update()`` as a separate host-driven
step (pack kernel → NCCL → unpack kernel).  Here the *entire* data-parallel
step — per-rank forward/backward on the local batch shard, gradient mean
over the communicator axis (optionally dtype-compressed / flat- or
size-bounded-bucketed, per the communicator's ``batch_collectives``),
and the optax update — is one ``shard_map``ped, jit-compiled program:
SURVEY §3.2's "this whole stack becomes ONE train_step".  XLA's
async-collective scheduler overlaps the gradient collectives with
remaining backward compute; the ``"bucketed"`` exchange hands it K
independently schedulable units instead of one monolithic transfer
(docs/performance.md §7, tools/comm_budgets.json).

``exchange="reduce_scatter"`` replaces the allreduce-then-replicated-
update structure with ``reduce_scatter(grads) → shard-local update →
all_gather(params)``: per-replica exchanged gradient bytes are halved
(the gradient crosses the wire once), the optimizer state lives
shard-local, and — unlike ``zero_sharding`` — it composes with double
buffering (the stale buffer is the 1/n mean-gradient chunk).

On a HIERARCHICAL communicator (ISSUE 6: a real (dcn, ici) two-level
mesh) every exchange composes with the topology: the allreduce path's
``grad_transform`` runs intra-host reduce-scatter → DCN chunk
allreduce → intra-host all-gather per bucket, and the sharded-update
path chains ``psum_scatter`` fast-hop-first (``comm.chunk_axes()``) so
the slow DCN wire only ever carries ``1/ici_size`` of the bytes in
either direction (docs/performance.md §8).

Batch convention (single-controller translation of "each rank feeds its
local batch"): ``update(lossfun, *args)`` receives the *global* batch
(leading dim divisible by ``comm.size``); the shard_map in_spec splits it
across ranks.  A per-rank batchsize of ``b`` in reference scripts becomes
an iterator batchsize of ``b * comm.size`` here (see
``examples/train_mnist_dp.py``).

``double_buffering=True`` reproduces the reference's one-step-stale
gradient semantics (SURVEY §7 hard-parts note: defined by *observable
semantics*, not stream mechanics): step ``t`` applies the mean gradient
computed at step ``t-1`` while step ``t``'s gradients are produced in the
same compiled program.  Since XLA already overlaps the collective with
compute, the staleness is the semantic contract kept for parity, and it
additionally lets the runtime pipeline consecutive steps (the update no
longer serializes on the current step's collective).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from . import observability
from .core import reporter as reporter_module
from .core.link import bind_state, extract_state

__all__ = ["create_multi_node_optimizer", "_MultiNodeOptimizer",
           "_DoubleBufferingOptimizer"]


def _rehome_replicated(tree, communicator):
    """Re-place a REPLICATED pytree onto ``communicator``'s mesh by
    value (elastic resize, ISSUE 10): a jax.Array committed to the OLD
    mesh — possibly spanning processes that are gone — cannot be fed to
    the new mesh's compiled step, but a replicated array's every local
    shard holds the full value, so the move is a host round-trip that
    needs no collective and no dead peer.  The commit goes through
    ``make_array_from_callback`` (like ``_commit_opt_state_to_mesh``),
    NOT ``device_put``: multi-process device_put runs a cross-process
    value-equality collective, and mid-resize the values are allowed to
    differ (a joiner's stale state is about to be replaced by the
    consensus load — it only has to be SHAPED right here)."""
    from jax.sharding import NamedSharding
    sharding = NamedSharding(communicator.mesh, P())

    def move(leaf):
        if not isinstance(leaf, jax.Array):
            return leaf
        if leaf.is_fully_addressable:
            host = np.asarray(leaf)
        else:
            host = np.asarray(leaf.addressable_shards[0].data)
        return jax.make_array_from_callback(
            host.shape, sharding, lambda idx: host[idx])

    return jax.tree.map(move, tree)


def create_multi_node_optimizer(actual_optimizer, communicator,
                                double_buffering=False, zero_fill=True,
                                zero_sharding=False, exchange=None,
                                autotune=None):
    """Wrap an optimizer so updates average gradients over the communicator.

    Reference signature and delegation semantics preserved: the returned
    object forwards attribute access to ``actual_optimizer``.

    ``exchange`` selects the gradient-exchange structure of the compiled
    DP step (docs/performance.md §7):

    * ``"allreduce"`` (default) — mean-``psum`` of the full gradient via
      the communicator's ``grad_transform`` (per-leaf / flat / bucketed
      per its ``batch_collectives``), then the replicated update.
    * ``"reduce_scatter"`` — the comm-optimal DP update:
      ``reduce_scatter(grads) → shard-local optimizer update →
      all_gather(params)``.  The gradient crosses the wire ONCE instead
      of twice — per-replica exchanged gradient bytes are halved vs any
      allreduce flavor (tools/comm_budgets.json commits the accounting)
      — and the optimizer state is maintained shard-local as a
      consequence (each rank only ever sees its 1/n gradient chunk), so
      it shares ZeRO-1's observable contract: ``Parameter.grad`` is not
      populated and the serialized optimizer state is the flat sharded
      vector.  Unlike ``zero_sharding`` it composes with
      ``double_buffering`` (the one-step-stale buffer is the sharded
      mean-gradient CHUNK — 1/n of a full stale buffer).  Trajectories
      are golden-equal to the allreduce flavors
      (tests/core_tests/test_exchange_equivalence.py).

    ``zero_sharding=True`` (beyond the reference — ZeRO-1 over the DP
    axis, TPU-idiomatic): the gradient mean becomes a ``psum_scatter``
    (reduce-scatter riding ICI), each rank updates only its 1/n shard of
    the flat parameter/optimizer-state vector, and an ``all_gather``
    rebuilds the replicated parameters — optimizer state and the reduced
    gradient buffer shrink by the communicator size (Adam: 2×params →
    2×params/n).  Observable differences, documented: ``Parameter.grad``
    is not populated (the full mean gradient never materializes) and the
    serialized optimizer state is the flat sharded vector, not the
    per-parameter tree.  ``zero_sharding`` already implies the
    reduce-scatter exchange; passing both is a redundancy error.

    ``autotune`` (ISSUE 19, docs/performance.md §12): self-tune the
    communicator's exchange knobs.  ``True``/``"startup"`` runs the
    startup micro-bench NOW (unless the communicator already carries an
    agreed plan) and wraps the retuned communicator; ``"online"`` (or an
    int N, default 3) re-tunes after the first N updates from the span
    tracer's payload-tagged ``train/grad_exchange`` spans — online mode
    needs tracing on (``CHAINERMN_TPU_TRACE=events``); with tracing off
    it falls back to the startup micro-bench WITH a warning, never a
    silent no-op.  The re-tune swap rides :meth:`change_communicator`,
    which also re-tunes automatically on every elastic resize when the
    outgoing communicator was autotuned.
    """
    online_after = 0
    if autotune not in (None, False, True, "startup", "online") \
            and not (isinstance(autotune, int)
                     and not isinstance(autotune, bool)
                     and autotune > 0):
        raise ValueError(
            f"autotune must be True/'startup', 'online', or a positive "
            f"int (online re-tune after N updates); got {autotune!r}")
    if autotune:
        from .communicators._autotune import retune_communicator
        if autotune in (True, "startup"):
            if getattr(communicator, "autotune_plan", None) is None:
                communicator = retune_communicator(communicator,
                                                   mode="startup")
        else:
            if observability.enabled():
                online_after = autotune if isinstance(autotune, int) \
                    and not isinstance(autotune, bool) else 3
                communicator._autotune_mode = "online"
            else:
                import warnings
                warnings.warn(
                    "autotune='online' reads the span tracer's "
                    "train/grad_exchange spans but tracing is off "
                    "(CHAINERMN_TPU_TRACE): running the startup "
                    "micro-bench instead", UserWarning, stacklevel=2)
                if getattr(communicator, "autotune_plan", None) is None:
                    communicator = retune_communicator(communicator,
                                                       mode="startup")
    if exchange is None:
        exchange = "allreduce"
    if exchange not in ("allreduce", "reduce_scatter"):
        raise ValueError(
            f"exchange must be 'allreduce' or 'reduce_scatter', got "
            f"{exchange!r} (per_leaf/flat/bucketed are communicator "
            f"batch_collectives flavors of the allreduce exchange)")
    if (exchange == "reduce_scatter" or zero_sharding) \
            and getattr(communicator, "striped", False) \
            and getattr(communicator, "quantized_wire_dtype", None) \
            is not None:
        # covers BOTH sharded-update routes (zero_sharding and the
        # plain-DP reduce-scatter exchange share _make_zero_update): a
        # quantized dtype reaching the striped chains would raw-cast
        # gradients to int8 with no scale or residual — silent
        # corruption, never acceptable
        raise ValueError(
            "a quantized (int8/fp8) wire does not compose with the "
            "STRIPED sharded update (zero_sharding or "
            "exchange='reduce_scatter') yet: the slow-hop-major "
            "chain has no quantized psum_scatter shape.  Use the "
            "allreduce striped exchange (which quantizes both slices' "
            "DCN crossings) or the non-striped hierarchical_rs path")
    if zero_sharding and exchange == "reduce_scatter":
        raise ValueError(
            "zero_sharding already exchanges gradients via reduce-scatter; "
            "exchange='reduce_scatter' on top of it is a redundancy error "
            "(pick one: zero_sharding=True for the ZeRO-1 contract, "
            "exchange='reduce_scatter' for the comm-optimal plain-DP step)")
    if double_buffering not in (False, True, "dcn"):
        raise ValueError(
            f"double_buffering must be False, True (full one-step-stale "
            f"semantics) or 'dcn' (the striped exchange's DCN-slice-only "
            f"stale variant, ISSUE 11); got {double_buffering!r}")
    if double_buffering:
        if zero_sharding:
            raise ValueError(
                "zero_sharding is incompatible with double buffering "
                "(a one-step-stale FULL gradient buffer would defeat "
                "the sharded-state memory contract)")
        if double_buffering == "dcn":
            if not getattr(communicator, "striped", False):
                raise ValueError(
                    "double_buffering='dcn' is the striped exchange's "
                    "DCN-slice-only stale variant: it needs a "
                    "communicator with stripe_ratio > 0 "
                    "(create_communicator('hierarchical', "
                    "stripe_ratio=...))")
            if exchange == "reduce_scatter":
                raise ValueError(
                    "double_buffering='dcn' rides the allreduce striped "
                    "exchange (the DCN-path slice of grad_transform); "
                    "with exchange='reduce_scatter' use "
                    "double_buffering=True — the stale chunk is already "
                    "1/n-sized")
        if communicator.name not in ("pure_nccl", "jax_ici", "hierarchical",
                                     "two_dimensional", "single_node", "flat",
                                     "dummy"):
            # reference restricts double buffering to PureNcclCommunicator
            raise ValueError(
                "double buffering requires a fused-bucket communicator "
                f"(reference: pure_nccl); got {communicator.name!r}")
        opt = _DoubleBufferingOptimizer(actual_optimizer, communicator,
                                        zero_fill, exchange=exchange,
                                        db_mode=double_buffering)
        opt._autotune_online_after = online_after
        return opt
    opt = _MultiNodeOptimizer(actual_optimizer, communicator, zero_fill,
                              zero_sharding=zero_sharding,
                              exchange=exchange)
    opt._autotune_online_after = online_after
    return opt


class _MultiNodeOptimizer:
    def __init__(self, actual_optimizer, communicator, zero_fill=True,
                 zero_sharding=False, exchange="allreduce"):
        super().__setattr__("communicator", communicator)
        super().__setattr__("actual_optimizer", actual_optimizer)
        super().__setattr__("zero_fill", zero_fill)
        super().__setattr__("zero_sharding", zero_sharding)
        super().__setattr__("exchange", exchange)
        super().__setattr__("_zero_layout", None)  # (spec, n, n_pad)
        from .core.optimizer import _LRUCache
        super().__setattr__("_mn_step_cache", _LRUCache())
        super().__setattr__("_stale_grads", None)  # double-buffer slot
        super().__setattr__("_residual", None)  # error-feedback slot

    _double_buffering = False
    #: "dcn" on the striped DCN-slice-only stale variant (ISSUE 11) —
    #: the update applies FRESH ICI-path gradients and one-step-stale
    #: DCN-path gradients, so the slow path's latency hides entirely
    #: behind compute while the fast path stays exact
    _db_mode = False
    #: online autotune (ISSUE 19): re-tune from the span tracer's
    #: payload-tagged exchange spans after this many updates (0 = off;
    #: armed by ``create_multi_node_optimizer(autotune='online')``)
    _autotune_online_after = 0
    _autotune_steps_done = 0

    @property
    def _db_dcn(self):
        return self._db_mode == "dcn"

    @property
    def _needs_residual(self):
        """True when the compiled step threads the error-feedback
        residual (ISSUE 8): the communicator quantizes a hop AND error
        feedback is on.  The residual rides the stale-grad machinery —
        a persistent flat f32 buffer, donated into the step, sharded by
        ``flat_chunk_spec`` (each device owns its slice), serialized
        next to the stale buffer so resume keeps the telescoping sum
        intact."""
        comm = self.communicator
        return bool(getattr(comm, "quantized", False)
                    and getattr(comm, "error_feedback", False))

    def _residual_global_len(self):
        """Length of the GLOBAL residual vector: per-device residual ×
        size.  Sharded-update steps quantize the post-fast-hop chunk
        (``n_pad / ici`` per device); allreduce steps quantize per
        bucket (the communicator owns that accounting)."""
        comm = self.communicator
        if self._sharded_update:
            _, _, n_pad = self._zero_layout
            slow = comm.dcn_size if comm.hierarchy is not None \
                else comm.size
            return n_pad * slow
        return comm.grad_residual_len_for(self.actual_optimizer.target) \
            * comm.size

    def _residual_operand(self):
        """The residual tuple operand the compiled step expects — ``()``
        when error feedback is off, ``(buffer,)`` (zero-seeded on first
        use: no error has been made yet) when on.  Shared by
        ``update()``/``update_scan()`` and the census tracer."""
        if not self._needs_residual:
            return ()
        if self._residual is None:
            super().__setattr__("_residual", jnp.zeros(
                (self._residual_global_len(),), jnp.float32))
        return (self._residual,)

    @property
    def _sharded_update(self):
        """True when the compiled step updates flat parameter CHUNKS
        after a reduce-scatter (ZeRO-1, or the comm-optimal plain-DP
        ``exchange="reduce_scatter"``) — the paths that share the flat
        sharded optimizer state, its serialization, and the
        grad-not-populated contract."""
        return self.zero_sharding or self.exchange == "reduce_scatter"

    def _emit_exchange_telemetry(self):
        """Per-bucket gradient-exchange attribution (ISSUE 14).

        The exchange runs INSIDE the compiled step, so host code cannot
        time individual buckets: the host trace instead carries one
        instant event per bucket stamped with the PLANNED wire payload
        (the same ``grad_buckets_for`` plan the census gates check),
        and the registry accumulates the per-bucket byte counters.
        Under ``CHAINERMN_TPU_TRACE=full`` the in-graph bucket emission
        is additionally wrapped in ``jax.named_scope`` (see
        ``communicators.mesh_communicator._bucket_scope``) so an XProf
        capture attributes real device time to the SAME names."""
        plan = self._exchange_plan_rows()
        comm = self.communicator
        exchange = getattr(comm, "exchange", None) or self.exchange
        counter = observability.registry().counter(
            "chainermn_tpu_grad_exchange_payload_bytes_total",
            help="planned per-bucket gradient wire payload (gradient "
                 "dtype; the census prices the per-hop wire dtypes)")
        for row in plan:
            observability.instant(
                f"train/grad_exchange/bucket{row['bucket']}",
                tags=dict(row, exchange=str(exchange)))
            counter.inc(row["payload_bytes"], bucket=str(row["bucket"]),
                        exchange=str(exchange))

    def _exchange_plan_rows(self):
        """The cached per-bucket ``{bucket, leaves, elems,
        payload_bytes}`` rows of the current exchange plan — shared by
        the telemetry instants, the timed eager span's payload tags
        (the ISSUE 19 small fix: bandwidth readable off a trace), and
        nothing else; invalidated wherever ``_obs_exchange_plan``
        resets (setup, change_communicator)."""
        plan = self.__dict__.get("_obs_exchange_plan")
        if plan is None:
            comm = self.communicator
            target = self.actual_optimizer.target
            try:
                shapes, dtypes = comm.grad_leaf_specs(target)
                buckets = comm.grad_buckets_for(target)
            except Exception:
                buckets, shapes, dtypes = [], [], []
            plan = []
            for i, idx in enumerate(buckets):
                elems = sum(int(np.prod(shapes[j])) for j in idx)
                nbytes = sum(int(np.prod(shapes[j]))
                             * np.dtype(dtypes[j]).itemsize for j in idx)
                plan.append({"bucket": i, "leaves": len(idx),
                             "elems": elems, "payload_bytes": nbytes})
            super().__setattr__("_obs_exchange_plan", plan)
        return plan

    def _maybe_online_retune(self):
        """Online autotune (ISSUE 19): after the armed number of
        updates, derive a plan from the tracer's payload-tagged
        ``train/grad_exchange*`` spans, agree it across ranks, and swap
        in the retuned communicator through
        :meth:`change_communicator`.  One-shot — the counter disarms
        whether or not the plan changed anything.  A plan the sharded
        striped layout cannot absorb in memory (ratio change without a
        checkpointer) is WARNED about and skipped, never a crash in the
        middle of training."""
        n = self._autotune_online_after
        if not n:
            return
        done = self._autotune_steps_done + 1
        self._autotune_steps_done = done
        if done < n:
            return
        self._autotune_online_after = 0
        from .communicators._autotune import (agree_exchange_plan,
                                              measurements_from_trace)
        comm = self.communicator
        measurement = measurements_from_trace(
            observability.tracer().events())
        plan = agree_exchange_plan(comm, measurement)
        new_comm = comm.retuned(plan)
        if new_comm is comm:
            return
        try:
            self.change_communicator(new_comm)
        except RuntimeError as e:
            import warnings
            warnings.warn(
                f"online autotune plan {plan.get('fingerprint')} not "
                f"applied: {e}", RuntimeWarning, stacklevel=2)

    # -- reference-style delegation ---------------------------------------
    def __getattr__(self, name):
        return getattr(self.actual_optimizer, name)

    def __setattr__(self, name, value):
        if name in self.__dict__ or hasattr(type(self), name):
            super().__setattr__(name, value)
        else:
            setattr(self.actual_optimizer, name, value)

    def setup(self, link):
        self.actual_optimizer.setup(link)
        # setup() resets the wrapped optimizer's _opt_state; every piece
        # of wrapper state whose lifetime tracks _opt_state (the ZeRO
        # flat-layout, compiled-step cache, double-buffer slot) must
        # reset with it — otherwise a later deserialize sees a stale
        # _zero_layout, skips the flat-template pre-seed, and restores
        # the saved flat chunks onto mismatched per-param slots.
        super().__setattr__("_zero_layout", None)
        super().__setattr__("_stale_grads", None)
        super().__setattr__("_residual", None)
        super().__setattr__("_obs_exchange_plan", None)
        self._mn_step_cache.clear()
        return self

    # -- elastic resize (ISSUE 10) -----------------------------------------
    def change_communicator(self, communicator, via_checkpoint=False):
        """Swap the transport after an elastic resize, re-planning every
        piece of state whose layout depends on the world size.

        What is PRESERVED vs RE-SEEDED (the contract
        ``docs/resilience.md`` §7 documents):

        * model params and (replicated) optimizer state — preserved:
          re-homed onto the new mesh by value;
        * compiled steps, bucket plans, the ZeRO flat layout —
          re-derived lazily (cache cleared; the padding multiple and
          chunk specs follow the new size);
        * the double-buffer stale-grad buffer and the error-feedback
          ``_residual`` — RE-SEEDED ZEROS: both are per-device content
          with no cross-partition meaning (the same rule size-changed
          snapshot resume already applies), costing one step of
          staleness/correction, never correctness;
        * SHARDED (``zero_sharding`` / ``exchange="reduce_scatter"``)
          optimizer state: fully-addressable flat leaves are sliced to
          the true length and re-committed to the new mesh's padded
          chunk layout (the PR 5 size-changed-resume brick, applied
          in-memory).  REAL multi-controller sharded leaves cannot be
          reassembled here — the old mesh's collectives may span dead
          processes — so they require ``via_checkpoint=True``: the
          state is dropped and the caller's consensus ``maybe_load``
          (which the elastic supervisor always runs next) restores it
          onto the new layout.
        """
        old = self.communicator
        if communicator is old:
            return self
        if getattr(old, "_autotune_mode", None) \
                and getattr(communicator, "autotune_plan", None) is None \
                and getattr(communicator, "axis_name", None) is not None:
            # the OLD communicator was autotuned and the incoming one
            # carries no agreed plan (an elastic rebuild): re-tune it —
            # the plan tracks the world it actually runs on, one fresh
            # plan artifact per epoch-suffixed mesh (ISSUE 19).  Knob
            # PROVENANCE carries over from the old communicator first:
            # the elastic factory passes the old knob VALUES as explicit
            # constructor arguments, which must not read as hand-set.
            hand = getattr(old, "_hand_knobs", None)
            if hand is not None:
                communicator._hand_knobs = dict(hand)
            communicator._autotune_mode = old._autotune_mode
            from .communicators._autotune import retune_communicator
            # a resize always re-MEASURES (startup micro-bench): the
            # old trace's spans timed the old world's fabric
            communicator = retune_communicator(communicator,
                                               mode="startup")
        actual = self.actual_optimizer
        if self._sharded_update and actual._opt_state is not None:
            leaves = jax.tree.leaves(actual._opt_state)
            nonaddr = any(isinstance(l, jax.Array)
                          and not l.is_fully_addressable for l in leaves)
            if nonaddr:
                if not via_checkpoint:
                    raise RuntimeError(
                        "change_communicator on a multi-controller "
                        "sharded optimizer needs via_checkpoint=True: "
                        "the old mesh's chunks cannot be reassembled "
                        "without the departed processes — resume the "
                        "state through the checkpointer's consensus "
                        "maybe_load instead")
                actual._opt_state = None
                old_state = None
            else:
                old_state = actual._opt_state
        else:
            old_state = None
        if old_state is not None and (
                (getattr(old, "striped", False),
                 getattr(old, "stripe_ratio", 0.0))
                != (getattr(communicator, "striped", False),
                    getattr(communicator, "stripe_ratio", 0.0))):
            # the striped pair layout's split point moves with the
            # ratio and its leaves are keyed per path — a cross-
            # topology in-memory re-commit would silently mis-slice;
            # resume through the checkpointer's consensus load instead
            if not via_checkpoint:
                raise RuntimeError(
                    "change_communicator across a striped-layout change "
                    "(striped<->flat chunking or a different "
                    "stripe_ratio) needs via_checkpoint=True: the "
                    "sharded flat state cannot be re-sliced in memory "
                    "across split layouts")
            actual._opt_state = None
            old_state = None
        super().__setattr__("communicator", communicator)
        super().__setattr__("_zero_layout", None)
        super().__setattr__("_stale_grads", None)  # re-seed zeros
        super().__setattr__("_residual", None)     # re-seed zeros
        super().__setattr__("_obs_exchange_plan", None)  # new plan
        self._mn_step_cache.clear()
        if old_state is not None:
            # recompute the flat layout at the NEW size, then slice/
            # re-pad + re-commit each flat leaf (what
            # _commit_opt_state_to_mesh does for a size-changed load)
            params = extract_state(actual.target)["params"]
            if params and all(v is not None for v in params.values()):
                from .communicators._memory_utility import tree_pack
                flat, spec = tree_pack(params)
                n = flat.shape[0]
                size = communicator.size
                if communicator.striped:
                    _, n_pa, n_pb = self._striped_split(n)
                    n_pad = n_pa + n_pb
                else:
                    n_pad = -(-n // size) * size
                super().__setattr__("_zero_layout", (spec, n, n_pad))
                actual._opt_state = \
                    self._commit_opt_state_to_mesh(old_state)
        elif not self._sharded_update and actual._opt_state is not None:
            # replicated per-param state: re-home by value onto the new
            # mesh (multi-controller arrays on the old mesh cannot be
            # fed to the new mesh's program directly)
            actual._opt_state = _rehome_replicated(
                actual._opt_state, communicator)
        return self

    # -- update -------------------------------------------------------------
    def update(self, lossfun=None, *args, **kwargs):
        actual = self.actual_optimizer
        if actual.target is None:
            raise RuntimeError("setup(link) was not called")
        if lossfun is None:
            # eager path: grads already on Parameter.grad (reference flow:
            # backward → allreduce_grad → update) — the one exchange the
            # host dispatches itself, so its span times the real thing.
            # The span carries the PLANNED wire payload (ISSUE 19 small
            # fix): bandwidth = payload_bytes / duration is readable
            # directly off the trace, which is what the online autotune
            # mode (and humans in Perfetto) consume
            tags = None
            if observability.enabled():
                rows = self._exchange_plan_rows()
                if rows:
                    tags = {"payload_bytes":
                            sum(r["payload_bytes"] for r in rows),
                            "buckets": len(rows)}
            with observability.span("train/grad_exchange", tags=tags):
                self.communicator.multi_node_mean_grad(
                    actual.target, zero_fill=self.zero_fill)
            out = actual.update()
            self._maybe_online_retune()
            return out
        if self.communicator.axis_name is None:
            # dummy communicator: plain local update
            return actual.update(lossfun, *args, **kwargs)

        if any(p.array is None for p in actual.target.params()):
            with bind_state(actual.target, extract_state(actual.target)):
                lossfun(*jax.tree.map(lambda a: a, args), **kwargs)
        if hasattr(self.communicator, "verify_step_signature"):
            # debug communicator: agree on shapes/dtypes across hosts
            # before launching (fail fast instead of collective deadlock)
            self.communicator.verify_step_signature((args, kwargs))
        state = extract_state(actual.target)
        params, pstate = state["params"], state["state"]
        if self._sharded_update:
            opt_state = self._ensure_zero_opt_state(params)
        else:
            opt_state = actual._ensure_opt_state(params)
        key = actual._cache_key(lossfun, args, kwargs) \
            + (self._double_buffering, self._db_mode,
               self._sharded_update, self._needs_residual)
        step = self._mn_step_cache.get(key)
        if step is None:
            step = (self._make_zero_step(lossfun, args, kwargs)
                    if self._sharded_update
                    else self._make_step(lossfun, args, kwargs))
            self._mn_step_cache[key] = step

        if self._double_buffering and self._stale_grads is None:
            if self._db_dcn:
                # DCN-slice-only staleness (ISSUE 11): the buffer is the
                # concatenated DCN-path slices of every bucket — a
                # stripe_ratio fraction of a full stale tree; first
                # update applies zeros on the DCN slices only
                zeros = jnp.zeros(
                    (self.communicator.grad_dcn_stale_len_for(
                        actual.target),), jnp.float32)
            elif self._sharded_update:
                # the stale buffer is the reduce-scattered mean-gradient
                # CHUNK (flat, padded, f32 — 1/n of a full stale tree on
                # each rank); first update applies zeros, same contract
                _, _, n_pad = self._zero_layout
                zeros = self._striped_chunk_template() \
                    if self.communicator.striped \
                    else jnp.zeros((n_pad,), jnp.float32)
            else:
                zeros = jax.tree.map(jnp.zeros_like, params)
            super().__setattr__("_stale_grads", zeros)
        stale = (self._stale_grads,) if self._double_buffering else ()
        residual = self._residual_operand()
        operands = (params, pstate, opt_state, actual._hyper_values(),
                    actual._next_rng_key(), stale, residual, args, kwargs)
        actual._stash_step_spec(step, operands)
        if observability.enabled():
            self._emit_exchange_telemetry()
        try:
            new_params, new_pstate, new_opt_state, loss, grads, \
                res_out, obs = step(*operands)
        except Exception as e:
            from .core.optimizer import raise_if_donated_state_lost
            raise_if_donated_state_lost(e, actual)
            raise
        if self._double_buffering:
            # the donated stale buffer is rebound to this step's fresh
            # mean gradient — through the wrapper, never a raw alias.
            # Under the DCN-slice variant the step returns (applied
            # gradient tree, fresh DCN-slice vector): only the latter
            # becomes the next stale buffer
            if self._db_dcn:
                grads, fresh_dcn = grads
                super().__setattr__("_stale_grads", fresh_dcn)
            else:
                super().__setattr__("_stale_grads", grads)
        if self._needs_residual:
            # same contract for the donated error-feedback buffer: this
            # step's quantization error becomes next step's correction
            super().__setattr__("_residual", res_out[0])
        # sharded updates never materialize the full mean gradient, so
        # Parameter.grad stays unpopulated (documented ZeRO contract;
        # under double buffering ``grads`` is the flat fresh CHUNK and
        # must not be scattered onto per-param slots)
        actual._write_back(new_params, new_pstate,
                           None if self._sharded_update else grads)
        actual._opt_state = new_opt_state
        actual.t += 1
        reporter_module.report(obs)
        self._maybe_online_retune()
        return loss

    # -- ZeRO-1 sharded optimizer state (beyond reference) -----------------
    def _zero_transform(self):
        """Hook chain for the ZeRO step: each rank's transform sees only
        its 1/n chunk of the flat gradient, so hooks whose semantics need
        GLOBAL gradient statistics (e.g. ``GradientClipping``'s global L2
        norm) psum across the axis — see ``Optimizer._transform``."""
        return self.actual_optimizer._transform(
            sharded_axis=self.communicator.axis_name)

    # -- striped sharded update (ISSUE 11) ---------------------------------
    def _striped_split(self, n):
        """``(n_i, n_pad_ici, n_pad_dcn)`` of the striped flat layout:
        the parameter vector splits at ``stripe_plan(n, ratio)`` and
        each slice pads to its own multiple of ``size`` (both chains
        scatter over all ``ici × dcn`` devices — only the chunk ORDER
        differs between the fast- and slow-hop-major layouts)."""
        from .communicators._memory_utility import stripe_plan
        size = self.communicator.size
        n_i, n_d = stripe_plan(n, self.communicator.stripe_ratio)
        return n_i, -(-n_i // size) * size, -(-n_d // size) * size

    def _flat_param_len(self):
        if self._zero_layout is not None:
            return self._zero_layout[1]
        from .communicators._memory_utility import tree_pack
        params = extract_state(self.actual_optimizer.target)["params"]
        return tree_pack(params)[0].shape[0]

    def _striped_chunk_template(self):
        """Zero-seeded pair of flat global vectors in the striped ZeRO
        layout — the stale-chunk template (and the restore template the
        serializer builds)."""
        n_i, n_pa, n_pb = self._striped_split(self._flat_param_len())
        return {"ici": jnp.zeros((n_pa,), jnp.float32),
                "dcn": jnp.zeros((n_pb,), jnp.float32)}

    def _stale_chunk_spec(self):
        """Sharding spec of the reduce-scatter stale buffer: the flat
        chunk layout, or the per-path pair on striped communicators."""
        comm = self.communicator
        if comm.striped:
            fast, slow = comm.striped_chunk_specs()
            return {"ici": fast, "dcn": slow}
        return comm.flat_chunk_spec()

    def _ensure_zero_opt_state(self, params):
        """Optimizer state over the PADDED FLAT parameter vector.

        Initialized on the full flat view so the compiled step can split
        it with an in_spec of ``P(axis)`` — each rank then holds (and
        updates) exactly its 1/n chunk; the returned state stays sharded
        across steps.

        On a STRIPED communicator (ISSUE 11) the flat vector splits
        into the ICI-path / DCN-path pair ``{"ici": ..., "dcn": ...}``
        — each slice padded to its own multiple of ``size`` and sharded
        by its own chunk layout (fast- vs slow-hop-major,
        ``striped_chunk_specs``); the optax transform inits over the
        pair tree, so state leaves mirror the two-slice structure.
        """
        actual = self.actual_optimizer
        if actual._opt_state is None:
            from .communicators._memory_utility import tree_pack
            flat, spec = tree_pack(params)
            n = flat.shape[0]
            size = self.communicator.size
            if self.communicator.striped:
                n_i, n_pa, n_pb = self._striped_split(n)
                super().__setattr__("_zero_layout",
                                    (spec, n, n_pa + n_pb))
                pair = {"ici": jnp.pad(flat[:n_i], (0, n_pa - n_i)),
                        "dcn": jnp.pad(flat[n_i:],
                                       (0, n_pb - (n - n_i)))}
                actual._opt_state = self._zero_transform().init(pair)
                return actual._opt_state
            n_pad = -(-n // size) * size
            flat = jnp.pad(flat, (0, n_pad - n))
            super().__setattr__("_zero_layout", (spec, n, n_pad))
            actual._opt_state = self._zero_transform().init(flat)
        return actual._opt_state

    def _zero_state_spec(self, opt_state):
        """Chunk spec for flat param-length leaves, replicated otherwise
        (e.g. Adam's step count).  The chunk layout is the
        communicator's (``flat_chunk_spec``): one axis on flat
        communicators, fast-hop-major over (ici, dcn) on hierarchical
        ones — the layout the chained reduce-scatter produces.  On
        striped communicators each slice of the pair layout gets its
        own spec, resolved by the leaf's position under the
        ``"ici"``/``"dcn"`` dict keys (the leaf LENGTHS can coincide,
        so the tree path — not the shape — is the disambiguator)."""
        _, n, n_pad = self._zero_layout
        if self.communicator.striped:
            from jax.tree_util import DictKey, tree_map_with_path
            n_i, n_pa, n_pb = self._striped_split(n)
            fast, slow = self.communicator.striped_chunk_specs()

            def spec_for(path, leaf):
                if getattr(leaf, "ndim", 0) != 1:
                    return P()
                keys = [k.key for k in path if isinstance(k, DictKey)
                        and k.key in ("ici", "dcn")]
                if keys and keys[-1] == "ici" and leaf.shape[0] == n_pa:
                    return fast
                if keys and keys[-1] == "dcn" and leaf.shape[0] == n_pb:
                    return slow
                return P()

            return tree_map_with_path(spec_for, opt_state)
        chunk_spec = self.communicator.flat_chunk_spec()
        return jax.tree.map(
            lambda leaf: chunk_spec if getattr(leaf, "ndim", 0) == 1
            and leaf.shape[0] == n_pad else P(), opt_state)

    def _make_zero_update(self):
        """Shared reduce-scatter core (ZeRO-1 AND the plain-DP
        ``exchange="reduce_scatter"`` step, per-step AND scan makers):
        flat-pack grads → reduce-scatter (each rank receives the SUM of
        its own 1/n segment — the reference's allreduce splits into
        reduce_scatter + all_gather; this path stops halfway and updates
        in the scattered domain) → chunk update → all-gather(params) →
        unpack.

        ``stale_chunk`` (double buffering × reduce-scatter): the update
        applies the PREVIOUS step's reduce-scattered mean-gradient chunk
        while this step's fresh chunk is returned to become the next
        stale buffer — the reference's one-step-stale semantics at 1/n
        of the stale-buffer footprint.

        On a HIERARCHICAL communicator the single reduce-scatter /
        all-gather becomes the hop chain ``comm.chunk_axes()`` traces
        fast-hop-first (ISSUE 6): ``psum_scatter`` over ICI on the full
        gradient, ``psum_scatter`` over DCN on the 1/ici chunk (the slow
        wire never sees more than 1/ici of the bytes; ``dcn_grad_dtype``
        can compress just that crossing), the chunk update, then
        ``all_gather`` over DCN first and ICI last — the params rebuild
        likewise puts only 1/ici of the parameter bytes on DCN.  The
        chunk layout is fast-hop-major (``comm.flat_chunk_spec()``);
        the chained index below addresses the same layout the gathers
        reassemble.

        QUANTIZED slow hop (ISSUE 8): an int8/fp8 ``dcn_grad_dtype``
        (or a quantized scalar dtype on a flat communicator — the
        escape-hatch collapse) replaces the slow hop's ``psum_scatter``
        with a quantized reduce-scatter: quantize the chunk with ONE
        per-bucket symmetric scale, ``all_to_all`` the quantized
        SEGMENTS (each crosses the slow wire exactly once — the wire
        carries the quantized fraction of the f32 reduce-scatter's
        bytes at any ring size), ``all_gather`` the scale scalars, and
        dequantize-sum on the owner.  ``residual`` (error feedback) is
        added before quantizing and the new residual ``v − Q(v)`` is
        returned to become next step's correction.
        """
        from .communicators._memory_utility import (
            dequantize_sum, is_quantized_dtype, quantize_with_feedback,
            tree_pack, tree_unpack)
        from .core.optimizer import apply_transform_update
        comm = self.communicator
        if comm.striped:
            return self._make_striped_zero_update()
        tx = self._zero_transform()
        size = comm.size
        spec, n, n_pad = self._zero_layout
        chunk = n_pad // size
        grad_dtype = comm.allreduce_grad_dtype
        dcn_dtype = getattr(comm, "dcn_grad_dtype", None)
        rs_axes = comm.chunk_axes()
        axis_sizes = [int(comm.mesh.shape[a]) for a in rs_axes]
        slow_axis = rs_axes[-1] if len(rs_axes) > 1 else None
        # the quantized hop: the slow (last) axis of the chain —
        # on a flat communicator the single world axis IS the wire the
        # quantized dtype compresses
        q_dtype = getattr(comm, "quantized_wire_dtype", None)
        q_axis = rs_axes[-1] if q_dtype is not None else None
        if is_quantized_dtype(grad_dtype):
            grad_dtype = None  # quantize at the wire, never pre-cast

        def zero_update(params, grads, opt_state, hyper, stale_chunk=None,
                        residual=None):
            new_residual = None
            with jax.named_scope("zero_reduce_scatter_grad"):
                gflat, _ = tree_pack(grads)
                gflat = jnp.pad(gflat, (0, n_pad - n))
                if grad_dtype is not None:
                    gflat = gflat.astype(grad_dtype)
                gchunk = gflat
                for a, a_size in zip(rs_axes, axis_sizes):
                    if a == q_axis:
                        with jax.named_scope("zero_quantized_rs"):
                            q, scale, new_residual = quantize_with_feedback(
                                gchunk, residual, q_dtype)
                            seg = lax.all_to_all(
                                q.reshape(a_size, -1), a,
                                split_axis=0, concat_axis=0)
                            sg = lax.all_gather(scale, a)
                            gchunk = dequantize_sum(seg, sg)
                        continue
                    if a == slow_axis and dcn_dtype is not None:
                        gchunk = gchunk.astype(dcn_dtype)
                    gchunk = lax.psum_scatter(
                        gchunk, a, scatter_dimension=0, tiled=True)
                gchunk = gchunk.astype(jnp.float32) / size
            with jax.named_scope("zero_shard_update"):
                pflat, _ = tree_pack(params)
                pflat = jnp.pad(pflat, (0, n_pad - n))
                idx = jnp.int32(0)
                for a, a_size in zip(rs_axes, axis_sizes):
                    idx = idx * a_size + lax.axis_index(a)
                pchunk = lax.dynamic_slice_in_dim(
                    pflat, idx * chunk, chunk)
                new_pchunk, new_opt_state = apply_transform_update(
                    tx, gchunk if stale_chunk is None else stale_chunk,
                    opt_state, pchunk, hyper["lr"],
                    hyper.get("decoupled_wd", 0.0))
            with jax.named_scope("zero_all_gather_params"):
                new_flat = new_pchunk
                for a in reversed(rs_axes):
                    new_flat = lax.all_gather(new_flat, a, tiled=True)
                new_params = tree_unpack(new_flat, spec)
            return new_params, new_opt_state, gchunk, new_residual

        return zero_update

    def _make_striped_zero_update(self):
        """The STRIPED two-slice sharded update (ISSUE 11): the flat
        gradient/parameter vector splits at ``stripe_plan(n, ratio)``;
        the ICI-path slice runs the fast-hop-major chained
        reduce-scatter (``psum_scatter`` over ICI on the full slice,
        then over DCN on the 1/ici chunk — the PR 6 chain), the
        DCN-path slice runs the TRANSPOSED chain (``psum_scatter`` over
        DCN on the full slice — the bulk rides the slow wire — then
        over ICI), both paths' scatters emitted before either path's
        chunk update so the two fabrics drain concurrently.  The chunk
        update runs on the ``{"ici", "dcn"}`` pair tree (optax is
        tree-generic), and the params rebuild all-gathers each slice
        along its chain in reverse — DCN carries the full DCN-path
        slice plus 1/ici of the ICI-path slice, in both directions.

        Per-hop dtype: ``dcn_grad_dtype`` compresses exactly the DCN
        crossings (the ICI-path chunk's DCN scatter AND the DCN-path
        slice's bulk scatter); the fast hop accumulates in f32
        (lossless by design — the DCN-path chunk upcasts before its ICI
        scatter).  Quantized wires are rejected at construction.
        ``stale_chunk`` (double buffering) is the one-step-stale pair
        of mean-gradient chunks — the PR 5 contract on both paths at
        the striped layout."""
        from .communicators._memory_utility import tree_pack, tree_unpack
        from .core.optimizer import apply_transform_update
        comm = self.communicator
        tx = self._zero_transform()
        size = comm.size
        spec, n, _ = self._zero_layout
        n_i, n_pa, n_pb = self._striped_split(n)
        n_d = n - n_i
        chunk_a = n_pa // size
        chunk_b = n_pb // size
        ici, dcn = comm.ici_axis, comm.dcn_axis
        intra, inter = comm.ici_size, comm.dcn_size
        grad_dtype = comm.allreduce_grad_dtype
        dcn_dtype = getattr(comm, "dcn_grad_dtype", None)

        def zero_update(params, grads, opt_state, hyper, stale_chunk=None,
                        residual=None):
            with jax.named_scope("striped_zero_rs_grad"):
                gflat, _ = tree_pack(grads)
                if grad_dtype is not None:
                    gflat = gflat.astype(grad_dtype)
                ga = jnp.pad(gflat[:n_i], (0, n_pa - n_i))
                gb = jnp.pad(gflat[n_i:n], (0, n_pb - n_d))
                # slow-path-first emission (hop_schedule's striped
                # contract): the DCN-path bulk scatter is issued first,
                # then the ICI-path bulk, then the two chunk scatters
                if dcn_dtype is not None:
                    gb = gb.astype(dcn_dtype)
                if n_d:
                    gb = lax.psum_scatter(gb, dcn, scatter_dimension=0,
                                          tiled=True)
                if n_i:
                    ga = lax.psum_scatter(ga, ici, scatter_dimension=0,
                                          tiled=True)
                if n_d:
                    # lossless fast hop: upcast before accumulating
                    gb = lax.psum_scatter(gb.astype(jnp.float32), ici,
                                          scatter_dimension=0, tiled=True)
                if n_i:
                    if dcn_dtype is not None:
                        ga = ga.astype(dcn_dtype)
                    ga = lax.psum_scatter(ga, dcn, scatter_dimension=0,
                                          tiled=True)
                gchunk = {"ici": ga.astype(jnp.float32) / size,
                          "dcn": gb.astype(jnp.float32) / size}
            with jax.named_scope("striped_zero_shard_update"):
                pflat, _ = tree_pack(params)
                pa = jnp.pad(pflat[:n_i], (0, n_pa - n_i))
                pb = jnp.pad(pflat[n_i:n], (0, n_pb - n_d))
                idx_a = lax.axis_index(ici) * inter + lax.axis_index(dcn)
                idx_b = lax.axis_index(dcn) * intra + lax.axis_index(ici)
                # a degenerate ratio (0/1) leaves one slice EMPTY: its
                # chunk is the (0,) vector itself — zero-length
                # dynamic_slices and all_gathers do not lower
                pchunk = {"ici": lax.dynamic_slice_in_dim(
                              pa, idx_a * chunk_a, chunk_a)
                          if n_i else pa,
                          "dcn": lax.dynamic_slice_in_dim(
                              pb, idx_b * chunk_b, chunk_b)
                          if n_d else pb}
                new_pchunk, new_opt_state = apply_transform_update(
                    tx, gchunk if stale_chunk is None else stale_chunk,
                    opt_state, pchunk, hyper["lr"],
                    hyper.get("decoupled_wd", 0.0))
            with jax.named_scope("striped_zero_all_gather_params"):
                fa = new_pchunk["ici"]
                if n_i:
                    for a in (dcn, ici):  # reverse of the (ici, dcn) chain
                        fa = lax.all_gather(fa, a, tiled=True)
                fb = new_pchunk["dcn"]
                if n_d:
                    for a in (ici, dcn):  # reverse of the (dcn, ici) chain
                        fb = lax.all_gather(fb, a, tiled=True)
                new_params = tree_unpack(
                    jnp.concatenate([fa[:n_i], fb[:n_d]]), spec)
            return new_params, new_opt_state, gchunk, None

        return zero_update

    def _make_zero_step(self, lossfun, ex_args, ex_kwargs):
        from chainermn_tpu.utils.compat import shard_map
        from .core.optimizer import make_loss_and_grad
        comm = self.communicator
        actual = self.actual_optimizer
        axis = comm.axis_name
        size = comm.size
        double_buffering = self._double_buffering
        needs_residual = self._needs_residual
        zero_update = self._make_zero_update()
        loss_and_grad = make_loss_and_grad(actual.target, lossfun)

        def rank_step(params, pstate, opt_state, hyper, rng_key, stale,
                      residual, args, kwargs):
            rng_local = jax.random.fold_in(rng_key, lax.axis_index(axis))
            with jax.named_scope("zero_forward_backward"):
                loss, new_pstate, obs, grads = loss_and_grad(
                    params, pstate, rng_local, args, kwargs)
            new_params, new_opt_state, fresh_chunk, new_residual = \
                zero_update(params, grads, opt_state, hyper,
                            stale[0] if double_buffering else None,
                            residual[0] if needs_residual else None)
            loss = lax.pmean(loss, axis)
            obs = jax.tree.map(lambda o: lax.pmean(o, axis), obs)
            new_pstate = jax.tree.map(lambda s: lax.pmean(s, axis),
                                      new_pstate)
            # grads out: the fresh mean-gradient CHUNK under double
            # buffering (it becomes the next stale buffer); otherwise
            # None — the full mean gradient never exists on this path
            out_grads = fresh_chunk if double_buffering else None
            res_out = (new_residual,) if needs_residual else ()
            return new_params, new_pstate, new_opt_state, loss, \
                out_grads, res_out, obs

        args_specs = jax.tree.map(
            lambda leaf: self._batch_spec(leaf, axis, size), ex_args)
        kwargs_specs = jax.tree.map(
            lambda leaf: self._batch_spec(leaf, axis, size), ex_kwargs)
        opt_specs = self._zero_state_spec(actual._opt_state)
        # the stale chunk is sharded like the opt state's flat leaves
        # (the per-path pair on striped communicators); the
        # error-feedback residual shares the flat layout (per-device
        # slice of a flat vector)
        stale_spec = self._stale_chunk_spec() if double_buffering else P()
        residual_spec = comm.flat_chunk_spec() if needs_residual else P()
        # the stale operand is tuple-wrapped; a dict-shaped striped
        # spec cannot prefix a tuple, so wrap the IN spec to match the
        # operand structure (the OUT slot is the bare fresh chunk)
        stale_in_spec = (stale_spec,) if double_buffering else P()
        mapped = shard_map(
            rank_step, mesh=comm.mesh,
            in_specs=(P(), P(), opt_specs, P(), P(), stale_in_spec,
                      residual_spec, args_specs, kwargs_specs),
            out_specs=(P(), P(), opt_specs, P(), stale_spec,
                       residual_spec, P()),
            check_vma=False)
        if getattr(actual, "donate_params", True):
            # under double buffering the stale chunk (argnum 5) is
            # replaced by this step's fresh chunk — donate it too; same
            # for the error-feedback residual (argnum 6)
            donate = (0, 2)
            donate += (5,) if double_buffering else ()
            donate += (6,) if needs_residual else ()
        else:
            donate = (2,)
        return jax.jit(mapped, donate_argnums=donate)

    # -- compiled DP step ------------------------------------------------------
    @staticmethod
    def _scan_batch_spec(leaf, axis, size):
        """update_scan leaves: leading axis = step axis (replicated),
        axis 1 = global batch (split across ranks)."""
        if leaf.shape[1] % size == 0 and leaf.shape[1] > 0:
            return P(None, axis)
        raise ValueError(
            f"update_scan leaf with batch dim {leaf.shape[1]} is not "
            f"divisible by communicator size {size}")

    def _batch_spec(self, leaf, axis, size):
        """Batch-sharding heuristic: leaves with a leading dim divisible by
        ``size`` are split across ranks; scalars are replicated; anything
        else is a shape error (scatter_dataset guarantees divisibility —
        silent replication would quietly discard data parallelism)."""
        if not hasattr(leaf, "shape") or leaf.ndim == 0:
            return P()
        if leaf.shape[0] % size == 0 and leaf.shape[0] > 0:
            return P(axis)
        raise ValueError(
            f"batch leaf with leading dim {leaf.shape[0]} is not divisible "
            f"by communicator size {size}; scatter_dataset keeps shards "
            f"equal — use batchsize = per_rank_bs * comm.size (pass "
            f"per-example weights with a batch-sized leading axis, scalars "
            f"as 0-d arrays)")

    def _make_step(self, lossfun, ex_args, ex_kwargs):
        from chainermn_tpu.utils.compat import shard_map
        from .core.optimizer import (apply_transform_update,
                                     make_loss_and_grad)
        comm = self.communicator
        actual = self.actual_optimizer
        tx = actual._transform()
        grad_transform = comm.grad_transform()
        axis = comm.axis_name
        size = comm.size
        double_buffering = self._double_buffering
        db_dcn = self._db_dcn
        needs_residual = self._needs_residual
        loss_and_grad = make_loss_and_grad(actual.target, lossfun)

        def rank_step(params, pstate, opt_state, hyper, rng_key, stale,
                      residual, args, kwargs):
            # decorrelate stochastic masks across ranks (each rank holds a
            # different batch shard)
            rng_local = jax.random.fold_in(rng_key, lax.axis_index(axis))
            with jax.named_scope("mn_forward_backward"):
                loss, new_pstate, obs, grads = loss_and_grad(
                    params, pstate, rng_local, args, kwargs)
            # the reference's allreduce_grad: mean over ranks, optional
            # dtype compression, optional flat bucket — all in-program;
            # quantized wires additionally thread the error-feedback
            # residual through the transform (ISSUE 8); the striped
            # DCN-slice stale variant (ISSUE 11) threads the previous
            # step's DCN-path results and receives the fresh ones back
            with jax.named_scope("mn_allreduce_grad"):
                if db_dcn:
                    out = grad_transform(
                        grads, residual[0] if needs_residual else None,
                        stale_dcn=stale[0])
                    if needs_residual:
                        grads, new_residual, fresh_dcn = out
                        res_out = (new_residual,)
                    else:
                        grads, fresh_dcn = out
                        res_out = ()
                elif needs_residual:
                    grads, new_residual = grad_transform(grads, residual[0])
                    res_out = (new_residual,)
                else:
                    grads = grad_transform(grads)
                    res_out = ()
            # db_dcn applies the transform's output directly — the stale
            # DCN slices are already assembled INSIDE it, per path
            apply_grads = stale[0] \
                if double_buffering and not db_dcn else grads
            with jax.named_scope("mn_optimizer_update"):
                new_params, new_opt_state = apply_transform_update(
                    tx, apply_grads, opt_state, params, hyper["lr"],
                    hyper.get("decoupled_wd", 0.0))
            # per-rank scalars → global means for reporting / BN state
            loss = lax.pmean(loss, axis)
            obs = jax.tree.map(lambda o: lax.pmean(o, axis), obs)
            new_pstate = jax.tree.map(lambda s: lax.pmean(s, axis), new_pstate)
            out_grads = (grads, fresh_dcn) if db_dcn else grads
            return new_params, new_pstate, new_opt_state, loss, out_grads, \
                res_out, obs

        args_specs = jax.tree.map(
            lambda leaf: self._batch_spec(leaf, axis, size), ex_args)
        kwargs_specs = jax.tree.map(
            lambda leaf: self._batch_spec(leaf, axis, size), ex_kwargs)
        # the residual is a per-device slice of a flat vector — the
        # same chunked layout (and resume plumbing) as the
        # reduce-scatter stale chunk
        residual_spec = comm.flat_chunk_spec() if needs_residual else P()
        mapped = shard_map(
            rank_step, mesh=comm.mesh,
            in_specs=(P(), P(), P(), P(), P(), P(), residual_spec,
                      args_specs, kwargs_specs),
            out_specs=(P(), P(), P(), P(), P(), residual_spec, P()),
            check_vma=False)
        # donate params + opt_state (and, under double buffering, the
        # params-sized stale-grad buffer at argnum 5: it is replaced by
        # this step's returned gradient, so XLA may update it in place;
        # same for the error-feedback residual at argnum 6).
        # Safe by default through the Link bridge — see core/optimizer.py
        # ``donate_params``; set it False on the wrapped optimizer to
        # keep pre-update buffers alive.
        if getattr(actual, "donate_params", True):
            donate = (0, 2)
            donate += (5,) if double_buffering else ()
            donate += (6,) if needs_residual else ()
        else:
            donate = (2,)
        return jax.jit(mapped, donate_argnums=donate)

    # -- multi-step fused dispatch ----------------------------------------------
    def update_scan(self, lossfun, *args, **kwargs):
        """Run K training steps in ONE compiled dispatch.

        Every array leaf in ``args``/``kwargs`` carries a leading *step*
        axis of length K stacked on top of the usual global-batch axis:
        shape ``(K, global_bs, ...)``.  The compiled program lax.scans
        over the step axis inside the shard_mapped body — K full
        forward/backward/allreduce/update iterations per host dispatch,
        so per-step host and dispatch latency is amortized K-fold (the
        TPU-idiomatic equivalent of the reference's tight C-level update
        loop; measured in BENCH_NOTES "fused multi-step").

        Returns the per-step loss array of shape ``(K,)``.  Reported
        observations are the MEAN over the K steps (what a LogReport
        consumer would average from K plain updates).  Hyperparams
        (lr, ...) are read once per dispatch — a schedule that must
        change *within* the K steps needs plain ``update`` calls.
        Double buffering is not supported here (one-step staleness
        inside a fused scan would reorder its observable semantics).
        ``zero_sharding`` composes: the scan carries one gathered
        params buffer plus the sharded flat optimizer state, each
        iteration running the full reduce-scatter → chunk update →
        all-gather step (``_make_zero_scan_step``).
        RNG streams differ from the per-step ``update()`` path (one
        dispatch key with the step index folded in, vs a fresh host key
        per step), so stochastic layers (dropout) are numerically equal
        only for deterministic models.
        """
        if self._double_buffering:
            raise RuntimeError("update_scan does not support double "
                               "buffering; use update()")
        actual = self.actual_optimizer
        if actual.target is None:
            raise RuntimeError("setup(link) was not called")
        if self.communicator.axis_name is None:
            raise RuntimeError("update_scan requires a mesh communicator")
        leaves = jax.tree.leaves((args, kwargs))
        if not leaves or any(not hasattr(l, "shape") or l.ndim < 2
                             for l in leaves):
            raise ValueError("update_scan arguments must be arrays with a "
                             "leading (n_steps, global_batch, ...) axis")
        n_steps = leaves[0].shape[0]
        if any(l.shape[0] != n_steps for l in leaves):
            raise ValueError("all update_scan leaves must share the same "
                             "leading step-axis length")

        if any(p.array is None for p in actual.target.params()):
            with bind_state(actual.target, extract_state(actual.target)):
                first = jax.tree.map(lambda a: a[0], (args, kwargs))
                lossfun(*first[0], **first[1])
        if hasattr(self.communicator, "verify_step_signature"):
            # debug communicator: agree on shapes/dtypes across hosts
            # before launching (fail fast instead of collective deadlock)
            self.communicator.verify_step_signature((args, kwargs))
        state = extract_state(actual.target)
        params, pstate = state["params"], state["state"]
        if self._sharded_update:
            opt_state = self._ensure_zero_opt_state(params)
        else:
            opt_state = actual._ensure_opt_state(params)
        key = ("scan", n_steps, self._sharded_update,
               self._needs_residual) \
            + actual._cache_key(lossfun, args, kwargs)
        step = self._mn_step_cache.get(key)
        if step is None:
            step = (self._make_zero_scan_step(lossfun, args, kwargs, n_steps)
                    if self._sharded_update
                    else self._make_scan_step(lossfun, args, kwargs, n_steps))
            self._mn_step_cache[key] = step
        residual = self._residual_operand()
        operands = (params, pstate, opt_state, actual._hyper_values(),
                    actual._next_rng_key(), residual, args, kwargs)
        actual._stash_step_spec(step, operands)
        if observability.enabled():
            self._emit_exchange_telemetry()
        try:
            new_params, new_pstate, new_opt_state, losses, grads, \
                res_out, obs = step(*operands)
        except Exception as e:
            from .core.optimizer import raise_if_donated_state_lost
            raise_if_donated_state_lost(e, actual)
            raise
        if self._needs_residual:
            # the residual rides the scan carry: the K-th step's error
            # comes back to seed dispatch K+1
            super().__setattr__("_residual", res_out[0])
        actual._write_back(new_params, new_pstate, grads)
        actual._opt_state = new_opt_state
        actual.t += n_steps
        reporter_module.report(obs)
        return losses

    def _make_scan_step(self, lossfun, ex_args, ex_kwargs, n_steps):
        from chainermn_tpu.utils.compat import shard_map
        from .core.optimizer import (apply_transform_update,
                                     make_loss_and_grad)
        comm = self.communicator
        actual = self.actual_optimizer
        tx = actual._transform()
        grad_transform = comm.grad_transform()
        axis = comm.axis_name
        size = comm.size
        needs_residual = self._needs_residual
        loss_and_grad = make_loss_and_grad(actual.target, lossfun)

        def rank_scan(params, pstate, opt_state, hyper, rng_key, residual,
                      args, kwargs):
            rng_rank = jax.random.fold_in(rng_key, lax.axis_index(axis))

            def one_step(carry, xs):
                params, pstate, opt_state, _, res, i = carry
                s_args, s_kwargs = xs
                rng_i = jax.random.fold_in(rng_rank, i)
                loss, new_pstate, obs, grads = loss_and_grad(
                    params, pstate, rng_i, s_args, s_kwargs)
                if needs_residual:
                    grads, res = grad_transform(grads, res)
                else:
                    grads = grad_transform(grads)
                new_params, new_opt_state = apply_transform_update(
                    tx, grads, opt_state, params, hyper["lr"],
                    hyper.get("decoupled_wd", 0.0))
                # grads ride the CARRY (one params-sized buffer, the last
                # step's value survives) — stacking them as scan ys would
                # materialize a (K, model-size) buffer in HBM, defeating
                # donate_params for exactly the large models K-step fusion
                # targets.  Only the small per-step scalars stack.  The
                # error-feedback residual rides the carry for the same
                # reason — each step corrects the previous one's error.
                return ((new_params, new_pstate, new_opt_state, grads,
                         res, i + 1), (loss, obs))

            init_grads = jax.tree.map(jnp.zeros_like, params)
            init_res = residual[0] if needs_residual else jnp.zeros((0,))
            (params, pstate, opt_state, last_grads, last_res, _), \
                (losses, all_obs) = \
                lax.scan(one_step, (params, pstate, opt_state, init_grads,
                                    init_res, jnp.int32(0)),
                         (args, kwargs))
            losses = lax.pmean(losses, axis)
            pstate = jax.tree.map(lambda s: lax.pmean(s, axis), pstate)
            # observations: mean over the K fused steps (matches what a
            # LogReport consumer would average from K plain updates), then
            # over ranks
            obs = jax.tree.map(
                lambda o: lax.pmean(jnp.mean(o, axis=0), axis), all_obs)
            res_out = (last_res,) if needs_residual else ()
            return params, pstate, opt_state, losses, last_grads, \
                res_out, obs

        args_specs = jax.tree.map(
            lambda leaf: self._scan_batch_spec(leaf, axis, size), ex_args)
        kwargs_specs = jax.tree.map(
            lambda leaf: self._scan_batch_spec(leaf, axis, size), ex_kwargs)
        residual_spec = comm.flat_chunk_spec() if needs_residual else P()
        mapped = shard_map(
            rank_scan, mesh=comm.mesh,
            in_specs=(P(), P(), P(), P(), P(), residual_spec, args_specs,
                      kwargs_specs),
            out_specs=(P(), P(), P(), P(), P(), residual_spec, P()),
            check_vma=False)
        donate = (0, 2) if getattr(actual, "donate_params", True) else (2,)
        if needs_residual and getattr(actual, "donate_params", True):
            donate += (5,)
        return jax.jit(mapped, donate_argnums=donate)

    def _make_zero_scan_step(self, lossfun, ex_args, ex_kwargs, n_steps):
        """ZeRO-1 × fused K-step dispatch: the scan carries the gathered
        params (ONE buffer, exactly as per-step ZeRO keeps one gathered
        copy live) plus the sharded flat opt state; each scan iteration
        is the full reduce-scatter → chunk update → all-gather step."""
        from chainermn_tpu.utils.compat import shard_map
        from .core.optimizer import make_loss_and_grad
        comm = self.communicator
        actual = self.actual_optimizer
        axis = comm.axis_name
        size = comm.size
        needs_residual = self._needs_residual
        zero_update = self._make_zero_update()
        loss_and_grad = make_loss_and_grad(actual.target, lossfun)

        def rank_scan(params, pstate, opt_state, hyper, rng_key, residual,
                      args, kwargs):
            rng_rank = jax.random.fold_in(rng_key, lax.axis_index(axis))

            def one_step(carry, xs):
                params, pstate, opt_state, res, i = carry
                s_args, s_kwargs = xs
                rng_i = jax.random.fold_in(rng_rank, i)
                loss, new_pstate, obs, grads = loss_and_grad(
                    params, pstate, rng_i, s_args, s_kwargs)
                new_params, new_opt_state, _, new_res = zero_update(
                    params, grads, opt_state, hyper, None,
                    res if needs_residual else None)
                if not needs_residual:
                    new_res = res
                return ((new_params, new_pstate, new_opt_state, new_res,
                         i + 1), (loss, obs))

            init_res = residual[0] if needs_residual else jnp.zeros((0,))
            (params, pstate, opt_state, last_res, _), (losses, all_obs) = \
                lax.scan(one_step,
                         (params, pstate, opt_state, init_res,
                          jnp.int32(0)), (args, kwargs))
            losses = lax.pmean(losses, axis)
            pstate = jax.tree.map(lambda s: lax.pmean(s, axis), pstate)
            obs = jax.tree.map(
                lambda o: lax.pmean(jnp.mean(o, axis=0), axis), all_obs)
            res_out = (last_res,) if needs_residual else ()
            # None grads: the full mean gradient never exists under ZeRO
            return params, pstate, opt_state, losses, None, res_out, obs

        args_specs = jax.tree.map(
            lambda leaf: self._scan_batch_spec(leaf, axis, size), ex_args)
        kwargs_specs = jax.tree.map(
            lambda leaf: self._scan_batch_spec(leaf, axis, size), ex_kwargs)
        opt_specs = self._zero_state_spec(actual._opt_state)
        residual_spec = comm.flat_chunk_spec() if needs_residual else P()
        mapped = shard_map(
            rank_scan, mesh=comm.mesh,
            in_specs=(P(), P(), opt_specs, P(), P(), residual_spec,
                      args_specs, kwargs_specs),
            out_specs=(P(), P(), opt_specs, P(), P(), residual_spec, P()),
            check_vma=False)
        donate = (0, 2) if getattr(actual, "donate_params", True) else (2,)
        if needs_residual and getattr(actual, "donate_params", True):
            donate += (5,)
        return jax.jit(mapped, donate_argnums=donate)

    # -- misc reference API -----------------------------------------------------
    def new_epoch(self):
        self.actual_optimizer.new_epoch()

    def add_hook(self, hook, name=None, timing="pre"):
        self.actual_optimizer.add_hook(hook, name, timing)
        # add_hook resets _opt_state; every piece of wrapper state whose
        # lifetime tracks it resets too (same invariant as setup()): a
        # stale _zero_layout would make the serialize pre-seed guard
        # skip rebuilding the flat template, and a kept _stale_grads
        # would apply a pre-hook gradient against fresh optimizer state
        # instead of the double-buffer fresh-start semantics
        super().__setattr__("_zero_layout", None)
        super().__setattr__("_stale_grads", None)
        super().__setattr__("_residual", None)
        self._mn_step_cache.clear()

    def remove_hook(self, name):
        self.actual_optimizer.remove_hook(name)
        super().__setattr__("_zero_layout", None)
        super().__setattr__("_stale_grads", None)
        super().__setattr__("_residual", None)
        self._mn_step_cache.clear()

    def _gather_opt_state_to_host(self, opt_state):
        """Assemble non-fully-addressable (real multi-controller sharded)
        leaves as full host ndarrays on EVERY process, via the object
        channel.  ``np.asarray`` on such leaves raises — each process only
        holds its own 1/n chunk — so the npz writer cannot see them
        directly.  Gathering to host makes every per-host snapshot carry
        the complete flat vector; ``_commit_opt_state_to_mesh`` re-pads it
        on load, so resume tolerates a changed communicator size.

        COLLECTIVE on a real multi-process mesh: every process must enter
        ``serialize`` (the per-host multi-node checkpointer does; a
        rank-0-only ``extensions.snapshot()`` pattern would deadlock in
        the allgather — use ``create_multi_node_checkpointer`` for ZeRO
        runs, as the reference does for distributed state)."""
        def materialize(leaf):
            if not isinstance(leaf, jax.Array) or leaf.is_fully_addressable:
                return leaf
            local = [(s.index, np.asarray(s.data))
                     for s in leaf.addressable_shards]
            gathered = self.communicator._process_allgather_pickled(local)
            out = np.empty(leaf.shape, leaf.dtype)
            for shards in gathered:
                for index, data in shards:
                    out[index] = data
            return out

        return jax.tree.map(materialize, opt_state)

    def _commit_opt_state_to_mesh(self, opt_state):
        """Re-commit restored flat (n_pad,) leaves to the ZeRO sharded
        layout.  ``deserialize_flat_tree`` leaves full host-replicated
        arrays; on a real multi-process mesh the compiled step's
        ``shard_map`` needs globally-sharded ``jax.Array`` inputs, and on
        any mesh committing up front avoids a device_put inside the first
        post-resume step.  A flat vector saved under a DIFFERENT
        communicator size (padding to a different multiple) is sliced to
        the true parameter length ``n`` and re-padded to this mesh's
        ``n_pad`` first — the host-gathered snapshots are full vectors,
        so size-changed resume is well-defined."""
        if self.communicator.striped:
            return self._commit_striped_state_to_mesh(opt_state)
        chunk_spec = self.communicator.flat_chunk_spec()
        mesh = self.communicator.mesh
        _, n, n_pad = self._zero_layout

        def commit(leaf):
            if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
                # already mesh-sharded (e.g. the pre-seeded template kept
                # by a partial/pre-feature snapshot): nothing to commit,
                # and np.asarray on it would raise
                return leaf
            if getattr(leaf, "ndim", 0) != 1:
                return leaf
            if leaf.shape[0] != n_pad:
                if leaf.shape[0] < n:
                    return leaf  # not a flat param vector
                leaf = jnp.pad(jnp.asarray(leaf)[:n], (0, n_pad - n))
            host = np.asarray(leaf)
            sharding = jax.sharding.NamedSharding(mesh, chunk_spec)
            return jax.make_array_from_callback(
                host.shape, sharding, lambda idx: host[idx])

        return jax.tree.map(commit, opt_state)

    def _commit_striped_state_to_mesh(self, tree):
        """Striped variant of :meth:`_commit_opt_state_to_mesh`: each
        flat leaf of the ``{"ici", "dcn"}`` pair layout commits to ITS
        path's chunk spec (fast- vs slow-hop-major), resolved by the
        leaf's dict-key path — the two padded lengths may coincide, so
        the tree position, not the shape, is the disambiguator.  A leaf
        saved under a different communicator SIZE re-pads from its
        path's true (size-independent) slice length; a different
        STRIPE RATIO moves the split point itself, which the ef-
        residual-style re-seed contract does not cover — resume striped
        state with the ratio it was saved under."""
        from jax.tree_util import DictKey, tree_map_with_path
        comm = self.communicator
        mesh = comm.mesh
        _, n, _ = self._zero_layout
        n_i, n_pa, n_pb = self._striped_split(n)
        fast, slow = comm.striped_chunk_specs()
        target = {"ici": (n_i, n_pa, fast), "dcn": (n - n_i, n_pb, slow)}

        def commit(path, leaf):
            if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
                return leaf
            if getattr(leaf, "ndim", 0) != 1:
                return leaf
            keys = [k.key for k in path if isinstance(k, DictKey)
                    and k.key in target]
            if not keys:
                return leaf
            true_n, n_pad, cspec = target[keys[-1]]
            if leaf.shape[0] != n_pad:
                if leaf.shape[0] < true_n:
                    return leaf  # not a flat slice vector
                leaf = jnp.pad(jnp.asarray(leaf)[:true_n],
                               (0, n_pad - true_n))
            host = np.asarray(leaf)
            sharding = jax.sharding.NamedSharding(mesh, cspec)
            return jax.make_array_from_callback(
                host.shape, sharding, lambda idx: host[idx])

        return tree_map_with_path(commit, tree)

    def serialize(self, serializer):
        actual = self.actual_optimizer
        if self._sharded_update and not serializer.is_writer \
                and actual.target is not None and self._zero_layout is None:
            # The saved opt_state leaves are flat (n_pad,) vectors.  The
            # base reader builds its template from the CURRENT _opt_state
            # — or, when None, from the default per-param tree, whose leaf
            # count/shapes mismatch the flat save.  Pre-seed the flat
            # sharded template + _zero_layout before delegating.  Guarded
            # on _zero_layout is None: a warm ZeRO process already holds a
            # valid flat template (and must NOT be reset — a snapshot
            # without opt_state keys would otherwise silently zero trained
            # state); a layout-less process either has no state or a
            # per-param tree from pre-wrapper use, both safely rebuilt.
            params = extract_state(actual.target)["params"]
            if not params or any(v is None for v in params.values()):
                # lazily-initialized model: take shapes from the snapshot
                # (idempotent — the delegated serialize re-reads this
                # section)
                actual.target.serialize(serializer["target"])
                params = extract_state(actual.target)["params"]
            if params and all(v is not None for v in params.values()):
                actual._opt_state = None
                self._ensure_zero_opt_state(params)
        device_state = None
        if serializer.is_writer and self._sharded_update \
                and actual._opt_state is not None \
                and any(isinstance(l, jax.Array)
                        and not l.is_fully_addressable
                        for l in jax.tree.leaves(actual._opt_state)):
            # real multi-controller mesh: swap in host-assembled full
            # vectors for the write, then restore the sharded originals
            device_state = actual._opt_state
            actual._opt_state = self._gather_opt_state_to_host(device_state)
        try:
            actual.serialize(serializer)
        finally:
            if device_state is not None:
                actual._opt_state = device_state
        if self._sharded_update and not serializer.is_writer \
                and actual._opt_state is not None \
                and self._zero_layout is not None:
            actual._opt_state = self._commit_opt_state_to_mesh(
                actual._opt_state)
        if self._needs_residual:
            # the error-feedback residual is OBSERVABLE state (ISSUE 8):
            # the telescoping sum — applied updates so far + residual ==
            # true gradient sum — must survive a checkpoint/restore, or
            # the resumed run silently drops the carried error.  Same
            # flat-vector plumbing as the stale chunk.  Size-changed
            # resume re-seeds ZEROS: the residual is per-DEVICE
            # quantization error with no global content invariant (a new
            # partition quantizes different chunks), and dropping it
            # costs exactly one step of correction, never correctness.
            self._serialize_residual(serializer)
        if self._double_buffering:
            # the one-step-stale gradient buffer is OBSERVABLE state:
            # without it a resumed run applies zeros on its first update
            # (fresh-start semantics) instead of the saved step's grads,
            # breaking bit-exact resume
            from .core.optimizer import (deserialize_flat_tree,
                                         serialize_flat_tree)
            sub = serializer["stale_grads"]
            if serializer.is_writer:
                if self._stale_grads is not None:
                    # reduce-scatter double buffering on a real
                    # multi-controller mesh: the stale buffer is
                    # P(axis)-sharded (each process holds its 1/n
                    # chunk) and np.asarray on it raises — same
                    # host-gather the opt_state write gets above
                    serialize_flat_tree(
                        sub,
                        self._gather_opt_state_to_host(self._stale_grads),
                        "n", "g")
                return
            if actual.target is None:
                return  # target-less load: base serialize skipped too
            params = extract_state(actual.target)["params"]
            if not params or any(v is None for v in params.values()):
                super().__setattr__("_stale_grads", None)
                return
            if self._db_dcn:
                # DCN-slice-only stale variant (ISSUE 11): a flat
                # replicated vector of the buckets' DCN-path slices —
                # length derivable from params + the committed ratio
                template = jnp.zeros(
                    (self.communicator.grad_dcn_stale_len_for(
                        actual.target),), jnp.float32)
            elif self._sharded_update:
                # reduce-scatter double buffering: the stale buffer is
                # the flat padded mean-gradient vector, not a per-param
                # tree.  Its length is derivable from params alone, so
                # compute it directly rather than depending on the
                # opt-state pre-seed having run.
                if self._zero_layout is not None:
                    _, n, n_pad = self._zero_layout
                else:
                    from .communicators._memory_utility import tree_pack
                    n = tree_pack(params)[0].shape[0]
                    size = self.communicator.size
                    n_pad = -(-n // size) * size
                template = jnp.zeros((n_pad,), jnp.float32) \
                    if not self.communicator.striped \
                    else self._striped_chunk_template()
            else:
                template = jax.tree.map(jnp.zeros_like, params)
            restored = deserialize_flat_tree(sub, template, "n", "g")
            if self._sharded_update and self.communicator.striped \
                    and restored is not None:
                # striped pair layout: commit each path's slice to its
                # own chunk spec (size-changed re-pad included)
                super().__setattr__(
                    "_stale_grads",
                    self._commit_striped_state_to_mesh(restored))
                return
            if self._sharded_update and restored is not None and not (
                    isinstance(restored, jax.Array)
                    and not restored.is_fully_addressable):
                if restored.shape != template.shape \
                        and restored.shape[0] >= n:
                    # saved under a DIFFERENT communicator size: the
                    # vector is padded to the old size's multiple, but
                    # content length n is invariant — slice and re-pad,
                    # the same size-changed resume contract
                    # _commit_opt_state_to_mesh gives the flat opt-state
                    # leaves
                    restored = jnp.pad(jnp.asarray(restored)[:n],
                                       (0, n_pad - n))
                # commit to the P(axis) layout the compiled step's
                # shard_map expects — on a real multi-controller mesh the
                # host-replicated restore cannot be auto-sharded at
                # dispatch (same reason the opt-state restore goes
                # through _commit_opt_state_to_mesh)
                host = np.asarray(restored)
                sharding = jax.sharding.NamedSharding(
                    self.communicator.mesh,
                    self.communicator.flat_chunk_spec())
                restored = jax.make_array_from_callback(
                    host.shape, sharding, lambda idx: host[idx])
            # None restored = snapshot predates stale-grad saving (or was
            # taken before the first update): fresh zero-seed semantics
            super().__setattr__("_stale_grads", restored)

    def _serialize_residual(self, serializer):
        from .core.optimizer import (deserialize_flat_tree,
                                     serialize_flat_tree)
        actual = self.actual_optimizer
        sub = serializer["ef_residual"]
        if serializer.is_writer:
            if self._residual is not None:
                # sharded on a real multi-controller mesh — same
                # host-gather the opt_state/stale writes get
                serialize_flat_tree(
                    sub, self._gather_opt_state_to_host(self._residual),
                    "n", "r")
                # the residual is per-DEVICE content: record the world
                # size it was partitioned for, so a size-changed resume
                # re-seeds even when the GLOBAL lengths coincide (e.g.
                # ceil(n/4)·8 == ceil(n/2)·4 — ISSUE 10 satellite)
                sub("world_size", self.communicator.size)
            return
        if actual.target is None:
            return
        params = extract_state(actual.target)["params"]
        if not params or any(v is None for v in params.values()):
            super().__setattr__("_residual", None)
            return
        if self._sharded_update and self._zero_layout is None:
            # no flat layout yet (e.g. pre-feature snapshot without
            # opt_state): the residual length is underivable — zero-seed
            # on first update instead
            super().__setattr__("_residual", None)
            return
        length = self._residual_global_len()
        template = jnp.zeros((length,), jnp.float32)
        restored = deserialize_flat_tree(sub, template, "n", "r")
        if restored is None:
            # pre-feature snapshot: fresh zero-seed on first update
            super().__setattr__("_residual", None)
            return
        try:
            saved_size = int(sub("world_size", -1))
        except KeyError:
            saved_size = -1  # strict reader, pre-field snapshot
        if saved_size not in (-1, self.communicator.size):
            # partitioned for a DIFFERENT world: zero-seed even when the
            # global length happens to coincide (the shape check below
            # cannot see a re-partition at equal length)
            super().__setattr__("_residual", None)
            return
        if not (isinstance(restored, jax.Array)
                and not restored.is_fully_addressable):
            if restored.shape != template.shape:
                # saved under a DIFFERENT communicator size/plan:
                # per-device error has no cross-partition meaning —
                # zero-seed (documented contract, one step of error)
                super().__setattr__("_residual", None)
                return
            host = np.asarray(restored)
            sharding = jax.sharding.NamedSharding(
                self.communicator.mesh,
                self.communicator.flat_chunk_spec())
            restored = jax.make_array_from_callback(
                host.shape, sharding, lambda idx: host[idx])
        super().__setattr__("_residual", restored)


class _DoubleBufferingOptimizer(_MultiNodeOptimizer):
    """One-step-stale gradient application (reference semantics).

    Reference: ``optimizers.py · _DoubleBufferingOptimizer`` — allreduce of
    step *t*'s grads overlaps step *t+1*'s compute; the applied gradient is
    one step old.  Here both live in the same compiled program and XLA's
    async dispatch provides the overlap; the observable contract (first
    update applies zeros, update ``t`` applies grads of ``t-1``) matches.

    ``db_mode="dcn"`` (ISSUE 11, striped communicators only): staleness
    applies PER PATH — the ICI-path slice of every bucket is applied
    fresh, only the DCN-path slice is one step old (first update applies
    zeros on the DCN slices).  The stale buffer shrinks to the
    ``stripe_ratio`` fraction of a full stale tree, and the slow
    fabric's latency is hidden without giving up freshness on the fast
    path.
    """

    _double_buffering = True

    def __init__(self, actual_optimizer, communicator, zero_fill=True,
                 exchange="allreduce", db_mode=True):
        super().__init__(actual_optimizer, communicator, zero_fill,
                         exchange=exchange)
        super().__setattr__("_db_mode", db_mode)
