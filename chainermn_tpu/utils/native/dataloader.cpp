// Native data-loader core: prefetching batch assembly.
//
// TPU-native counterpart of the reference's performance-critical host
// components (SURVEY.md §2.5): where ChainerMN's input pipeline leaned on
// MultiprocessIterator workers and its comm layer on batched-copy CUDA
// kernels (`_memory_utility.py` N2), the TPU host's job is to keep the
// device fed — assembling example rows into contiguous batch buffers and
// having the next batch ready before the device asks.  This core does the
// gather with a thread pool over a ring of reusable buffers, entirely off
// the Python GIL; Python drives it through a minimal C ABI (ctypes — no
// pybind11 in this image).
//
// Model: one Loader per (dataset array); jobs are index lists; each job
// fills one ring buffer with data[indices[i]] rows via parallel memcpy.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

namespace {

struct Job {
    std::vector<int64_t> indices;
    int buffer_id;
};

struct Loader {
    const uint8_t* data = nullptr;
    size_t n_rows = 0;
    size_t row_bytes = 0;
    size_t max_batch = 0;
    int n_buffers = 0;

    // Ring slots.  `buffers` are raw slot pointers; when the caller
    // supplies the ring memory (the Python binding passes a numpy-owned
    // block), `owned` stays empty and destroy() never frees the slots —
    // so a consumer-held view can never dangle, whatever its lifetime.
    std::vector<uint8_t*> buffers;
    std::vector<std::vector<uint8_t>> owned;
    std::vector<size_t> buffer_rows;  // rows filled per buffer

    // free buffer pool / pending jobs / completed buffers
    std::deque<int> free_buffers;
    std::deque<Job> pending;
    std::deque<int> completed;

    std::mutex mu;
    std::condition_variable cv_free;      // buffer became free
    std::condition_variable cv_pending;   // job arrived
    std::condition_variable cv_done;      // batch completed

    std::vector<std::thread> workers;
    std::atomic<bool> stop{false};
    int n_threads = 1;

    // intra-batch parallel gather state.  `current` may only be
    // (re)assigned under gmu AND with active_gatherers == 0: helpers
    // read it lock-free inside gather_rows, so reassigning while one is
    // still copying is a use-after-move on the indices vector (a real
    // crash seen as a flaky suite segfault).  `epoch` stops a helper
    // that finished its chunks early from re-entering the same job in a
    // spin while `gathering` is still up.
    std::mutex gmu;
    std::condition_variable cv_gather;
    Job current;
    uint64_t epoch = 0;               // guarded by gmu
    std::atomic<int> active_gatherers{0};
    std::atomic<size_t> next_row{0};
    std::atomic<size_t> rows_done{0};
    std::atomic<bool> gathering{false};
};

void gather_rows(Loader* L) {
    // workers cooperatively pull row ranges of the current job
    const size_t chunk = 64;
    uint8_t* dst = L->buffers[L->current.buffer_id];
    const size_t n = L->current.indices.size();
    for (;;) {
        size_t start = L->next_row.fetch_add(chunk);
        if (start >= n) break;
        size_t end = start + chunk < n ? start + chunk : n;
        for (size_t i = start; i < end; ++i) {
            int64_t row = L->current.indices[i];
            std::memcpy(dst + i * L->row_bytes,
                        L->data + static_cast<size_t>(row) * L->row_bytes,
                        L->row_bytes);
        }
        L->rows_done.fetch_add(end - start);
    }
}

void worker_main(Loader* L, bool leader) {
    uint64_t last_epoch = 0;  // helpers: last job generation gathered
    for (;;) {
        if (leader) {
            Job job;
            {
                std::unique_lock<std::mutex> lk(L->mu);
                L->cv_pending.wait(lk, [&] {
                    return L->stop.load() || !L->pending.empty();
                });
                if (L->stop.load()) break;
                job = std::move(L->pending.front());
                L->pending.pop_front();
            }
            {
                // helpers from the PREVIOUS job must be fully out of
                // gather_rows before `current` is reassigned (they read
                // it lock-free)
                std::unique_lock<std::mutex> g(L->gmu);
                L->cv_gather.wait(g, [&] {
                    return L->stop.load() ||
                           L->active_gatherers.load() == 0;
                });
                if (L->stop.load()) break;
                L->current = std::move(job);
                L->epoch++;
                L->next_row.store(0);
                L->rows_done.store(0);
                L->gathering.store(true);
            }
            L->cv_gather.notify_all();
            gather_rows(L);
            // wait until all rows are in (helpers may still be copying)
            while (L->rows_done.load() < L->current.indices.size()) {
                std::this_thread::yield();
                if (L->stop.load()) return;
            }
            {
                std::lock_guard<std::mutex> lk(L->mu);
                L->gathering.store(false);
                L->buffer_rows[L->current.buffer_id] =
                    L->current.indices.size();
                L->completed.push_back(L->current.buffer_id);
            }
            L->cv_done.notify_all();
        } else {
            {
                std::unique_lock<std::mutex> lk(L->gmu);
                L->cv_gather.wait(lk, [&] {
                    return L->stop.load() ||
                           (L->gathering.load() &&
                            L->epoch != last_epoch);
                });
                if (L->stop.load()) break;
                last_epoch = L->epoch;
                L->active_gatherers.fetch_add(1);
            }
            gather_rows(L);
            {
                std::lock_guard<std::mutex> lk(L->gmu);
                L->active_gatherers.fetch_sub(1);
            }
            L->cv_gather.notify_all();  // leader may wait for idle
        }
    }
}

}  // namespace

extern "C" {

// `ring`: optional caller-owned slot memory (n_buffers contiguous slots
// of max_batch*row_bytes each).  NULL = loader-allocated (freed on
// destroy; callers must then drop every view before destroy).
void* loader_create(const void* data, int64_t n_rows, int64_t row_bytes,
                    int64_t max_batch, int n_buffers, int n_threads,
                    void* ring) {
    Loader* L = new Loader();
    L->data = static_cast<const uint8_t*>(data);
    L->n_rows = static_cast<size_t>(n_rows);
    L->row_bytes = static_cast<size_t>(row_bytes);
    L->max_batch = static_cast<size_t>(max_batch);
    L->n_buffers = n_buffers;
    L->n_threads = n_threads > 0 ? n_threads : 1;
    L->buffers.resize(n_buffers);
    L->buffer_rows.resize(n_buffers, 0);
    const size_t slot_bytes = L->max_batch * L->row_bytes;
    if (ring != nullptr) {
        uint8_t* base = static_cast<uint8_t*>(ring);
        for (int i = 0; i < n_buffers; ++i)
            L->buffers[i] = base + static_cast<size_t>(i) * slot_bytes;
    } else {
        L->owned.resize(n_buffers);
        for (int i = 0; i < n_buffers; ++i) {
            L->owned[i].resize(slot_bytes);
            L->buffers[i] = L->owned[i].data();
        }
    }
    for (int i = 0; i < n_buffers; ++i)
        L->free_buffers.push_back(i);
    L->workers.emplace_back(worker_main, L, true);
    for (int t = 1; t < L->n_threads; ++t)
        L->workers.emplace_back(worker_main, L, false);
    return L;
}

// Enqueue a gather job. Blocks if no ring buffer is free (backpressure).
// Returns 0 on success, -1 on invalid arguments.
int loader_submit(void* handle, const int64_t* indices, int64_t n) {
    Loader* L = static_cast<Loader*>(handle);
    if (n < 0 || static_cast<size_t>(n) > L->max_batch) return -1;
    for (int64_t i = 0; i < n; ++i)
        if (indices[i] < 0 ||
            static_cast<size_t>(indices[i]) >= L->n_rows) return -1;
    Job job;
    job.indices.assign(indices, indices + n);
    {
        std::unique_lock<std::mutex> lk(L->mu);
        L->cv_free.wait(lk, [&] {
            return L->stop.load() || !L->free_buffers.empty();
        });
        if (L->stop.load()) return -1;
        job.buffer_id = L->free_buffers.front();
        L->free_buffers.pop_front();
        L->pending.push_back(std::move(job));
    }
    L->cv_pending.notify_all();
    return 0;
}

// Block until a completed batch is available; returns buffer id and
// writes the row count + buffer pointer.
int loader_next(void* handle, void** out_ptr, int64_t* out_rows) {
    Loader* L = static_cast<Loader*>(handle);
    std::unique_lock<std::mutex> lk(L->mu);
    L->cv_done.wait(lk, [&] {
        return L->stop.load() || !L->completed.empty();
    });
    if (L->stop.load() && L->completed.empty()) return -1;
    int id = L->completed.front();
    L->completed.pop_front();
    *out_ptr = L->buffers[id];
    *out_rows = static_cast<int64_t>(L->buffer_rows[id]);
    return id;
}

// Return a buffer to the pool once its contents have been consumed.
void loader_release(void* handle, int buffer_id) {
    Loader* L = static_cast<Loader*>(handle);
    {
        std::lock_guard<std::mutex> lk(L->mu);
        L->free_buffers.push_back(buffer_id);
    }
    L->cv_free.notify_all();
}

void loader_destroy(void* handle) {
    Loader* L = static_cast<Loader*>(handle);
    // store stop while holding each CV's mutex: a bare store+notify can
    // land between a waiter's predicate check and its sleep (the waiter
    // holds the mutex there, but a notifier that never takes it can
    // slip into that window) — the wakeup is lost and join() hangs
    {
        std::lock_guard<std::mutex> lk(L->mu);
        L->stop.store(true);
    }
    {
        std::lock_guard<std::mutex> g(L->gmu);
    }
    L->cv_pending.notify_all();
    L->cv_gather.notify_all();
    L->cv_free.notify_all();
    L->cv_done.notify_all();
    for (auto& t : L->workers) t.join();
    delete L;
}

}  // extern "C"
