"""ctypes binding + build for the native data-loader core.

Compiled on first use with g++ (cached beside the source); degrades
gracefully to None when no toolchain is available — consumers fall back
to the pure-Python iterators.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

__all__ = ["load_library", "bind_signatures", "NativeLoader"]

_lock = threading.Lock()
_lib = None
_tried = False


def _build(src, out):
    subprocess.run(
        ["g++", "-O3", "-march=native", "-shared", "-fPIC", "-std=c++17",
         "-pthread", src, "-o", out],
        check=True, capture_output=True)


def load_library():
    """Build (if needed) and load the shared library; None on failure."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        here = os.path.dirname(__file__)
        src = os.path.join(here, "dataloader.cpp")
        out = os.path.join(here, "_dataloader.so")
        try:
            if not os.path.exists(out) or \
                    os.path.getmtime(out) < os.path.getmtime(src):
                _build(src, out)
            lib = ctypes.CDLL(out)
        except Exception:
            return None
        bind_signatures(lib)
        _lib = lib
        return _lib


def bind_signatures(lib):
    """Declare the C ABI on a loaded library handle.  The single source
    of truth for the loader's ctypes signatures — also used by
    tools/tsan_check_dataloader.sh on its sanitizer-built variant, so a
    signature change cannot silently drift between the two."""
    lib.loader_create.restype = ctypes.c_void_p
    lib.loader_create.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int, ctypes.c_int, ctypes.c_void_p]
    lib.loader_submit.restype = ctypes.c_int
    lib.loader_submit.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64]
    lib.loader_next.restype = ctypes.c_int
    lib.loader_next.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_int64)]
    lib.loader_release.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.loader_destroy.argtypes = [ctypes.c_void_p]
    return lib


class NativeLoader:
    """One gather engine over a contiguous [N, ...] numpy array.

    Ring-slot memory is allocated HERE as a numpy array and lent to the
    C++ engine: batch views are numpy slices whose ``.base`` chain keeps
    the ring alive, so a view held past ``close()`` (or interpreter
    shutdown teardown order) can go stale in CONTENT but never dangle —
    the use-after-free class of bugs is excluded by ownership."""

    def __init__(self, array: np.ndarray, max_batch: int, n_buffers=3,
                 n_threads=4):
        lib = load_library()
        if lib is None:
            raise RuntimeError("native loader unavailable (no g++?)")
        self._lib = lib
        self._array = np.ascontiguousarray(array)  # keep alive
        self.row_shape = self._array.shape[1:]
        self.dtype = self._array.dtype
        self._row_bytes = int(self._array.dtype.itemsize
                              * np.prod(self.row_shape, dtype=np.int64))
        self.max_batch = max_batch
        self._ring = np.empty((n_buffers, max_batch * self._row_bytes),
                              dtype=np.uint8)
        self._handle = lib.loader_create(
            self._array.ctypes.data_as(ctypes.c_void_p),
            self._array.shape[0], self._row_bytes, max_batch,
            n_buffers, n_threads,
            self._ring.ctypes.data_as(ctypes.c_void_p))

    def submit(self, indices: np.ndarray):
        idx = np.ascontiguousarray(indices, dtype=np.int64)
        rc = self._lib.loader_submit(
            self._handle, idx.ctypes.data_as(
                ctypes.POINTER(ctypes.c_int64)), idx.size)
        if rc != 0:
            raise ValueError("invalid indices or batch too large")

    def _next_raw(self):
        ptr = ctypes.c_void_p()
        rows = ctypes.c_int64()
        buf_id = self._lib.loader_next(self._handle, ctypes.byref(ptr),
                                       ctypes.byref(rows))
        if buf_id < 0:
            raise RuntimeError("loader stopped")
        n = rows.value
        # slice of the python-owned ring (not a raw-pointer frombuffer):
        # the view's .base keeps the memory alive beyond close()
        view = self._ring[buf_id, :n * self._row_bytes] \
            .view(self.dtype).reshape((n,) + self.row_shape)
        return view, buf_id

    def next(self) -> np.ndarray:
        """Owned batch copy (ring slot released immediately)."""
        view, buf_id = self._next_raw()
        batch = view.copy()
        self._lib.loader_release(self._handle, buf_id)
        return batch

    def next_view(self):
        """Zero-copy ``(view, buf_id)`` of the ring slot — the DLPack
        hand-off path.  The view aliases loader-owned memory: the caller
        must ``release(buf_id)`` once the batch has been consumed, and
        must not touch the view afterwards."""
        return self._next_raw()

    def release(self, buf_id):
        self._lib.loader_release(self._handle, buf_id)

    def close(self):
        if getattr(self, "_handle", None):
            self._lib.loader_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
