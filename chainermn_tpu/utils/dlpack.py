"""DLPack host bridge (north star, SURVEY.md §2.8 cupy row).

The reference's ``cupy`` interop moved tensors between host numpy and
device with explicit copies.  The TPU-native translation is the DLPack
protocol, with an asymmetric zero-copy story dictated by JAX's
immutability model:

* **export** (``to_numpy``): a committed-to-CPU ``jax.Array`` exports as
  a numpy *view* — zero bytes moved, stable pointer.  Serialization,
  metrics, and checkpoint writes ride this.
* **import** (``from_numpy``): standard DLPack semantics — the CPU
  backend MAY alias the source buffer (zero-copy; observed on the
  simulated-mesh configuration) or copy once; on TPU the host→HBM DMA
  is the single copy.  Either way there is never a second host-side
  staging duplicate.  Contract: callers must not mutate the source
  array after importing (aliasing makes mutation visible to XLA, which
  assumes immutability).  The native iterator's ring hand-off defers
  slot release until the batch is consumed for exactly this reason.

Both are total functions: they fall back to plain conversions for
non-contiguous buffers or exotic platforms, so callers use them
unconditionally.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

__all__ = ["from_numpy", "to_numpy"]


def from_numpy(x):
    """numpy → ``jax.Array``; may alias the source (zero-copy) — do not
    mutate ``x`` afterwards (see module doc)."""
    if not isinstance(x, np.ndarray):
        return jnp.asarray(x)
    if x.flags.c_contiguous:
        try:
            return jnp.from_dlpack(x)
        except Exception:
            pass  # backend can't import host DLPack (e.g. TPU-only)
    return jnp.asarray(x)


def to_numpy(x):
    """``jax.Array`` → numpy; zero-copy for committed-to-CPU arrays,
    ``device_get`` copy for device arrays."""
    if isinstance(x, np.ndarray):
        return x
    try:
        if all(d.platform == "cpu" for d in x.devices()):
            return np.from_dlpack(x)
    except Exception:
        pass
    return np.asarray(jax.device_get(x))
