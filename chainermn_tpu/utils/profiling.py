"""Profiling helpers (SURVEY.md §5 tracing note).

The reference's story was TimerHook/CupyMemoryProfileHook + nvprof; the
TPU rebuild rides ``jax.profiler`` (XProf/TensorBoard traces with HLO,
fusion, and ICI collective timelines) — strictly better out of the box.
These helpers wrap it in the framework's vocabulary, plus a trainer
extension that captures a trace window mid-run.  The ``dummy``
communicator remains the tool for compute-vs-communication attribution
(run the same script twice, diff the step times — the reference's own
methodology).
"""

from __future__ import annotations

import contextlib

import jax

from ..training.trainer import Extension

__all__ = ["trace", "annotate", "Profile"]


@contextlib.contextmanager
def trace(log_dir="/tmp/chainermn_tpu_trace"):
    """Capture a jax.profiler trace (open with TensorBoard/XProf)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()


def annotate(name):
    """Named scope visible in trace timelines (``jax.named_scope``)."""
    return jax.named_scope(name)


class Profile(Extension):
    """Trainer extension: trace iterations [start, start+n_steps).

    ``trainer.extend(Profile(start=10, n_steps=3))`` captures steady-state
    steps (skipping compilation) into ``<out>/trace``.

    Leak contract (ISSUE 14 satellite): a run that ends — or RAISES —
    inside the trace window must still stop the trace.  Three layers
    close it:

    * ``on_error`` stops the trace the moment a failure escapes the
      training loop — BEFORE any recovery supervisor resumes, so a
      recovered run's capture cannot silently bleed across the failure
      (and a fail-stop run doesn't rely on finalizers at all);
    * ``finalize`` (the trainer's ``finally``) stops it on any exit,
      and ``Trainer.run`` exception-isolates the finalize fan-out so
      another extension's failing ``finalize`` can no longer starve
      this one (the leak the regression test pins);
    * ``_stop`` itself is idempotent and swallows ``stop_trace``'s own
      errors into a warning — a profiler wedge must not mask the
      original exception.
    """

    trigger = (1, "iteration")
    priority = 400  # before anything else each iteration

    def __init__(self, start=10, n_steps=3, log_dir=None):
        self.start = start
        self.n_steps = n_steps
        self.log_dir = log_dir
        self._active = False

    def __call__(self, trainer):
        it = trainer.updater.iteration
        if not self._active and it == self.start:
            jax.profiler.start_trace(
                self.log_dir or f"{trainer.out}/trace")
            self._active = True
        elif self._active and it >= self.start + self.n_steps:
            self._stop()

    def _stop(self):
        if not self._active:
            return
        self._active = False   # first: a failing stop must not re-fire
        try:
            jax.profiler.stop_trace()
        except Exception as e:  # noqa: BLE001 — never mask the caller
            import warnings
            warnings.warn(f"jax.profiler.stop_trace failed while "
                          f"closing a Profile window: {e}", stacklevel=2)

    def on_error(self, trainer, exc, tb):
        self._stop()

    def finalize(self):
        self._stop()
