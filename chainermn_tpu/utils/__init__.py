from .platform import use_platform, simulate_devices
