"""JAX version-compat shims.

The framework targets current jax (``from jax import shard_map`` with a
``check_vma`` kwarg); older releases ship the same callable at
``jax.experimental.shard_map`` under the pre-rename ``check_rep`` kwarg.
Code imports :func:`shard_map` from here so one site absorbs the API
move — the same discipline as the pallas ``CompilerParams`` rename gate
in ``ops/flash_attention.py``.
"""

from __future__ import annotations

import inspect

__all__ = ["shard_map", "axis_env_contains"]

try:
    from jax import shard_map as _shard_map  # jax >= 0.6-era export
except ImportError:  # pragma: no cover - depends on installed jax
    from jax.experimental.shard_map import shard_map as _shard_map

_PARAMS = set(inspect.signature(_shard_map).parameters)


def shard_map(*args, **kwargs):
    """``jax.shard_map`` with the replication-check kwarg translated to
    whatever name the installed jax uses (``check_vma`` ⇄ ``check_rep``)."""
    if "check_vma" in kwargs and "check_vma" not in _PARAMS:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    elif "check_rep" in kwargs and "check_rep" not in _PARAMS:
        kwargs["check_vma"] = kwargs.pop("check_rep")
    return _shard_map(*args, **kwargs)


_axis_query = None


def _resolve_axis_query():
    """The installed jax's explicit axis-environment query.  Two known
    homes; resolving fails LOUDLY (ImportError) rather than falling back
    to exception-probe dispatch — a jax upgrade that moves the API again
    must surface here, not silently flip eager/traced mode selection
    (VERDICT open item 7)."""
    try:  # jax >= 0.4.3x: the trace-global axis env object
        from jax._src.core import get_axis_env
        return lambda name: bool(get_axis_env().axis_exists(name))
    except ImportError:
        pass
    from jax import core as _core  # public-ish accessor on the same env
    unsafe_names = getattr(_core, "unsafe_get_axis_names_DO_NOT_USE", None)
    if unsafe_names is not None:
        return lambda name: name in unsafe_names()
    raise ImportError(
        "no axis-environment query found in this jax "
        "(jax._src.core.get_axis_env / "
        "jax.core.unsafe_get_axis_names_DO_NOT_USE); update "
        "chainermn_tpu.utils.compat.axis_env_contains for this version")


def axis_env_contains(name):
    """True when ``name`` is bound as a mapped axis by an enclosing
    ``shard_map``/``pmap`` of the current trace — the explicit check
    behind ``Communicator._axis_in_scope`` (no traced-probe-and-catch)."""
    global _axis_query
    if _axis_query is None:
        _axis_query = _resolve_axis_query()
    return _axis_query(name)
