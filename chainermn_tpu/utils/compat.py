"""JAX version-compat shims.

The framework targets current jax (``from jax import shard_map`` with a
``check_vma`` kwarg); older releases ship the same callable at
``jax.experimental.shard_map`` under the pre-rename ``check_rep`` kwarg.
Code imports :func:`shard_map` from here so one site absorbs the API
move — the same discipline as the pallas ``CompilerParams`` rename gate
in ``ops/flash_attention.py``.
"""

from __future__ import annotations

import inspect

__all__ = ["shard_map"]

try:
    from jax import shard_map as _shard_map  # jax >= 0.6-era export
except ImportError:  # pragma: no cover - depends on installed jax
    from jax.experimental.shard_map import shard_map as _shard_map

_PARAMS = set(inspect.signature(_shard_map).parameters)


def shard_map(*args, **kwargs):
    """``jax.shard_map`` with the replication-check kwarg translated to
    whatever name the installed jax uses (``check_vma`` ⇄ ``check_rep``)."""
    if "check_vma" in kwargs and "check_vma" not in _PARAMS:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    elif "check_rep" in kwargs and "check_rep" not in _PARAMS:
        kwargs["check_vma"] = kwargs.pop("check_rep")
    return _shard_map(*args, **kwargs)
