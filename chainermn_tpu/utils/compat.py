"""JAX version-compat shims.

The framework targets current jax (``from jax import shard_map`` with a
``check_vma`` kwarg); older releases ship the same callable at
``jax.experimental.shard_map`` under the pre-rename ``check_rep`` kwarg.
Code imports :func:`shard_map` from here so one site absorbs the API
move — the same discipline as the pallas ``CompilerParams`` rename gate
in ``ops/flash_attention.py``.
"""

from __future__ import annotations

import inspect
import os

__all__ = ["shard_map", "axis_env_contains", "persistent_cache_safe",
           "configure_persistent_cache"]

try:
    from jax import shard_map as _shard_map  # jax >= 0.6-era export
except ImportError:  # pragma: no cover - depends on installed jax
    from jax.experimental.shard_map import shard_map as _shard_map

_PARAMS = set(inspect.signature(_shard_map).parameters)


def shard_map(*args, **kwargs):
    """``jax.shard_map`` with the replication-check kwarg translated to
    whatever name the installed jax uses (``check_vma`` ⇄ ``check_rep``)."""
    if "check_vma" in kwargs and "check_vma" not in _PARAMS:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    elif "check_rep" in kwargs and "check_rep" not in _PARAMS:
        kwargs["check_vma"] = kwargs.pop("check_rep")
    return _shard_map(*args, **kwargs)


_axis_query = None


def _resolve_axis_query():
    """The installed jax's explicit axis-environment query.  Two known
    homes; resolving fails LOUDLY (ImportError) rather than falling back
    to exception-probe dispatch — a jax upgrade that moves the API again
    must surface here, not silently flip eager/traced mode selection
    (VERDICT open item 7)."""
    try:  # jax >= 0.4.3x: the trace-global axis env object
        from jax._src.core import get_axis_env
        return lambda name: bool(get_axis_env().axis_exists(name))
    except ImportError:
        pass
    from jax import core as _core  # public-ish accessor on the same env
    unsafe_names = getattr(_core, "unsafe_get_axis_names_DO_NOT_USE", None)
    if unsafe_names is not None:
        return lambda name: name in unsafe_names()
    raise ImportError(
        "no axis-environment query found in this jax "
        "(jax._src.core.get_axis_env / "
        "jax.core.unsafe_get_axis_names_DO_NOT_USE); update "
        "chainermn_tpu.utils.compat.axis_env_contains for this version")


def axis_env_contains(name):
    """True when ``name`` is bound as a mapped axis by an enclosing
    ``shard_map``/``pmap`` of the current trace — the explicit check
    behind ``Communicator._axis_in_scope`` (no traced-probe-and-catch)."""
    global _axis_query
    if _axis_query is None:
        _axis_query = _resolve_axis_query()
    return _axis_query(name)


# ---------------------------------------------------------------------------
# XLA persistent compile cache — replay-segfault guard
# ---------------------------------------------------------------------------

def _platform_guess():
    """Best backend guess WITHOUT initializing jax (asking the backend
    would dial the wedge-prone TPU relay — the hazard bench.py exists
    to avoid): 'axon' only where the axon TPU plugin is actually
    installed (its sitecustomize home), else a plain CPU host."""
    return "axon" if os.path.exists("/root/.axon_site") else "cpu"


def persistent_cache_safe(platform, scan_program=False,
                          donated_program=False):
    """Is the XLA persistent compile cache safe for this (backend,
    program-kind) pair?

    Known defect on jax 0.4.37's CPU backend: a persisted executable
    for a scan-over-train-steps program (``update_scan`` /
    ``BENCH_SCAN`` — BENCH_NOTES r5 tail, run1 RC=0 / run2 RC=139) or
    for a step program with DONATED parameter buffers
    (``donate_argnums`` covering params; isolated during round 6's
    donation work — replay aborts/segfaults identically, and the
    donate-off program replays clean, reproduced at the pre-PR base
    commit too) compiles and runs clean on a COLD cache, then CRASHES
    when the next process replays the cached entry.  Undonated per-step
    programs (opt-state-only aliasing included) replay fine, and the
    TPU relay backend has not shown the defect (a warm cache is itself
    a relay-safety feature there — long compiles are what wedge it), so
    the skip stays scoped to the CONFIRMED-broken pairs.  A falsy
    ``platform`` is resolved via :func:`_platform_guess`: the axon box
    defaults to its TPU relay, any OTHER host defaults to CPU — where
    the replay crash is live.  Correctness first: the cache is an
    optimization.
    """
    plat = (platform or _platform_guess()).lower()
    return not ((scan_program or donated_program) and "cpu" in plat)


def configure_persistent_cache(jax_module, cache_dir=None, platform=None,
                               scan_program=False, donated_program=False):
    """Enable the persistent XLA compile cache when it is safe to.

    ``platform``: the backend the caller has pinned (None/"" = platform
    left to the runtime — the TPU relay on the bench box);
    ``scan_program`` / ``donated_program``: whether the process will
    compile scan-over-step programs / params-donated step programs (the
    two kinds whose persisted executables crash on CPU replay — see
    :func:`persistent_cache_safe`).  Scan programs that DO get a cache
    use a ``.scan``-keyed sibling directory, so a future backend showing
    the replay defect poisons only the scan slice (``rm -rf
    <dir>.scan`` heals it without recompiling every per-step program).
    Returns True when persistence was enabled.  One shared gate for
    ``bench.py`` and ``tools/probe_perf.py`` so the two cannot drift
    (the regression tests drive it through real warm-cache double
    runs).
    """
    if not persistent_cache_safe(platform, scan_program, donated_program):
        return False
    cache_dir = cache_dir or os.environ.get(
        "CHAINERMN_TPU_XLA_CACHE_DIR", "/tmp/chainermn_tpu_jax_cache")
    if scan_program:
        cache_dir = cache_dir + ".scan"
    try:
        jax_module.config.update("jax_compilation_cache_dir", cache_dir)
        jax_module.config.update(
            "jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        return False
    return True
