"""Mixture-of-experts transformer LM (expert parallelism end to end).

Beyond-reference model family: Switch-style MoE feed-forward blocks whose
expert bank shards one-expert-per-rank over an ``ep`` mesh axis
(``parallel.moe``), composed with the attention stack of
``models.transformer``.  Inside a compiled step each rank slices its
expert from the replicated bank (``functions.psum_gradient`` keeps the
bank's gradients exact under the replicated-loss convention) and tokens
are exchanged with one ``all_to_all`` round trip per layer — TWO-STAGE
over the ici × dcn hierarchy when ``ep_comm`` is hierarchical (ISSUE 12:
on-host tokens never touch the slow fabric, the DCN crossing compresses
under the communicator's per-hop dtype; ``two_stage=False`` is the
explicit single-axis escape).  ``topk > 1`` switches the router to the
GShard-style top-k mixture.  Outside any mesh axis the layer degrades to
dense routing — same math, no collectives — so the same weights run
single-device and expert-parallel.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.link import Chain, ChainList, Parameter
from ..core import reporter
from ..nn import functions as F
from ..nn import links as L
from .. import functions as mnfn
from .transformer import MultiHeadAttention, _axis_bound, _remat_policy

__all__ = ["MoEFeedForward", "MoETransformerBlock", "MoETransformerLM"]


class MoEFeedForward(Chain):
    def __init__(self, d_model, d_ff, ep_comm, capacity_factor=1.25,
                 seed=0, topk=1, two_stage=None):
        super().__init__()
        self.ep_comm = ep_comm
        self.capacity_factor = capacity_factor
        self.topk = int(topk)
        self.two_stage = two_stage
        E = ep_comm.size
        rng = np.random.RandomState(seed)
        with self.init_scope():
            self.router = Parameter(rng.normal(0, 0.02, (d_model, E))
                                    .astype(np.float32))
            self.w_in = Parameter(rng.normal(0, 0.02, (E, d_model, d_ff))
                                  .astype(np.float32))
            self.b_in = Parameter(np.zeros((E, d_ff), np.float32))
            self.w_out = Parameter(rng.normal(0, 0.02, (E, d_ff, d_model))
                                   .astype(np.float32))
            self.b_out = Parameter(np.zeros((E, d_model), np.float32))

    def forward(self, x, aux_sink=None):
        B, T, D = x.shape
        tokens = x.reshape(B * T, D)
        comm = self.ep_comm
        if _axis_bound(comm):
            from ..parallel.moe import (moe_dispatch_combine,
                                        moe_dispatch_combine_topk)
            # slice this rank's expert from the (replicated) bank;
            # psum_gradient reassembles the bank's gradient exactly
            idx = jax.lax.axis_index(comm.axis_name)
            w_in = jax.lax.dynamic_index_in_dim(
                mnfn.psum_gradient(comm, self.w_in.array), idx, 0, False)
            b_in = jax.lax.dynamic_index_in_dim(
                mnfn.psum_gradient(comm, self.b_in.array), idx, 0, False)
            w_out = jax.lax.dynamic_index_in_dim(
                mnfn.psum_gradient(comm, self.w_out.array), idx, 0, False)
            b_out = jax.lax.dynamic_index_in_dim(
                mnfn.psum_gradient(comm, self.b_out.array), idx, 0, False)
            gate_logits = tokens @ self.router.array

            def expert_fn(h):
                return F.gelu(h @ w_in + b_in) @ w_out + b_out

            if self.topk > 1:
                out, aux = moe_dispatch_combine_topk(
                    comm, tokens, gate_logits, expert_fn, k=self.topk,
                    capacity_factor=self.capacity_factor,
                    two_stage=self.two_stage)
            else:
                out, aux = moe_dispatch_combine(
                    comm, tokens, gate_logits, expert_fn,
                    capacity_factor=self.capacity_factor,
                    two_stage=self.two_stage)
            if aux_sink is not None:
                aux_sink.append({"aux_loss": aux["aux_loss"],
                                 "dropped_frac": aux["dropped_frac"]})
            return out.reshape(B, T, D)
        # dense fallback (no mesh axis): every expert computed, top-1
        # argmax-selected (or the top-k mixture) per token — identical
        # routing math, no capacity cut (dense drops nothing)
        probs = jax.nn.softmax(tokens @ self.router.array, axis=-1)
        E = comm.size
        h = jnp.einsum("td,edh->teh", tokens, self.w_in.array) \
            + self.b_in.array[None]
        y = jnp.einsum("teh,ehd->ted", F.gelu(h), self.w_out.array) \
            + self.b_out.array[None]
        if self.topk > 1:
            gates, experts = jax.lax.top_k(probs, self.topk)   # [T, k]
            gates = gates / jnp.maximum(
                gates.sum(axis=1, keepdims=True), 1e-9)
            picked = jnp.take_along_axis(
                y, experts[:, :, None].repeat(D, axis=2), 1)   # [T, k, D]
            out = jnp.sum(picked * gates[:, :, None], axis=1)
            frac = jnp.mean(
                jax.nn.one_hot(experts, E).max(axis=1), axis=0)
        else:
            eidx = jnp.argmax(probs, axis=-1)
            gate = jnp.take_along_axis(probs, eidx[:, None], 1)[:, 0]
            out = jnp.take_along_axis(
                y, eidx[:, None, None].repeat(D, axis=2), 1)[:, 0]
            out = out * gate[:, None]
            frac = jnp.mean(jax.nn.one_hot(eidx, E), axis=0)
        if aux_sink is not None:
            aux_sink.append({
                "aux_loss": E * jnp.sum(frac * jnp.mean(probs, axis=0)),
                "dropped_frac": jnp.float32(0.0)})
        return out.reshape(B, T, D)


class MoETransformerBlock(Chain):
    def __init__(self, d_model, n_heads, d_ff, ep_comm, seed=0,
                 sp_comm=None, sp_mode="ring", capacity_factor=1.25,
                 topk=1, two_stage=None):
        super().__init__()
        with self.init_scope():
            self.ln1 = L.LayerNormalization(d_model)
            self.attn = MultiHeadAttention(d_model, n_heads, seed=seed,
                                           sp_comm=sp_comm, sp_mode=sp_mode)
            self.ln2 = L.LayerNormalization(d_model)
            self.moe = MoEFeedForward(d_model, d_ff, ep_comm,
                                      capacity_factor, seed=seed + 50,
                                      topk=topk, two_stage=two_stage)

    def forward(self, x, aux_sink=None, causal=True):
        h = x + self.attn(self.ln1(x), causal=causal)
        return h + self.moe(self.ln2(h), aux_sink=aux_sink)


class MoETransformerLM(Chain):
    """Causal LM with MoE feed-forwards; ``aux_weight`` scales the Switch
    load-balancing loss added to the LM loss.  ``topk``/``two_stage``
    thread through to every block's dispatch (ISSUE 12); the reported
    observations carry ``moe_aux`` (mean load-balancing loss) and
    ``moe_dropped`` (mean capacity-cut fraction — the honesty column
    the bench rows read)."""

    def __init__(self, n_vocab, ep_comm, d_model=128, n_heads=4,
                 n_layers=2, d_ff=None, max_len=2048, seed=0,
                 aux_weight=0.01, capacity_factor=1.25,
                 compute_dtype=None, remat=False, topk=1,
                 two_stage=None):
        super().__init__()
        d_ff = d_ff or 4 * d_model
        self.aux_weight = aux_weight
        # same knobs as TransformerLM: bf16 MXU compute with fp32
        # params/statistics, and per-block remat with jax.checkpoint
        # POLICIES (True/"full"/"dots"/...).  Remat caveat specific to
        # MoE: the block's all_to_all expert exchange is recomputed in
        # the backward under full remat — "dots" keeps the expert GEMM
        # outputs but still re-runs the exchange; policy choice trades
        # a2a traffic against activation memory.
        self.compute_dtype = compute_dtype
        self.remat = remat
        with self.init_scope():
            self.embed = L.EmbedID(n_vocab, d_model, seed=seed)
            self.pos_embed = L.EmbedID(max_len, d_model, seed=seed + 1)
            self.blocks = ChainList(*[
                MoETransformerBlock(d_model, n_heads, d_ff, ep_comm,
                                    seed=seed + 100 * (i + 1),
                                    capacity_factor=capacity_factor,
                                    topk=topk, two_stage=two_stage)
                for i in range(n_layers)])
            self.ln_f = L.LayerNormalization(d_model)
            self.head = L.Linear(d_model, n_vocab, nobias=True,
                                 seed=seed + 999)

    def forward(self, x, t):
        B, T = x.shape
        pos = jax.lax.broadcasted_iota(jnp.int32, (1, T), 1)
        h = self.embed(x) + self.pos_embed(jnp.broadcast_to(pos, (B, T)))
        if self.compute_dtype is not None:
            h = h.astype(self.compute_dtype)
        aux_sink = []
        for block in self.blocks:
            if self.remat:
                # aux outputs must cross the checkpoint boundary as
                # explicit results (appending to a closed-over list
                # inside the remat region would leak tracers)
                def run(hh, blk=block):
                    sink = []
                    out = blk(hh, aux_sink=sink)
                    return out, sink[0]
                h, aux = jax.checkpoint(
                    run, policy=_remat_policy(self.remat))(h)
                aux_sink.append(aux)
            else:
                h = block(h, aux_sink=aux_sink)
        h = self.ln_f(h)
        # head GEMM stays in the compute dtype (large-vocab GEMMs are
        # exactly where bf16 MXU rate matters); softmax_cross_entropy
        # upcasts the logits to fp32 internally — same discipline as
        # TransformerLM
        logits = self.head(h.reshape(B * T, -1))
        loss = F.softmax_cross_entropy(logits, t.reshape(-1),
                                       ignore_label=-1)
        n = max(len(aux_sink), 1)
        aux = sum(a["aux_loss"] for a in aux_sink) / n
        dropped = sum(a["dropped_frac"] for a in aux_sink) / n
        reporter.report({"loss": loss, "moe_aux": aux,
                         "moe_dropped": dropped}, self)
        return loss + self.aux_weight * aux
