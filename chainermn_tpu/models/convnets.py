"""Classic ImageNet convnets (reference ``examples/imagenet`` archs).

The reference's ``train_imagenet.py --arch`` offered alex / nin /
googlenet / resnet50; this module supplies the non-ResNet family —
AlexNet, NIN, VGG16, and GoogLeNet (inception-v1) — as TPU-first NCHW
``jnp`` programs (big static convs for the MXU, fused pools).
"""

from __future__ import annotations

from ..core.link import Chain
from ..nn import functions as F
from ..nn import links as L
from .resnet import input_norm_consts, normalize_input

__all__ = ["AlexNet", "NIN", "VGG16", "GoogLeNet"]


class AlexNet(Chain):
    """AlexNet (reference example ``alex.py``), 227×227 inputs."""

    insize = 227

    def __init__(self, n_classes=1000, seed=0, input_norm=None):
        super().__init__()
        self._in_consts = input_norm_consts(input_norm)
        s = lambda k: seed + k
        with self.init_scope():
            self.conv1 = L.Convolution2D(3, 96, 11, stride=4, seed=s(0))
            self.conv2 = L.Convolution2D(96, 256, 5, pad=2, seed=s(1))
            self.conv3 = L.Convolution2D(256, 384, 3, pad=1, seed=s(2))
            self.conv4 = L.Convolution2D(384, 384, 3, pad=1, seed=s(3))
            self.conv5 = L.Convolution2D(384, 256, 3, pad=1, seed=s(4))
            self.fc6 = L.Linear(None, 4096, seed=s(5))
            self.fc7 = L.Linear(4096, 4096, seed=s(6))
            self.fc8 = L.Linear(4096, n_classes, seed=s(7))

    def forward(self, x):
        x = normalize_input(x, self._in_consts, "NCHW", None)
        h = F.max_pooling_2d(F.local_response_normalization(
            F.relu(self.conv1(x))), 3, stride=2)
        h = F.max_pooling_2d(F.local_response_normalization(
            F.relu(self.conv2(h))), 3, stride=2)
        h = F.relu(self.conv3(h))
        h = F.relu(self.conv4(h))
        h = F.max_pooling_2d(F.relu(self.conv5(h)), 3, stride=2)
        h = F.dropout(F.relu(self.fc6(h)))
        h = F.dropout(F.relu(self.fc7(h)))
        return self.fc8(h)


class NIN(Chain):
    """Network-in-Network (reference example ``nin.py``), 227×227."""

    insize = 227

    def __init__(self, n_classes=1000, seed=0, input_norm=None):
        super().__init__()
        self._in_consts = input_norm_consts(input_norm)
        s = lambda k: seed + k

        def mlpconv(in_ch, out_ch, ksize, stride, pad, k):
            return [L.Convolution2D(in_ch, out_ch, ksize, stride=stride,
                                    pad=pad, seed=s(k)),
                    L.Convolution2D(out_ch, out_ch, 1, seed=s(k + 1)),
                    L.Convolution2D(out_ch, out_ch, 1, seed=s(k + 2))]

        with self.init_scope():
            for i, (layers, name) in enumerate(zip(
                    [mlpconv(3, 96, 11, 4, 0, 0),
                     mlpconv(96, 256, 5, 1, 2, 10),
                     mlpconv(256, 384, 3, 1, 1, 20)],
                    ["mlp1", "mlp2", "mlp3"])):
                for j, layer in enumerate(layers):
                    setattr(self, f"{name}_{j}", layer)
            self.out_0 = L.Convolution2D(384, n_classes, 3, pad=1, seed=s(30))
            self.out_1 = L.Convolution2D(n_classes, n_classes, 1, seed=s(31))
            self.out_2 = L.Convolution2D(n_classes, n_classes, 1, seed=s(32))
        self.n_classes = n_classes

    def _mlp(self, prefix, h):
        for j in range(3):
            h = F.relu(getattr(self, f"{prefix}_{j}")(h))
        return h

    def forward(self, x):
        x = normalize_input(x, self._in_consts, "NCHW", None)
        h = F.max_pooling_2d(self._mlp("mlp1", x), 3, stride=2)
        h = F.max_pooling_2d(self._mlp("mlp2", h), 3, stride=2)
        h = F.max_pooling_2d(self._mlp("mlp3", h), 3, stride=2)
        h = F.relu(self.out_0(h))
        h = F.relu(self.out_1(h))
        h = self.out_2(h)
        return F.global_average_pooling_2d(h)


class VGG16(Chain):
    """VGG-16 (reference ``L.VGG16Layers`` shape), 224×224."""

    insize = 224

    def __init__(self, n_classes=1000, seed=0, input_norm=None):
        super().__init__()
        self._in_consts = input_norm_consts(input_norm)
        cfg = [(3, 64), (64, 64), "M", (64, 128), (128, 128), "M",
               (128, 256), (256, 256), (256, 256), "M",
               (256, 512), (512, 512), (512, 512), "M",
               (512, 512), (512, 512), (512, 512), "M"]
        self._plan = []
        with self.init_scope():
            idx = 0
            for item in cfg:
                if item == "M":
                    self._plan.append("M")
                    continue
                in_ch, out_ch = item
                name = f"conv{idx}"
                setattr(self, name, L.Convolution2D(in_ch, out_ch, 3,
                                                    pad=1, seed=seed + idx))
                self._plan.append(name)
                idx += 1
            self.fc6 = L.Linear(None, 4096, seed=seed + 100)
            self.fc7 = L.Linear(4096, 4096, seed=seed + 101)
            self.fc8 = L.Linear(4096, n_classes, seed=seed + 102)

    def forward(self, x):
        h = normalize_input(x, self._in_consts, "NCHW", None)
        for item in self._plan:
            if item == "M":
                h = F.max_pooling_2d(h, 2, stride=2, cover_all=False)
            else:
                h = F.relu(getattr(self, item)(h))
        h = F.dropout(F.relu(self.fc6(h)))
        h = F.dropout(F.relu(self.fc7(h)))
        return self.fc8(h)


class _Inception(Chain):
    """GoogLeNet inception block (1x1 / 3x3 / 5x5 / pool-proj)."""

    def __init__(self, in_ch, c1, r3, c3, r5, c5, pp, seed=0):
        super().__init__()
        s = lambda k: seed + k
        with self.init_scope():
            self.b1 = L.Convolution2D(in_ch, c1, 1, seed=s(0))
            self.b3r = L.Convolution2D(in_ch, r3, 1, seed=s(1))
            self.b3 = L.Convolution2D(r3, c3, 3, pad=1, seed=s(2))
            self.b5r = L.Convolution2D(in_ch, r5, 1, seed=s(3))
            self.b5 = L.Convolution2D(r5, c5, 5, pad=2, seed=s(4))
            self.bp = L.Convolution2D(in_ch, pp, 1, seed=s(5))

    def forward(self, x):
        a = F.relu(self.b1(x))
        b = F.relu(self.b3(F.relu(self.b3r(x))))
        c = F.relu(self.b5(F.relu(self.b5r(x))))
        d = F.relu(self.bp(F.max_pooling_2d(x, 3, stride=1, pad=1,
                                            cover_all=False)))
        return F.concat([a, b, c, d], axis=1)


class _AuxHead(Chain):
    """GoogLeNet auxiliary classifier (avg-pool 5/3 → 1x1 conv → fc)."""

    def __init__(self, in_ch, n_classes, seed=0):
        super().__init__()
        with self.init_scope():
            self.conv = L.Convolution2D(in_ch, 128, 1, seed=seed)
            self.fc1 = L.Linear(None, 1024, seed=seed + 1)
            self.fc2 = L.Linear(1024, n_classes, seed=seed + 2)

    def forward(self, x):
        if x.shape[2] >= 5 and x.shape[3] >= 5:
            h = F.average_pooling_2d(x, 5, stride=3)
        else:  # small-input regimes (tests, CIFAR-scale)
            h = F.global_average_pooling_2d(x)[:, :, None, None]
        h = F.relu(self.conv(h))
        h = F.relu(self.fc1(h))
        return self.fc2(F.dropout(h, 0.7))


class GoogLeNet(Chain):
    """GoogLeNet / inception-v1 (reference example ``googlenet.py``),
    224×224, with the reference's train-time auxiliary heads at inc4a and
    inc4d (``forward`` returns the main logits; ``forward_with_aux`` the
    triple; ``loss`` combines them with the 0.3 aux weights)."""

    insize = 224

    def __init__(self, n_classes=1000, seed=0, aux_heads=True,
                 input_norm=None):
        super().__init__()
        self.aux_heads = aux_heads
        self._in_consts = input_norm_consts(input_norm)
        s = lambda k: seed + 1000 * k
        with self.init_scope():
            if aux_heads:
                self.aux1 = _AuxHead(512, n_classes, seed=s(20))
                self.aux2 = _AuxHead(528, n_classes, seed=s(21))
            self.conv1 = L.Convolution2D(3, 64, 7, stride=2, pad=3,
                                         seed=s(1))
            self.conv2r = L.Convolution2D(64, 64, 1, seed=s(2))
            self.conv2 = L.Convolution2D(64, 192, 3, pad=1, seed=s(3))
            self.inc3a = _Inception(192, 64, 96, 128, 16, 32, 32, s(4))
            self.inc3b = _Inception(256, 128, 128, 192, 32, 96, 64, s(5))
            self.inc4a = _Inception(480, 192, 96, 208, 16, 48, 64, s(6))
            self.inc4b = _Inception(512, 160, 112, 224, 24, 64, 64, s(7))
            self.inc4c = _Inception(512, 128, 128, 256, 24, 64, 64, s(8))
            self.inc4d = _Inception(512, 112, 144, 288, 32, 64, 64, s(9))
            self.inc4e = _Inception(528, 256, 160, 320, 32, 128, 128, s(10))
            self.inc5a = _Inception(832, 256, 160, 320, 32, 128, 128, s(11))
            self.inc5b = _Inception(832, 384, 192, 384, 48, 128, 128, s(12))
            self.fc = L.Linear(1024, n_classes, seed=s(13))

    def _features(self, x):
        x = normalize_input(x, self._in_consts, "NCHW", None)
        h = F.max_pooling_2d(F.relu(self.conv1(x)), 3, stride=2, pad=1,
                             cover_all=False)
        h = F.relu(self.conv2(F.relu(self.conv2r(h))))
        h = F.max_pooling_2d(h, 3, stride=2, pad=1, cover_all=False)
        h = self.inc3b(self.inc3a(h))
        h = F.max_pooling_2d(h, 3, stride=2, pad=1, cover_all=False)
        h4a = self.inc4a(h)
        h4d = self.inc4d(self.inc4c(self.inc4b(h4a)))
        h = self.inc4e(h4d)
        h = F.max_pooling_2d(h, 3, stride=2, pad=1, cover_all=False)
        h = self.inc5b(self.inc5a(h))
        h = F.global_average_pooling_2d(h)
        h = F.dropout(h, 0.4)
        return self.fc(h), h4a, h4d

    def forward_with_aux(self, x):
        main, h4a, h4d = self._features(x)
        if not self.aux_heads:
            return main, None, None
        return main, self.aux1(h4a), self.aux2(h4d)

    def forward(self, x):
        return self._features(x)[0]

    def loss(self, x, t, aux_weight=0.3):
        """Reference training objective: main + 0.3·(aux1 + aux2)."""
        from ..core.config import config
        main, a1, a2 = self.forward_with_aux(x)
        total = F.softmax_cross_entropy(main, t)
        if self.aux_heads and config.train:
            total = total + aux_weight * (
                F.softmax_cross_entropy(a1, t)
                + F.softmax_cross_entropy(a2, t))
        return total
