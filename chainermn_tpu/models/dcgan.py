"""DCGAN generator/discriminator + updater (BASELINE config #5).

Reference capability: ChainerMN ``examples/dcgan/train_dcgan.py`` (CIFAR
DCGAN with multi-node optimizers for both networks).  TPU-first: both
adversarial updates run as compiled steps; the generator's noise is an
explicit PRNG key argument (idiomatic-JAX replacement for hidden RNG
state).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.link import Chain
from ..core import reporter
from ..nn import functions as F
from ..nn import links as L
from ..training.updaters import StandardUpdater

__all__ = ["Generator", "Discriminator", "DCGANUpdater"]


class Generator(Chain):
    """z [B, n_hidden] → image [B, 3, 32, 32]."""

    def __init__(self, n_hidden=128, ch=256, bottom_width=4, seed=0):
        super().__init__()
        self.n_hidden = n_hidden
        self.ch = ch
        self.bottom_width = bottom_width
        with self.init_scope():
            self.l0 = L.Linear(n_hidden, bottom_width * bottom_width * ch,
                               seed=seed)
            self.bn0 = L.BatchNormalization(bottom_width * bottom_width * ch)
            self.dc1 = L.Deconvolution2D(ch, ch // 2, 4, stride=2, pad=1,
                                         seed=seed + 1)
            self.bn1 = L.BatchNormalization(ch // 2)
            self.dc2 = L.Deconvolution2D(ch // 2, ch // 4, 4, stride=2,
                                         pad=1, seed=seed + 2)
            self.bn2 = L.BatchNormalization(ch // 4)
            self.dc3 = L.Deconvolution2D(ch // 4, ch // 8, 4, stride=2,
                                         pad=1, seed=seed + 3)
            self.bn3 = L.BatchNormalization(ch // 8)
            self.dc4 = L.Deconvolution2D(ch // 8, 3, 3, stride=1, pad=1,
                                         seed=seed + 4)

    def make_hidden(self, batchsize, key=None):
        if key is None:
            key = jax.random.PRNGKey(np.random.randint(0, 2**31 - 1))
        return jax.random.normal(key, (batchsize, self.n_hidden),
                                 jnp.float32)

    def forward(self, z):
        h = F.relu(self.bn0(self.l0(z)))
        h = h.reshape(-1, self.ch, self.bottom_width, self.bottom_width)
        h = F.relu(self.bn1(self.dc1(h)))
        h = F.relu(self.bn2(self.dc2(h)))
        h = F.relu(self.bn3(self.dc3(h)))
        return F.tanh(self.dc4(h))


class Discriminator(Chain):
    def __init__(self, ch=256, seed=100):
        super().__init__()
        with self.init_scope():
            self.c0 = L.Convolution2D(3, ch // 4, 3, stride=1, pad=1,
                                      seed=seed)
            self.c1 = L.Convolution2D(ch // 4, ch // 2, 4, stride=2, pad=1,
                                      seed=seed + 1)
            self.bn1 = L.BatchNormalization(ch // 2)
            self.c2 = L.Convolution2D(ch // 2, ch, 4, stride=2, pad=1,
                                      seed=seed + 2)
            self.bn2 = L.BatchNormalization(ch)
            self.l4 = L.Linear(ch * 8 * 8, 1, seed=seed + 3)

    def forward(self, x):
        h = F.leaky_relu(self.c0(x))
        h = F.leaky_relu(self.bn1(self.c1(h)))
        h = F.leaky_relu(self.bn2(self.c2(h)))
        return self.l4(h.reshape(h.shape[0], -1))


class DCGANUpdater(StandardUpdater):
    """Adversarial updater (reference: the dcgan example's custom updater).

    Both networks' parameters must be *traced arguments* of one compiled
    step — updating them alternately through two independent jitted losses
    would bake the opposite network's weights as stale constants.  Each
    iteration therefore runs ONE program: discriminator grads → dis
    update → generator grads against the updated discriminator → gen
    update (the reference's sequential semantics).  When the optimizers
    are multi-node wrappers, the step is shard_mapped over the
    communicator axis with the real batch sharded and both nets' grads
    pmean'd — data-parallel GAN for free.
    """

    def __init__(self, iterator, opt_gen, opt_dis, seed=0, **kwargs):
        super().__init__(iterator,
                         {"gen": opt_gen, "dis": opt_dis}, **kwargs)
        self._key = jax.random.PRNGKey(seed)
        self._gan_step = None

    def _next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    def _actual(self, name):
        opt = self._optimizers[name]
        return getattr(opt, "actual_optimizer", opt)

    def _communicator(self):
        opt = self._optimizers["dis"]
        comm = getattr(opt, "communicator", None)
        return comm if comm is not None and comm.axis_name is not None \
            else None

    def _build_step(self):
        from ..core.optimizer import apply_transform_update
        gen = self._actual("gen").target
        dis = self._actual("dis").target
        tx_gen = self._actual("gen")._transform()
        tx_dis = self._actual("dis")._transform()
        comm = self._communicator()
        from ..core.link import bind_state

        def losses(gen_params, dis_params, pstate_gen, pstate_dis,
                   x_real, z):
            def dis_loss(dp):
                with bind_state(gen, {"params": gen_params,
                                      "state": pstate_gen}) as hg:
                    with bind_state(dis, {"params": dp,
                                          "state": pstate_dis}) as hd:
                        y_real = dis(x_real)
                        x_fake = gen(z)
                        y_fake = dis(jax.lax.stop_gradient(x_fake))
                        loss = F.sigmoid_cross_entropy(
                            y_real, jnp.ones_like(y_real, jnp.int32)) + \
                            F.sigmoid_cross_entropy(
                                y_fake, jnp.zeros_like(y_fake, jnp.int32))
                        new_pd = hd.collect()
                return loss, new_pd

            def gen_loss(gp, dis_params_now):
                with bind_state(gen, {"params": gp,
                                      "state": pstate_gen}) as hg:
                    with bind_state(dis, {"params": dis_params_now,
                                          "state": pstate_dis}):
                        x_fake = gen(z)
                        y_fake = dis(x_fake)
                        loss = F.sigmoid_cross_entropy(
                            y_fake, jnp.ones_like(y_fake, jnp.int32))
                        new_pg = hg.collect()
                return loss, new_pg

            return dis_loss, gen_loss

        def step(gen_state, dis_state, opt_gen_state, opt_dis_state,
                 hyper_gen, hyper_dis, x_real, z):
            gen_params, pstate_gen = gen_state
            dis_params, pstate_dis = dis_state
            dis_loss, gen_loss = losses(gen_params, dis_params, pstate_gen,
                                        pstate_dis, x_real, z)
            (l_dis, new_pd), g_dis = jax.value_and_grad(
                dis_loss, has_aux=True)(dis_params)
            if comm is not None:
                g_dis = comm.grad_transform()(g_dis)
            new_dis_params, new_opt_dis = apply_transform_update(
                tx_dis, g_dis, opt_dis_state, dis_params, hyper_dis["lr"],
                hyper_dis.get("decoupled_wd", 0.0))
            (l_gen, new_pg), g_gen = jax.value_and_grad(
                gen_loss, has_aux=True)(gen_params, new_dis_params)
            if comm is not None:
                g_gen = comm.grad_transform()(g_gen)
            new_gen_params, new_opt_gen = apply_transform_update(
                tx_gen, g_gen, opt_gen_state, gen_params, hyper_gen["lr"],
                hyper_gen.get("decoupled_wd", 0.0))
            out = ((new_gen_params, new_pg), (new_dis_params, new_pd),
                   new_opt_gen, new_opt_dis, l_gen, l_dis)
            if comm is not None:
                from jax import lax as jlax
                out = (out[0], out[1], out[2], out[3],
                       jlax.pmean(l_gen, comm.axis_name),
                       jlax.pmean(l_dis, comm.axis_name))
            return out

        if comm is None:
            # donate optimizer states (replaced by returned values)
            return jax.jit(step, donate_argnums=(2, 3))
        from chainermn_tpu.utils.compat import shard_map
        from jax.sharding import PartitionSpec as P
        mapped = shard_map(
            step, mesh=comm.mesh,
            in_specs=(P(), P(), P(), P(), P(), P(),
                      P(comm.axis_name), P(comm.axis_name)),
            out_specs=(P(), P(), P(), P(), P(), P()),
            check_vma=False)
        return jax.jit(mapped, donate_argnums=(2, 3))

    def update_core(self):
        from ..core.link import extract_state
        gen_opt, dis_opt = self._actual("gen"), self._actual("dis")
        gen, dis = gen_opt.target, dis_opt.target
        batch = self._iterators["main"].next()
        x_real = self.converter(batch, self.device)
        if isinstance(x_real, tuple):
            x_real = x_real[0]
        x_real = jnp.asarray(x_real)
        z = gen.make_hidden(x_real.shape[0], key=self._next_key())

        sg, sd = extract_state(gen), extract_state(dis)
        opt_gen_state = gen_opt._ensure_opt_state(sg["params"])
        opt_dis_state = dis_opt._ensure_opt_state(sd["params"])
        if self._gan_step is None:
            self._gan_step = self._build_step()
        (new_gen, new_pg), (new_dis, new_pd), new_og, new_od, l_gen, l_dis = \
            self._gan_step((sg["params"], sg["state"]),
                           (sd["params"], sd["state"]),
                           opt_gen_state, opt_dis_state,
                           gen_opt._hyper_values(), dis_opt._hyper_values(),
                           x_real, z)
        gen_opt._write_back(new_gen, new_pg)
        dis_opt._write_back(new_dis, new_pd)
        gen_opt._opt_state = new_og
        dis_opt._opt_state = new_od
        gen_opt.t += 1
        dis_opt.t += 1
        reporter.report({"gen/loss": float(l_gen), "dis/loss": float(l_dis)})
        if self.is_new_epoch:
            for opt in self._optimizers.values():
                opt.new_epoch()
