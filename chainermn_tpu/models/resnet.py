"""ResNet family (the ImageNet vertical — BASELINE config #2).

Reference capability: ``chainer.links.model.vision.resnet ·
ResNet50Layers`` and ChainerMN's ``examples/imagenet/train_imagenet.py``
(SURVEY.md §6: ResNet-50/ImageNet is the reference's headline benchmark).
Freshly designed for TPU rather than transcribed:

* Activations run in a selectable layout: ``layout="NHWC"`` (the TPU
  native channels-last layout — channels map onto the MXU lane dimension,
  so XLA inserts no relayout transposes between conv/BN/relu) or
  ``"NCHW"`` (the reference layout, kept as the compatibility default).
  Kernels are stored OIHW either way, so checkpoints are layout-portable.
* ``compute_dtype=bfloat16`` runs conv/matmul compute in bf16 (MXU-native)
  with fp32 parameters and fp32 BN statistics — the TPU translation of the
  reference era's fp16 training recipe.
* Identity shortcuts use stride-slicing + channel-pad (option A) or
  projection (option B, the ResNet-50 default), all fusible.
* ``input_norm="imagenet"`` moves input normalization IN-GRAPH: the host
  pipeline ships raw uint8 pixels and the cast + per-channel standardize
  fuses into the first conv on device.  Measured motivation (BENCH_NOTES
  r5 input-pipeline probe): host-side float32 casting caps the one-core
  input pipeline at ~2.6k img/s — below the 25-30% MFU target's ~4.5k
  img/s demand — while the uint8 gather sustains ~9k img/s; shipping
  uint8 also cuts host→HBM DMA traffic 4×.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..core.link import Chain, ChainList
from ..nn import functions as F
from ..nn import links as L

__all__ = ["ResNet50", "ResNet18", "ResNet101", "BottleneckBlock",
           "BasicBlock", "IMAGENET_MEAN", "IMAGENET_STD",
           "input_norm_consts", "normalize_input"]

# ImageNet channel statistics in 0-1 scale (the standard ImageNet
# normalization the reference's example pipeline applies on HOST per
# image; here the same math runs in-graph over 0-255 inputs)
IMAGENET_MEAN = (0.485, 0.456, 0.406)
IMAGENET_STD = (0.229, 0.224, 0.225)


def input_norm_consts(input_norm):
    """(scale, bias) folding 0-255→0-1 and channel standardization into
    one multiply-add: y = x·scale + bias ≡ (x/255 − mean)/std.  Returns
    None for ``input_norm=None`` (inputs already normalized floats).
    Shared input-norm infrastructure: every ImageNet model family
    (ResNet here, the classic convnets in ``convnets.py``) consumes
    these two helpers — treat their contract as public."""
    if input_norm is None:
        return None
    if isinstance(input_norm, str):
        if input_norm != "imagenet":
            raise ValueError(
                f"unknown input_norm preset {input_norm!r}; valid: "
                "'imagenet', None, or a (mean, std) pair in 0-1 scale")
        mean, std = IMAGENET_MEAN, IMAGENET_STD
    else:  # (mean, std) pair in 0-1 scale
        mean, std = input_norm
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    return 1.0 / (255.0 * std), -mean / std


def normalize_input(x, consts, layout, compute_dtype):
    """Cast + (optionally) standardize on DEVICE, inside the compiled
    step: constants fold, XLA fuses the multiply-add into the first
    conv's input, and uint8 host→device transfers stay uint8.  The
    multiply-add itself runs in float32 and only the RESULT casts to
    ``compute_dtype`` — matching the host-normalized pipeline's
    precision (one rounding, not a bf16 FMA over bf16-rounded
    constants)."""
    if consts is None:
        return x.astype(compute_dtype) if compute_dtype is not None else x
    scale, bias = consts
    shape = (1, 1, 1, 3) if layout == "NHWC" else (1, 3, 1, 1)
    out = (x.astype(jnp.float32)
           * jnp.asarray(scale, jnp.float32).reshape(shape)
           + jnp.asarray(bias, jnp.float32).reshape(shape))
    return out.astype(compute_dtype) if compute_dtype is not None else out


class ConvBN(Chain):
    def __init__(self, in_ch, out_ch, ksize, stride=1, pad=0, seed=None,
                 layout="NCHW"):
        super().__init__()
        self.stride = stride
        self.pad = pad
        self.layout = layout
        bn_axis = (0, 1, 2) if layout == "NHWC" else None  # None → (0,2,3)
        with self.init_scope():
            self.conv = L.Convolution2D(in_ch, out_ch, ksize, stride=stride,
                                        pad=pad, nobias=True, seed=seed,
                                        layout=layout)
            self.bn = L.BatchNormalization(out_ch, axis=bn_axis)

    def forward(self, x, activate=True):
        # conv compute in the activation dtype (bf16 on the MXU when the
        # model casts); BN keeps the activation dtype end-to-end while its
        # statistics accumulate in fp32 internally (links.py _moments /
        # functions.py _apply_bn) — the elementwise chain conv→BN→relu
        # never round-trips the full tensor through fp32
        W = self.conv.W.array.astype(x.dtype)
        h = F.convolution_2d(x, W, None, self.stride, self.pad,
                             layout=self.layout)
        h = self.bn(h)
        if activate:
            h = F.relu(h)
        return h.astype(x.dtype)


class BottleneckBlock(Chain):
    """1x1 → 3x3 → 1x1 bottleneck with optional projection shortcut."""

    def __init__(self, in_ch, mid_ch, out_ch, stride=1, project=False,
                 seed=0, layout="NCHW"):
        super().__init__()
        self.project = project or in_ch != out_ch or stride != 1
        with self.init_scope():
            self.a = ConvBN(in_ch, mid_ch, 1, seed=seed, layout=layout)
            self.b = ConvBN(mid_ch, mid_ch, 3, stride=stride, pad=1,
                            seed=seed + 1, layout=layout)
            self.c = ConvBN(mid_ch, out_ch, 1, seed=seed + 2, layout=layout)
            if self.project:
                self.shortcut = ConvBN(in_ch, out_ch, 1, stride=stride,
                                       seed=seed + 3, layout=layout)

    def forward(self, x):
        h = self.a(x)
        h = self.b(h)
        h = self.c(h, activate=False)
        s = self.shortcut(x, activate=False) if self.project else x
        return F.relu(h + s)


class BasicBlock(Chain):
    """3x3 → 3x3 block (ResNet-18/34)."""

    def __init__(self, in_ch, out_ch, stride=1, seed=0, layout="NCHW"):
        super().__init__()
        self.project = in_ch != out_ch or stride != 1
        with self.init_scope():
            self.a = ConvBN(in_ch, out_ch, 3, stride=stride, pad=1, seed=seed,
                            layout=layout)
            self.b = ConvBN(out_ch, out_ch, 3, pad=1, seed=seed + 1,
                            layout=layout)
            if self.project:
                self.shortcut = ConvBN(in_ch, out_ch, 1, stride=stride,
                                       seed=seed + 2, layout=layout)

    def forward(self, x):
        h = self.a(x)
        h = self.b(h, activate=False)
        s = self.shortcut(x, activate=False) if self.project else x
        return F.relu(h + s)


class _Stage(ChainList):
    def __init__(self, n_blocks, in_ch, mid_ch, out_ch, stride, seed,
                 layout="NCHW"):
        blocks = [BottleneckBlock(in_ch, mid_ch, out_ch, stride=stride,
                                  project=True, seed=seed, layout=layout)]
        for i in range(1, n_blocks):
            blocks.append(BottleneckBlock(out_ch, mid_ch, out_ch,
                                          seed=seed + 10 * i, layout=layout))
        super().__init__(*blocks)

    def forward(self, x):
        for block in self:
            x = block(x)
        return x


class ResNet(Chain):
    def __init__(self, block_counts, n_classes=1000, compute_dtype=None,
                 seed=42, remat=False, layout="NCHW", input_norm=None):
        super().__init__()
        self.compute_dtype = compute_dtype
        self.remat = remat
        self.layout = layout
        self.input_norm = input_norm
        self._in_consts = input_norm_consts(input_norm)
        with self.init_scope():
            self.conv1 = ConvBN(3, 64, 7, stride=2, pad=3, seed=seed,
                                layout=layout)
            self.res2 = _Stage(block_counts[0], 64, 64, 256, 1, seed + 100,
                               layout=layout)
            self.res3 = _Stage(block_counts[1], 256, 128, 512, 2, seed + 200,
                               layout=layout)
            self.res4 = _Stage(block_counts[2], 512, 256, 1024, 2, seed + 300,
                               layout=layout)
            self.res5 = _Stage(block_counts[3], 1024, 512, 2048, 2, seed + 400,
                               layout=layout)
            self.fc = L.Linear(2048, n_classes, seed=seed + 500)

    def _apply_stage(self, stage, h):
        if not self.remat:
            return stage(h)
        # rematerialize per stage: backward recomputes activations instead
        # of keeping them resident — trades MXU FLOPs for HBM (SURVEY §7
        # hardware note), buying larger per-chip batches.  BN running
        # stats must flow through the checkpoint boundary as explicit
        # inputs/outputs (attribute mutation would leak tracers out of the
        # remat region).
        import jax
        from ..core.link import _persistent_slots
        slots = list(_persistent_slots(stage))

        def run(h, values):
            for (sl, n, _), v in zip(slots, values):
                object.__setattr__(sl, n, v)
                sl._persistent[n] = v
            out = stage(h)
            new = tuple(getattr(sl, n) for sl, n, _ in slots)
            return out, new

        values = tuple(getattr(sl, n) for sl, n, _ in slots)
        out, new = jax.checkpoint(run)(h, values)
        for (sl, n, _), v in zip(slots, new):
            object.__setattr__(sl, n, v)
            sl._persistent[n] = v
        return out

    def forward(self, x):
        x = normalize_input(x, self._in_consts, self.layout,
                             self.compute_dtype)
        h = self.conv1(x)
        h = F.max_pooling_2d(h, 3, stride=2, pad=1, cover_all=False,
                             layout=self.layout)
        h = self._apply_stage(self.res2, h)
        h = self._apply_stage(self.res3, h)
        h = self._apply_stage(self.res4, h)
        h = self._apply_stage(self.res5, h)
        h = F.global_average_pooling_2d(h, layout=self.layout)
        return self.fc(h.astype(jnp.float32))


class ResNet50(ResNet):
    def __init__(self, n_classes=1000, compute_dtype=None, seed=42,
                 remat=False, layout="NCHW", input_norm=None):
        super().__init__([3, 4, 6, 3], n_classes, compute_dtype, seed,
                         remat=remat, layout=layout,
                         input_norm=input_norm)


class ResNet101(ResNet):
    def __init__(self, n_classes=1000, compute_dtype=None, seed=42,
                 remat=False, layout="NCHW", input_norm=None):
        super().__init__([3, 4, 23, 3], n_classes, compute_dtype, seed,
                         remat=remat, layout=layout,
                         input_norm=input_norm)


class ResNet18(Chain):
    def __init__(self, n_classes=1000, compute_dtype=None, seed=42,
                 input_norm=None):
        super().__init__()
        self.compute_dtype = compute_dtype
        self.input_norm = input_norm
        self._in_consts = input_norm_consts(input_norm)
        cfg = [(64, 64, 1), (64, 128, 2), (128, 256, 2), (256, 512, 2)]
        with self.init_scope():
            self.conv1 = ConvBN(3, 64, 7, stride=2, pad=3, seed=seed)
            stages = []
            for i, (in_ch, out_ch, stride) in enumerate(cfg):
                stages.append(BasicBlock(in_ch, out_ch, stride,
                                         seed=seed + 100 * (i + 1)))
                stages.append(BasicBlock(out_ch, out_ch,
                                         seed=seed + 100 * (i + 1) + 50))
            self.body = ChainList(*stages)
            self.fc = L.Linear(512, n_classes, seed=seed + 999)

    def forward(self, x):
        x = normalize_input(x, self._in_consts, "NCHW",
                             self.compute_dtype)
        h = self.conv1(x)
        h = F.max_pooling_2d(h, 3, stride=2, pad=1, cover_all=False)
        for block in self.body:
            h = block(h)
        h = F.global_average_pooling_2d(h)
        return self.fc(h.astype(jnp.float32))
