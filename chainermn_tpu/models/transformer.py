"""Transformer language model with sequence-parallel attention.

Beyond-reference model family (ChainerMN predates transformers; SURVEY.md
§5 long-context note prescribes ring/Ulysses layers as the rebuild's
long-context story).  TPU-first: pre-norm blocks whose FLOPs are three
fused GEMMs (qkv, attention output, MLP), ``ops.attention`` dispatching
to the Pallas flash kernel on TPU, and a ``sequence_parallel`` mode that
shards the sequence over a communicator axis — attention runs as ring
attention (ppermute KV rotation) or Ulysses (all_to_all head exchange)
while every other op stays position-local, so the same weights serve
single-chip and sequence-parallel execution bit-compatibly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.link import Chain, ChainList
from ..core import reporter
from ..nn import functions as F
from ..nn import links as L
from ..ops import attention as fused_attention

__all__ = ["MultiHeadAttention", "TransformerBlock", "TransformerLM"]


def _axis_bound(comm):
    # a hierarchical communicator's axis_name is a (dcn, ici) TUPLE and
    # ALL of its axes must be bound — a bare axis_exists(tuple) probe is
    # False, which used to silently drop parallel layers (the MoE block
    # fell back to dense routing on a two-level mesh; ISSUE 12 guard
    # rail).  Communicators own the multi-axis form of this query.
    if comm is None or comm.axis_name is None:
        return False
    check = getattr(comm, "axis_in_scope", None)
    if check is not None:
        return check()
    from jax._src.core import get_axis_env
    names = comm.axis_name if isinstance(comm.axis_name, (tuple, list)) \
        else (comm.axis_name,)
    return all(get_axis_env().axis_exists(n) for n in names)


class MultiHeadAttention(Chain):
    def __init__(self, d_model, n_heads, seed=0, sp_comm=None,
                 sp_mode="ring"):
        super().__init__()
        assert d_model % n_heads == 0
        self.n_heads = n_heads
        self.d_head = d_model // n_heads
        self.sp_comm = sp_comm
        self.sp_mode = sp_mode
        with self.init_scope():
            self.qkv = L.Linear(d_model, 3 * d_model, seed=seed)
            self.proj = L.Linear(d_model, d_model, seed=seed + 1)

    def forward(self, x, causal=True):
        B, T, D = x.shape
        qkv = self.qkv(x.reshape(B * T, D)).reshape(B, T, 3, self.n_heads,
                                                    self.d_head)
        q, k, v = [jnp.moveaxis(qkv[:, :, i], 1, 2) for i in range(3)]
        if _axis_bound(self.sp_comm):
            if self.sp_mode in ("ring", "zigzag"):
                from ..parallel import ring_self_attention
                schedule = "zigzag" if self.sp_mode == "zigzag" else "naive"
                out = ring_self_attention(self.sp_comm, q, k, v,
                                          causal=causal, schedule=schedule)
            else:
                from ..parallel import ulysses_attention
                out = ulysses_attention(self.sp_comm, q, k, v,
                                        causal=causal)
        else:
            out = fused_attention(q, k, v, causal=causal)
        out = jnp.moveaxis(out, 2, 1).reshape(B * T, D)
        return self.proj(out).reshape(B, T, D)


class TransformerBlock(Chain):
    def __init__(self, d_model, n_heads, d_ff=None, seed=0, sp_comm=None,
                 sp_mode="ring"):
        super().__init__()
        d_ff = d_ff or 4 * d_model
        with self.init_scope():
            self.ln1 = L.LayerNormalization(d_model)
            self.attn = MultiHeadAttention(d_model, n_heads, seed=seed,
                                           sp_comm=sp_comm, sp_mode=sp_mode)
            self.ln2 = L.LayerNormalization(d_model)
            self.fc1 = L.Linear(d_model, d_ff, seed=seed + 10)
            self.fc2 = L.Linear(d_ff, d_model, seed=seed + 11)

    def forward(self, x, causal=True):
        B, T, D = x.shape
        h = x + self.attn(self.ln1(x), causal=causal)
        m = self.fc2(F.gelu(self.fc1(self.ln2(h).reshape(B * T, D))))
        return h + m.reshape(B, T, D)


def _remat_policy(remat):
    """Map the ``remat`` knob to a ``jax.checkpoint`` policy.

    ``True``/``"full"`` — save nothing (maximal memory saving, full
    recompute; the plain long-context lever).  ``"dots"`` — save
    weight-GEMM outputs, recompute elementwise/attention
    (``dots_with_no_batch_dims_saveable``: the transformer-standard
    trade — backward skips re-running the big MXU GEMMs at a modest
    activation-memory cost, typically better MFU at long sequence than
    full remat).  Any other string resolves as an attribute of
    ``jax.checkpoint_policies``."""
    if remat in (True, "full"):
        return None
    if remat == "dots":
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    policy = getattr(jax.checkpoint_policies, str(remat), None)
    if policy is None:
        raise ValueError(
            f"unknown remat policy {remat!r}; use True/'full', 'dots', "
            "or a jax.checkpoint_policies attribute name")
    return policy


class TransformerLM(Chain):
    """Causal LM.  ``sequence_parallel``: pass ``sp_comm`` and call inside
    a program sharding the T dimension over its axis.  Position ids are
    supplied automatically when the axis is bound: contiguous offsets for
    ``sp_mode="ring"``/``"ulysses"`` (rank · T_local), the two-half-chunk
    layout for ``sp_mode="zigzag"`` (the balanced causal ring — shard
    inputs/targets with ``parallel.zigzag_shard`` along T).

    ``remat``: ``False`` | ``True``/``"full"`` | ``"dots"`` | any
    ``jax.checkpoint_policies`` name — see :func:`_remat_policy`."""

    def __init__(self, n_vocab, d_model=128, n_heads=4, n_layers=2,
                 max_len=2048, seed=0, sp_comm=None, sp_mode="ring",
                 remat=False, compute_dtype=None):
        super().__init__()
        self.sp_comm = sp_comm
        self.sp_mode = sp_mode
        self.remat = remat
        self.compute_dtype = compute_dtype
        with self.init_scope():
            self.embed = L.EmbedID(n_vocab, d_model, seed=seed)
            self.pos_embed = L.EmbedID(max_len, d_model, seed=seed + 1)
            self.blocks = ChainList(*[
                TransformerBlock(d_model, n_heads, seed=seed + 100 * (i + 1),
                                 sp_comm=sp_comm, sp_mode=sp_mode)
                for i in range(n_layers)])
            self.ln_f = L.LayerNormalization(d_model)
            self.head = L.Linear(d_model, n_vocab, nobias=True,
                                 seed=seed + 999)

    def hidden(self, x):
        B, T = x.shape
        if _axis_bound(self.sp_comm) and self.sp_mode == "zigzag":
            # zigzag layout: rank i holds global half-chunks i and
            # 2n−1−i, so its positions are two disjoint ranges
            n = self.sp_comm.size
            i = jax.lax.axis_index(self.sp_comm.axis_name)
            h = T // 2
            local = jax.lax.broadcasted_iota(jnp.int32, (1, T), 1)
            pos = jnp.where(local < h,
                            i * h + local,
                            (2 * n - 1 - i) * h + (local - h))
        else:
            offset = 0
            if _axis_bound(self.sp_comm):
                offset = jax.lax.axis_index(self.sp_comm.axis_name) * T
            pos = offset + jax.lax.broadcasted_iota(jnp.int32, (1, T), 1)
        h = self.embed(x) + self.pos_embed(jnp.broadcast_to(pos, (B, T)))
        if self.compute_dtype is not None:
            # params stay fp32; all block compute (matmuls, attention,
            # residual stream) runs in the compute dtype — LN/softmax
            # statistics are fp32 internally (nn.functions discipline)
            h = h.astype(self.compute_dtype)
        for block in self.blocks:
            if self.remat:
                # per-block rematerialization: backward recomputes the
                # block, trading FLOPs for activation memory — the lever
                # for long contexts (blocks hold no persistent state, so
                # closing over bound params is safe).  The policy decides
                # WHAT to recompute (see _remat_policy): "dots" keeps the
                # GEMM outputs so the backward re-runs only the cheap
                # elementwise/attention tail.
                h = jax.checkpoint(lambda hh, blk=block: blk(hh),
                                   policy=_remat_policy(self.remat))(h)
            else:
                h = block(h)
        return self.ln_f(h)

    def logits(self, x):
        B, T = x.shape
        h = self.hidden(x)
        return self.head(h.reshape(B * T, -1)).reshape(B, T, -1)

    def forward(self, x, t):
        """LM loss with ignore_label=-1 padding."""
        logits = self.logits(x)
        loss = F.softmax_cross_entropy(
            logits.reshape(-1, logits.shape[-1]), t.reshape(-1),
            ignore_label=-1)
        reporter.report({"loss": loss}, self)
        return loss


# -- incremental decoding (KV cache) ----------------------------------------

def _attend_cached(q, k_cache, v_cache, pos, scale):
    """q: [B,H,1,D]; caches [B,H,Tmax,D]; attend over positions ≤ pos."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k_cache.astype(jnp.float32)) * scale
    Tmax = k_cache.shape[2]
    kpos = jax.lax.broadcasted_iota(jnp.int32, (1, 1, 1, Tmax), 3)
    s = jnp.where(kpos <= pos, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v_cache.astype(jnp.float32))


class _GenerationMixin:
    """Greedy / temperature sampling with per-layer KV caches."""

    def init_cache(self, batch, max_len):
        H = self.blocks[0].attn.n_heads
        D = self.blocks[0].attn.d_head
        n = len(self.blocks)
        shape = (n, 2, batch, H, max_len, D)
        return jnp.zeros(shape, jnp.float32)

    def _prefill(self, prompt, cache):
        """Full-forward pass over the prompt, capturing per-layer K/V into
        the cache; returns (cache, last-position logits)."""
        B, T0 = prompt.shape
        pos = jax.lax.broadcasted_iota(jnp.int32, (1, T0), 1)
        h = self.embed(prompt) + self.pos_embed(
            jnp.broadcast_to(pos, (B, T0)))
        for i, block in enumerate(self.blocks):
            x = block.ln1(h)
            qkv = block.attn.qkv(x.reshape(B * T0, -1)).reshape(
                B, T0, 3, block.attn.n_heads, block.attn.d_head)
            q, k, v = [jnp.moveaxis(qkv[:, :, j], 1, 2) for j in range(3)]
            cache = cache.at[i, 0, :, :, :T0].set(k.astype(jnp.float32))
            cache = cache.at[i, 1, :, :, :T0].set(v.astype(jnp.float32))
            from ..ops import xla_attention
            att = xla_attention(q, k, v, causal=True)
            att = jnp.moveaxis(att, 2, 1).reshape(B * T0, -1)
            h = h + block.attn.proj(att).reshape(B, T0, -1)
            m = block.fc2(F.gelu(block.fc1(
                block.ln2(h).reshape(B * T0, -1))))
            h = h + m.reshape(B, T0, -1)
        h = self.ln_f(h)
        logits = self.head(h[:, -1])
        return cache, logits

    def _step_logits(self, tok, pos, cache):
        """One-token forward through all blocks using/updating the cache."""
        B = tok.shape[0]
        h = self.embed(tok)[:, None] + self.pos_embed(
            jnp.full((B, 1), pos))
        new_cache = cache
        for i, block in enumerate(self.blocks):
            x = block.ln1(h)
            qkv = block.attn.qkv(x.reshape(B, -1)).reshape(
                B, 1, 3, block.attn.n_heads, block.attn.d_head)
            q, k, v = [jnp.moveaxis(qkv[:, :, j], 1, 2) for j in range(3)]
            k_cache = jax.lax.dynamic_update_slice(
                new_cache[i, 0], k.astype(jnp.float32), (0, 0, pos, 0))
            v_cache = jax.lax.dynamic_update_slice(
                new_cache[i, 1], v.astype(jnp.float32), (0, 0, pos, 0))
            new_cache = new_cache.at[i, 0].set(k_cache).at[i, 1].set(v_cache)
            scale = 1.0 / (block.attn.d_head ** 0.5)
            att = _attend_cached(q, k_cache, v_cache, pos, scale)
            att = jnp.moveaxis(att, 2, 1).reshape(B, 1, -1)
            h = h + block.attn.proj(att.reshape(B, -1))[:, None]
            m = block.fc2(F.gelu(block.fc1(block.ln2(h).reshape(B, -1))))
            h = h + m[:, None]
        h = self.ln_f(h)
        logits = self.head(h.reshape(B, -1))
        return logits, new_cache

    def generate(self, prompt, max_new_tokens, temperature=0.0, key=None):
        """Autoregressive continuation as one compiled scan.

        ``prompt``: int [B, T0].  ``temperature=0`` → greedy; otherwise
        requires ``key``.  Returns [B, max_new_tokens].
        """
        B, T0 = prompt.shape
        max_len = T0 + max_new_tokens
        cache = self.init_cache(B, max_len)
        # batched prefill: one full forward over the prompt fills every
        # layer's K/V cache (MXU-sized GEMMs instead of T0 tiny steps)
        cache, logits = self._prefill(prompt, cache)

        def pick(logits, k):
            if temperature == 0.0:
                return jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return jax.random.categorical(
                k, logits / temperature, axis=-1).astype(jnp.int32)

        key = key if key is not None else jax.random.PRNGKey(0)

        def step(carry, i):
            cache, logits, key = carry
            key, sub = jax.random.split(key)
            tok = pick(logits, sub)
            new_logits, cache = self._step_logits(tok, T0 + i, cache)
            return (cache, new_logits, key), tok

        (_, _, _), toks = jax.lax.scan(
            step, (cache, logits, key), jnp.arange(max_new_tokens))
        return jnp.swapaxes(toks, 0, 1)


# graft generation onto the LM (kept separate for readability)
TransformerLM.init_cache = _GenerationMixin.init_cache
TransformerLM._prefill = _GenerationMixin._prefill
TransformerLM._step_logits = _GenerationMixin._step_logits
TransformerLM.generate = _GenerationMixin.generate
