"""Transformer language model with sequence-parallel attention.

Beyond-reference model family (ChainerMN predates transformers; SURVEY.md
§5 long-context note prescribes ring/Ulysses layers as the rebuild's
long-context story).  TPU-first: pre-norm blocks whose FLOPs are three
fused GEMMs (qkv, attention output, MLP), ``ops.attention`` dispatching
to the Pallas flash kernel on TPU, and a ``sequence_parallel`` mode that
shards the sequence over a communicator axis — attention runs as ring
attention (ppermute KV rotation) or Ulysses (all_to_all head exchange)
while every other op stays position-local, so the same weights serve
single-chip and sequence-parallel execution bit-compatibly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.link import Chain, ChainList
from ..core import reporter
from ..nn import functions as F
from ..nn import links as L
from ..ops import attention as fused_attention

__all__ = ["MultiHeadAttention", "TransformerBlock", "TransformerLM"]


def _axis_bound(comm):
    if comm is None or comm.axis_name is None:
        return False
    from jax._src.core import get_axis_env
    return get_axis_env().axis_exists(comm.axis_name)


class MultiHeadAttention(Chain):
    def __init__(self, d_model, n_heads, seed=0, sp_comm=None,
                 sp_mode="ring"):
        super().__init__()
        assert d_model % n_heads == 0
        self.n_heads = n_heads
        self.d_head = d_model // n_heads
        self.sp_comm = sp_comm
        self.sp_mode = sp_mode
        with self.init_scope():
            self.qkv = L.Linear(d_model, 3 * d_model, seed=seed)
            self.proj = L.Linear(d_model, d_model, seed=seed + 1)

    def forward(self, x, causal=True):
        B, T, D = x.shape
        qkv = self.qkv(x.reshape(B * T, D)).reshape(B, T, 3, self.n_heads,
                                                    self.d_head)
        q, k, v = [jnp.moveaxis(qkv[:, :, i], 1, 2) for i in range(3)]
        if _axis_bound(self.sp_comm):
            if self.sp_mode == "ring":
                from ..parallel import ring_self_attention
                out = ring_self_attention(self.sp_comm, q, k, v,
                                          causal=causal)
            else:
                from ..parallel import ulysses_attention
                out = ulysses_attention(self.sp_comm, q, k, v,
                                        causal=causal)
        else:
            out = fused_attention(q, k, v, causal=causal)
        out = jnp.moveaxis(out, 2, 1).reshape(B * T, D)
        return self.proj(out).reshape(B, T, D)


class TransformerBlock(Chain):
    def __init__(self, d_model, n_heads, d_ff=None, seed=0, sp_comm=None,
                 sp_mode="ring"):
        super().__init__()
        d_ff = d_ff or 4 * d_model
        with self.init_scope():
            self.ln1 = L.LayerNormalization(d_model)
            self.attn = MultiHeadAttention(d_model, n_heads, seed=seed,
                                           sp_comm=sp_comm, sp_mode=sp_mode)
            self.ln2 = L.LayerNormalization(d_model)
            self.fc1 = L.Linear(d_model, d_ff, seed=seed + 10)
            self.fc2 = L.Linear(d_ff, d_model, seed=seed + 11)

    def forward(self, x, causal=True):
        B, T, D = x.shape
        h = x + self.attn(self.ln1(x), causal=causal)
        m = self.fc2(F.gelu(self.fc1(self.ln2(h).reshape(B * T, D))))
        return h + m.reshape(B, T, D)


class TransformerLM(Chain):
    """Causal LM.  ``sequence_parallel``: pass ``sp_comm`` and call inside
    a program sharding the T dimension over its axis (positions must be
    offset-consistent: ``pos_offset`` = rank * T_local, supplied
    automatically when the axis is bound)."""

    def __init__(self, n_vocab, d_model=128, n_heads=4, n_layers=2,
                 max_len=2048, seed=0, sp_comm=None, sp_mode="ring",
                 remat=False):
        super().__init__()
        self.sp_comm = sp_comm
        self.remat = remat
        with self.init_scope():
            self.embed = L.EmbedID(n_vocab, d_model, seed=seed)
            self.pos_embed = L.EmbedID(max_len, d_model, seed=seed + 1)
            self.blocks = ChainList(*[
                TransformerBlock(d_model, n_heads, seed=seed + 100 * (i + 1),
                                 sp_comm=sp_comm, sp_mode=sp_mode)
                for i in range(n_layers)])
            self.ln_f = L.LayerNormalization(d_model)
            self.head = L.Linear(d_model, n_vocab, nobias=True,
                                 seed=seed + 999)

    def hidden(self, x):
        B, T = x.shape
        offset = 0
        if _axis_bound(self.sp_comm):
            offset = jax.lax.axis_index(self.sp_comm.axis_name) * T
        pos = offset + jax.lax.broadcasted_iota(jnp.int32, (1, T), 1)
        h = self.embed(x) + self.pos_embed(jnp.broadcast_to(pos, (B, T)))
        for block in self.blocks:
            if self.remat:
                # per-block rematerialization: backward recomputes the
                # block, trading FLOPs for activation memory — the lever
                # for long contexts (blocks hold no persistent state, so
                # closing over bound params is safe)
                h = jax.checkpoint(lambda hh, blk=block: blk(hh))(h)
            else:
                h = block(h)
        return self.ln_f(h)

    def logits(self, x):
        B, T = x.shape
        h = self.hidden(x)
        return self.head(h.reshape(B * T, -1)).reshape(B, T, -1)

    def forward(self, x, t):
        """LM loss with ignore_label=-1 padding."""
        logits = self.logits(x)
        loss = F.softmax_cross_entropy(
            logits.reshape(-1, logits.shape[-1]), t.reshape(-1),
            ignore_label=-1)
        reporter.report({"loss": loss}, self)
        return loss
