"""Distributed trainer extensions (reference: ``chainermn.extensions``)."""

from .checkpoint import create_multi_node_checkpointer, _MultiNodeCheckpointer
from .observation_aggregator import ObservationAggregator

__all__ = ["create_multi_node_checkpointer", "_MultiNodeCheckpointer",
           "ObservationAggregator"]
