"""Distributed trainer extensions (reference: ``chainermn.extensions``)."""

from .checkpoint import create_multi_node_checkpointer, _MultiNodeCheckpointer
from .failure_recovery import FailureRecovery, RecoveryGivingUp
from .observation_aggregator import ObservationAggregator

try:
    from .orbax_checkpoint import OrbaxCheckpointer
except Exception:  # pragma: no cover - orbax optional
    OrbaxCheckpointer = None

__all__ = ["create_multi_node_checkpointer", "_MultiNodeCheckpointer",
           "FailureRecovery", "RecoveryGivingUp",
           "ObservationAggregator", "OrbaxCheckpointer"]
