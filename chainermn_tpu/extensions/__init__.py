"""Distributed trainer extensions (reference: ``chainermn.extensions``)."""

from .checkpoint import create_multi_node_checkpointer, _MultiNodeCheckpointer
from .elastic import (ElasticConfigError, ElasticRecovery,
                      create_elastic_membership, global_batch_plan)
from .failure_recovery import FailureRecovery, RecoveryGivingUp
from .observation_aggregator import ObservationAggregator

try:
    from .orbax_checkpoint import (OrbaxCheckpointer,
                                   create_multi_node_orbax_checkpointer,
                                   _MultiNodeOrbaxCheckpointer)
except Exception:  # pragma: no cover - orbax optional
    OrbaxCheckpointer = None
    create_multi_node_orbax_checkpointer = None
    _MultiNodeOrbaxCheckpointer = None

__all__ = ["create_multi_node_checkpointer", "_MultiNodeCheckpointer",
           "FailureRecovery", "RecoveryGivingUp",
           "ElasticRecovery", "ElasticConfigError",
           "create_elastic_membership", "global_batch_plan",
           "ObservationAggregator", "OrbaxCheckpointer",
           "create_multi_node_orbax_checkpointer",
           "_MultiNodeOrbaxCheckpointer"]
