"""Fail-stop auto-recovery — the consumer of the ``on_error`` hook.

Reference contract (SURVEY.md §2.4/§3.5): a crashed rank takes the whole
job down (fail-stop), the scheduler relaunches it, and the checkpointer's
consensus ``maybe_load`` converges every rank on the newest snapshot
present on *all* ranks.  That contract recovers from *process death*; a
large class of real faults — a failed collective, a host-channel timeout,
a lost peer detected by heartbeat — kills no process and can be recovered
*in place*, without paying a relaunch.

:class:`FailureRecovery` is a trainer extension consumed by the
supervisor loop in ``Trainer.run`` (see ``docs/resilience.md`` for the
state machine):

1. a recoverable communicator fault escapes the training loop,
2. the trainer fires ``on_error`` on every extension (flush/abandon
   partial state),
3. this extension quiesces the transport — clears any posted abort flag
   and bumps the host channel's key *generation*, so keys stranded by the
   failed op can never match ops from the recovered incarnation,
4. the checkpointer's consensus ``maybe_load`` rolls every rank back to
   the newest commonly-held verified snapshot,
5. an optional ``rebuild`` hook replaces/repairs the communicator (the
   seam where a real multi-host deployment re-initializes its mesh), and
6. the training loop resumes.

Lock-step caveat: in a multi-controller run every process must take the
same recovery decision at the same call site, which holds when faults are
fail-stop-visible everywhere (a collective that fails, fails for all) or
injected from a shared seeded schedule (the chaos harness's discipline).
"""

from __future__ import annotations

import sys
import time

from .. import observability
from ..communicators._host_channel import ChannelError, PeerLostError
from ..communicators.fault_schedule import InjectedFault
from ..training.trainer import Extension, PRIORITY_READER

__all__ = ["FailureRecovery", "RecoveryGivingUp"]

_DEFAULT_RECOVERABLE = (InjectedFault, ChannelError)
# A dead PEER cannot be recovered in place: the consensus allgather would
# block on its contribution for the full op deadline.  Prompt fail-stop
# (relaunch + consensus) is the correct outcome — deployments whose
# ``rebuild`` hook actually respawns peers can opt in via
# ``unrecoverable=()``.
_DEFAULT_UNRECOVERABLE = (PeerLostError,)


def _never_fire(trainer):
    return False


class RecoveryGivingUp(RuntimeError):
    """Raised (chaining the fault) when the recovery budget is spent.

    Carries the last known membership view (``membership`` — an
    :class:`~..communicators.MembershipView` on elastic runs, None on
    fixed-size ones) IN THE MESSAGE: a give-up is precisely the moment
    an operator reads one line of a crash log, and "who was in the
    world when we stopped trying" is the first question (ISSUE 10
    satellite — a bare budget count told you nothing about *who* was
    missing).  The message also names the view's GROUP role (ISSUE 15
    satellite): a give-up inside a serving-role membership group
    (``role="fleet"``) must point the operator at the fleet namespace,
    not the training ``elastic`` one — the same process may hold both."""

    def __init__(self, message, membership=None):
        self.membership = membership
        if membership is not None:
            message = (f"{message} [last membership view: epoch "
                       f"{membership.epoch}, members "
                       f"{list(membership.members)}, group "
                       f"'{getattr(membership, 'role', 'elastic')}']")
        super().__init__(message)


class FailureRecovery(Extension):
    """Supervisor-consumed extension implementing inject → detect →
    recover → converge.

    ``checkpointer``: a ``_MultiNodeCheckpointer`` (its ``maybe_load``
    is the convergence step; optional — without one, recovery restarts
    from live in-memory state, which is only safe for idempotent loops).
    ``recoverable``: exception types worth recovering (default:
    ``InjectedFault`` + the typed channel errors).  ``unrecoverable``:
    types that always fail-stop even when ``recoverable`` matches
    (default: ``PeerLostError`` — see module docstring).
    ``max_recoveries``:
    lifetime budget; exhaustion re-raises through
    :class:`RecoveryGivingUp` so a crash-looping job still fail-stops.
    ``rebuild``: optional ``rebuild(trainer, exc) -> communicator|None``
    hook replacing the transport.  ``cooldown_s``: pause before resuming
    (real deployments back off to let the fabric settle).
    """

    # a None trigger means fire-every-iteration to Trainer.run; this
    # extension's behavior lives on the supervisor path only, so its
    # iteration trigger genuinely never fires
    trigger = staticmethod(_never_fire)
    priority = PRIORITY_READER
    name = "FailureRecovery"

    def __init__(self, checkpointer=None, comm=None, recoverable=None,
                 unrecoverable=None, max_recoveries=3, rebuild=None,
                 cooldown_s=0.0, sleep=time.sleep, on_recover=None,
                 verbose=True):
        self.checkpointer = checkpointer
        self.comm = comm if comm is not None \
            else getattr(checkpointer, "comm", None)
        self.recoverable = tuple(recoverable) if recoverable is not None \
            else _DEFAULT_RECOVERABLE
        self.unrecoverable = tuple(unrecoverable) \
            if unrecoverable is not None else _DEFAULT_UNRECOVERABLE
        self.max_recoveries = int(max_recoveries)
        self.rebuild = rebuild
        self.cooldown_s = float(cooldown_s)
        self._sleep = sleep
        self.on_recover = on_recover
        self.verbose = verbose
        self.stats = {"recoveries": 0, "resumed_iterations": [],
                      "generation_bumps": 0,
                      # elastic telemetry (ISSUE 10): world-size changes
                      # and the rank churn behind them — zero forever on
                      # fixed-size runs, filled by ElasticRecovery
                      "resizes": 0, "ranks_lost": 0, "ranks_joined": 0}
        # the last membership view this supervisor acted on (elastic
        # runs); attached to RecoveryGivingUp so a give-up names who
        # was present
        self.last_view = None

    def __call__(self, trainer):
        pass  # all behavior lives on the supervisor path

    # -- supervisor protocol -------------------------------------------------
    def can_recover(self, exc):
        """Type check only — a spent budget is reported by
        :meth:`recover` raising :class:`RecoveryGivingUp` (chaining the
        fault), so the crash output distinguishes 'never recoverable'
        from 'gave up after N recoveries'.  ``unrecoverable`` types
        (default: :class:`PeerLostError` — a dead peer can never answer
        the consensus allgather) always fail-stop."""
        return (isinstance(exc, self.recoverable)
                and not isinstance(exc, self.unrecoverable))

    def _spend_recovery_budget(self, exc):
        """Shared budget gate (fixed-size AND elastic recover paths):
        exhaustion raises :class:`RecoveryGivingUp` chaining the fault
        and naming the last membership view; otherwise one attempt is
        spent."""
        if self.stats["recoveries"] >= self.max_recoveries:
            raise RecoveryGivingUp(
                f"recovery budget exhausted "
                f"({self.stats['recoveries']}/{self.max_recoveries})",
                membership=self.last_view
                if self.last_view is not None
                else getattr(self, "view", None),
            ) from exc
        self.stats["recoveries"] += 1

    def recover(self, trainer, exc):
        """Run the recovery state machine; returns the resumed iteration
        (or None when no common snapshot existed and training restarts
        from live state)."""
        self._spend_recovery_budget(exc)
        if self.verbose:
            print(f"chainermn_tpu: recovering from {type(exc).__name__}: "
                  f"{exc} (attempt {self.stats['recoveries']}"
                  f"/{self.max_recoveries})", file=sys.stderr)
        observability.instant("recover/detect",
                              tags={"exc": type(exc).__name__})
        if self.cooldown_s:
            self._sleep(self.cooldown_s)
        with observability.span("recover/quiesce"):
            self._quiesce_transport()
        resumed = None
        if self.checkpointer is not None:
            # checkpointer.maybe_load carries its own
            # "recover/consensus_load" span
            resumed = self.checkpointer.maybe_load(trainer)
        if self.rebuild is not None:
            with observability.span("recover/rebuild"):
                new_comm = self.rebuild(trainer, exc)
            if new_comm is not None:
                self.comm = new_comm
                if self.checkpointer is not None:
                    self.checkpointer.comm = new_comm
        self.stats["resumed_iterations"].append(resumed)
        self._publish_stats()
        if self.verbose:
            print(f"chainermn_tpu: consensus resume -> iteration "
                  f"{resumed if resumed is not None else '(fresh state)'}",
                  file=sys.stderr)
        if self.on_recover is not None:
            self.on_recover(trainer, exc, resumed)
        return resumed

    def _publish_stats(self):
        """Fold :attr:`stats` into the observability registry (ISSUE
        14): the supervisor's lifetime telemetry — recoveries,
        generation bumps, and the elastic resize/rank-churn counts —
        become gauges a ``PROBE=obs`` render (or a real scraper) reads
        next to the subsystem counters.  No-op when observability is
        off."""
        if not observability.enabled():
            return
        reg = observability.registry()
        for key in ("recoveries", "generation_bumps", "resizes",
                    "ranks_lost", "ranks_joined"):
            reg.gauge(f"chainermn_tpu_recovery_{key}",
                      help="FailureRecovery.stats['%s']" % key).set(
                self.stats[key])

    def _quiesce_transport(self):
        """Clear a posted abort flag and rotate the host channel's key
        generation, so the resumed run can never match keys stranded by
        the failed op (every process does this lock-step before the
        consensus allgather below runs over the NEW generation)."""
        comm = self.comm
        ch = None
        if comm is not None and hasattr(comm, "_host_channel"):
            try:
                ch = comm._host_channel()
            except Exception:
                ch = None
        if ch is not None:
            ch.clear_abort()
            ch.bump_generation()
            self.stats["generation_bumps"] += 1

    def serialize(self, serializer):
        pass  # recovery budget is per-process-lifetime, not snapshot state
