"""Orbax-backed checkpointing for sharded pod-scale state.

SURVEY.md §5 checkpoint note: "orbax-style sharded checkpoint of the
jitted train state; keep the consensus-resume semantic".  The npz
checkpointer (``extensions.checkpoint``) is the reference-parity path
(per-host files, host-gathered arrays); :class:`OrbaxCheckpointer` writes
device-sharded pytrees directly — each host persists only its shards,
restore re-places them — which is the right mechanics once models
outgrow one host's memory.

:class:`_MultiNodeOrbaxCheckpointer` (factory:
:func:`create_multi_node_orbax_checkpointer`) closes VERDICT r5 Missing
#3: it is the TRAINER EXTENSION face of the Orbax path, with the same
trigger / generation-GC / consensus-``maybe_load`` semantics as the npz
``_MultiNodeCheckpointer`` (SURVEY §2.4) — so it drops into
``extensions.FailureRecovery`` and ``Trainer.run``'s supervisor loop
unchanged.  Trainer state crosses through the serializer protocol
(``DictionarySerializer`` → flat host pytree → Orbax ``StandardSave``),
reusing the exact logic every other checkpointer speaks; atomicity and
on-disk GC are Orbax's (tmp-dir + rename per step), replacing the npz
path's hand-rolled tmp/rename + SHA-256 sidecars.
"""

from __future__ import annotations

import os

from ..core.link import extract_state, load_param_tree, _persistent_slots
from ..training.trainer import Extension

__all__ = ["OrbaxCheckpointer", "create_multi_node_orbax_checkpointer",
           "_MultiNodeOrbaxCheckpointer"]


class OrbaxCheckpointer:
    def __init__(self, directory, max_to_keep=3):
        import orbax.checkpoint as ocp
        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep))

    def delete(self, step):
        self._manager.delete(step)

    # -- raw pytrees -------------------------------------------------------
    def save(self, step, pytree):
        self._manager.save(step, args=self._ocp.args.StandardSave(pytree))
        self._manager.wait_until_finished()

    def restore(self, step=None, template=None):
        step = self.latest_step() if step is None else step
        if step is None:
            return None
        if template is not None:
            return self._manager.restore(
                step, args=self._ocp.args.StandardRestore(template))
        # template-less restore must still name the handler: a FRESH
        # manager (new process, e.g. consensus resume after relaunch)
        # has no registry entry until its first save, and bare
        # restore(step) then fails with 'Item "default" ... could not
        # be restored'
        return self._manager.restore(
            step, args=self._ocp.args.StandardRestore())

    def latest_step(self):
        return self._manager.latest_step()

    def all_steps(self):
        return list(self._manager.all_steps())

    # -- links -------------------------------------------------------------
    def save_link(self, step, link):
        self.save(step, extract_state(link))

    def restore_link(self, link, step=None):
        state = self.restore(step, template=extract_state(link))
        if state is None:
            return False
        load_param_tree(link, state["params"])
        slots = {full: (sublink, name)
                 for sublink, name, full in _persistent_slots(link)}
        for path, value in state.get("state", {}).items():
            if path in slots:
                sublink, name = slots[path]
                object.__setattr__(sublink, name, value)
                sublink._persistent[name] = value
        return True

    def close(self):
        self._manager.close()


def create_multi_node_orbax_checkpointer(comm, directory, cp_interval=5):
    """Reference-shaped factory (the Orbax sibling of
    ``create_multi_node_checkpointer``).  ``cp_interval``: snapshot
    generations kept per rank."""
    return _MultiNodeOrbaxCheckpointer(comm, directory, cp_interval)


class _MultiNodeOrbaxCheckpointer(Extension):
    """Trigger-driven Orbax snapshots with consensus resume.

    Single-controller translation of the npz checkpointer's contract
    (one snapshot per HOST — ``comm.inter_rank`` — under
    ``<directory>/rank<k>/``; the consensus allgather runs over the
    object channel): ``maybe_load`` resumes every rank from the newest
    step present on *all* ranks, and that generation is pinned against
    GC until the next resume.  Orbax provides per-step atomicity and
    deletion; this extension owns the generation policy (``cp_interval``
    newest kept, protected generation never swept) so the semantics stay
    identical to the npz path — which is what ``FailureRecovery``
    assumes of a ``checkpointer``.
    """

    trigger = (1, "epoch")
    priority = -100  # after everything else mutated state this iteration

    def __init__(self, comm, directory, cp_interval=5):
        self.comm = comm
        self.directory = os.path.abspath(directory)
        self.cp_interval = cp_interval
        self._ckpt = OrbaxCheckpointer(
            os.path.join(self.directory, f"rank{comm.inter_rank}"),
            max_to_keep=None)  # GC is THIS extension's generation policy
        self._protected_iteration = None
        self.stats = {"snapshots": 0, "gc": 0}

    @property
    def rank(self):
        return self.comm.inter_rank

    # -- save -------------------------------------------------------------
    def __call__(self, trainer):
        self.save(trainer, trainer.updater.iteration)

    def save(self, trainer, iteration):
        from ..serializers.npz import DictionarySerializer
        s = DictionarySerializer()
        trainer.serialize(s)
        self._ckpt.save(iteration, s.target)
        self.stats["snapshots"] += 1
        self._gc()

    def _gc(self):
        steps = sorted(self._ckpt.all_steps())
        for step in steps[:-self.cp_interval] if self.cp_interval else []:
            if step == self._protected_iteration:
                # never sweep the generation the last consensus resumed
                # from: a peer may still be loading it, and it is the
                # newest iteration guaranteed present on ALL ranks
                continue
            self._ckpt.delete(step)
            self.stats["gc"] += 1

    # -- consensus resume -------------------------------------------------
    def maybe_load(self, trainer, optimizer=None):
        """Resume from the newest step *every* rank has a snapshot of
        (allgather of step sets → max of the intersection → per-rank
        restore through the serializer protocol).  Returns the resumed
        iteration or None."""
        from ..serializers.npz import NpzDeserializer
        local = sorted(self._ckpt.all_steps())
        all_sets = self.comm.allgather_obj(local)
        common = set(all_sets[0])
        for s in all_sets[1:]:
            common &= set(s)
        if not common:
            return None
        iteration = max(common)
        tree = self._ckpt.restore(iteration)
        # the restored flat {path/key: ndarray} mapping speaks the same
        # protocol an open npz file does — reuse the npz deserializer
        trainer.serialize(NpzDeserializer(tree, strict=False))
        self._protected_iteration = iteration
        return iteration

    def finalize(self):
        self._ckpt.close()

    def serialize(self, serializer):
        pass
