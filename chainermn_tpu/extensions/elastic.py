"""Elastic shrink/grow — training that survives preemptible capacity.

ISSUE 10 (ROADMAP item 5): :class:`~.failure_recovery.FailureRecovery`
recovers faults at a FIXED world size — a lost peer fail-stops the job.
On spot/preemptible capacity the production event is a rank LEAVING
(host reclaimed) and later a rank JOINING (replacement capacity), and
the collective schedule here is a *pure re-plannable function of the
topology* (``plan_buckets`` / ``hop_schedule`` / ``flat_chunk_spec``):
a changed world size means re-planning, never restarting from scratch.

:class:`ElasticRecovery` extends the supervisor with three moves, all
built on the membership protocol
(:class:`~..communicators.ElasticMembership`):

* **shrink** — a survivor's typed failure (channel timeout, lost-peer
  heartbeat, injected fault) triggers a membership resolve with typed
  timeouts for unresponsive peers; the survivors rebuild the
  communicator over the decided member set
  (:class:`~..communicators.ElasticMeshCommunicator`), re-plan every
  size-dependent structure through
  ``optimizer.change_communicator`` (bucket plans, ZeRO
  ``flat_chunk_spec`` chunking; stale-grad and error-feedback buffers
  re-seed zeros — the documented size-changed contract), converge on
  the checkpointer's consensus snapshot, and keep training.
* **leave** — the preempted rank (:class:`RankPreempted` from the
  fault schedule, or the real scheduler's signal) announces a
  generation-keyed ``leave`` so survivors never burn the full timeout
  on it, then either fail-stops (production default: the scheduler
  restarts the process) or parks and re-joins (``rejoin_after_s`` —
  the chaos harness's preempt-and-return shape).
* **grow** — survivors poll join announcements at iteration
  boundaries (a lock-step object-channel broadcast, so every survivor
  enters the resize at the same call site), admit the joiner through
  the same resolve, rebuild at the larger size, ship the newest
  snapshot to the joiner over the new channel, and consensus-load so
  every member — including the one that just arrived with stale
  state — resumes bit-exact from the same generation.

Global batch across resizes: the repo's batch convention already makes
``"rescale"`` free — ``update()`` receives the GLOBAL batch and the
``shard_map`` in_spec splits it over however many ranks exist, so the
gradient stays the full-batch mean at any world size (convergence
parity, not bit-exactness: reduction order changes).
:func:`global_batch_plan` computes the policy table (per-rank rescale
vs gradient accumulation) and :meth:`ElasticRecovery._check_batch`
validates divisibility at resize time, failing with the plan attached
instead of a shape error inside the first resized step.

Detection caveat (same lock-step discipline as the fixed-size
supervisor): a departed rank is detected at the CONTROL PLANE — a
host-channel op's typed timeout, a heartbeat, an announced leave —
between steps.  Keep a per-iteration channel op in the loop (the
multi-node iterator's batch broadcast, a beacon, or the checkpoint
trigger); a rank lost while a peer is already blocked inside a
compiled data-plane collective surfaces through the runtime's own
error instead, and recovery proceeds from there.
"""

from __future__ import annotations

import hashlib
import os
import sys
import time

import numpy as np

from .. import observability
from ..communicators._host_channel import ChannelError
from ..communicators._membership import ElasticMembership
from ..communicators.fault_schedule import InjectedFault, RankPreempted
from ..communicators.mesh_communicator import ElasticMeshCommunicator
from .failure_recovery import FailureRecovery, RecoveryGivingUp

__all__ = ["ElasticRecovery", "global_batch_plan", "ElasticConfigError",
           "create_elastic_membership"]

_ELASTIC_RECOVERABLE = (InjectedFault, ChannelError, RankPreempted)


class ElasticConfigError(RuntimeError):
    """A resize produced a configuration the run cannot satisfy (e.g.
    the global batch does not divide over the new world and the policy
    forbids accumulation).  Carries the computed ``plan``."""

    def __init__(self, message, plan=None):
        self.plan = plan
        super().__init__(message)


def global_batch_plan(global_bs, world_size, policy="rescale",
                      max_per_rank=None):
    """The global-batch preservation table (``docs/resilience.md`` §7):
    how one logical step of ``global_bs`` samples is fed to a world of
    ``world_size`` ranks.

    Returns ``{"policy", "global_bs", "world_size", "dispatch_bs",
    "per_rank_bs", "accum_steps"}`` where one logical step =
    ``accum_steps`` dispatches of ``dispatch_bs`` samples
    (``dispatch_bs × accum_steps == global_bs``), each dispatch
    sharding ``per_rank_bs = dispatch_bs / world_size`` per rank.

    * ``"rescale"`` (default): one dispatch of the full global batch —
      the per-rank share rescales implicitly through the shard_map
      in_spec.  Requires ``global_bs % world_size == 0`` and (when
      given) ``per_rank_bs <= max_per_rank``; otherwise falls through
      to the accumulation search so the caller still gets a feasible
      plan to act on (or reject).
    * ``"accumulate"``: the smallest ``accum_steps`` dividing
      ``global_bs`` whose dispatch batch divides over the world (and
      fits ``max_per_rank``) — per-rank memory stays bounded on a
      shrink at the cost of extra dispatches.

    Pure function — every member computes the identical plan from the
    identical (global_bs, world_size) pair.
    """
    if policy not in ("rescale", "accumulate"):
        raise ValueError(f"unknown global-batch policy {policy!r} "
                         f"(rescale|accumulate)")
    global_bs = int(global_bs)
    world_size = int(world_size)
    if global_bs < 1 or world_size < 1:
        raise ValueError(f"global_bs={global_bs}/world_size={world_size} "
                         f"must be >= 1")

    def fits(dispatch):
        per = dispatch // world_size
        return dispatch % world_size == 0 and per >= 1 \
            and (max_per_rank is None or per <= max_per_rank)

    if policy == "rescale" and fits(global_bs):
        return {"policy": "rescale", "global_bs": global_bs,
                "world_size": world_size, "dispatch_bs": global_bs,
                "per_rank_bs": global_bs // world_size, "accum_steps": 1}
    for k in range(1 if policy == "accumulate" else 2, global_bs + 1):
        if global_bs % k:
            continue
        dispatch = global_bs // k
        if fits(dispatch):
            return {"policy": "accumulate", "global_bs": global_bs,
                    "world_size": world_size, "dispatch_bs": dispatch,
                    "per_rank_bs": dispatch // world_size,
                    "accum_steps": k}
    raise ElasticConfigError(
        f"no feasible batch plan: global_bs={global_bs} cannot be "
        f"preserved over world_size={world_size}"
        + (f" within max_per_rank={max_per_rank}" if max_per_rank
           else ""),
        plan=None)


def create_elastic_membership(comm, **kwargs):
    """An :class:`ElasticMembership` bound to this process, over the
    communicator's coordination-service client.  Returns ``None`` when
    no cross-process channel exists (single-controller runs inject a
    scripted membership in tests, or run without elasticity)."""
    ch = comm._host_channel() if hasattr(comm, "_host_channel") else None
    if ch is None:
        return None
    import jax
    kwargs.setdefault("namespace", ch._ns.split("/el", 1)[0])
    return ElasticMembership(ch._client, rank=jax.process_index(),
                             world=jax.process_count(), **kwargs)


class ElasticRecovery(FailureRecovery):
    """The elastic supervisor extension (see module docstring).

    Beyond :class:`FailureRecovery`'s arguments:

    ``membership``: an :class:`ElasticMembership` (default: built from
    the communicator's coordination client; ``None`` on single-process
    runs — elasticity then requires an injected membership).
    ``comm_factory``: ``factory(view) -> communicator`` called
    lock-step by every member of a decided view (default:
    :class:`ElasticMeshCommunicator` over the view's members,
    inheriting the boot communicator's exchange knobs and re-forcing
    its ici×dcn split when one existed and still divides).
    ``min_world``: shrink floor — a view smaller than this raises
    :class:`RecoveryGivingUp` (with the view in the message) instead
    of limping on.
    ``rejoin_after_s``: preempted-rank behavior — ``None`` (default)
    re-raises and fail-stops (the production scheduler restarts the
    process); a number parks that long, announces ``join``, and waits
    for re-admission (the chaos harness's preempt-and-return).
    ``batch_policy``/``max_per_rank_bs``: the global-batch
    preservation policy validated at each resize
    (:func:`global_batch_plan`).
    ``join_poll_interval``: iterations between the survivors'
    lock-step join polls (one object-channel broadcast each).
    """

    priority = 100
    name = "ElasticRecovery"

    def __init__(self, checkpointer=None, comm=None, membership=None,
                 comm_factory=None, min_world=1, rejoin_after_s=None,
                 batch_policy="rescale", max_per_rank_bs=None,
                 join_poll_interval=1, recoverable=None,
                 unrecoverable=None, max_recoveries=3, cooldown_s=0.0,
                 sleep=time.sleep, on_recover=None, on_resize=None,
                 verbose=True, resolve_timeout_ms=None):
        super().__init__(checkpointer=checkpointer, comm=comm,
                         recoverable=(tuple(recoverable)
                                      if recoverable is not None
                                      else _ELASTIC_RECOVERABLE),
                         # a lost peer is exactly what elasticity
                         # recovers — nothing is unrecoverable by
                         # default here
                         unrecoverable=(tuple(unrecoverable)
                                        if unrecoverable is not None
                                        else ()),
                         max_recoveries=max_recoveries,
                         cooldown_s=cooldown_s, sleep=sleep,
                         on_recover=on_recover, verbose=verbose)
        if membership is None and self.comm is not None:
            membership = create_elastic_membership(self.comm)
        self.membership = membership
        self._boot_comm = self.comm
        self._boot_channel = (self.comm._host_channel()
                              if self.comm is not None
                              and hasattr(self.comm, "_host_channel")
                              else None)
        self.comm_factory = comm_factory
        self.min_world = int(min_world)
        self.rejoin_after_s = rejoin_after_s
        self.batch_policy = batch_policy
        self.max_per_rank_bs = max_per_rank_bs
        self.on_resize = on_resize
        self.resolve_timeout_ms = resolve_timeout_ms
        self.view = membership.current_view() if membership is not None \
            else None
        self.trigger = (int(join_poll_interval), "iteration")

    # -- identity ------------------------------------------------------------
    @property
    def stable_rank(self):
        """This process's global controller rank (membership identity)."""
        if self.membership is not None:
            return self.membership.rank
        return getattr(self.comm, "stable_rank",
                       getattr(self.comm, "rank", 0))

    def _log(self, msg):
        if self.verbose:
            print(f"chainermn_tpu elastic[r{self.stable_rank}]: {msg}",
                  file=sys.stderr)

    # -- per-iteration join poll (the grow trigger) -------------------------
    def __call__(self, trainer):
        if self.membership is None or self.view is None:
            return
        # lock-step poll: slot 0 reads the KV store, the result is
        # broadcast over the members' object channel so every survivor
        # enters (or skips) the resize at the same call site — two
        # survivors seeing a join one iteration apart would otherwise
        # split the resolve
        mine = self.membership.pending_joins(self.view) \
            if self.comm.inter_rank == 0 else None
        joins = tuple(self.comm.bcast_obj(mine, root=0) or ())
        if joins:
            self._log(f"admitting joins {list(joins)} at iteration "
                      f"{trainer.updater.iteration}")
            self._resize(trainer,
                         expect=set(self.view.members) | set(joins))

    # -- supervisor protocol -------------------------------------------------
    def recover(self, trainer, exc):
        if self.membership is None:
            # no membership protocol: elastic behavior is impossible;
            # degrade to the fixed-size supervisor for in-place faults
            # (RankPreempted then fail-stops through the type check
            # below)
            if isinstance(exc, RankPreempted):
                raise exc
            return super().recover(trainer, exc)
        self._spend_recovery_budget(exc)
        if self.cooldown_s:
            self._sleep(self.cooldown_s)
        if isinstance(exc, RankPreempted) and (
                exc.rank is None or exc.rank == self.stable_rank):
            return self._preempted(trainer, exc)
        # survivor path: a typed failure that may mean lost peers —
        # resolve the membership (unresponsive ranks time out of the
        # view), rebuild, converge.  A fault with no casualties decides
        # the SAME member set at a new epoch: the rebuild then doubles
        # as the fixed-size quiesce.
        self._log(f"recovering from {type(exc).__name__}: {exc} "
                  f"(attempt {self.stats['recoveries']}"
                  f"/{self.max_recoveries})")
        # the elastic timeline's first mark: detection is the moment
        # the typed failure reached the supervisor (the time between
        # the wire fault and here is the detection timeout the chaos
        # gate budgets)
        observability.instant("elastic/preempt_detect",
                              tags={"exc": type(exc).__name__,
                                    "rank": getattr(exc, "rank", None)})
        with observability.span("recover/quiesce"):
            self._quiesce_transport()
        suspects = set()
        rank = getattr(exc, "rank", None)
        if rank is not None and not isinstance(exc, InjectedFault):
            rank = int(rank)
            # channel-borne ranks (PeerLostError from the members-only
            # sub-channel) are dense SLOTS of the current view, not
            # global ids — translate, or a post-resize suspect would
            # drop the wrong member from the fast path
            members = getattr(self.comm, "members", None)
            if members is not None and 0 <= rank < len(members):
                rank = members[rank]
            suspects.add(rank)
        expect = set(self.view.members) - suspects
        resumed = self._resize(trainer, expect=expect)
        if self.on_recover is not None:
            self.on_recover(trainer, exc, resumed)
        return resumed

    # -- capacity transfer (ISSUE 16) ----------------------------------------
    # The CapacityBroker's view of this supervisor: a training rank
    # converting to a serving replica departs CLEANLY (no exception,
    # no checkpoint rollback — the survivors' shrink preserves the
    # global batch exactly like a preemption shrink) and later
    # re-enters through the same guarded admission the
    # preempt-and-return arc uses.

    def capacity_leave(self, note="capacity transfer: to serving"):
        """Announce this rank's clean departure for a role conversion.
        Survivors shrink without burning a timeout (the announced-leave
        fast path); returns the epoch at departure so the caller can
        wait for the shrink decision before doing anything that races
        it."""
        epoch = self.membership.current_epoch()
        self.membership.announce_leave(note=note)
        observability.instant("capacity/leave_announced",
                              tags={"rank": self.stable_rank})
        self._log(f"clean leave announced ({note})")
        return epoch

    def capacity_rejoin(self, trainer=None,
                        note="capacity transfer: rejoin"):
        """Re-enter training after a serving stint — the same guarded
        two-attempt admission the preempt-and-return arc uses
        (``require=`` the survivors: a joiner never settles a world by
        itself).  With a ``trainer``, the full adopt runs (rebuild,
        snapshot sync, consensus load); without one, the decided view
        is adopted and returned for callers that rebuild on their own
        schedule.  Raises :class:`RecoveryGivingUp` when the survivors
        never admit us."""
        view = prev = self.membership.current_view()
        for attempt in range(2):
            self.membership.announce_join(note=note)
            prev = self.membership.current_view()
            self._log(f"capacity rejoin (current view "
                      f"{list(prev.members)}, attempt {attempt + 1})")
            with observability.span("elastic/resolve",
                                    tags={"rejoin": True,
                                          "capacity": True,
                                          "attempt": attempt + 1}):
                view = self.membership.resolve(
                    expect=set(prev.members) | {self.stable_rank},
                    require=set(prev.members) - {self.stable_rank},
                    timeout_ms=self.resolve_timeout_ms)
            if self.stable_rank in view:
                break
        if self.stable_rank not in view:
            raise RecoveryGivingUp(
                "capacity re-join was not admitted", membership=view)
        if trainer is not None:
            return self._adopt(trainer, view, prev_view=prev)
        self.view = view
        return view

    # -- the three moves -----------------------------------------------------
    def _preempted(self, trainer, exc):
        """This rank's capacity was reclaimed: announce the departure
        (survivors then shrink without burning a timeout on us), then
        fail-stop — or park and re-join when the harness asks for the
        full preempt-and-return arc.

        The park waits for the survivors' shrink decision (the epoch
        advancing past the one we left at) BEFORE the ``rejoin_after_s``
        dwell: a join announced while the departure is still being
        resolved would collapse the shrink and the grow into one no-op
        resolve — the world would never actually change size."""
        epoch_at_leave = self.membership.current_epoch()
        self.membership.announce_leave(note=str(exc))
        observability.instant("elastic/preempt_detect",
                              tags={"exc": type(exc).__name__,
                                    "self_preempted": True})
        self._log(f"preempted ({exc}); leave announced")
        if self.rejoin_after_s is None:
            raise exc  # hard exit: the scheduler owns the restart
        timeout_ms = self.resolve_timeout_ms \
            if self.resolve_timeout_ms is not None \
            else self.membership.timeout_ms
        deadline = time.monotonic() + timeout_ms / 1000.0
        while self.membership.current_epoch() == epoch_at_leave \
                and time.monotonic() < deadline:
            self._sleep(self.membership.poll_s)
        self._sleep(self.rejoin_after_s)
        # two admission attempts: the first resolve can race a
        # CONCURRENT survivors' resolve (another failure, or a join
        # poll that predates our announce) and adopt a view deciding
        # that event without us — the join intent is still standing, so
        # one re-announce + resolve rides the survivors' next poll
        # (the same exclusion retry _resize applies)
        for attempt in range(2):
            self.membership.announce_join(note="rejoin after preemption")
            prev = self.membership.current_view()
            self._log(f"re-joining (current view {list(prev.members)}, "
                      f"attempt {attempt + 1})")
            # require= the survivors: a joiner must never settle a
            # world by itself (a resolve that cannot reach them times
            # out typed)
            with observability.span("elastic/resolve",
                                    tags={"rejoin": True,
                                          "attempt": attempt + 1}):
                view = self.membership.resolve(
                    expect=set(prev.members) | {self.stable_rank},
                    require=set(prev.members) - {self.stable_rank},
                    timeout_ms=self.resolve_timeout_ms)
            if self.stable_rank in view:
                break
        if self.stable_rank not in view:
            raise RecoveryGivingUp(
                "re-join was not admitted", membership=view) from exc
        return self._adopt(trainer, view, prev_view=prev)

    def _resize(self, trainer, expect):
        """Survivor-side resolve → rebuild → converge (both shrink and
        grow ride this; the joiner enters at :meth:`_adopt` after its
        own resolve returns the same view)."""
        prev = self.view
        with observability.span("elastic/resolve",
                                tags={"expect": sorted(expect)}):
            view = self.membership.resolve(
                expect=expect, timeout_ms=self.resolve_timeout_ms)
        if self.stable_rank not in view:
            # the split-brain escape: we were too slow and the leader
            # settled without us — re-enter as a joiner rather than
            # continuing a second, disjoint world
            self._log(f"excluded from view {list(view.members)}")
            if self.rejoin_after_s is None:
                raise RecoveryGivingUp(
                    "excluded from the decided membership view",
                    membership=view)
            self.membership.announce_join(note="excluded, re-joining")
            with observability.span("elastic/resolve",
                                    tags={"rejoin": True}):
                view = self.membership.resolve(
                    expect=set(view.members) | {self.stable_rank},
                    require=set(view.members) - {self.stable_rank},
                    timeout_ms=self.resolve_timeout_ms)
            if self.stable_rank not in view:
                raise RecoveryGivingUp(
                    "re-join after exclusion was not admitted",
                    membership=view)
        if view.size < self.min_world:
            raise RecoveryGivingUp(
                f"world shrank below min_world={self.min_world}",
                membership=view)
        return self._adopt(trainer, view, prev_view=prev)

    def _adopt(self, trainer, view, prev_view):
        """Lock-step across ``view.members``: rebuild the communicator,
        re-plan all size-dependent state, sync the newest snapshot to
        joiners, and converge everyone on it."""
        lost = [r for r in prev_view.members if r not in view]
        joined = [r for r in view.members if r not in prev_view]
        self.last_view = view
        self.view = view
        with observability.span("elastic/rebuild",
                                tags={"epoch": view.epoch,
                                      "members": list(view.members),
                                      "lost": lost, "joined": joined}):
            new_comm = (self.comm_factory(view) if self.comm_factory
                        is not None else self._default_factory(view))
            self._check_batch(trainer, new_comm)
            self._swap_communicator(trainer, new_comm)
        self.stats["ranks_lost"] += len(lost)
        self.stats["ranks_joined"] += len(joined)
        if view.size != prev_view.size:
            self.stats["resizes"] += 1
        self._publish_stats()
        self._log(f"world e{view.epoch}: members {list(view.members)} "
                  f"(lost {lost}, joined {joined}, size {prev_view.size}"
                  f"->{view.size})")
        resumed = None
        if self.checkpointer is not None:
            with observability.span("elastic/snapshot_sync",
                                    tags={"joined": joined}):
                if joined:
                    self._sync_snapshot_to_joiners(trainer, joined)
                resumed = self.checkpointer.maybe_load(trainer)
        elif joined:
            raise ElasticConfigError(
                "growing the world needs a checkpointer: the joiner's "
                "state must be adopted from the survivors' newest "
                "snapshot (pass checkpointer= to ElasticRecovery)")
        self.stats["resumed_iterations"].append(resumed)
        self._log(f"converged -> iteration "
                  f"{resumed if resumed is not None else '(live state)'}")
        if self.on_resize is not None:
            self.on_resize(trainer, view, resumed)
        return resumed

    # -- rebuild plumbing ----------------------------------------------------
    def _default_factory(self, view):
        """Members-only communicator inheriting the boot communicator's
        exchange knobs.  A hierarchical boot split is RE-FORCED when the
        per-group device count still divides the new world (the ici
        size is a property of the hosts, which did not change) and
        degrades to flat otherwise — with the per-hop dtype intent
        collapsing onto the single hop exactly like the
        ``CHAINERMN_TPU_HIERARCHY=flat`` hatch."""
        old = self._boot_comm
        kwargs = dict(batch_collectives=getattr(old, "batch_collectives",
                                                True),
                      bucket_mb=getattr(old, "bucket_mb", None),
                      error_feedback=getattr(old, "error_feedback", True),
                      channel=self._boot_channel)
        grad_dtype = getattr(old, "allreduce_grad_dtype", None)
        if getattr(old, "hierarchy", None) is not None:
            import jax
            n_devices = sum(
                1 for d in jax.devices()
                if getattr(d, "process_index", 0) in view.members)
            intra = old.ici_size
            dcn_dtype = old.dcn_grad_dtype
            if n_devices % intra == 0 and n_devices // intra >= 1:
                kwargs["intra_size"] = intra
                kwargs["axis_name"] = (f"dcn_e{view.epoch}",
                                       f"ici_e{view.epoch}")
                kwargs["allreduce_grad_dtype"] = {
                    "ici": grad_dtype, "dcn": dcn_dtype}
            else:
                # the dcn (slow-hop) intent wins on the one flat hop —
                # never a silent drop to lossless
                kwargs["allreduce_grad_dtype"] = dcn_dtype or grad_dtype
        else:
            kwargs["allreduce_grad_dtype"] = grad_dtype
        return ElasticMeshCommunicator(view.members, epoch=view.epoch,
                                       **kwargs)

    def _swap_communicator(self, trainer, new_comm):
        """Point every comm consumer at the rebuilt transport: the
        supervisor itself, the checkpointer, every multi-node optimizer
        (``change_communicator`` re-plans buckets/chunking and re-seeds
        the stale/EF buffers), comm-holding iterators (the multi-node /
        synchronized batch broadcasters — left on the boot comm their
        every batch fetch would ride the dead world's channel), and the
        model's replicated placement."""
        self.comm = new_comm
        if self.checkpointer is not None:
            self.checkpointer.comm = new_comm
        for it in (getattr(trainer.updater, "_iterators", None)
                   or {}).values():
            while it is not None:
                if hasattr(it, "comm"):
                    it.comm = new_comm
                it = getattr(it, "actual_iterator", None)
        for opt in trainer.updater.get_all_optimizers().values():
            if hasattr(opt, "change_communicator"):
                opt.change_communicator(
                    new_comm, via_checkpoint=self.checkpointer is not None)
            target = getattr(opt, "target", None)
            if target is not None:
                _rehome_model(target, new_comm)

    def _check_batch(self, trainer, new_comm):
        """Validate the global-batch policy against the new world BEFORE
        the first resized step: a failure here carries the computed plan
        instead of surfacing as a shape error inside shard_map."""
        try:
            it = trainer.updater.get_iterator("main")
        except Exception:
            return
        # unwrap comm-holding broadcasters (_MultiNodeIterator /
        # _SynchronizedIterator): batch_size and the scattered dataset
        # live on the wrapped base iterator — skipping here would defer
        # an indivisible batch to a shard_map shape error inside the
        # first resized step, exactly what this hook pre-empts
        while not hasattr(it, "batch_size") \
                and getattr(it, "actual_iterator", None) is not None:
            it = it.actual_iterator
        global_bs = getattr(it, "batch_size", None)
        if not global_bs:
            return
        plan = global_batch_plan(global_bs, new_comm.size,
                                 policy=self.batch_policy,
                                 max_per_rank=self.max_per_rank_bs)
        if plan["accum_steps"] != 1:
            raise ElasticConfigError(
                f"global batch {global_bs} needs "
                f"{plan['accum_steps']}-step gradient accumulation at "
                f"world size {new_comm.size} "
                f"(dispatch_bs={plan['dispatch_bs']}); plain updaters "
                f"dispatch one global batch per step — re-shard the "
                f"iterator or use an accumulation-aware updater "
                f"(docs/resilience.md §7 policy table)", plan=plan)
        # rescale: nothing to mutate — update() feeds the global batch
        # and the new mesh's in_spec re-splits it.  A host-scattered
        # dataset re-slices for the new topology at EVERY world size:
        # a shrink to one controller must widen the survivor's shard to
        # the full order (keeping the old partial shard would silently
        # train on a fraction of the epoch — the union-preservation
        # contract docs/resilience.md §7 commits).
        from ..dataset.datasets import SubDataset
        from ..datasets import rescatter_dataset
        ds = getattr(it, "dataset", None)
        if isinstance(ds, SubDataset) and hasattr(it, "reset"):
            it.dataset = rescatter_dataset(ds, new_comm)
            it.reset()

    # -- snapshot shipping (the grow convergence) ---------------------------
    def _sync_snapshot_to_joiners(self, trainer, joined):
        """Survivors snapshot their CURRENT state; slot 0 ships its file
        to the joiners over the new members-only channel; joiners adopt
        the bytes under their OWN stable-rank filename (+ checksum
        sidecar).  The consensus ``maybe_load`` right after then finds
        the fresh generation on every member — the joiner resumes
        bit-exact from the survivors' live state, and the survivors
        reload the snapshot they just wrote (a no-op by construction).

        Cost note: the bcast ships the snapshot to EVERY member, so
        surviving non-roots download bytes they discard.  Deliberate at
        this scale — bcast is the one lock-step collective whose done
        barrier already synchronizes file durability with the vote; a
        targeted ``send_obj`` per joiner (+ explicit barrier) is the
        optimization seam when large-N grows with large snapshots hurt.
        """
        cp = self.checkpointer
        me_joined = self.stable_rank in joined
        iteration = None
        if not me_joined:
            iteration = trainer.updater.iteration
            cp.save(trainer, iteration)
        # the shipping root is the lowest-ranked SURVIVOR (a joiner has
        # nothing current to ship); every member computes the same slot
        # from the same view
        survivors = [r for r in self.view.members if r not in joined]
        root = self.view.slot(min(survivors))
        payload = None
        if self.stable_rank == min(survivors):
            out = cp._dir(trainer)
            with open(os.path.join(out, cp._filename(iteration)),
                      "rb") as f:
                payload = (iteration, f.read())
        iteration, data = self.comm.bcast_obj(payload, root=root)
        if me_joined:
            out = cp._dir(trainer)
            os.makedirs(out, exist_ok=True)
            fname = cp._filename(iteration)
            digest = hashlib.sha256(data).hexdigest()
            with open(os.path.join(out, fname + ".sum"), "w") as f:
                f.write(digest)  # sidecar before data: same durability
            with open(os.path.join(out, fname), "wb") as f:
                f.write(data)    # order as checkpoint.save documents
            self._log(f"adopted snapshot generation {iteration} "
                      f"({len(data)} bytes)")
        # every member (joiners included) barriers through the bcast
        # above, so the files are durable before the consensus vote


def _rehome_model(model, comm):
    """Re-place a model's params/persistents replicated on ``comm``'s
    mesh by VALUE (the old mesh may span departed processes, so
    ``bcast_data``'s direct device_put cannot be used)."""
    from ..optimizers import _rehome_replicated
    for param in model.params():
        if param.array is not None:
            param.array = _rehome_replicated(param.array, comm)
        # a gradient from the old world has no meaning in the new one
        # (and may live on the old mesh): drop it — the next step
        # recomputes
        param.grad = None
    from ..core.link import _persistent_slots
    for sublink, name, _ in _persistent_slots(model):
        value = getattr(sublink, name)
        if value is not None and not np.isscalar(value) \
                and not isinstance(value, (int, float)):
            placed = _rehome_replicated(value, comm)
            object.__setattr__(sublink, name, placed)
            sublink._persistent[name] = placed
    return model
