"""Distributed checkpointing with consensus resume.

Reference: ``chainermn/extensions/checkpoint.py ·
create_multi_node_checkpointer, _MultiNodeCheckpointer`` (SURVEY.md §2.4,
call stack §3.5): every rank snapshots its own trainer state
(``<name>.<iteration>.<rank>``) on a trigger, old generations are
garbage-collected, and ``maybe_load`` allgathers each rank's available
snapshot iterations, picks the newest iteration present on *all* ranks,
and resumes everyone consistently — the fail-stop recovery contract
(crash → relaunch → converge on the newest common checkpoint).  The same
consensus is the convergence step of the in-place recovery supervisor
(``extensions.FailureRecovery`` + ``Trainer.run``; ``docs/resilience.md``
documents the full inject → detect → recover → converge machinery).

Single-controller translation: one snapshot per *host* (``comm.inter_rank``
— this process drives all its devices' state); the consensus allgather
runs over the object channel (DCN multi-host, loopback single-host).
Device-sharded arrays are pulled to host by the npz serializer; for
pod-scale sharded state see ``chainermn_tpu.extensions.orbax_checkpoint``.

Integrity (see ``docs/resilience.md``): snapshots are written atomically
(tmp + rename) and paired with a SHA-256 sidecar (``<file>.sum``) written
*before* the data rename, so a snapshot torn by a crash or an injected
fault either never becomes visible or fails verification — and
``_scan``/``maybe_load`` only offer *verified* iterations to the
consensus vote, so a corrupt snapshot can never win it.  The generation a
consensus resume restored from is pinned against GC
(``_protected_iteration``): a rank that runs ahead can never sweep the
newest *common* generation while a peer may still be resuming from it.
"""

from __future__ import annotations

import hashlib
import io
import os
import re
import tempfile
import time

from ..serializers.npz import load_npz, save_npz
from ..training.trainer import Extension

__all__ = ["create_multi_node_checkpointer", "_MultiNodeCheckpointer"]


def _sha256_file(path, bufsize=1 << 20):
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            block = f.read(bufsize)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


def create_multi_node_checkpointer(comm, name="", cp_interval=5,
                                   gc_interval=5, path=None):
    """Reference-shaped factory.

    ``cp_interval``: number of snapshot generations kept.  ``gc_interval``:
    collection cadence — stale generations are removed once they number at
    least ``gc_interval`` (batching deletes instead of one unlink per save).
    """
    return _MultiNodeCheckpointer(comm, name, cp_interval, gc_interval, path)


class _MultiNodeCheckpointer(Extension):
    trigger = (1, "epoch")
    priority = -100  # after everything else mutated state this iteration

    def __init__(self, comm, name, cp_interval, gc_interval, path):
        self.comm = comm
        self.name = name
        self.cp_interval = cp_interval
        self.gc_interval = gc_interval
        self.path = path
        self.stats = {"snapshots": 0, "gc": 0, "save_time": 0.0,
                      "verify_failures": 0}
        self._files = []
        # the iteration the last consensus resume loaded: pinned against
        # GC so a rank running ahead cannot sweep the newest COMMON
        # generation while a peer may still be resuming from it
        self._protected_iteration = None
        # test seam: called with (tmp_path, final_name) between the
        # serialized write and the atomic publish — the chaos harness
        # raises here to model a crash mid-checkpoint-write
        self._write_fault_hook = None

    @property
    def rank(self):
        # prefer the communicator's STABLE process identity (elastic
        # communicators keep it invariant across resizes, ISSUE 10) so
        # a process always re-reads its OWN snapshots — the per-view
        # slot would silently re-key files after a shrink/grow
        return getattr(self.comm, "stable_rank", self.comm.inter_rank)

    def _dir(self, trainer=None):
        if self.path is not None:
            return self.path
        assert trainer is not None
        return trainer.out

    def _filename(self, iteration):
        return f"{self.name}.{iteration}.{self.rank}"

    _pattern = property(lambda self: re.compile(
        re.escape(self.name) + r"\.(\d+)\.(\d+)$"))

    # -- save -------------------------------------------------------------
    def __call__(self, trainer):
        self.save(trainer, trainer.updater.iteration)

    def save(self, trainer, iteration):
        """Atomic, checksummed snapshot write.

        Order matters: serialize to a tmp file, write the SHA-256
        sidecar (itself tmp + rename), then rename the data into place.
        A crash or injected fault at ANY point leaves either no visible
        snapshot (tmp files are scrubbed / never scanned — the ``\\.``
        in the name pattern cannot match ``mkstemp`` suffixes) or a
        visible snapshot whose sidecar was already durable — never a
        torn file that could win the consensus vote (``_scan`` refuses
        unverifiable files).
        """
        from .. import observability
        with observability.span("train/checkpoint_serialize",
                                tags={"iteration": int(iteration)}):
            return self._save_impl(trainer, iteration)

    def _save_impl(self, trainer, iteration):
        start = time.time()
        out = self._dir(trainer)
        os.makedirs(out, exist_ok=True)
        fname = self._filename(iteration)
        fd, tmp = tempfile.mkstemp(prefix=fname + ".tmp", dir=out)
        os.close(fd)
        sum_tmp = None
        try:
            # serialize once to memory: the digest comes from the bytes
            # in hand (no read-back of the file we just wrote — zipfile
            # seeks during write, so hash-while-writing would be wrong)
            buf = io.BytesIO()
            save_npz(buf, trainer)
            data = buf.getbuffer()  # zero-copy view: one snapshot in RAM
            with open(tmp, "wb") as f:
                f.write(data)
            if self._write_fault_hook is not None:
                self._write_fault_hook(tmp, fname)
            digest = hashlib.sha256(data).hexdigest()
            fd, sum_tmp = tempfile.mkstemp(prefix=fname + ".sum.tmp",
                                           dir=out)
            with os.fdopen(fd, "w") as f:
                f.write(digest)
            os.replace(sum_tmp, os.path.join(out, fname + ".sum"))
            sum_tmp = None
            os.replace(tmp, os.path.join(out, fname))
        except Exception:
            for leftover in (tmp, sum_tmp):
                if leftover is not None and os.path.exists(leftover):
                    os.remove(leftover)
            raise
        if fname not in self._files:  # re-crossed after a rollback: one
            self._files.append(fname)  # entry, or _gc's keep/stale split
            # would count the generation twice and delete a kept file
        self.stats["snapshots"] += 1
        self.stats["save_time"] += time.time() - start
        if len(self._files) >= self.cp_interval + self.gc_interval:
            self._gc(out)

    def _gc(self, out):
        keep = sorted(self._files,
                      key=lambda f: int(self._pattern.match(f).group(1)))
        stale, keep = keep[: -self.cp_interval], keep[-self.cp_interval:]
        protected = []
        for fname in stale:
            # never sweep the generation the last consensus resumed
            # from: a peer may still be loading it, and after a crash it
            # is the newest iteration guaranteed present on ALL ranks
            if self._protected_iteration is not None and \
                    int(self._pattern.match(fname).group(1)) == \
                    self._protected_iteration:
                protected.append(fname)
                continue
            try:
                os.remove(os.path.join(out, fname))
                self.stats["gc"] += 1
            except OSError:
                # data survived: keep its sidecar (or the file would
                # re-enter the vote unverifiable-but-admitted) and keep
                # tracking it so the next gc retries the removal
                protected.append(fname)
                continue
            try:
                os.remove(os.path.join(out, fname + ".sum"))
            except OSError:
                pass
        self._files = protected + keep

    # -- consensus resume ---------------------------------------------------
    def maybe_load(self, trainer, optimizer=None, path=None):
        """Resume from the newest iteration *every* rank has a snapshot of.

        Reference semantics: local scan → allgather of iteration sets →
        max of the intersection → ``load_npz`` on each rank's own file.
        Returns the resumed iteration or None.

        Only *verified* snapshots enter the vote: ``_scan`` drops files
        whose SHA-256 sidecar mismatches, so a torn/corrupted snapshot on
        any rank excludes that iteration from the consensus globally
        (every rank intersects the same sets) and the vote falls back to
        the newest intact common generation.  The resumed iteration is
        then pinned against GC (see ``_gc``).
        """
        from .. import observability
        with observability.span("recover/consensus_load"):
            return self._maybe_load_impl(trainer, optimizer, path)

    def _maybe_load_impl(self, trainer, optimizer=None, path=None):
        out = path or self._dir(trainer)
        local = self._scan(out)
        all_sets = self.comm.allgather_obj(sorted(local))
        common = set(all_sets[0])
        for s in all_sets[1:]:
            common &= set(s)
        if not common:
            return None
        iteration = max(common)
        load_npz(os.path.join(out, self._filename(iteration)), trainer,
                 strict=False)
        self._files = [self._filename(i) for i in sorted(local)]
        self._protected_iteration = iteration
        return iteration

    def _scan(self, out):
        """Local snapshot census: iterations of this rank whose files
        verify against their checksum sidecar.  Sidecar-less files are
        admitted (snapshots written before the integrity pass); files
        with a mismatching sidecar are excluded and counted in
        ``stats['verify_failures']``."""
        iterations = set()
        if not os.path.isdir(out):
            return iterations
        for fname in os.listdir(out):
            m = self._pattern.match(fname)
            if not (m and int(m.group(2)) == self.rank):
                continue
            if not self._verify(os.path.join(out, fname)):
                self.stats["verify_failures"] += 1
                continue
            iterations.add(int(m.group(1)))
        return iterations

    def _verify(self, path):
        sum_path = path + ".sum"
        if not os.path.exists(sum_path):
            return True  # pre-integrity-pass snapshot: no sidecar to check
        try:
            with open(sum_path) as f:
                expect = f.read().strip()
            return _sha256_file(path) == expect
        except OSError:
            return False

    def finalize(self):
        pass

    def serialize(self, serializer):
        pass
