"""Multi-node evaluator.

Reference: ``chainermn/evaluators.py · create_multi_node_evaluator``
(SURVEY.md §2.4): patches an ``Evaluator`` so every rank's local metric
dict is allreduce-averaged, making report/trigger logic behave identically
everywhere.

Single-controller translation: evaluation runs once per *host* over the
host's data shard; the average is taken across hosts (``allreduce_obj``
over DCN when multi-host; identity on one host, where local metrics
already cover all local devices' data).
"""

from __future__ import annotations

import numpy as np

__all__ = ["create_multi_node_evaluator"]


def create_multi_node_evaluator(actual_evaluator, communicator):
    """Patch ``actual_evaluator.evaluate`` in place (reference behavior:
    returns the same object with a wrapped ``evaluate``)."""

    actual_evaluator._mn_original_evaluate = actual_evaluator.evaluate
    actual_evaluator._mn_communicator = communicator

    def evaluate():
        local = actual_evaluator._mn_original_evaluate()
        comm = actual_evaluator._mn_communicator
        # sample-weighted reduction: evaluators exposing per-key SAMPLE
        # counts (this framework's Evaluator sets ``_mn_counts`` to the
        # number of examples each key's metrics covered) contribute
        # proportionally, so ragged shards don't skew the mean; foreign
        # evaluators without counts fall back to the reference's
        # unweighted average (weight 1 per host)
        counts = getattr(actual_evaluator, "_mn_counts", {})
        gathered = comm.allgather_obj(
            {k: (float(np.asarray(v)), float(counts.get(k, 1.0)))
             for k, v in local.items()})
        keys = set()
        for d in gathered:
            keys.update(d)
        out = {}
        for k in keys:
            pairs = [d[k] for d in gathered if k in d]
            total = sum(n for _, n in pairs)
            out[k] = (sum(v * n for v, n in pairs) / total if total
                      else float(np.mean([v for v, _ in pairs])))
        return out

    actual_evaluator.evaluate = evaluate
    return actual_evaluator
