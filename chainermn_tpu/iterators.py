"""Multi-node iterators.

Reference: ``chainermn/iterators.py · create_multi_node_iterator,
create_synchronized_iterator`` (SURVEY.md §2.4):

* ``create_multi_node_iterator`` — the master rank runs the real iterator
  and broadcasts each batch; replicas yield the received batch.  Used when
  all ranks must see the *same* batch (model parallelism).
* ``create_synchronized_iterator`` — synchronizes RNG state across ranks
  so each rank's local iterator draws identical shuffles.

Single-controller translation: within one host, every device trivially
sees the controller's batch, so both wrappers are about *host*-level
agreement: batches (resp. RNG seeds) are shipped over the object channel
when ``inter_size > 1`` and are pass-through on one host — same
observable contract, zero cost where the topology makes it free.
"""

from __future__ import annotations

import numpy as np

from .dataset.iterators import Iterator

__all__ = ["create_multi_node_iterator", "create_synchronized_iterator"]


class _MultiNodeIterator(Iterator):
    def __init__(self, actual_iterator, communicator, rank_master=0):
        self.comm = communicator
        self.rank_master = rank_master
        self.actual_iterator = actual_iterator

    @property
    def _is_master(self):
        return self.comm.inter_rank == self.rank_master

    def __next__(self):
        if self.comm.inter_size <= 1:
            return self.actual_iterator.next()
        if self._is_master:
            try:
                batch = self.actual_iterator.next()
                payload = ("batch", batch,
                           self.actual_iterator.epoch,
                           self.actual_iterator.is_new_epoch,
                           self.actual_iterator.epoch_detail,
                           self.actual_iterator.previous_epoch_detail)
            except StopIteration:
                payload = ("stop", None, None, None, None, None)
            payload = self.comm.bcast_obj(payload, root=self.rank_master)
        else:
            payload = self.comm.bcast_obj(None, root=self.rank_master)
        kind, batch, epoch, is_new_epoch, detail, prev_detail = payload
        if kind == "stop":
            raise StopIteration
        self._epoch = epoch
        self._is_new_epoch = is_new_epoch
        self._epoch_detail = detail
        self._previous_epoch_detail = prev_detail
        return batch

    next = __next__

    @property
    def epoch(self):
        if self.comm.inter_size <= 1 or self._is_master:
            return self.actual_iterator.epoch
        return getattr(self, "_epoch", 0)

    @property
    def is_new_epoch(self):
        if self.comm.inter_size <= 1 or self._is_master:
            return self.actual_iterator.is_new_epoch
        return getattr(self, "_is_new_epoch", False)

    @property
    def epoch_detail(self):
        if self.comm.inter_size <= 1 or self._is_master:
            return self.actual_iterator.epoch_detail
        # replicas never advance their local iterator — epoch progress is
        # part of the broadcast payload so 'epoch'-unit triggers stay in
        # lock-step with the master (collective-bearing extensions depend
        # on every host firing together)
        return getattr(self, "_epoch_detail", 0.0)

    @property
    def previous_epoch_detail(self):
        if self.comm.inter_size <= 1 or self._is_master:
            return self.actual_iterator.previous_epoch_detail
        return getattr(self, "_previous_epoch_detail", -1.0)

    def reset(self):
        if hasattr(self.actual_iterator, "reset"):
            self.actual_iterator.reset()

    def serialize(self, serializer):
        self.actual_iterator.serialize(serializer)

    def finalize(self):
        self.actual_iterator.finalize()


def create_multi_node_iterator(actual_iterator, communicator, rank_master=0):
    return _MultiNodeIterator(actual_iterator, communicator, rank_master)


def create_synchronized_iterator(actual_iterator, communicator):
    """Agree on RNG state across hosts so local shuffles are identical.

    The master's existing RNG *state* is broadcast and installed on every
    host (reference: RNG state synchronization) — a user's pre-seeded
    iterator keeps its seed; the master's own stream is untouched.
    """
    rng = getattr(actual_iterator, "_rng", None)
    if rng is not None:
        state = rng.get_state() if communicator.inter_rank == 0 else None
        state = communicator.bcast_obj(state, root=0)
        actual_iterator._rng.set_state(state)
        if hasattr(actual_iterator, "reset"):
            actual_iterator.reset()
    return actual_iterator
