"""Pallas TPU kernels for hot ops (the rebuild's N2/N3 escape hatch)."""

from .flash_attention import attention, flash_attention, xla_attention
from .paged_attention import (paged_attn_mode, paged_decode_attention,
                              paged_prefill_attention)

__all__ = ["attention", "flash_attention", "xla_attention",
           "paged_decode_attention", "paged_prefill_attention",
           "paged_attn_mode"]
