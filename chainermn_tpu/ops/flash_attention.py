"""Fused flash attention — Pallas TPU kernel.

N2/N3-class component (SURVEY.md §2.5): where the reference hand-wrote
CUDA kernels for its hot paths, the TPU rebuild's escape hatch beyond
XLA fusion is Pallas.  Attention is the canonical case: the fused kernel
keeps the [Tq, Tk] score matrix out of HBM entirely — scores live in VMEM
tiles, softmax runs online (running max/normalizer), and the MXU sees one
[BQ, D]×[D, Tk-block] matmul stream per query tile.

``attention(q, k, v)`` dispatches: Pallas kernel on TPU backends, a
jnp reference elsewhere (CPU tests run the kernel in interpreter mode to
pin kernel↔reference equivalence).

The backward is a FUSED one-pass kernel by default
(:func:`_flash_bwd_fused_kernel`): each (qi, ki) attention tile is
recomputed once — s = q·kᵀ, mask, p = exp(s − lse) — and feeds all
three gradients (dk/dv accumulate in VMEM across the query loop, dq
leaves as per-key-block partial planes reduced by one XLA sum).  The
legacy two-kernel lowering (one dq pass + one dkv pass, each
recomputing the tile) stays available bit-for-bit behind
``CHAINERMN_TPU_FLASH_BWD=split``.  Backward tiles are tuned
independently of the forward's (``CHAINERMN_TPU_FLASH_BWD_BLOCK_Q/K``,
sweep-driven per-T table — `make sweep-flash`).

Ring-attention composition: ``parallel.ring_attention`` rotates KV blocks
between chips; within a chip this kernel computes each block's
contribution — ICI transfers at the outer level, VMEM tiling at the
inner.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["attention", "flash_attention", "xla_attention"]

# Both grid dims are embarrassingly parallel (independent programs per
# (batch*head, block) pair).  vmem_limit_bytes raises Mosaic's scoped-VMEM
# cap from its 16 MB default: at long T, XLA can place whole kernel
# outputs in VMEM (observed OOM on v5e at T=8192 with the default).
def _make_compiler_params():
    # pallas renamed TPUCompilerParams -> CompilerParams across jax
    # releases; accept either (and run parameter-less if the kwargs
    # themselves ever change — the kernel is correct without them, the
    # params only lift the scoped-VMEM cap / mark grid parallelism).
    cls = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams", None)
    if cls is None:
        return None
    try:
        return cls(dimension_semantics=("parallel", "parallel"),
                   vmem_limit_bytes=100 * 1024 * 1024)
    except TypeError:
        try:
            return cls()
        except Exception:
            return None


_COMPILER_PARAMS = _make_compiler_params()


def xla_attention(q, k, v, causal=False, scale=None):
    """jnp reference implementation (and non-TPU fallback).

    Dtype discipline: q/k/v keep their storage dtype INTO the matmuls
    (bf16 inputs ride the MXU's native bf16 path) while
    ``preferred_element_type=float32`` makes the accumulator fp32; the
    softmax itself runs in fp32 and its probabilities are cast back to
    the value dtype for the second matmul."""
    D = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        Tq, Tk = s.shape[-2], s.shape[-1]
        qpos = lax.broadcasted_iota(jnp.int32, (Tq, Tk), 0)
        kpos = lax.broadcasted_iota(jnp.int32, (Tq, Tk), 1)
        s = jnp.where((qpos >= kpos)[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v,
                      preferred_element_type=jnp.float32).astype(q.dtype)


def _flash_kernel_lse(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k,
                      causal, scale):
    """Forward kernel variant that also writes the log-sum-exp row
    statistics (softmax normalizer) needed by the backward kernels."""
    bq, d = q_ref.shape
    tk = k_ref.shape[0]
    qi = pl.program_id(1)

    # dtype discipline: blocks go into the dots in their STORAGE dtype
    # (bf16 rides the MXU's native path; an f32 upcast would force the
    # 3-pass f32 matmul emulation) with fp32 accumulators via
    # preferred_element_type; the online-softmax state stays fp32.
    q = q_ref[:]
    m = jnp.full((bq, 1), -jnp.inf, jnp.float32)
    l = jnp.zeros((bq, 1), jnp.float32)
    acc = jnp.zeros((bq, d), jnp.float32)
    n_kblocks = tk // block_k
    q_pos = (qi * bq + lax.broadcasted_iota(jnp.int32, (bq, 1), 0))

    def body(ki, carry):
        m, l, acc = carry
        k_blk = k_ref[pl.ds(ki * block_k, block_k), :]
        v_blk = v_ref[pl.ds(ki * block_k, block_k), :]
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            k_pos = (ki * block_k
                     + lax.broadcasted_iota(jnp.int32, (1, block_k), 1))
            s = jnp.where(q_pos >= k_pos, s, -jnp.inf)
        m_blk = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_blk)
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.where(jnp.isfinite(s), jnp.exp(s - m_safe), 0.0)
        corr = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * corr + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    if causal:
        last = jnp.minimum((qi * bq + bq + block_k - 1) // block_k,
                           n_kblocks)
    else:
        last = n_kblocks
    m, l, acc = jax.lax.fori_loop(0, last, body, (m, l, acc))
    o_ref[:] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    # lse is [bq, 1]: Mosaic requires the block's trailing dims to divide
    # (8, 128) or equal the array dims — a trailing singleton qualifies,
    # a squeezed 1-D block does not
    lse_ref[:] = m_safe + jnp.log(jnp.maximum(l, 1e-30))


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                         dq_ref, *, block_k, causal, scale):
    """dq for one query block: recompute P from (q, k, lse); then
    dq = scale * sum_j (P_ij (g_i·v_j - delta_i)) k_j."""
    bq, d = q_ref.shape
    tk = k_ref.shape[0]
    qi = pl.program_id(1)
    q = q_ref[:]          # storage dtype into the dots (see fwd kernel)
    g = g_ref[:]
    lse = lse_ref[:].reshape(bq, 1)   # block arrives [bq, 1]
    delta = delta_ref[:].reshape(bq, 1)
    n_kblocks = tk // block_k
    q_pos = (qi * bq + lax.broadcasted_iota(jnp.int32, (bq, 1), 0))
    dq = jnp.zeros((bq, d), jnp.float32)

    def body(ki, dq):
        k_blk = k_ref[pl.ds(ki * block_k, block_k), :]
        v_blk = v_ref[pl.ds(ki * block_k, block_k), :]
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            k_pos = (ki * block_k
                     + lax.broadcasted_iota(jnp.int32, (1, block_k), 1))
            s = jnp.where(q_pos >= k_pos, s, -jnp.inf)
        p = jnp.where(jnp.isfinite(s), jnp.exp(s - lse), 0.0)
        gv = jax.lax.dot_general(g, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (gv - delta)
        return dq + jax.lax.dot_general(
            ds.astype(k_blk.dtype), k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        last = jnp.minimum((qi * bq + bq + block_k - 1) // block_k,
                           n_kblocks)
    else:
        last = n_kblocks
    dq = jax.lax.fori_loop(0, last, body, dq)
    dq_ref[:] = (dq * scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, *, block_q, causal, scale):
    """dk/dv for one key block: loop over query blocks;
    dv = P^T g ; dk = scale * sum_i (P_ij (g_i·v_j - delta_i)) q_i."""
    bk, d = k_ref.shape
    tq = q_ref.shape[0]
    ki = pl.program_id(1)
    k = k_ref[:]          # storage dtype into the dots (see fwd kernel)
    v = v_ref[:]
    n_qblocks = tq // block_q
    k_pos = (ki * bk + lax.broadcasted_iota(jnp.int32, (1, bk), 1))
    dk = jnp.zeros((bk, d), jnp.float32)
    dv = jnp.zeros((bk, d), jnp.float32)

    def body(qi, carry):
        dk, dv = carry
        q_blk = q_ref[pl.ds(qi * block_q, block_q), :]
        g_blk = g_ref[pl.ds(qi * block_q, block_q), :]
        lse = lse_ref[pl.ds(qi * block_q, block_q), :] \
            .reshape(block_q, 1)
        delta = delta_ref[pl.ds(qi * block_q, block_q), :] \
            .reshape(block_q, 1)
        s = jax.lax.dot_general(q_blk, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = (qi * block_q
                     + lax.broadcasted_iota(jnp.int32, (block_q, 1), 0))
            s = jnp.where(q_pos >= k_pos, s, -jnp.inf)
        p = jnp.where(jnp.isfinite(s), jnp.exp(s - lse), 0.0)
        dv = dv + jax.lax.dot_general(
            p.astype(g_blk.dtype), g_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        gv = jax.lax.dot_general(g_blk, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (gv - delta)
        dk = dk + jax.lax.dot_general(
            ds.astype(q_blk.dtype), q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk, dv

    if causal:
        # query blocks at or after this key block participate
        first = (ki * bk) // block_q
    else:
        first = 0
    dk, dv = jax.lax.fori_loop(first, n_qblocks, body, (dk, dv))
    # ds was computed from UNSCALED q·k products with scale folded into s,
    # so dk = scale · Σ ds·q (the fwd scale that s carries)
    dk_ref[:] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


def _flash_bwd_fused_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                            dq_part_ref, dk_ref, dv_ref, *, block_q,
                            causal, scale):
    """Fused backward: ONE pass over the (qi, ki) tiles per key block.

    The split lowering (`_flash_bwd_dq_kernel` + `_flash_bwd_dkv_kernel`)
    recomputes the attention block twice: each kernel re-runs the
    s = q·kᵀ dot, the mask, exp(s − lse) and the g·vᵀ dot for every tile
    it touches.  Here each (qi, ki) tile is recomputed ONCE and all three
    gradient contributions leave together:

        dv  += pᵀ g                      (accumulated in VMEM over qi)
        dk  += dsᵀ q                     (accumulated in VMEM over qi)
        dq_part[qi] = ds·k               (per-key-block partial plane)

    dq cannot be accumulated in-place across key blocks — the grid is
    parallel over ki and Mosaic offers no cross-program accumulation —
    so each program writes its [Tq, D] dq contribution to its own slot
    of a [n_kblocks, Tq, D] partial array; the caller reduces it with
    one XLA sum (the splash-attention fused-backward shape; the reduce
    is HBM-bound but a rounding error next to the recomputed dots it
    replaces).  Per tile pair the split lowering runs 8 MXU dots + 2
    exp's; this runs 5 dots + 1 exp — the recompute-once argument in
    docs/performance.md quantifies it.
    """
    bk, d = k_ref.shape
    tq = q_ref.shape[0]
    ki = pl.program_id(1)
    k = k_ref[:]          # storage dtype into the dots (see fwd kernel)
    v = v_ref[:]
    n_qblocks = tq // block_q
    k_pos = (ki * bk + lax.broadcasted_iota(jnp.int32, (1, bk), 1))
    dk = jnp.zeros((bk, d), jnp.float32)
    dv = jnp.zeros((bk, d), jnp.float32)
    # causally-skipped query tiles must still leave a defined partial:
    # zero the whole plane once, the live tiles overwrite below
    dq_part_ref[:] = jnp.zeros((tq, d), jnp.float32)

    def body(qi, carry):
        dk, dv = carry
        q_blk = q_ref[pl.ds(qi * block_q, block_q), :]
        g_blk = g_ref[pl.ds(qi * block_q, block_q), :]
        lse = lse_ref[pl.ds(qi * block_q, block_q), :] \
            .reshape(block_q, 1)
        delta = delta_ref[pl.ds(qi * block_q, block_q), :] \
            .reshape(block_q, 1)
        s = jax.lax.dot_general(q_blk, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = (qi * block_q
                     + lax.broadcasted_iota(jnp.int32, (block_q, 1), 0))
            s = jnp.where(q_pos >= k_pos, s, -jnp.inf)
        p = jnp.where(jnp.isfinite(s), jnp.exp(s - lse), 0.0)  # ONCE
        dv = dv + jax.lax.dot_general(
            p.astype(g_blk.dtype), g_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        gv = jax.lax.dot_general(g_blk, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (gv - delta)
        dk = dk + jax.lax.dot_general(
            ds.astype(q_blk.dtype), q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # dq contribution of this (qi, ki) tile; scale is applied after
        # the cross-block sum (mirrors the split dq kernel's `dq * scale`
        # after its fori accumulation)
        dq_part_ref[pl.ds(qi * block_q, block_q), :] = \
            jax.lax.dot_general(ds.astype(k.dtype), k,
                                (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        return dk, dv

    if causal:
        # query blocks at or after this key block participate
        first = (ki * bk) // block_q
    else:
        first = 0
    dk, dv = jax.lax.fori_loop(first, n_qblocks, body, (dk, dv))
    # ds was computed from UNSCALED q·k products with scale folded into s,
    # so dk = scale · Σ ds·q (the fwd scale that s carries)
    dk_ref[:] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k, causal, scale,
                  q_offset_blocks):
    """One (batch*head, q-block) program: stream K/V blocks through VMEM
    with the online-softmax recurrence."""
    bq, d = q_ref.shape
    tk = k_ref.shape[0]
    qi = pl.program_id(1)

    q = q_ref[:]          # storage dtype into the dots (see _flash_kernel_lse)
    m = jnp.full((bq, 1), -jnp.inf, jnp.float32)
    l = jnp.zeros((bq, 1), jnp.float32)
    acc = jnp.zeros((bq, d), jnp.float32)

    n_kblocks = tk // block_k
    q_pos = (qi * bq + lax.broadcasted_iota(jnp.int32, (bq, 1), 0))

    def body(ki, carry):
        m, l, acc = carry
        k_blk = k_ref[pl.ds(ki * block_k, block_k), :]
        v_blk = v_ref[pl.ds(ki * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, block_k]
        if causal:
            k_pos = (ki * block_k
                     + lax.broadcasted_iota(jnp.int32, (1, block_k), 1))
            s = jnp.where(q_pos >= k_pos, s, -jnp.inf)
        m_blk = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_blk)
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.where(jnp.isfinite(s), jnp.exp(s - m_safe), 0.0)
        corr = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * corr + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    if causal:
        # only blocks that intersect the causal triangle contribute
        last_needed = jnp.minimum(
            (qi * bq + bq + block_k - 1) // block_k, n_kblocks)
    else:
        last_needed = n_kblocks
    m, l, acc = jax.lax.fori_loop(0, last_needed, body, (m, l, acc))
    o_ref[:] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


# Adaptive-default tile candidates, largest first.  The round-5 on-chip
# sweep (tools/flash_block_sweep.py, BENCH_NOTES r5): 128×128 tiles
# serialize the online-softmax loop into too-small MXU dots — 1024-wide
# tiles ran the same fwd+bwd 2.85× faster at T=8192 (11.2 → 31.8
# TFLOP/s) and lifted the end-to-end seq-1024 transformer step 1.40×
# (74.1k → 103.4k tokens/sec/chip, MFU 29.1% → 40.6%).  VMEM cost at
# 1024: the f32 score/probability tiles are 4 MB each — comfortably
# inside the kernel's 100 MB scoped-VMEM cap with whole-T K/V staging
# up to T≈64k.
_BLOCK_CANDIDATES = (1024, 512, 256, 128)


def _adaptive_block(t):
    """Largest candidate tile that divides T (so the grid stays exact);
    falls back to the legacy 128 (clamped to T by the callers) when T
    is not a multiple of any candidate — e.g. T=64 keeps the old
    min(128, T) behavior, odd T keeps its XLA-fallback path."""
    if t is not None:
        for b in _BLOCK_CANDIDATES:
            if t % b == 0:
                return b
    return 128


def _flash_blocks(block_q=None, block_k=None, tq=None, tk=None):
    """Resolve kernel tile sizes: explicit arguments win, else the
    CHAINERMN_TPU_FLASH_BLOCK_Q/K env knobs (so an on-chip session can
    A/B block shapes without code edits), else the shape-adaptive
    default (:func:`_adaptive_block` over the given Tq/Tk).  Env changes
    only affect programs traced AFTERWARDS — jit caches are not keyed on
    them, so run each configuration in a fresh process (the probe does).
    Values must be positive multiples of 8 (Mosaic sublane tiling)."""
    out = []
    for name, given, t in (("CHAINERMN_TPU_FLASH_BLOCK_Q", block_q, tq),
                           ("CHAINERMN_TPU_FLASH_BLOCK_K", block_k, tk)):
        if given is None:
            raw = os.environ.get(name)
            if raw is None:
                given = _adaptive_block(t)
            else:
                try:
                    given = int(raw)
                except ValueError:
                    raise ValueError(f"{name}={raw!r} is not an integer")
                if given <= 0 or given % 8:
                    raise ValueError(
                        f"{name}={given} invalid: flash block sizes must "
                        "be positive multiples of 8")
        out.append(given)
    return tuple(out)


# -- backward lowering selection ---------------------------------------------

#: CHAINERMN_TPU_FLASH_BWD: "fused" (default) = the one-pass dq/dkv
#: kernel; "split" = the legacy two-kernel lowering (dq pass + dkv pass,
#: each recomputing the attention block) — the escape hatch, kept
#: exactly like nn.functions' CHAINERMN_TPU_MAXPOOL_VJP=xla: read once
#: at import, monkeypatchable in tests, and the legacy kernels are
#: untouched so `split` restores the old lowering bit-for-bit.
_FLASH_BWD = os.environ.get("CHAINERMN_TPU_FLASH_BWD", "fused")

#: Backward-specific tile table, keyed by sequence length — the bwd
#: kernels have a different VMEM/recompute balance than the forward
#: (whole-T q/g staging + an f32 [Tq, D] partial plane vs the forward's
#: K/V streaming), so their best tiles need not match.  Regenerate with
#: `make sweep-flash` (tools/flash_sweep.py sweeps fwd/bwd/fwd+bwd per
#: (block_q, block_k) and rewrites tools/flash_budgets.json; paste the
#: winners here).  Committed values are the best KNOWN config — the r5
#: on-chip sweep's 1024-tile winner for the split backward (BENCH_NOTES
#: r5: 128-tiles 11.2 → 1024-tiles 31.8 TFLOP/s at T=8192); the fused
#: kernel's own sweep refines them on the next chip session.
_BWD_BLOCK_TABLE = {
    1024: (1024, 1024),
    2048: (1024, 1024),
    8192: (1024, 1024),
    16384: (1024, 1024),
}


def _flash_bwd_mode():
    mode = _FLASH_BWD
    if mode not in ("fused", "split"):
        raise ValueError(
            f"CHAINERMN_TPU_FLASH_BWD={mode!r} invalid (fused|split)")
    return mode


def _flash_bwd_blocks(block_q=None, block_k=None, tq=None, tk=None):
    """Backward tile resolution: explicit arguments win, else the
    CHAINERMN_TPU_FLASH_BWD_BLOCK_Q/K env knobs, else the sweep-driven
    per-T table (:data:`_BWD_BLOCK_TABLE`), else the forward's
    shape-adaptive default.  Same env-retrace caveat and multiple-of-8
    validation as :func:`_flash_blocks`."""
    out = []
    for i, (name, given, t) in enumerate(
            (("CHAINERMN_TPU_FLASH_BWD_BLOCK_Q", block_q, tq),
             ("CHAINERMN_TPU_FLASH_BWD_BLOCK_K", block_k, tk))):
        if given is None:
            raw = os.environ.get(name)
            if raw is None:
                entry = _BWD_BLOCK_TABLE.get(t)
                given = entry[i] if entry else _adaptive_block(t)
            else:
                try:
                    given = int(raw)
                except ValueError:
                    raise ValueError(f"{name}={raw!r} is not an integer")
                if given <= 0 or given % 8:
                    raise ValueError(
                        f"{name}={given} invalid: flash block sizes must "
                        "be positive multiples of 8")
        out.append(given)
    return tuple(out)


def _interpret_forced():
    """CHAINERMN_TPU_FLASH_INTERPRET=1 routes the `attention` /
    `attention_with_lse` dispatchers through the Pallas kernels in
    interpreter mode on ANY backend — how the CPU tier-1 suite drives
    the ring/Ulysses consumers through the real custom-VJP backward
    instead of the blockwise-jnp fallback."""
    return os.environ.get("CHAINERMN_TPU_FLASH_INTERPRET", "0") == "1"


def flash_attention(q, k, v, causal=False, scale=None, block_q=None,
                    block_k=None, interpret=False):
    """Fused attention via Pallas.  q/k/v: [B, H, T, D].  Default block
    sizes come from :func:`_flash_blocks` (env-tunable)."""
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    block_q, block_k = _flash_blocks(block_q, block_k, tq=Tq, tk=Tk)
    block_q = min(block_q, Tq)
    block_k = min(block_k, Tk)
    if Tq % block_q or Tk % block_k:
        return xla_attention(q, k, v, causal=causal, scale=scale)

    qr = q.reshape(B * H, Tq, D)
    kr = k.reshape(B * H, Tk, D)
    vr = v.reshape(B * H, Tk, D)

    kernel = functools.partial(_flash_kernel, block_k=block_k,
                               causal=causal, scale=scale,
                               q_offset_blocks=0)
    out = pl.pallas_call(
        kernel,
        grid=(B * H, Tq // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, Tk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, Tk, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Tq, D), q.dtype),
        interpret=interpret,
        compiler_params=_COMPILER_PARAMS,
    )(qr, kr, vr)
    return out.reshape(B, H, Tq, D)


def flash_attention_fwd(q, k, v, causal=False, scale=None, block_q=None,
                        block_k=None, interpret=False):
    """Forward kernel returning (out, lse [B, H, Tq])."""
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    block_q, block_k = _flash_blocks(block_q, block_k, tq=Tq, tk=Tk)
    block_q = min(block_q, Tq)
    block_k = min(block_k, Tk)
    qr = q.reshape(B * H, Tq, D)
    kr = k.reshape(B * H, Tk, D)
    vr = v.reshape(B * H, Tk, D)
    kernel = functools.partial(_flash_kernel_lse, block_k=block_k,
                               causal=causal, scale=scale)
    out, lse = pl.pallas_call(
        kernel,
        grid=(B * H, Tq // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, Tk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, Tk, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Tq, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, Tq, 1), jnp.float32),
        ],
        interpret=interpret,
        compiler_params=_COMPILER_PARAMS,
    )(qr, kr, vr)
    return out.reshape(B, H, Tq, D), lse.reshape(B, H, Tq)


def flash_attention_bwd(q, k, v, out, lse, g, causal=False, scale=None,
                        block_q=None, block_k=None, interpret=False,
                        g_lse=None, bwd_block_q=None, bwd_block_k=None):
    """Backward: (dq, dk, dv) with flash memory behavior.

    Default lowering is the FUSED one-pass kernel
    (:func:`_flash_bwd_fused_kernel`): one recompute of each (qi, ki)
    attention tile feeds dq, dk and dv together, with its own
    sweep-tunable tiles (``bwd_block_q``/``bwd_block_k`` →
    :func:`_flash_bwd_blocks`).  ``CHAINERMN_TPU_FLASH_BWD=split``
    restores the legacy two-kernel lowering (a dq pass and a dkv pass,
    each recomputing exp(q·kᵀ − lse)) bit-for-bit — the escape hatch,
    same contract as PR 3's ``MAXPOOL_VJP=xla``.

    ``g_lse``: optional cotangent of the lse output.  Since
    ∂lse_i/∂s_ij = p_ij, its whole contribution is ``ds += g_lse_i * p``
    — algebraically identical to replacing ``delta`` with
    ``delta - g_lse`` in the kernels (``ds = p*(gv - delta)``), so no
    kernel changes are needed on either path.  Ring attention depends on
    this: the cross-block merge weights are functions of each block's
    lse."""
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    block_q, block_k = _flash_blocks(block_q, block_k, tq=Tq, tk=Tk)
    block_q = min(block_q, Tq)
    block_k = min(block_k, Tk)
    qr = q.reshape(B * H, Tq, D)
    kr = k.reshape(B * H, Tk, D)
    vr = v.reshape(B * H, Tk, D)
    gr = g.reshape(B * H, Tq, D)
    lser = lse.reshape(B * H, Tq, 1)  # trailing singleton: Mosaic-legal
    # delta_i = rowsum(g_i * out_i) — one fused elementwise reduce
    delta = jnp.sum(gr.astype(jnp.float32)
                    * out.reshape(B * H, Tq, D).astype(jnp.float32),
                    axis=-1, keepdims=True)
    if g_lse is not None:
        delta = delta - g_lse.reshape(B * H, Tq, 1).astype(jnp.float32)

    if _flash_bwd_mode() == "fused":
        # bwd-specific tiles; the (already shape-validated) forward
        # tiles are the fallback when the table/env tiles don't divide
        # this T — e.g. ragged lengths reached with explicit fwd blocks
        bq, bk = _flash_bwd_blocks(bwd_block_q, bwd_block_k,
                                   tq=Tq, tk=Tk)
        bq = min(bq, Tq)
        bk = min(bk, Tk)
        if Tq % bq or Tk % bk:
            bq, bk = block_q, block_k
        n_kblocks = Tk // bk
        dq_part, dk, dv = pl.pallas_call(
            functools.partial(_flash_bwd_fused_kernel, block_q=bq,
                              causal=causal, scale=scale),
            grid=(B * H, n_kblocks),
            in_specs=[
                pl.BlockSpec((None, Tq, D), lambda b, i: (b, 0, 0)),
                pl.BlockSpec((None, bk, D), lambda b, i: (b, i, 0)),
                pl.BlockSpec((None, bk, D), lambda b, i: (b, i, 0)),
                pl.BlockSpec((None, Tq, D), lambda b, i: (b, 0, 0)),
                pl.BlockSpec((None, Tq, 1), lambda b, i: (b, 0, 0)),
                pl.BlockSpec((None, Tq, 1), lambda b, i: (b, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((None, None, Tq, D),
                             lambda b, i: (b, i, 0, 0)),
                pl.BlockSpec((None, bk, D), lambda b, i: (b, i, 0)),
                pl.BlockSpec((None, bk, D), lambda b, i: (b, i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((B * H, n_kblocks, Tq, D),
                                     jnp.float32),
                jax.ShapeDtypeStruct((B * H, Tk, D), k.dtype),
                jax.ShapeDtypeStruct((B * H, Tk, D), v.dtype),
            ],
            interpret=interpret,
            compiler_params=_COMPILER_PARAMS,
        )(qr, kr, vr, gr, lser, delta)
        # the cross-key-block dq reduction the grid cannot express:
        # one XLA sum over the partial planes, then the fwd scale
        dq = (jnp.sum(dq_part, axis=1) * scale).astype(q.dtype)
        return (dq.reshape(B, H, Tq, D), dk.reshape(B, H, Tk, D),
                dv.reshape(B, H, Tk, D))

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, block_k=block_k,
                          causal=causal, scale=scale),
        grid=(B * H, Tq // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, Tk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, Tk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Tq, D), q.dtype),
        interpret=interpret,
        compiler_params=_COMPILER_PARAMS,
    )(qr, kr, vr, gr, lser, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, block_q=block_q,
                          causal=causal, scale=scale),
        grid=(B * H, Tk // block_k),
        in_specs=[
            pl.BlockSpec((None, Tq, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, block_k, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_k, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, Tq, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, Tq, 1), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, Tq, 1), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_k, D), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Tk, D), k.dtype),
            jax.ShapeDtypeStruct((B * H, Tk, D), v.dtype),
        ],
        interpret=interpret,
        compiler_params=_COMPILER_PARAMS,
    )(qr, kr, vr, gr, lser, delta)
    return (dq.reshape(B, H, Tq, D), dk.reshape(B, H, Tk, D),
            dv.reshape(B, H, Tk, D))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_diff(q, k, v, causal, scale, interpret):
    return flash_attention(q, k, v, causal=causal, scale=scale,
                           interpret=interpret)


def _flash_diff_fwd(q, k, v, causal, scale, interpret):
    Tq, Tk = q.shape[2], k.shape[2]
    bq, bk = _flash_blocks(tq=Tq, tk=Tk)
    if Tq % min(bq, Tq) or Tk % min(bk, Tk):
        # irregular shapes: XLA fallback for both directions
        out = xla_attention(q, k, v, causal=causal, scale=scale)
        return out, (q, k, v, None, None, None)
    out, lse = flash_attention_fwd(q, k, v, causal=causal, scale=scale,
                                   block_q=bq, block_k=bk,
                                   interpret=interpret)
    # carry the block config in the residuals: the backward's SHAPE
    # validation must use the exact tiles the forward was validated with
    # (they are the fused path's divisibility fallback and the split
    # path's tiles; re-reading the fwd env there would silently corrupt
    # gradients if it changed mid-process)
    return out, (q, k, v, out, lse, (bq, bk))


def _flash_diff_bwd(causal, scale, interpret, res, g):
    q, k, v, out, lse, blocks = res
    if lse is None:
        _, vjp = jax.vjp(
            lambda q, k, v: xla_attention(q, k, v, causal=causal,
                                          scale=scale), q, k, v)
        return vjp(g)
    bq, bk = blocks
    return flash_attention_bwd(q, k, v, out, lse, g, causal=causal,
                               scale=scale, block_q=bq, block_k=bk,
                               interpret=interpret)


_flash_diff.defvjp(_flash_diff_fwd, _flash_diff_bwd)


def attention(q, k, v, causal=False, scale=None):
    """Dispatch: Pallas kernels on TPU (flash forward AND fused backward
    via custom VJP), XLA reference elsewhere.
    CHAINERMN_TPU_FLASH_INTERPRET=1 forces the Pallas path in
    interpreter mode on any backend (CPU kernel tests)."""
    if jax.default_backend() in ("tpu", "axon"):
        return _flash_diff(q, k, v, causal, scale, False)
    if _interpret_forced():
        return _flash_diff(q, k, v, causal, scale, True)
    return xla_attention(q, k, v, causal=causal, scale=scale)


# ---------------------------------------------------------------------------
# (out, lse) attention — the composable block primitive for ring/Ulysses
# ---------------------------------------------------------------------------

def _blockwise_attention_lse_jnp(q, k, v, causal, scale, block_k=512):
    """Blockwise jnp (out, lse): scans KV blocks with the online-softmax
    recurrence — never materializes a [Tq, Tk] score matrix.  Fallback
    for non-TPU backends and irregular shapes; differentiable through
    the scan."""
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    block_k = min(block_k, Tk)
    if Tk % block_k:
        # pad KV to a block multiple; padded keys are masked out below —
        # NEVER fall back to one full-width block (that would materialize
        # the [Tq, Tk] scores this function exists to avoid)
        pad = block_k - Tk % block_k
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    Tk_pad = k.shape[2]
    nb = Tk_pad // block_k
    ks = jnp.moveaxis(k.reshape(B, H, nb, block_k, D), 2, 0)
    vs = jnp.moveaxis(v.reshape(B, H, nb, block_k, D), 2, 0)
    q_pos = lax.broadcasted_iota(jnp.int32, (Tq, 1), 0)

    def step(carry, blk):
        m, l, acc = carry
        k_blk, v_blk, bi = blk
        # storage dtype into the matmul (bf16 MXU path), fp32 accumulator
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k_blk,
                       preferred_element_type=jnp.float32) * scale
        k_pos = (bi * block_k
                 + lax.broadcasted_iota(jnp.int32, (1, block_k), 1))
        valid = k_pos < Tk  # mask padded keys
        if causal:
            valid = valid & (q_pos >= k_pos)
        if causal or Tk != Tk_pad:
            s = jnp.where(valid[None, None], s, -jnp.inf)
        m_blk = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_blk)
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.where(jnp.isfinite(s), jnp.exp(s - m_safe), 0.0)
        corr = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * corr + jnp.einsum(
            "bhqk,bhkd->bhqd", p.astype(v_blk.dtype), v_blk,
            preferred_element_type=jnp.float32)
        return (m_new, l_new, acc_new), None

    m0 = jnp.full((B, H, Tq, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, H, Tq, 1), jnp.float32)
    acc0 = jnp.zeros((B, H, Tq, D), jnp.float32)
    (m, l, acc), _ = lax.scan(step, (m0, l0, acc0),
                              (ks, vs, jnp.arange(nb)))
    l_safe = jnp.maximum(l, 1e-30)
    out = (acc / l_safe).astype(q.dtype)
    m_fin = jnp.where(jnp.isfinite(m), m, 0.0)
    lse = (m_fin + jnp.log(l_safe))[..., 0]
    lse = jnp.where(jnp.isfinite(m[..., 0]), lse, -jnp.inf)
    return out, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_lse_diff(q, k, v, causal, scale, interpret):
    out, lse = flash_attention_fwd(q, k, v, causal=causal, scale=scale,
                                   interpret=interpret)
    return out, lse


def _flash_lse_fwd(q, k, v, causal, scale, interpret):
    bq, bk = _flash_blocks(tq=q.shape[2], tk=k.shape[2])
    out, lse = flash_attention_fwd(q, k, v, causal=causal, scale=scale,
                                   block_q=bq, block_k=bk,
                                   interpret=interpret)
    # same residual-carried block config as _flash_diff: the fwd tiles
    # are the backward's validated divisibility fallback
    return (out, lse), (q, k, v, out, lse, (bq, bk))


def _flash_lse_bwd(causal, scale, interpret, res, cots):
    q, k, v, out, lse, (bq, bk) = res
    g, g_lse = cots
    return flash_attention_bwd(q, k, v, out, lse, g, causal=causal,
                               scale=scale, block_q=bq, block_k=bk,
                               interpret=interpret, g_lse=g_lse)


_flash_lse_diff.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def attention_with_lse(q, k, v, causal=False, scale=None):
    """Differentiable blockwise attention returning ``(out, lse)``.

    ``lse`` (log-sum-exp softmax normalizer, [B, H, Tq], fp32) is what
    lets independently-computed attention blocks be merged exactly —
    ring attention's cross-chip recurrence (`parallel.ring_attention`)
    and any flash-style composition build on it.  Dispatch: Pallas
    kernels on TPU (128-aligned shapes), blockwise jnp otherwise —
    neither path materializes a [Tq, Tk] score matrix.
    """
    D = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    Tq, Tk = q.shape[2], k.shape[2]
    bq, bk = _flash_blocks(tq=Tq, tk=Tk)
    on_tpu = jax.default_backend() in ("tpu", "axon")
    if ((on_tpu or _interpret_forced())
            and Tq % min(bq, Tq) == 0 and Tk % min(bk, Tk) == 0):
        return _flash_lse_diff(q, k, v, causal, scale, not on_tpu)
    return _blockwise_attention_lse_jnp(q, k, v, causal, scale)


def blockwise_attention(q, k, v, causal=False, scale=None):
    """Memory-bounded attention (no [Tq, Tk] materialization on any
    backend): flash kernel on TPU, blockwise jnp scan elsewhere."""
    return attention_with_lse(q, k, v, causal=causal, scale=scale)[0]
