"""Paged single-query decode attention — the serving hot loop's kernel.

The decode step of the serving engine (``chainermn_tpu.serving``) is the
byte-bound roofline of PR 3 all over again: per generated token it must
read every cached K/V byte of every running sequence exactly once, and
nothing else matters.  The cache lives in a PAGED pool — fixed-size
blocks in one preallocated array (`serving.kv_cache`), with each
sequence owning a list of pages (its *block table*) — so the attention
step gathers K/V **through the block table** instead of assuming a
contiguous per-sequence buffer:

    k_pages = k_pool[block_table]        # ONE gather per pool
    scores  = q · k_pages (per page block, online softmax)

Two lowerings, selected by ``CHAINERMN_TPU_PAGED_ATTN``:

* ``paged`` (default): one gather per pool, then a **page-blockwise
  online softmax** (the flash-attention recurrence over the page axis:
  running max / normalizer, score width bounded at ``page_size``) — the
  numerics and memory shape a future Pallas paged kernel drops into.
* ``dense``: the escape hatch and parity reference — the same single
  gather, flattened to a dense ``[B, T, H, D]`` view, one full-width
  masked softmax.  Greedy decode trajectories are identical (pinned by
  ``tests/serving_tests/test_decode_parity.py``); per-logit deltas are
  fp32 rounding only.

Neither lowering ever forms a ``[Tq, Tk]`` score matrix — the query is
one token per sequence, so scores are ``[B, H, T]`` rows.  The serving
budget census (`tools/serving_census.py`) pins both facts tier-1: one
gather per pool per layer, zero full-T score dots.

Dtype discipline (PR 3): pages are stored bf16 by default and enter the
dots in their storage dtype (the MXU's native bf16 path); accumulators
and the softmax state are fp32 via ``preferred_element_type``.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["paged_decode_attention", "paged_prefill_attention",
           "paged_verify_attention", "paged_attn_mode", "head_sharding"]


def paged_attn_mode(mode=None):
    """Resolve the decode-attention lowering: explicit argument wins,
    else the ``CHAINERMN_TPU_PAGED_ATTN`` env knob (``paged`` default,
    ``dense`` = the reference escape hatch).  Read at call time so tests
    can flip it, but jit caches are NOT keyed on the env — the serving
    engine resolves the mode ONCE at construction and threads it
    explicitly, so a mid-process env flip cannot desync a cached decode
    program from a fresh prefill trace."""
    if mode is None:
        mode = os.environ.get("CHAINERMN_TPU_PAGED_ATTN", "paged")
    if mode not in ("paged", "dense"):
        raise ValueError(
            f"CHAINERMN_TPU_PAGED_ATTN={mode!r} invalid (paged|dense)")
    return mode


def head_sharding(mesh, ndim, head_dim, axis="tp"):
    """``NamedSharding`` pinning the HEAD dimension of an ``ndim``-rank
    array to the ``tp`` mesh axis (the tensor-parallel decode layout:
    heads shard like the ulysses path, every other dim replicated).
    Used by the serving engine to place the KV pools per shard and by
    :func:`paged_decode_attention` to constrain the gathered pages."""
    from jax.sharding import NamedSharding, PartitionSpec
    spec = [None] * ndim
    spec[head_dim] = axis
    return NamedSharding(mesh, PartitionSpec(*spec))


def _constrain_heads(x, head_dim, tp_mesh, tp_axis):
    """Pin ``x``'s head dimension to the tp axis (no-op without a
    mesh).  Keeps GSPMD from re-replicating the pool gathers — the
    whole point of tp decode is that each shard reads only ITS heads'
    cache bytes."""
    if tp_mesh is None:
        return x
    return jax.lax.with_sharding_constraint(
        x, head_sharding(tp_mesh, x.ndim, head_dim, tp_axis))


def _masked_softmax_stats(s, valid):
    """NaN-free masked softmax pieces shared by both lowerings: masked
    scores -> (p, l) with all-masked rows yielding p == 0 (an idle batch
    lane must produce zeros, not NaN)."""
    s = jnp.where(valid, s, -jnp.inf)
    m = jnp.max(s, axis=-1, keepdims=True)
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.where(jnp.isfinite(s), jnp.exp(s - m_safe), 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    return p, l


def _dense_decode(q, k, v, ctx_len, scale):
    """Dense reference: q [B, H, D] over contiguous k/v [B, T, H, D]
    with positions >= ctx_len masked.  One full-width softmax."""
    s = jnp.einsum("bhd,bthd->bht", q, k,
                   preferred_element_type=jnp.float32) * scale
    T = k.shape[1]
    kpos = lax.broadcasted_iota(jnp.int32, (1, 1, T), 2)
    p, l = _masked_softmax_stats(s, kpos < ctx_len[:, None, None])
    p = p / jnp.maximum(l, 1e-30)
    out = jnp.einsum("bht,bthd->bhd", p.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def paged_prefill_attention(q, k_pool, v_pool, block_table_row, start,
                            true_len, scale=None):
    """Suffix attention for a PREFIX-SHARED prefill (round 14).

    ``q``: ``[T, H, D]`` — the suffix's queries, query ``t`` sitting at
    absolute position ``start + t`` (``start`` = matched prefix
    length).  The suffix's own K/V must already be WRITTEN into the
    pools (``write_prompt_kv_at`` runs first), so ONE gather per pool
    through ``block_table_row`` covers the whole context — shared
    prefix pages and fresh suffix pages alike — and **zero flash
    kernels ever touch the shared pages** (the committed
    ``prefix_prefill`` census config pins this).  One masked softmax:
    query ``t`` sees positions ``<= start + t`` (causality subsumes the
    written-context bound since ``t < true_len``).  Scores are
    ``[H, T, N·S]`` — suffix-length by context, never ``[T_ctx,
    T_ctx]``: the FLOP saving IS the prefix hit.  Returns ``[T, H, D]``
    in ``q.dtype``.
    """
    T, H, D = q.shape
    S = k_pool.shape[1]
    N = block_table_row.shape[0]
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    k = k_pool[block_table_row].reshape(N * S, H, D)
    v = v_pool[block_table_row].reshape(N * S, H, D)
    s = jnp.einsum("thd,khd->htk", q, k,
                   preferred_element_type=jnp.float32) * scale
    kpos = lax.broadcasted_iota(jnp.int32, (1, 1, N * S), 2)
    qpos = start + lax.broadcasted_iota(jnp.int32, (1, T, 1), 1)
    p, l = _masked_softmax_stats(s, kpos <= qpos)
    p = p / jnp.maximum(l, 1e-30)
    out = jnp.einsum("htk,khd->thd", p.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def paged_verify_attention(q, k_pool, v_pool, block_table, start,
                           scale=None, tp_mesh=None, tp_axis="tp"):
    """Multi-query verify attention for SPECULATIVE decoding (round 20).

    ``q``: ``[B, K1, H, D]`` — ``K1 = K + 1`` query tokens per sequence
    (the pending token plus K draft tokens), query ``j`` of lane ``b``
    sitting at absolute position ``start[b] + j``.  The speculated
    K/V must already be WRITTEN into the pools (``write_span_kv`` runs
    first), so ONE gather per pool through ``block_table`` (``[B, N]``)
    covers the whole context, and the per-query causal mask ``kpos <=
    start + j`` makes query ``j`` score exactly the trajectory prefix
    it would have seen in a vanilla decode step — which is what makes
    greedy accept/reject bit-identical to one-token-at-a-time decode.
    ``start[b] < 0`` marks an idle lane (all queries masked, output
    zeros).  Scores are ``[B, H, K1, N·S]`` — K1 stays a small
    constant, never the context length, so no ``[T, T]`` score matrix
    ever forms (the committed ``spec_verify`` census config pins this
    and the one-gather-per-pool fact).  Returns ``[B, K1, H, D]`` in
    ``q.dtype``.

    This is the whole speculative bargain in one shape: the dense-side
    cost of scoring K extra tokens rides the SAME cache-byte reads the
    single-query step already pays, so accepted tokens are (HBM-wise)
    free — dispatch count per emitted token drops by ``1/(1 +
    accepted)``.
    """
    B, K1, H, D = q.shape
    S = k_pool.shape[1]
    N = block_table.shape[1]
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    q = _constrain_heads(q, 2, tp_mesh, tp_axis)
    k = _constrain_heads(k_pool[block_table], 3, tp_mesh, tp_axis)
    v = _constrain_heads(v_pool[block_table], 3, tp_mesh, tp_axis)
    k = k.reshape(B, N * S, H, D)
    v = v.reshape(B, N * S, H, D)
    s = jnp.einsum("bjhd,bkhd->bhjk", q, k,
                   preferred_element_type=jnp.float32) * scale
    kpos = lax.broadcasted_iota(jnp.int32, (1, 1, 1, N * S), 3)
    st = start[:, None, None, None]
    qpos = st + lax.broadcasted_iota(jnp.int32, (1, 1, K1, 1), 2)
    # idle lanes (start < 0) mask EVERY query — start + j crosses zero
    # for j >= |start|, so causality alone would leak
    p, l = _masked_softmax_stats(s, (kpos <= qpos) & (st >= 0))
    p = p / jnp.maximum(l, 1e-30)
    out = jnp.einsum("bhjk,bkhd->bjhd", p.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return _constrain_heads(out.astype(q.dtype), 2, tp_mesh, tp_axis)


def paged_decode_attention(q, k_pool, v_pool, block_table, ctx_len,
                           scale=None, mode=None, tp_mesh=None,
                           tp_axis="tp"):
    """One decode step of attention for a batch of cached sequences.

    q: ``[B, H, D]`` — ONE query token per sequence (the just-appended
    position).  ``k_pool``/``v_pool``: ``[P, S, H, D]`` page pools
    (``P`` pages of ``S`` token slots).  ``block_table``: ``[B, N]``
    int32 page ids — sequence ``b``'s token ``t`` lives in page
    ``block_table[b, t // S]`` at slot ``t % S``; entries past the live
    prefix may hold any valid page id (their positions are masked by
    ``ctx_len``).  ``ctx_len``: ``[B]`` int32 valid context lengths
    (``0`` = idle lane, output is zeros).  Returns ``[B, H, D]`` in
    ``q.dtype``.

    ``tp_mesh``/``tp_axis``: tensor-parallel decode — the pools arrive
    sharded over heads (``head_sharding``), and the constraints below
    keep the gathers and the attention output sharded the same way, so
    each shard reads only its own heads' cache bytes; the head axis is
    elementwise throughout, so no collective fires inside this op (the
    projection that consumes the output pays the one psum).
    """
    B, H, D = q.shape
    P, S = k_pool.shape[0], k_pool.shape[1]
    N = block_table.shape[1]
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    mode = paged_attn_mode(mode)
    q = _constrain_heads(q, 1, tp_mesh, tp_axis)

    # the gather: every cached byte of the batch's context, exactly once,
    # addressed through the block table (pages, not contiguous buffers)
    k_pages = _constrain_heads(k_pool[block_table], 3, tp_mesh, tp_axis)
    v_pages = _constrain_heads(v_pool[block_table], 3, tp_mesh, tp_axis)

    if mode == "dense":
        k = k_pages.reshape(B, N * S, H, D)
        v = v_pages.reshape(B, N * S, H, D)
        return _constrain_heads(_dense_decode(q, k, v, ctx_len, scale),
                                1, tp_mesh, tp_axis)

    # page-blockwise online softmax: scan the page axis with the flash
    # recurrence — score width bounded at S, fp32 running (m, l, acc)
    ks = jnp.moveaxis(k_pages, 1, 0)       # [N, B, S, H, D]
    vs = jnp.moveaxis(v_pages, 1, 0)
    ctx = ctx_len[:, None, None]

    def step(carry, blk):
        m, l, acc = carry
        k_blk, v_blk, i = blk
        s = jnp.einsum("bhd,bshd->bhs", q, k_blk,
                       preferred_element_type=jnp.float32) * scale
        kpos = (i * S + lax.broadcasted_iota(jnp.int32, (1, 1, S), 2))
        s = jnp.where(kpos < ctx, s, -jnp.inf)
        m_blk = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_blk)
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.where(jnp.isfinite(s), jnp.exp(s - m_safe), 0.0)
        corr = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * corr + jnp.einsum(
            "bhs,bshd->bhd", p.astype(v_blk.dtype), v_blk,
            preferred_element_type=jnp.float32)
        return (m_new, l_new, acc_new), None

    m0 = jnp.full((B, H, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, H, 1), jnp.float32)
    acc0 = jnp.zeros((B, H, D), jnp.float32)
    (m, l, acc), _ = lax.scan(step, (m0, l0, acc0),
                              (ks, vs, jnp.arange(N)))
    out = acc / jnp.maximum(l, 1e-30)
    return _constrain_heads(out.astype(q.dtype), 1, tp_mesh, tp_axis)
