"""Global exception hook — fail-stop crash propagation.

Reference: ``chainermn/global_except_hook.py · _add_hook_if_enabled``
(SURVEY.md §2.4, §5 failure-detection): an uncaught exception on any rank
prints its traceback and aborts the whole MPI job, so surviving ranks die
loudly instead of deadlocking inside a collective.

TPU translation: one controller per host; an uncaught exception here
prints the traceback, asks the JAX distributed runtime to shut down (so
the coordinator notifies peers), and hard-exits non-zero.  Peer hosts
blocked in a DCN/ICI collective then fail fast instead of hanging —
the same fail-stop contract; recovery is relaunch + the checkpointer's
``maybe_load`` consensus (SURVEY §3.5).

Enabled automatically on import when multi-host (mirroring the reference's
env-gated install); force with ``CHAINERMN_TPU_FORCE_ABORT_ON_EXCEPTION=1``
or disable with ``=0``.
"""

from __future__ import annotations

import os
import sys
import traceback

__all__ = ["add_hook", "_add_hook_if_enabled"]

_hook_installed = False


def add_hook():
    """Install the except hook (idempotent).

    Chains: any previously-installed excepthook (a test harness's
    capture hook, a logging framework's reporter) runs BEFORE the abort
    machinery, and stderr is flushed before the hard exit — so an
    injected-fault traceback can never be lost in buffered pipes
    (pytest capture, subprocess PIPEs) when ``os._exit`` skips the
    interpreter's normal flush-at-exit.
    """
    global _hook_installed
    if _hook_installed:
        return
    _hook_installed = True
    original = sys.excepthook

    def _hook(exc_type, exc_value, exc_traceback):
        try:
            import jax
            host = jax.process_index()
        except Exception:
            host = -1
        sys.stderr.write(
            f"chainermn_tpu: uncaught exception on host {host} — "
            f"aborting the distributed job (fail-stop)\n")
        traceback.print_exception(exc_type, exc_value, exc_traceback)
        sys.stderr.flush()
        # chain to whatever hook was installed before ours (never the
        # abort path's job to silence other tooling; a failing chained
        # hook must not stop the abort).  The interpreter default is
        # skipped — we already printed the traceback above
        if original is not None and original is not _hook \
                and original is not sys.__excepthook__:
            try:
                original(exc_type, exc_value, exc_traceback)
            except BaseException:
                # BaseException: a chained hook ending in sys.exit()
                # raises SystemExit, which must not skip the abort
                # broadcast below and leave peers hanging
                pass
        try:
            # unblock peers waiting in host-channel receives (fail-stop:
            # the KV analog of MPI_Abort) before tearing down our client
            from .communicators._host_channel import get_host_channel
            ch = get_host_channel()
            if ch is not None:
                ch.post_abort(f"host {host}: "
                              f"{exc_type.__name__}: {exc_value}")
        except Exception:
            pass
        try:
            import jax
            if jax.process_count() > 1:
                jax.distributed.shutdown()
        except Exception:
            pass
        if exc_type is KeyboardInterrupt:
            return  # the chained hook already reported it; no abort exit
        try:
            sys.stderr.flush()
            sys.stdout.flush()
        except Exception:
            pass
        os._exit(1)

    sys.excepthook = _hook


def _add_hook_if_enabled():
    flag = os.environ.get("CHAINERMN_TPU_FORCE_ABORT_ON_EXCEPTION")
    if flag == "0":
        return
    if flag == "1":
        add_hook()
        return
    # Auto-install only when the distributed runtime is already up.
    # Deliberately avoids jax.process_count(): that would force backend
    # initialization as an import side effect (slow, and wrong for
    # processes that configure platforms after import).
    try:
        from jax._src import distributed
        if getattr(distributed.global_state, "client", None) is not None:
            add_hook()
    except Exception:
        pass
