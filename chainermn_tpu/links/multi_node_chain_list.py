"""Model-parallel chain composition.

Reference: ``chainermn/links/multi_node_chain_list.py · MultiNodeChainList``
(SURVEY.md §2.3, call stack §3.3): components registered with
``add_link(chain, rank_in=, rank_out=)`` execute on their owner rank,
receiving inputs from ``rank_in`` and sending outputs to ``rank_out`` via
the differentiable point-to-point ops; fan-out/fan-in via rank lists;
multi-head stitching via ``pseudo_connect``.

SPMD translation (single controller): the reference is MPMD — each process
constructs a chain list holding only *its* components.  Here one
controller declares the whole topology: ``add_link`` takes the owning
``rank`` explicitly (default: registration order, the common pipeline
case).  ``forward`` runs as ONE program over the ``stage`` mesh axis:
every rank traces every component (SPMD), transfer edges are
``ppermute``s between statically-known (owner → consumer) pairs, and
non-owner ranks' computations feed nothing and are dead-code-eliminated
where XLA can prove it.  The terminal component's output is broadcast
from its owner so every rank (and the host) sees the result — strictly
more convenient than the reference's ``None`` on non-owners, and what the
loss/optimizer path expects.

The reference's sequential-per-minibatch schedule is reproduced here
(SURVEY §3.3: no microbatching, bubble = (stages-1)/stages); the
TPU-performance path with GPipe-style microbatching is
``chainermn_tpu.parallel.pipeline``.
"""

from __future__ import annotations

import jax
from jax.sharding import PartitionSpec as P

from ..core.link import Chain
from .. import functions as mnfn

__all__ = ["MultiNodeChainList"]


def _as_list(x):
    if x is None:
        return None
    return list(x) if isinstance(x, (list, tuple)) else [x]


class MultiNodeChainList(Chain):
    def __init__(self, comm):
        super().__init__()
        self._comm = comm
        self._components = []  # (name, rank, rank_in, rank_out)

    def add_link(self, link, rank_in=None, rank_out=None, rank=None,
                 pass_inputs=False):
        """Register a component.

        ``rank``: owner stage (default: registration order).  ``rank_in``:
        rank(s) whose outputs feed this component (None → the original
        inputs).  ``rank_out``: rank(s) consuming this component's output
        (None → terminal output).  ``pass_inputs``: also forward the
        original call inputs after the received values — the
        single-controller stand-in for the reference pattern where a
        downstream rank's own iterator feeds it side inputs (e.g. the
        decoder's teacher-forcing batch).
        """
        index = len(self._components)
        name = f"mn_component_{index}"
        with self.init_scope():
            setattr(self, name, link)
        owner = index if rank is None else int(rank)
        self._components.append((name, owner, _as_list(rank_in),
                                 _as_list(rank_out), pass_inputs))
        return link

    # -- execution ---------------------------------------------------------
    def forward(self, *inputs):
        comm = self._comm
        if comm._axis_in_scope():
            # already inside a shard_map over the stage axis (e.g. the
            # multi-node optimizer's compiled step) — emit edges directly
            return self._forward_spmd(*inputs)
        # Launch as a compiled SPMD program over the stage axis.  The
        # current parameter/persistent arrays — possibly outer-jit tracers
        # installed by an enclosing optimizer step — must enter the
        # shard_map as explicit replicated ARGUMENTS: closing over outer
        # tracers poisons the Manual mesh context (notably inside
        # lax.scan bodies).
        from ..core.link import bind_state, extract_state, _persistent_slots
        state = extract_state(self)
        n_in = len(inputs)

        def fn(state, *args):
            with bind_state(self, state) as handle:
                out = self._forward_spmd(*args)
                new_pstate = handle.collect()
            return out, new_pstate

        out, new_pstate = comm.run_spmd(
            fn, state, *inputs,
            in_specs=tuple(P() for _ in range(n_in + 1)),
            out_specs=(P(), P()))
        # re-install forward-mutated persistent values (BN stats inside
        # pipeline stages) so an enclosing bind_state handle collects them
        slots = {full: (sublink, name)
                 for sublink, name, full in _persistent_slots(self)}
        for path, value in new_pstate.items():
            if path in slots:
                sublink, name = slots[path]
                object.__setattr__(sublink, name, value)
                sublink._persistent[name] = value
        return out

    def _forward_spmd(self, *inputs):
        comm = self._comm
        from ..functions.point_to_point_communication import clear_stash
        clear_stash(comm)
        # per-(src, dst) edge sequence numbers: the n-th send on a rank
        # pair gets tag n and pairs with that pair's n-th recv — multiple
        # interleaved edges between the same two ranks each get their own
        # channel instead of leaning on stash FIFO order (reference MPI
        # tag discipline; VERDICT r1 Weak #9)
        send_seq = {}
        recv_seq = {}

        def next_tag(table, src, dst):
            n = table.get((src, dst), 0)
            table[(src, dst)] = n + 1
            return n

        delegates = []
        terminal = None
        terminal_owner = None
        for name, owner, rank_in, rank_out, pass_inputs in self._components:
            link = getattr(self, name)
            if rank_in is None:
                x_in = inputs
            else:
                received = []
                for src in rank_in:
                    y = mnfn.recv(comm, src, self_rank=owner,
                                  tag=next_tag(recv_seq, src, owner))
                    received.append(y)
                x_in = tuple(received)
                if pass_inputs:
                    x_in = x_in + inputs
            y = link(*x_in)
            self._fix_persistent_to_owner(link, owner)
            if rank_out is None:
                if terminal is not None:
                    raise ValueError(
                        "multiple terminal components (rank_out=None); "
                        "fan-in the graph explicitly instead")
                terminal = y
                terminal_owner = owner
            else:
                for dst in rank_out:
                    delegate = mnfn.send(y, comm, dst, self_rank=owner,
                                         tag=next_tag(send_seq, owner, dst))
                    delegates.append(delegate)
        if terminal is None:
            raise ValueError("no terminal component (rank_out=None)")
        # broadcast the terminal value from its owner so every rank (and
        # the host) sees the result; fuse dangling delegates to keep all
        # send edges on the backward path (pseudo_connect semantics)
        out = mnfn.bcast(comm, terminal, root=terminal_owner)
        for d in delegates:
            out = mnfn.pseudo_connect(d, out)
        return out

    def _fix_persistent_to_owner(self, link, owner):
        """Overwrite a component's forward-mutated persistent state (BN
        running stats) with the owner rank's values.

        SPMD ranks other than the owner execute the component on
        zeros/garbage delivered by the transfer edges; without this
        selection, any collector of persistent state could surface a
        non-owner's corrupted statistics.
        """
        from ..core.link import _persistent_slots
        for sublink, name, _ in _persistent_slots(link):
            value = getattr(sublink, name)
            if isinstance(value, jax.core.Tracer):
                fixed = mnfn.bcast(self._comm, value, root=owner)
                object.__setattr__(sublink, name, fixed)
                sublink._persistent[name] = fixed

