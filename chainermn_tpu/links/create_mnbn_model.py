"""BatchNormalization → MultiNodeBatchNormalization rewrite.

Reference: ``chainermn/links/create_mnbn_model.py · create_mnbn_model``
(SURVEY.md §2.3): recursively rewrites a model, replacing every
``BatchNormalization`` with the multi-node version so existing
single-device model code gains global-batch statistics unchanged.
"""

from __future__ import annotations

import copy

from ..nn.links import BatchNormalization
from .batch_normalization import MultiNodeBatchNormalization

__all__ = ["create_mnbn_model"]


def create_mnbn_model(link, comm):
    """Return a copy of ``link`` with every BN replaced by sync-BN."""
    model = copy.deepcopy(link)
    _replace(model, comm)
    return model


def _replace(link, comm):
    for name, child in list(link._children.items()):
        if isinstance(child, BatchNormalization) and \
                not isinstance(child, MultiNodeBatchNormalization):
            mnbn = MultiNodeBatchNormalization(
                child.size, comm, decay=child.decay, eps=child.eps,
                use_gamma=child.use_gamma, use_beta=child.use_beta,
                axis=child.axis)
            if child.use_gamma:
                mnbn.gamma.array = child.gamma.array
            if child.use_beta:
                mnbn.beta.array = child.beta.array
            mnbn.avg_mean = child.avg_mean
            mnbn.avg_var = child.avg_var
            mnbn.N = child.N
            mnbn.name = name
            link._children[name] = mnbn
            object.__setattr__(link, name, mnbn)
            # ChainList/Sequential also hold positional references
            for attr in ("_chainlist", "_layers"):
                seq = getattr(link, attr, None)
                if seq is not None:
                    for i, item in enumerate(seq):
                        if item is child:
                            seq[i] = mnbn
        else:
            _replace(child, comm)
