"""Multi-node (sync) batch normalization.

Reference: ``chainermn/links/batch_normalization.py ·
MultiNodeBatchNormalization`` (SURVEY.md §2.3): forward allreduces the
per-batch mean and squared-mean so statistics cover the global batch; the
custom backward's allreduced gradient terms come for free here — JAX
transposes the ``pmean`` automatically, producing exactly the reference's
hand-written gradient communication.

Inside a data-parallel compiled step the moments are ``pmean``ed over the
communicator axis; outside a trace the host already sees the full batch,
so plain moments are global moments and the op degrades to the base BN.
"""

from __future__ import annotations

import jax
from jax import lax

from ..nn.links import BatchNormalization

__all__ = ["MultiNodeBatchNormalization"]


class MultiNodeBatchNormalization(BatchNormalization):
    def __init__(self, size, comm, decay=0.9, eps=2e-5, dtype=None,
                 use_gamma=True, use_beta=True, initial_gamma=None,
                 initial_beta=None, communication_backend="auto",
                 axis=None):
        # communication_backend kept for reference-signature parity
        # (mpi/nccl/auto selectable there; one XLA backend here)
        import numpy as np
        super().__init__(size, decay=decay, eps=eps,
                         dtype=dtype or np.float32, use_gamma=use_gamma,
                         use_beta=use_beta, initial_gamma=initial_gamma,
                         initial_beta=initial_beta, axis=axis)
        self.comm = comm
        self.communication_backend = communication_backend

    def _sync_moments(self, mean, sq_mean, x):
        # global-batch statistics: one fused pmean of both single-pass
        # accumulators (the base class forms the variance afterwards)
        if isinstance(x, jax.core.Tracer) and self.comm.axis_name is not None:
            mean = lax.pmean(mean, self.comm.axis_name)
            sq_mean = lax.pmean(sq_mean, self.comm.axis_name)
        return mean, sq_mean

    def _moment_count(self, x, axis):
        m = super()._moment_count(x, axis)
        if isinstance(x, jax.core.Tracer) and self.comm.axis_name is not None:
            m *= self.comm.size  # moments are pmean'd: global batch count
        return m
