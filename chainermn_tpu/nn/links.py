"""Built-in links (consumed-Chainer surface: ``chainer.links``).

Reference anchors: ``chainer/links/connection/linear.py · Linear``,
``convolution_2d.py · Convolution2D``, ``deconvolution_2d.py ·
Deconvolution2D``, ``normalization/batch_normalization.py ·
BatchNormalization``, ``connection/embed_id.py · EmbedID``,
``connection/lstm.py · LSTM`` (SURVEY.md §2.8).

Parameters are initialized eagerly on host (numpy RNG for reproducibility)
and live as ``jax.Array`` leaves; every ``forward`` is a pure ``jnp``
program, so links compose under ``jax.jit`` / ``jax.grad`` via
``core.link.apply_state``.  BatchNormalization's running statistics are
*persistent* state threaded functionally through compiled steps.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..core.link import Chain, Link, Parameter
from ..core.config import config
from . import functions as F
from . import initializers as I

__all__ = ["Linear", "Convolution2D", "Deconvolution2D",
           "DepthwiseConvolution2D", "BatchNormalization",
           "LayerNormalization", "EmbedID", "LSTM", "StatelessLSTM",
           "GroupNormalization", "StatelessGRU", "GRU", "NStepLSTM",
           "NStepGRU", "Highway", "Maxout", "Scale", "Classifier"]

_default_rng = np.random.RandomState(817)


def _rng(seed=None):
    return _default_rng if seed is None else np.random.RandomState(seed)


class Linear(Link):
    """Fully-connected layer, weight shape (out, in) like the reference."""

    def __init__(self, in_size, out_size=None, nobias=False,
                 initialW=None, initial_bias=None, seed=None):
        super().__init__()
        if out_size is None:
            in_size, out_size = None, in_size
        self.in_size = in_size
        self.out_size = out_size
        self.nobias = nobias
        self._initW = I._get_initializer(initialW, I.LeCunNormal())
        self._initb = I._get_initializer(initial_bias, I.Zero())
        self._seed = seed
        with self.init_scope():
            self.W = Parameter()
            if not nobias:
                self.b = Parameter()
        if in_size is not None:
            self._init_params(in_size)

    def _init_params(self, in_size):
        rng = _rng(self._seed)
        self.in_size = in_size
        self.W.array = jnp.asarray(self._initW((self.out_size, in_size), np.float32, rng))
        if not self.nobias:
            self.b.array = jnp.asarray(self._initb((self.out_size,), np.float32, rng))

    def forward(self, x, n_batch_axes=1):
        if self.W.array is None:
            in_size = int(np.prod(x.shape[n_batch_axes:]))
            self._init_params(in_size)
        W, b = self.W.array, None if self.nobias else self.b.array
        if x.dtype in (jnp.bfloat16, jnp.float16) and W.dtype != x.dtype:
            # mixed precision convention: parameters stored fp32, compute
            # follows the activation dtype (bf16 matmuls on the MXU)
            W = W.astype(x.dtype)
            b = None if b is None else b.astype(x.dtype)
        return F.linear(x, W, b, n_batch_axes=n_batch_axes)


class Convolution2D(Link):
    """2-D convolution, kernel (out, in, kh, kw) regardless of layout.

    ``layout`` selects the ACTIVATION layout: "NCHW" (reference default)
    or "NHWC" (TPU-native channels-last — see F.convolution_2d).  Kernel
    storage stays OIHW either way, so checkpoints are layout-portable.
    """

    def __init__(self, in_channels, out_channels=None, ksize=None, stride=1,
                 pad=0, nobias=False, initialW=None, initial_bias=None,
                 dilate=1, groups=1, seed=None, layout="NCHW"):
        super().__init__()
        if ksize is None:
            # Chainer-style remap: Convolution2D(out_channels, ksize)
            in_channels, out_channels, ksize = None, in_channels, out_channels
        self.in_channels = in_channels
        self.layout = layout
        self.out_channels = out_channels
        self.ksize = ksize
        self.stride = stride
        self.pad = pad
        self.dilate = dilate
        self.groups = groups
        self.nobias = nobias
        self._initW = I._get_initializer(initialW, I.HeNormal())
        self._initb = I._get_initializer(initial_bias, I.Zero())
        self._seed = seed
        with self.init_scope():
            self.W = Parameter()
            if not nobias:
                self.b = Parameter()
        if in_channels is not None:
            self._init_params(in_channels)

    def _init_params(self, in_channels):
        rng = _rng(self._seed)
        kh, kw = (self.ksize, self.ksize) if np.isscalar(self.ksize) else self.ksize
        self.in_channels = in_channels
        shape = (self.out_channels, in_channels // self.groups, kh, kw)
        self.W.array = jnp.asarray(self._initW(shape, np.float32, rng))
        if not self.nobias:
            self.b.array = jnp.asarray(self._initb((self.out_channels,), np.float32, rng))

    def forward(self, x):
        if self.W.array is None:
            self._init_params(x.shape[3] if self.layout == "NHWC"
                              else x.shape[1])
        return F.convolution_2d(x, self.W.array,
                                None if self.nobias else self.b.array,
                                self.stride, self.pad, self.dilate,
                                self.groups, layout=self.layout)


class Deconvolution2D(Link):
    """Transposed convolution, kernel (in, out, kh, kw) like the reference."""

    def __init__(self, in_channels, out_channels, ksize, stride=1, pad=0,
                 nobias=False, outsize=None, initialW=None, initial_bias=None,
                 seed=None):
        super().__init__()
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.ksize = ksize
        self.stride = stride
        self.pad = pad
        self.outsize = outsize
        self.nobias = nobias
        rng = _rng(seed)
        kh, kw = (ksize, ksize) if np.isscalar(ksize) else ksize
        initW = I._get_initializer(initialW, I.HeNormal())
        initb = I._get_initializer(initial_bias, I.Zero())
        with self.init_scope():
            self.W = Parameter(initW((in_channels, out_channels, kh, kw), np.float32, rng))
            if not nobias:
                self.b = Parameter(initb((out_channels,), np.float32, rng))

    def forward(self, x):
        return F.deconvolution_2d(x, self.W.array,
                                  None if self.nobias else self.b.array,
                                  self.stride, self.pad, self.outsize)


class DepthwiseConvolution2D(Link):
    def __init__(self, in_channels, channel_multiplier, ksize, stride=1,
                 pad=0, nobias=False, initialW=None, initial_bias=None,
                 seed=None):
        super().__init__()
        self.stride = stride
        self.pad = pad
        self.nobias = nobias
        rng = _rng(seed)
        kh, kw = (ksize, ksize) if np.isscalar(ksize) else ksize
        initW = I._get_initializer(initialW, I.HeNormal())
        initb = I._get_initializer(initial_bias, I.Zero())
        with self.init_scope():
            self.W = Parameter(initW((channel_multiplier, in_channels, kh, kw), np.float32, rng))
            if not nobias:
                self.b = Parameter(initb((channel_multiplier * in_channels,), np.float32, rng))

    def forward(self, x):
        return F.depthwise_convolution_2d(x, self.W.array,
                                          None if self.nobias else self.b.array,
                                          self.stride, self.pad)


class BatchNormalization(Link):
    """Batch normalization with running statistics as persistent state.

    Reference: ``chainer/links/normalization/batch_normalization.py``.
    In train mode, batch moments normalize and the exponential moving
    averages are updated (functionally — the new values are collected by
    ``bind_state`` and threaded out of the jitted step).  In test mode the
    stored averages are used.  ``comm`` hooks (multi-node sync BN) live in
    ``chainermn_tpu.links.batch_normalization`` (SURVEY §2.3).
    """

    def __init__(self, size, decay=0.9, eps=2e-5, dtype=np.float32,
                 use_gamma=True, use_beta=True, initial_gamma=None,
                 initial_beta=None, axis=None):
        super().__init__()
        self.decay = decay
        self.eps = eps
        self.axis = axis
        with self.init_scope():
            if use_gamma:
                ig = I._get_initializer(initial_gamma, I.One())
                self.gamma = Parameter(ig((size,), dtype))
            if use_beta:
                ib = I._get_initializer(initial_beta, I.Zero())
                self.beta = Parameter(ib((size,), dtype))
        self.use_gamma = use_gamma
        self.use_beta = use_beta
        self.size = size
        self.add_persistent("avg_mean", jnp.zeros((size,), dtype))
        self.add_persistent("avg_var", jnp.ones((size,), dtype))
        self.add_persistent("N", 0)

    def _gamma_beta(self, dtype):
        gamma = self.gamma.array if self.use_gamma else jnp.ones((self.size,), dtype)
        beta = self.beta.array if self.use_beta else jnp.zeros((self.size,), dtype)
        return gamma, beta

    def _moments(self, x, axis):
        """Single-pass batch moments (``F.batch_moments``): mean and
        E[x²] accumulate over ONE fp32-accumulated read of the
        activation instead of the two-pass mean/var loop — the BN-stat
        fusions were the largest non-conv HBM row in the r5 ResNet
        trace.  The multi-node subclass overrides ``_sync_moments`` to
        pmean the two accumulators across ranks before the variance is
        formed."""
        x32 = x.astype(jnp.float32)
        mean = x32.mean(axis=axis)
        sq_mean = jnp.mean(x32 * x32, axis=axis)
        mean, sq_mean = self._sync_moments(mean, sq_mean, x)
        return mean, jnp.maximum(sq_mean - jnp.square(mean), 0.0)

    def _sync_moments(self, mean, sq_mean, x):
        """Cross-rank moment hook (identity here; the multi-node sync BN
        pmeans both accumulators over its communicator axis)."""
        del x
        return mean, sq_mean

    def _moment_count(self, x, axis):
        """Number of elements each moment reduces over (the multi-node
        subclass multiplies by communicator size: stats cover the global
        batch)."""
        m = 1
        for a in axis:
            m *= x.shape[a]
        return m

    def forward(self, x, finetune=False):
        axis = self.axis
        if axis is None:
            axis = (0,) + tuple(range(2, x.ndim))
        gamma, beta = self._gamma_beta(x.dtype)
        if config.train:
            mean, var = self._moments(x, axis)
            y = F._apply_bn(x, gamma, beta, mean, var, self.eps, axis)
            if finetune:
                self.N = self.N + 1
                decay = 1.0 - 1.0 / self.N
            else:
                decay = self.decay
            # functional EMA update — collected via bind_state.  Running
            # variance accumulates the UNBIASED batch variance (× m/(m-1)),
            # matching the reference's adjustment in
            # `chainer/links/normalization/batch_normalization.py`.
            m = self._moment_count(x, axis)
            unbiased = var * (m / max(m - 1, 1))
            self.avg_mean = decay * self.avg_mean + (1 - decay) * mean
            self.avg_var = decay * self.avg_var + (1 - decay) * unbiased
            return y
        return F._apply_bn(x, gamma, beta, jnp.asarray(self.avg_mean),
                           jnp.asarray(self.avg_var), self.eps, axis)


class GroupNormalization(Link):
    def __init__(self, groups, size, eps=1e-5):
        super().__init__()
        self.groups = groups
        self.eps = eps
        with self.init_scope():
            self.gamma = Parameter(jnp.ones((size,)))
            self.beta = Parameter(jnp.zeros((size,)))

    def forward(self, x):
        n, c = x.shape[0], x.shape[1]
        g = self.groups
        xg = x.reshape((n, g, c // g) + x.shape[2:])
        axes = tuple(range(2, xg.ndim))
        mean = xg.mean(axis=axes, keepdims=True)
        var = xg.var(axis=axes, keepdims=True)
        xg = (xg - mean) * jnp.reciprocal(jnp.sqrt(var + self.eps))
        x = xg.reshape(x.shape)
        shape = [1, c] + [1] * (x.ndim - 2)
        return x * self.gamma.array.reshape(shape) + self.beta.array.reshape(shape)


class LayerNormalization(Link):
    def __init__(self, size, eps=1e-5):
        super().__init__()
        self.eps = eps
        with self.init_scope():
            self.gamma = Parameter(jnp.ones((size,)))
            self.beta = Parameter(jnp.zeros((size,)))

    def forward(self, x):
        return F.layer_normalization(x, self.gamma.array, self.beta.array, self.eps)


class EmbedID(Link):
    """Embedding lookup (reference: ``L.EmbedID``)."""

    ignore_label = None

    def __init__(self, in_size, out_size, initialW=None, ignore_label=None,
                 seed=None):
        super().__init__()
        self.ignore_label = ignore_label
        rng = _rng(seed)
        initW = I._get_initializer(initialW, I.Normal(1.0))
        with self.init_scope():
            self.W = Parameter(initW((in_size, out_size), np.float32, rng))

    def forward(self, x):
        return F.embed_id(x, self.W.array, self.ignore_label)


class StatelessLSTM(Chain):
    """One LSTM step: (c, h, x) -> (c, h).  Reference: ``L.StatelessLSTM``.

    The gate weight layout packs [input, forget, cell, output] gates into a
    single matmul — the MXU-friendly formulation (one large GEMM per step,
    scanned with ``lax.scan`` for sequences).
    """

    def __init__(self, in_size, out_size, seed=None):
        super().__init__()
        self.out_size = out_size
        with self.init_scope():
            self.upward = Linear(in_size, 4 * out_size, seed=seed)
            self.lateral = Linear(out_size, 4 * out_size, nobias=True,
                                  seed=None if seed is None else seed + 1)

    def forward(self, c, h, x):
        gates = self.upward(x)
        if h is not None:
            gates = gates + self.lateral(h)
        i, f, g, o = jnp.split(gates, 4, axis=1)
        i = F.sigmoid(i)
        f = F.sigmoid(f + 1.0)  # forget-gate bias +1 (reference init convention)
        g = F.tanh(g)
        o = F.sigmoid(o)
        if c is None:
            c = jnp.zeros((x.shape[0], self.out_size), x.dtype)
        c_next = f * c + i * g
        h_next = o * F.tanh(c_next)
        return c_next, h_next


class LSTM(StatelessLSTM):
    """Stateful LSTM holding (c, h) between calls (reference: ``L.LSTM``).

    Statefulness is eager-mode convenience; inside jitted programs prefer
    ``StatelessLSTM`` + ``lax.scan`` (see ``models/seq2seq.py``).
    ``_volatile_attrs`` lets ``bind_state`` restore (c, h) after traced
    calls so tracers never leak into the link.
    """

    _volatile_attrs = ("c", "h")

    def __init__(self, in_size, out_size, seed=None):
        super().__init__(in_size, out_size, seed=seed)
        self.c = None
        self.h = None

    def reset_state(self):
        self.c = None
        self.h = None

    def set_state(self, c, h):
        self.c = c
        self.h = h

    def forward(self, x):
        self.c, self.h = super().forward(self.c, self.h, x)
        return self.h


# RNN family lives in nn/rnn.py (imported late: it consumes Linear above)
from .rnn import StatelessGRU, GRU, NStepLSTM, NStepGRU  # noqa: E402


class Highway(Link):
    """Highway layer (reference: ``L.Highway``)."""

    def __init__(self, in_out_size, nobias=False, activate=None, seed=None):
        super().__init__()
        self.activate = activate or F.relu
        s = (lambda k: None if seed is None else seed + k)
        with self.init_scope():
            self.plain = Linear(in_out_size, in_out_size, nobias=nobias,
                                seed=s(0))
            self.transform = Linear(in_out_size, in_out_size,
                                    nobias=nobias,
                                    initial_bias=I.Constant(-1.0), seed=s(1))

    def forward(self, x):
        h = self.activate(self.plain(x))
        t = F.sigmoid(self.transform(x))
        return h * t + x * (1 - t)


class Maxout(Link):
    """Fully-connected maxout (reference: ``L.Maxout``)."""

    def __init__(self, in_size, out_size, pool_size, seed=None):
        super().__init__()
        self.out_size = out_size
        self.pool_size = pool_size
        with self.init_scope():
            self.linear = Linear(in_size, out_size * pool_size, seed=seed)

    def forward(self, x):
        h = self.linear(x)
        return jnp.max(h.reshape(-1, self.out_size, self.pool_size), axis=2)


class Scale(Link):
    """Elementwise scale + optional shift (reference: ``L.Scale``)."""

    def __init__(self, axis=1, W_shape=None, bias_term=False):
        super().__init__()
        self.axis = axis
        with self.init_scope():
            self.W = Parameter(jnp.ones(W_shape))
            if bias_term:
                self.bias = Parameter(jnp.zeros(W_shape))
        self.bias_term = bias_term

    def forward(self, x):
        shape = [1] * x.ndim
        for i, s in enumerate(self.W.array.shape):
            shape[self.axis + i] = s
        y = x * self.W.array.reshape(shape)
        if self.bias_term:
            y = y + self.bias.array.reshape(shape)
        return y


def __getattr__(name):
    # L.Classifier lives with the models (avoids a circular import);
    # exposed here for chainer-parity `L.Classifier(...)` call sites
    if name == "Classifier":
        from ..models.mlp import Classifier
        return Classifier
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
