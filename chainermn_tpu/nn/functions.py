"""Neural-network functions (consumed-Chainer surface: ``chainer.functions``).

Reference anchors: ``chainer/functions/ · relu, softmax_cross_entropy,
convolution_2d, max_pooling_2d, batch_normalization, ...`` (SURVEY.md §2.8).
All functions are pure ``jnp`` programs: differentiable by ``jax.grad``,
fusible by XLA, layout NCHW to match the reference's convention (XLA
re-layouts internally for the MXU; the API contract is what matters here).
Stochastic functions (``dropout``) take an explicit ``key`` — the idiomatic
JAX replacement for the reference's hidden global RNG; if omitted, a
fresh per-step subkey comes from the compiled train step's key scope
(``core.rng``), falling back to a host-drawn key in eager use.
"""

from __future__ import annotations

import builtins
import functools
import os

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "relu", "leaky_relu", "elu", "sigmoid", "tanh", "softplus", "gelu", "silu",
    "softmax", "log_softmax", "softmax_cross_entropy", "sigmoid_cross_entropy",
    "mean_squared_error", "mean_absolute_error", "huber_loss", "accuracy",
    "dropout", "linear", "embed_id",
    "convolution_2d", "deconvolution_2d", "depthwise_convolution_2d",
    "max_pooling_2d", "average_pooling_2d", "unpooling_2d",
    "global_average_pooling_2d", "resize_images",
    "batch_normalization", "fixed_batch_normalization", "batch_moments",
    "layer_normalization",
    "concat", "stack", "hstack", "vstack", "split_axis", "separate",
    "average", "select_item", "absolute", "maximum", "minimum", "swish",
    "normalize", "local_response_normalization", "squared_error",
    "reshape", "flatten", "transpose", "expand_dims", "squeeze", "tile",
    "broadcast_to", "sum", "mean", "max", "min", "argmax", "sqrt", "exp",
    "log", "clip", "matmul", "batch_matmul", "where", "pad",
]


# -- activations -----------------------------------------------------------

def relu(x):
    return jnp.maximum(x, 0)


def leaky_relu(x, slope=0.2):
    return jnp.where(x >= 0, x, slope * x)


def elu(x, alpha=1.0):
    return jnp.where(x >= 0, x, alpha * (jnp.exp(x) - 1))


def sigmoid(x):
    return jax.nn.sigmoid(x)


def tanh(x):
    return jnp.tanh(x)


def softplus(x, beta=1.0):
    return jax.nn.softplus(beta * x) / beta


def gelu(x):
    return jax.nn.gelu(x)


def silu(x):
    return jax.nn.silu(x)


def softmax(x, axis=1):
    return jax.nn.softmax(x, axis=axis)


def log_softmax(x, axis=1):
    return jax.nn.log_softmax(x, axis=axis)


# -- losses ----------------------------------------------------------------

def softmax_cross_entropy(x, t, ignore_label=-1, reduce="mean",
                          normalize=True, class_weight=None):
    """Softmax + NLL with ignore-label masking.

    Matches the reference semantics (``F.softmax_cross_entropy``): ``t`` holds
    int class ids; entries equal to ``ignore_label`` contribute zero loss and
    are excluded from the normalizer; ``class_weight`` ([n_classes]) scales
    each example's loss by its target class's weight.
    """
    x = x.astype(jnp.float32)  # fp32 log-softmax even for bf16 logits
    logp = jax.nn.log_softmax(x, axis=1)
    t_safe = jnp.where(t == ignore_label, 0, t)
    # gather the log-prob of the target class along axis 1
    nll = -jnp.take_along_axis(
        logp, t_safe[:, None] if logp.ndim == 2 else jnp.expand_dims(t_safe, 1), axis=1
    ).squeeze(1)
    if class_weight is not None:
        nll = nll * jnp.asarray(class_weight)[t_safe]
    mask = (t != ignore_label)
    nll = jnp.where(mask, nll, 0.0)
    if reduce == "no":
        return nll
    if normalize:
        count = jnp.maximum(mask.sum(), 1)
    else:
        count = x.shape[0]
    return nll.sum() / count


def sigmoid_cross_entropy(x, t, reduce="mean"):
    t = t.astype(x.dtype)
    loss = jnp.maximum(x, 0) - x * t + jnp.log1p(jnp.exp(-jnp.abs(x)))
    if reduce == "no":
        return loss
    return loss.mean()


def mean_squared_error(x, t):
    return jnp.mean((x - t) ** 2)


def mean_absolute_error(x, t):
    return jnp.mean(jnp.abs(x - t))


def huber_loss(x, t, delta=1.0, reduce="sum_along_second_axis"):
    d = x - t
    abs_d = jnp.abs(d)
    loss = jnp.where(abs_d <= delta, 0.5 * d * d, delta * (abs_d - 0.5 * delta))
    if reduce == "no":
        return loss
    return loss.sum(axis=1)


def accuracy(y, t, ignore_label=None):
    pred = jnp.argmax(y, axis=1)
    if ignore_label is not None:
        mask = (t != ignore_label)
        correct = jnp.where(mask, pred == t, False)
        return correct.sum() / jnp.maximum(mask.sum(), 1)
    return jnp.mean((pred == t).astype(jnp.float32))


# -- stochastic ------------------------------------------------------------

def dropout(x, ratio=0.5, key=None, train: bool | None = None):
    from ..core.config import config
    if train is None:
        train = config.train
    if not train or ratio == 0.0:
        return x
    if key is None:
        # per-step key pushed by the compiled train step (core.rng);
        # outside any step scope, fall back to a host-drawn key (eager
        # use — matches the reference's hidden global RNG)
        from ..core import rng as rng_module
        key = rng_module.next_key()
    if key is None:
        key = jax.random.PRNGKey(np.random.randint(0, 2**31 - 1))
    keep = 1.0 - ratio
    mask = jax.random.bernoulli(key, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0).astype(x.dtype)


# -- linear / embedding ----------------------------------------------------

def linear(x, W, b=None, n_batch_axes=1):
    if n_batch_axes > 1:
        batch_shape = x.shape[:n_batch_axes]
        x = x.reshape((int(np.prod(batch_shape)), -1))
    elif x.ndim > 2:
        x = x.reshape((x.shape[0], -1))
        batch_shape = None
    else:
        batch_shape = None
    y = x @ W.T
    if b is not None:
        y = y + b
    if n_batch_axes > 1:
        y = y.reshape(batch_shape + (W.shape[0],))
    return y


def embed_id(x, W, ignore_label=None):
    if ignore_label is not None:
        safe = jnp.where(x == ignore_label, 0, x)
        emb = W[safe]
        return jnp.where((x == ignore_label)[..., None], 0.0, emb)
    return W[x]


# -- convolutions -----------------------------------------------------------
#
# Kernel storage is always OIHW (the reference layout — checkpoints stay
# portable); the ACTIVATION layout is a per-call choice.  "NCHW" is the
# reference's layout; "NHWC" is the TPU-native layout (channels-last maps
# directly onto the MXU's lane dimension, so XLA inserts no relayout
# transposes between conv, BN, and elementwise ops).

def _pair(v):
    return (v, v) if np.isscalar(v) else tuple(v)


def _spatial_dims(layout):
    """(h_dim, w_dim, channel_dim) for a 4-D activation layout string."""
    if layout == "NCHW":
        return 2, 3, 1
    if layout == "NHWC":
        return 1, 2, 3
    raise ValueError(f"unsupported activation layout {layout!r}")


def convolution_2d(x, W, b=None, stride=1, pad=0, dilate=1, groups=1,
                   layout="NCHW"):
    sy, sx = _pair(stride)
    ph, pw = _pair(pad)
    dy, dx = _pair(dilate)
    y = lax.conv_general_dilated(
        x, W,
        window_strides=(sy, sx),
        padding=((ph, ph), (pw, pw)),
        rhs_dilation=(dy, dx),
        dimension_numbers=(layout, "OIHW", layout),
        feature_group_count=groups,
    )
    if b is not None:
        y = y + (b[None, :, None, None] if layout == "NCHW"
                 else b[None, None, None, :])
    return y


def deconvolution_2d(x, W, b=None, stride=1, pad=0, outsize=None):
    """Transposed convolution; kernel (in_ch, out_ch, kh, kw) like the
    reference (``L.Deconvolution2D``).

    Implemented as the literal transpose of the corresponding forward
    convolution (the reference's definition) via ``jax.vjp`` — XLA lowers
    this to a single transposed-conv kernel, and the kernel-layout
    conventions can't drift from the conv they transpose.
    """
    sy, sx = _pair(stride)
    ph, pw = _pair(pad)
    in_ch, out_ch, kh, kw = W.shape
    n, _, h, w = x.shape
    if outsize is None:
        oh, ow = sy * (h - 1) + kh - 2 * ph, sx * (w - 1) + kw - 2 * pw
    else:
        oh, ow = outsize

    # analytic shape check: the forward conv of (oh, ow) must give (h, w)
    if (oh + 2 * ph - kh) // sy + 1 != h or (ow + 2 * pw - kw) // sx + 1 != w \
            or oh + 2 * ph < kh or ow + 2 * pw < kw:
        raise ValueError(
            f"invalid outsize {(oh, ow)} for input {(h, w)} with "
            f"k={(kh, kw)} s={(sy, sx)} p={(ph, pw)}")

    def fwd(a):  # [N, out_ch, oh, ow] → [N, in_ch, h, w]
        return lax.conv_general_dilated(
            a, W, (sy, sx), ((ph, ph), (pw, pw)),
            dimension_numbers=("NCHW", "OIHW", "NCHW"))

    # fwd is linear in its input — linear_transpose traces it once and
    # never evaluates the discarded primal
    f_t = jax.linear_transpose(
        fwd, jax.ShapeDtypeStruct((n, out_ch, oh, ow), x.dtype))
    (y,) = f_t(x)
    if b is not None:
        y = y + b[None, :, None, None]
    return y


def depthwise_convolution_2d(x, W, b=None, stride=1, pad=0):
    # W: (channel_multiplier, in_channels, kh, kw) in the reference
    cm, ic, kh, kw = W.shape
    Wg = W.transpose(1, 0, 2, 3).reshape(ic * cm, 1, kh, kw)
    return convolution_2d(x, Wg, b, stride, pad, groups=ic)


# -- pooling ---------------------------------------------------------------

def _pool_geometry(kh, kw, sy, sx, pads, layout):
    """(window_dims, window_strides, padding) for a 4-D pooling op in
    either activation layout; ``pads`` is ((ph_lo, ph_hi), (pw_lo, pw_hi))."""
    hd, wd, _ = _spatial_dims(layout)
    dims, strides, padding = [1] * 4, [1] * 4, [(0, 0)] * 4
    dims[hd], dims[wd] = kh, kw
    strides[hd], strides[wd] = sy, sx
    padding[hd], padding[wd] = pads
    return tuple(dims), tuple(strides), tuple(padding)


#: Backward lowering for float max pooling: "argmax" (default) stores the
#: per-window argmax in the forward and scatters the cotangent through it
#: in ONE fused pass; "xla" keeps the reduce_window VJP, whose
#: `select-and-scatter` re-compares the whole input against the output on
#: the backward pass (an unfusible HBM-bound op — the 0.75 ms/step row in
#: the r5 ResNet trace).  Env knob for A/B and fallback; tests pin the
#: two paths equal.
_MAXPOOL_VJP = os.environ.get("CHAINERMN_TPU_MAXPOOL_VJP", "argmax")


def max_pooling_2d(x, ksize, stride=None, pad=0, cover_all=True,
                   layout="NCHW"):
    kh, kw = _pair(ksize)
    sy, sx = _pair(stride if stride is not None else ksize)
    ph, pw = _pair(pad)
    hd, wd, _ = _spatial_dims(layout)
    if cover_all:
        # reference semantics: pad enough that every element is covered
        h, w = x.shape[hd], x.shape[wd]
        # NB: this module shadows builtin max with the F.max alias
        eh = builtins.max(0, (-(h + 2 * ph - kh) % sy)) if sy > 1 else 0
        ew = builtins.max(0, (-(w + 2 * pw - kw) % sx)) if sx > 1 else 0
    else:
        eh = ew = 0
    pads = ((ph, ph + eh), (pw, pw + ew))
    if _MAXPOOL_VJP == "argmax" and kh * kw <= 255 \
            and jnp.issubdtype(x.dtype, jnp.floating):
        # uint8 argmax storage caps the window at 255 taps; larger
        # windows (never seen in practice) keep the XLA path
        return _max_pool_argmax(x, (kh, kw), (sy, sx), pads,
                                (x.shape[hd], x.shape[wd]), layout)
    return _max_pool_xla(x, (kh, kw), (sy, sx), pads, layout)


def _max_pool_xla(x, kdims, sdims, pads, layout):
    """Plain reduce_window max (XLA differentiates it via
    select-and-scatter) — the pre-argmax lowering, kept as the integer
    path, the >255-tap fallback, and the equivalence-test reference."""
    kh, kw = kdims
    sy, sx = sdims
    neg = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) \
        else jnp.iinfo(x.dtype).min
    dims, strides, padding = _pool_geometry(kh, kw, sy, sx, pads, layout)
    return lax.reduce_window(x, neg, lax.max, dims, strides, padding)


def _window_taps(x_p, kh, kw, sy, sx, oh, ow, hd, wd):
    """(offset, strided slice of the padded input) per window tap — each
    slice is an output-shaped view; XLA fuses the whole chain into one
    pass over the input."""
    nd = x_p.ndim
    for i in range(kh):
        for j in range(kw):
            start = [0] * nd
            limit = list(x_p.shape)
            strides = [1] * nd
            start[hd], start[wd] = i, j
            limit[hd] = i + sy * (oh - 1) + 1
            limit[wd] = j + sx * (ow - 1) + 1
            strides[hd], strides[wd] = sy, sx
            yield i * kw + j, lax.slice(x_p, start, limit, strides)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5))
def _max_pool_argmax(x, kdims, sdims, pads, hw, layout):
    """Max pooling whose VJP scatters through STORED argmax indices.

    Forward: the max itself comes from the same fused ``reduce_window``
    as the XLA path (bit-identical values); a fused compare chain over
    the k·k strided window taps additionally materializes each window's
    (first) argmax as a uint8 plane.  Backward: one pass summing the
    k·k dilated placements of ``where(idx == tap, g, 0)`` — all pads and
    adds, fully fusible — instead of XLA's ``select-and-scatter``, which
    re-reads the entire input AND output to re-discover the argmax.
    Gradients match the XLA lowering bit-exactly for tie-free inputs.
    With EXACT ties (realistic in bf16) the two lowerings diverge: this
    path routes the whole cotangent to the FIRST maximum in window order
    (the argmax convention, and the reference Chainer's), while XLA's
    packed select-and-gather picks a tied winner by tangent bit pattern
    — effectively arbitrary.  Deterministic-first is the better
    contract, so the divergence is intentional; NaN windows likewise
    route to tap 0 here where XLA propagates.
    """
    y, _ = _max_pool_argmax_fwd_impl(x, kdims, sdims, pads, layout)
    return y


def _max_pool_argmax_fwd_impl(x, kdims, sdims, pads, layout):
    kh, kw = kdims
    sy, sx = sdims
    (ph_lo, ph_hi), (pw_lo, pw_hi) = pads
    hd, wd, _ = _spatial_dims(layout)
    dims, strides, padding = _pool_geometry(kh, kw, sy, sx, pads, layout)
    y = lax.reduce_window(x, -jnp.inf, lax.max, dims, strides, padding)
    pad_cfg = [(0, 0, 0)] * x.ndim
    pad_cfg[hd] = (ph_lo, ph_hi, 0)
    pad_cfg[wd] = (pw_lo, pw_hi, 0)
    x_p = lax.pad(x, jnp.array(-jnp.inf, x.dtype), pad_cfg)
    oh, ow = y.shape[hd], y.shape[wd]
    best = idx = None
    for o, tap in _window_taps(x_p, kh, kw, sy, sx, oh, ow, hd, wd):
        if best is None:
            best, idx = tap, jnp.zeros(tap.shape, jnp.uint8)
        else:
            take = tap > best  # strict >: first max wins, like argmax
            best = jnp.where(take, tap, best)
            idx = jnp.where(take, jnp.uint8(o), idx)
    return y, idx


def _max_pool_argmax_fwd(x, kdims, sdims, pads, hw, layout):
    y, idx = _max_pool_argmax_fwd_impl(x, kdims, sdims, pads, layout)
    # residual: ONE uint8 output-shaped plane (vs select-and-scatter
    # keeping the full input AND output live into the backward)
    return y, idx


def _max_pool_argmax_bwd(kdims, sdims, pads, hw, layout, idx, g):
    kh, kw = kdims
    sy, sx = sdims
    (ph_lo, ph_hi), (pw_lo, pw_hi) = pads
    h_in, w_in = hw
    hd, wd, _ = _spatial_dims(layout)
    oh, ow = g.shape[hd], g.shape[wd]
    hp = h_in + ph_lo + ph_hi
    wp = w_in + pw_lo + pw_hi
    zero = jnp.array(0, g.dtype)
    dx_p = None
    for i in range(kh):
        for j in range(kw):
            o = i * kw + j
            contrib = jnp.where(idx == jnp.uint8(o), g, zero)
            # transpose of the forward's strided slice: dilate by the
            # stride, offset by the tap position
            pad_cfg = [(0, 0, 0)] * g.ndim
            pad_cfg[hd] = (i, hp - (i + sy * (oh - 1) + 1), sy - 1)
            pad_cfg[wd] = (j, wp - (j + sx * (ow - 1) + 1), sx - 1)
            placed = lax.pad(contrib, zero, pad_cfg)
            dx_p = placed if dx_p is None else dx_p + placed
    start = [0] * dx_p.ndim
    limit = list(dx_p.shape)
    start[hd], start[wd] = ph_lo, pw_lo
    limit[hd], limit[wd] = ph_lo + h_in, pw_lo + w_in
    return (lax.slice(dx_p, start, limit),)


_max_pool_argmax.defvjp(_max_pool_argmax_fwd, _max_pool_argmax_bwd)


def average_pooling_2d(x, ksize, stride=None, pad=0, layout="NCHW"):
    kh, kw = _pair(ksize)
    sy, sx = _pair(stride if stride is not None else ksize)
    ph, pw = _pair(pad)
    dims, strides, padding = _pool_geometry(
        kh, kw, sy, sx, ((ph, ph), (pw, pw)), layout)
    summed = lax.reduce_window(x, 0.0, lax.add, dims, strides, padding)
    # reference divides by the full window size (count_include_pad=True);
    # the scale stays in x.dtype (weak-typed), so a bf16 activation is
    # read and written as bf16 — no f32 round-trip through HBM
    return summed / (kh * kw)


def unpooling_2d(x, ksize, stride=None, pad=0, outsize=None, cover_all=True):
    """Inverse of sum-pooling: each value scatter-adds over its k×k window.

    Reference semantics (``F.unpooling_2d``): output size
    ``s*(in-1)+k-2p`` (minus ``s-1`` under ``cover_all``).  Implemented as
    the VJP of sum-pooling — the transposed scatter-add XLA compiles to a
    single fused kernel.
    """
    kh, kw = _pair(ksize)
    sy, sx = _pair(stride if stride is not None else ksize)
    ph, pw = _pair(pad)
    h, w = x.shape[2], x.shape[3]
    if outsize is None:
        oh = sy * (h - 1) + kh - 2 * ph - (sy - 1 if cover_all else 0)
        ow = sx * (w - 1) + kw - 2 * pw - (sx - 1 if cover_all else 0)
    else:
        oh, ow = outsize
    if (sy, sx) == (kh, kw) and (ph, pw) == (0, 0) and (oh, ow) == (h * kh, w * kw):
        return jnp.repeat(jnp.repeat(x, kh, axis=2), kw, axis=3)
    # trailing pad so that pooling the (oh, ow) plane yields exactly (h, w)
    prh = (h - 1) * sy + kh - oh - ph
    prw = (w - 1) * sx + kw - ow - pw

    def pool(y):
        return lax.reduce_window(
            y, 0.0, lax.add,
            window_dimensions=(1, 1, kh, kw),
            window_strides=(1, 1, sy, sx),
            padding=((0, 0), (0, 0), (ph, prh), (pw, prw)))

    zeros = jnp.zeros(x.shape[:2] + (oh, ow), x.dtype)
    _, vjp = jax.vjp(pool, zeros)
    (y,) = vjp(x)
    return y


def global_average_pooling_2d(x, layout="NCHW"):
    # one reduction in x.dtype: bf16 activations pool as bf16 (half the
    # HBM read of an f32 upcast); heads needing f32 cast the RESULT
    # (a [N, C] vector), as models/resnet.py does before its fc
    hd, wd, _ = _spatial_dims(layout)
    return x.mean(axis=(hd, wd))


def resize_images(x, output_shape):
    n, c, _, _ = x.shape
    oh, ow = output_shape
    return jax.image.resize(x, (n, c, oh, ow), method="bilinear")


# -- normalization ---------------------------------------------------------

def batch_moments(x, axis):
    """Single-pass batch moments: mean and E[x²] accumulate side by side
    over ONE read of ``x`` (fp32 accumulation regardless of activation
    dtype), ``var = E[x²] − mean²`` clamped at 0 against fp32
    cancellation.  The two-pass formulation this replaces (mean, then
    mean of squared deviations) read the activation three times — for a
    ResNet the BN-stat loop fusions were the largest non-conv HBM row in
    the r5 trace.  The VJP is also one pass (d/dx of both sums is a
    fused axpy), where the two-pass var backward re-read x.  Same
    formulation as the multi-node sync BN, which pmeans the two
    accumulators — so single- and multi-node BN now share their numerics.
    """
    x32 = x.astype(jnp.float32)
    mean = x32.mean(axis=axis)
    sq_mean = jnp.mean(x32 * x32, axis=axis)
    var = jnp.maximum(sq_mean - jnp.square(mean), 0.0)
    return mean, var


def batch_normalization(x, gamma, beta, eps=2e-5, axis=None):
    if axis is None:
        axis = (0,) + tuple(range(2, x.ndim))
    mean, var = batch_moments(x, axis)
    return _apply_bn(x, gamma, beta, mean, var, eps, axis)


def fixed_batch_normalization(x, gamma, beta, mean, var, eps=2e-5, axis=None):
    if axis is None:
        axis = (0,) + tuple(range(2, x.ndim))
    return _apply_bn(x, gamma, beta, mean, var, eps, axis)


def _apply_bn(x, gamma, beta, mean, var, eps, axis):
    # Fold the normalization into a per-channel scale/shift computed in
    # fp32 (tiny vectors), applied in x.dtype: one fused mul-add over the
    # activation instead of sub/mul/mul/add — and when x is bf16 the big
    # elementwise op stays bf16 (half the HBM traffic), while all the
    # statistics math stays fp32.
    f32 = jnp.float32
    inv = lax.rsqrt(var.astype(f32) + eps)
    a = gamma.astype(f32) * inv
    shape = [1] * x.ndim
    kept = [d for d in range(x.ndim) if d not in axis]
    for d in kept:
        shape[d] = x.shape[d]
    if x.dtype == f32:
        # fp32 activations keep the unfolded (x - mean) * a + beta form:
        # when |mean| >> std the folded ``x*a + (beta - mean*a)`` loses
        # precision to cancellation, and fp32 gains nothing from folding
        # (the fusion win is bf16 HBM traffic only).
        m = mean.astype(f32).reshape(shape)
        a = a.reshape(shape)
        b = beta.astype(f32).reshape(shape)
        return (x - m) * a + b
    b = beta.astype(f32) - mean.astype(f32) * a
    a = a.reshape(shape).astype(x.dtype)
    b = b.reshape(shape).astype(x.dtype)
    return x * a + b


def layer_normalization(x, gamma, beta, eps=1e-5):
    # statistics in fp32 (bf16 mean/var of wide rows loses precision),
    # output in the activation dtype — same discipline as _apply_bn
    x32 = x.astype(jnp.float32)
    mean = x32.mean(axis=-1, keepdims=True)
    var = x32.var(axis=-1, keepdims=True)
    y = (x32 - mean) * lax.rsqrt(var + eps) * gamma.astype(jnp.float32) \
        + beta.astype(jnp.float32)
    return y.astype(x.dtype)


# -- shape / array ops (thin jnp aliases, reference names) ------------------

def concat(xs, axis=1):
    return jnp.concatenate(list(xs), axis=axis)


def stack(xs, axis=0):
    return jnp.stack(list(xs), axis=axis)


def hstack(xs):
    return jnp.hstack(list(xs))


def vstack(xs):
    return jnp.vstack(list(xs))


def split_axis(x, indices_or_sections, axis):
    return tuple(jnp.split(x, indices_or_sections, axis=axis))


def separate(x, axis=0):
    return tuple(jnp.moveaxis(x, axis, 0))


def reshape(x, shape):
    return jnp.reshape(x, shape)


def flatten(x):
    return jnp.reshape(x, (-1,))


def transpose(x, axes=None):
    return jnp.transpose(x, axes)


def expand_dims(x, axis):
    return jnp.expand_dims(x, axis)


def squeeze(x, axis=None):
    return jnp.squeeze(x, axis)


def tile(x, reps):
    return jnp.tile(x, reps)


def broadcast_to(x, shape):
    return jnp.broadcast_to(x, shape)


def sum(x, axis=None, keepdims=False):
    return jnp.sum(x, axis=axis, keepdims=keepdims)


def mean(x, axis=None, keepdims=False):
    return jnp.mean(x, axis=axis, keepdims=keepdims)


def max(x, axis=None, keepdims=False):
    return jnp.max(x, axis=axis, keepdims=keepdims)


def min(x, axis=None, keepdims=False):
    return jnp.min(x, axis=axis, keepdims=keepdims)


def argmax(x, axis=None):
    return jnp.argmax(x, axis=axis)


def sqrt(x):
    return jnp.sqrt(x)


def exp(x):
    return jnp.exp(x)


def log(x):
    return jnp.log(x)


def clip(x, x_min, x_max):
    return jnp.clip(x, x_min, x_max)


def matmul(a, b, transa=False, transb=False):
    if transa:
        a = jnp.swapaxes(a, -1, -2)
    if transb:
        b = jnp.swapaxes(b, -1, -2)
    return a @ b


def batch_matmul(a, b, transa=False, transb=False):
    if a.ndim == 2:
        a = a[:, :, None]
    if b.ndim == 2:
        b = b[:, :, None]
    return matmul(a, b, transa, transb)


def where(cond, x, y):
    return jnp.where(cond, x, y)


def pad(x, pad_width, mode="constant", **kwargs):
    return jnp.pad(x, pad_width, mode=mode, **kwargs)


# -- additional reference-surface functions ---------------------------------

def average(x, axis=None, weights=None, keepdims=False):
    """Weighted mean (reference: ``F.average``)."""
    if weights is None:
        return jnp.mean(x, axis=axis, keepdims=keepdims)
    return jnp.average(x, axis=axis, weights=weights)


def select_item(x, t):
    """x[i, t[i]] for each row (reference: ``F.select_item``)."""
    return jnp.take_along_axis(x, t[:, None], axis=1).squeeze(1)


def absolute(x):
    return jnp.abs(x)


def maximum(a, b):
    return jnp.maximum(a, b)


def minimum(a, b):
    return jnp.minimum(a, b)


def swish(x, beta=1.0):
    return x * jax.nn.sigmoid(beta * x)


def normalize(x, eps=1e-5, axis=1):
    """L2 normalization along ``axis`` (reference: ``F.normalize``)."""
    norm = jnp.sqrt(jnp.sum(x * x, axis=axis, keepdims=True)) + eps
    return x / norm


def local_response_normalization(x, n=5, k=2.0, alpha=1e-4, beta=0.75):
    """Cross-channel LRN on NCHW (reference: ``F.local_response_
    normalization``; AlexNet-era)."""
    sq = x * x
    half = n // 2
    padded = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    # note: this module shadows builtin sum with the reference F.sum alias
    window = padded[:, 0:x.shape[1]]
    for i in range(1, n):
        window = window + padded[:, i:i + x.shape[1]]
    return x / (k + alpha * window) ** beta


def squared_error(x, t):
    return (x - t) ** 2


def log_softmax_cross_entropy_components(x, t, ignore_label=-1):
    """(per-example nll, valid mask) — building block for custom losses."""
    nll = softmax_cross_entropy(x, t, ignore_label=ignore_label, reduce="no")
    return nll, t != ignore_label


# -- elementwise math aliases (reference F.* long tail) ---------------------

def sin(x):
    return jnp.sin(x)


def cos(x):
    return jnp.cos(x)


def tan(x):
    return jnp.tan(x)


def arcsin(x):
    return jnp.arcsin(x)


def arccos(x):
    return jnp.arccos(x)


def arctan(x):
    return jnp.arctan(x)


def arctan2(x1, x2):
    return jnp.arctan2(x1, x2)


def sinh(x):
    return jnp.sinh(x)


def cosh(x):
    return jnp.cosh(x)


def erf(x):
    return jax.scipy.special.erf(x)


def erfc(x):
    return jax.scipy.special.erfc(x)


def floor(x):
    return jnp.floor(x)


def ceil(x):
    return jnp.ceil(x)


def sign(x):
    return jnp.sign(x)


def square(x):
    return jnp.square(x)


def rsqrt(x):
    return lax.rsqrt(x)


def log2(x):
    return jnp.log2(x)


def log10(x):
    return jnp.log10(x)


def log1p(x):
    return jnp.log1p(x)


def expm1(x):
    return jnp.expm1(x)


def cumsum(x, axis=None):
    return jnp.cumsum(x, axis=axis)


def cumprod(x, axis=None):
    return jnp.cumprod(x, axis=axis)


def prod(x, axis=None, keepdims=False):
    return jnp.prod(x, axis=axis, keepdims=keepdims)


def logsumexp(x, axis=None):
    return jax.scipy.special.logsumexp(x, axis=axis)


def fmod(x, divisor):
    return jnp.fmod(x, divisor)


def fix(x):
    # jnp.fix is deprecated (removed in jax 0.10); trunc is identical
    # (round toward zero)
    return jnp.trunc(x)


def relu6(x):
    return jnp.clip(x, 0, 6)


def hard_sigmoid(x):
    return jnp.clip(x * 0.2 + 0.5, 0.0, 1.0)


def softmin(x, axis=1):
    return jax.nn.softmax(-x, axis=axis)


def crelu(x, axis=1):
    return jnp.concatenate([jnp.maximum(x, 0), jnp.maximum(-x, 0)],
                           axis=axis)


def flip(x, axis):
    return jnp.flip(x, axis)


def fliplr(x):
    return jnp.fliplr(x)


def flipud(x):
    return jnp.flipud(x)


def rollaxis(x, axis, start=0):
    return jnp.rollaxis(x, axis, start)


def swapaxes(x, axis1, axis2):
    return jnp.swapaxes(x, axis1, axis2)


def moveaxis(x, source, destination):
    return jnp.moveaxis(x, source, destination)


def repeat(x, repeats, axis=None):
    return jnp.repeat(x, repeats, axis=axis)


def diagonal(x, offset=0, axis1=0, axis2=1):
    return jnp.diagonal(x, offset, axis1, axis2)


def cast(x, typ):
    return x.astype(typ)


def identity(*xs):
    return xs[0] if len(xs) == 1 else xs


def scale(x, y, axis=1):
    shape = [1] * x.ndim
    for i, s in enumerate(jnp.shape(y)):
        shape[axis + i] = s
    return x * jnp.reshape(y, shape)


def bias(x, y, axis=1):
    shape = [1] * x.ndim
    for i, s in enumerate(jnp.shape(y)):
        shape[axis + i] = s
    return x + jnp.reshape(y, shape)


def matmul_nn(a, b):
    return a @ b


def tensordot(a, b, axes=2):
    return jnp.tensordot(a, b, axes=axes)


def einsum(subscripts, *operands):
    return jnp.einsum(subscripts, *operands)
