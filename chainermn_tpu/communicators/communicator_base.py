"""Communicator abstract base.

Reference: ``chainermn/communicators/communicator_base.py ·
CommunicatorBase`` (SURVEY.md §2.1) — the full method vocabulary:
properties ``rank/size/intra_rank/inter_rank/inter_size``, ndarray
collectives ``send/recv/bcast/gather/allgather/alltoall/allreduce/scatter``,
pickled-object variants ``*_obj``, model ops ``bcast_data`` /
``allreduce_grad`` (alias ``multi_node_mean_grad``), and ``split``.

Semantics shift for the single-controller SPMD world (documented here once,
inherited everywhere):

* The reference is MPMD: N processes, each owning one GPU, each executing
  its own copy of the script; ``rank`` addresses a process.  JAX is
  single-controller SPMD: one Python process per *host* drives all devices,
  and per-device code exists only inside compiled programs.  Therefore a
  "rank" here is a **device index along the communicator's mesh axis**, and
  the communicator has two operating modes:

  - **Eager (host) mode** — collectives act on *stacked* arrays whose
    leading axis is ``size`` (element ``i`` = rank ``i``'s value).  This is
    the single-controller view of "every rank holds a value" and is what
    the reference's per-process test patterns map onto.
  - **In-step (traced) mode** — inside a program launched via
    :meth:`run_spmd` (a ``shard_map`` over the communicator's axis), the
    same methods emit ``lax`` collectives (``psum``/``all_gather``/
    ``ppermute``/``all_to_all``) that compile onto ICI/DCN.  This is the
    hot path; SURVEY §3.2's pack/cast/allreduce machinery becomes part of
    one XLA program.

* ``rank``/``intra_rank`` address the *controlling process* (host): used
  for the reference's ``if comm.rank == 0:`` logging/IO patterns, which in
  JAX run once per host rather than once per device.  ``size`` is the
  device count along the communicator axis (the data-parallel degree).
"""

from __future__ import annotations

__all__ = ["CommunicatorBase"]


class CommunicatorBase:
    # -- topology ----------------------------------------------------------
    @property
    def rank(self) -> int:
        """Host/process rank for control-flow (logging, IO)."""
        raise NotImplementedError

    @property
    def size(self) -> int:
        """Number of ranks (devices along the communicator axis)."""
        raise NotImplementedError

    @property
    def intra_rank(self) -> int:
        """Rank within the local host (reference: GPU index within node)."""
        raise NotImplementedError

    @property
    def intra_size(self) -> int:
        raise NotImplementedError

    @property
    def inter_rank(self) -> int:
        """Host index (reference: node index)."""
        raise NotImplementedError

    @property
    def inter_size(self) -> int:
        """Number of hosts (reference: number of nodes)."""
        raise NotImplementedError

    # -- ndarray collectives -------------------------------------------------
    def send(self, data, dest, tag=0):
        raise NotImplementedError

    def recv(self, source, tag=0):
        raise NotImplementedError

    def bcast(self, data, root=0):
        raise NotImplementedError

    def gather(self, data, root=0):
        """Gather one value per rank; EVERY rank receives the result.

        Root-symmetric return — a deliberate semantics shift from the
        reference (MPI ``gather`` returns the gathered list on ``root``
        and ``None`` elsewhere): in single-controller SPMD there is no
        per-process asymmetry to express — the one controlling process
        plays every rank, and in-step (traced) mode the lowering is
        ``lax.all_gather`` either way.  ``root`` is accepted for
        signature compatibility and ignored by the return convention;
        reference code guarding on ``if comm.rank == root:`` before
        using the result keeps working unchanged, code relying on the
        ``None`` on non-root ranks must drop that branch (see
        docs/migration.md).
        """
        raise NotImplementedError

    def allgather(self, x):
        raise NotImplementedError

    def alltoall(self, xs):
        raise NotImplementedError

    def scatter(self, xs, root=0):
        raise NotImplementedError

    def allreduce(self, data, op="sum"):
        raise NotImplementedError

    # -- object (pickle) channel ----------------------------------------------
    def send_obj(self, obj, dest, tag=0):
        raise NotImplementedError

    def recv_obj(self, source, tag=0):
        raise NotImplementedError

    def bcast_obj(self, obj, root=0):
        raise NotImplementedError

    def gather_obj(self, obj, root=0):
        """Gather one picklable object per rank; EVERY rank receives the
        gathered list (root-symmetric, same convention and rationale as
        :meth:`gather` — the reference returned ``None`` on non-root
        ranks)."""
        raise NotImplementedError

    def allgather_obj(self, obj):
        raise NotImplementedError

    def allreduce_obj(self, obj):
        raise NotImplementedError

    # -- model ops -------------------------------------------------------------
    def bcast_data(self, model):
        """Replicate model parameters from root across ranks.

        Reference: ``CommunicatorBase.bcast_data`` — called once before
        training so all ranks start from identical weights.
        """
        raise NotImplementedError

    def multi_node_mean_grad(self, model, zero_fill=False):
        """Average ``param.grad`` across ranks in place."""
        raise NotImplementedError

    # historical alias (reference kept both names through the rename)
    def allreduce_grad(self, model, zero_fill=False):
        return self.multi_node_mean_grad(model, zero_fill)

    # -- topology manipulation ---------------------------------------------------
    def split(self, color, key):
        """Partition ranks into disjoint sub-communicators (MPI_Comm_Split)."""
        raise NotImplementedError

    # -- lifecycle ------------------------------------------------------------
    def _axis_in_scope(self):
        """True when this communicator's mesh axis is bound by an
        enclosing shard_map of the current trace (mesh backends override;
        non-mesh communicators have no axis to bind)."""
        return False

    def finalize(self):
        pass
