"""Mesh-backed communicator — the ``jax_ici`` backend.

Reference: the whole of ``chainermn/communicators/`` (SURVEY.md §2.1).
The reference's eight communicator classes solve GPU-cluster problems
(CUDA-aware MPI, host staging, node hierarchy, NCCL rings).  On TPU the
transport is one thing — XLA collectives over ICI/DCN — so the taxonomy
collapses into *mesh-axis choice + gradient dtype choice* (SURVEY §2.7),
and the named variants (``naive``/``flat``/``hierarchical``/
``two_dimensional``/``single_node``/``non_cuda_aware``/``pure_nccl``)
are aliases of this class with their distinguishing knobs preserved:

* ``pure_nccl(allreduce_grad_dtype=float16)`` → ``grad_dtype=bfloat16``
  compressed gradient ``psum`` (N3 in SURVEY §2.5; bf16 is the TPU-native
  half type — fp16 is honored if explicitly requested).
* ``flat``'s single fused buffer → ``batch_collectives=True``: gradients
  are flattened into one contiguous bucket before the collective (N2;
  XLA usually fuses this anyway — measured, not assumed; see bench/).
* pure_nccl's size-bounded allreduce pipeline →
  ``batch_collectives="bucketed"``: gradients are packed into K
  size-bounded buckets (``CHAINERMN_TPU_BUCKET_MB`` / ``bucket_mb``,
  default ~4 MB) in reverse parameter-registration order, one ``pmean``
  per bucket — schedulable units XLA's async-collective scheduler can
  overlap with the remaining backward compute (the reference hid its
  NCCL allreduces behind backward the same way; see
  docs/performance.md §7 and tools/comm_budgets.json).
* ``hierarchical``/``two_dimensional`` → a REAL two-level ``(dcn, ici)``
  mesh axis split (ISSUE 6; no longer aliases of the flat path): the
  gradient exchange composes with the machine topology as intra-host
  ``reduce_scatter`` over ICI → inter-host exchange over DCN on the
  1/intra chunk → intra-host ``all_gather`` over ICI, so the slow DCN
  hop only ever carries ``1/ici_size`` of the gradient bytes.  The
  split is inferred from the controller topology (``process_count`` ×
  local devices), forced with ``intra_size=``/``inter_size=`` (the
  simulated-2-host tier-1 grid), or taken from two named axes of an
  existing mesh (:meth:`from_mesh_axis` with a 2-tuple).  Per-hop
  compression: ``allreduce_grad_dtype={"dcn": "bfloat16"}`` lowers DCN
  traffic while ICI stays lossless.  ``CHAINERMN_TPU_HIERARCHY=flat``
  is the escape hatch back to the one-axis alias behavior.

Two operating modes (see ``communicator_base`` docstring): eager host-mode
collectives on stacked arrays, and in-step ``lax`` collectives inside
``shard_map`` programs launched by :meth:`run_spmd`.
"""

from __future__ import annotations

import threading

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .communicator_base import CommunicatorBase

__all__ = ["MeshCommunicator", "ElasticMeshCommunicator"]


def _is_traced(*xs):
    return any(isinstance(leaf, jax.core.Tracer)
               for x in xs for leaf in jax.tree.leaves(x))


def _bucket_scope(name, bucket):
    """Trace-time name for one bucket's collective emission.

    Default: the stable census-era scope name alone.  Under
    ``CHAINERMN_TPU_TRACE=full`` (ISSUE 14) the scope is prefixed with
    the span-tracer vocabulary (``train.grad_exchange.bucketK``) so an
    XProf/jax.profiler capture attributes real device time to the SAME
    names the host-side Chrome trace carries — the two timelines join
    on the span name.  Pure trace-time metadata: no primitive is added
    and the compiled program's census is unchanged."""
    from .. import observability
    if observability.named_scopes_enabled():
        return jax.named_scope(
            f"train.grad_exchange.bucket{bucket}.{name}")
    return jax.named_scope(name)


_warned_inert_ef = False


def _warn_inert_error_feedback():
    """A quantized transform was invoked through the legacy 1-arg form
    while the communicator asked for error feedback: quantization still
    happens, but the residual is DISCARDED — the exact EF-off mode the
    parity ablation shows drifting from the lossless trajectory.  Warn
    once per process (trace-time, so the hot path never pays): callers
    that cannot thread the residual should construct the communicator
    with error_feedback=False to make the ablation explicit."""
    global _warned_inert_ef
    if _warned_inert_ef:
        return
    _warned_inert_ef = True
    import warnings
    warnings.warn(
        "quantized grad_transform called without a residual while "
        "error_feedback=True: the quantization error is being discarded "
        "(error feedback is inert on this call path — e.g. the DCGAN "
        "updater's direct grad_transform use).  Pass "
        "error_feedback=False at communicator construction to make the "
        "ablation explicit, or use the multi-node optimizer, which "
        "threads the residual.", UserWarning, stacklevel=3)


class MeshCommunicator(CommunicatorBase):
    """Communicator over a 1-D device mesh axis.

    ``devices``: list of ``jax.Device`` (default: all).  ``axis_name``: the
    mesh axis this communicator's collectives run over.  For hybrid
    DP×MP (reference: ``CommunicatorBase.split`` + two communicators),
    construct one communicator per axis of a shared N-D mesh via
    :meth:`from_mesh_axis`.
    """

    def __init__(self, devices=None, axis_name="mn_world",
                 allreduce_grad_dtype=None, batch_collectives=False,
                 bucket_mb=None, name="jax_ici", _mesh=None,
                 intra_size=None, inter_size=None, error_feedback=True,
                 stripe_ratio=None):
        self.name = name
        self.hierarchy = None
        self._hier_sizes = None
        # knob PROVENANCE (ISSUE 19): which exchange knobs the caller
        # hand-set (explicit argument here; the env-read sites below OR
        # in their knobs).  The autotune planner only fills knobs left
        # free — hand knobs always win, and :meth:`retuned` carries
        # these flags onto clones and elastic rebuilds so a rebuilt
        # communicator remembers what was a human decision vs a derived
        # one (the elastic factory passes the OLD comm's knob values as
        # explicit arguments, which must not launder them into "hand").
        self._hand_knobs = {
            "bucket_mb": bucket_mb is not None,
            "stripe_ratio": stripe_ratio is not None,
            "grad_dtype": allreduce_grad_dtype is not None,
        }
        #: the agreed autotune plan this communicator runs under (None
        #: = hand-knobbed); attached by :meth:`retuned`
        self.autotune_plan = None
        self._autotune_mode = None
        want_hier = (name in ("hierarchical", "two_dimensional")
                     or intra_size is not None or inter_size is not None
                     or isinstance(axis_name, (tuple, list)))
        if isinstance(axis_name, (tuple, list)):
            names = tuple(axis_name)
            if len(names) != 2:
                raise ValueError(
                    f"a hierarchical axis_name is a (dcn, ici) 2-tuple; "
                    f"got {names!r}")
        elif want_hier:
            names = ("dcn", "ici")
        if _mesh is not None:
            self.mesh = _mesh
            self._devices = list(np.asarray(_mesh.devices).reshape(-1))
        else:
            self._devices = list(devices) if devices is not None else list(jax.devices())
            if want_hier:
                inter, intra = self._resolve_hierarchy(
                    len(self._devices), intra_size, inter_size)
                self.mesh = Mesh(np.asarray(self._devices)
                                 .reshape(inter, intra), names)
            else:
                self.mesh = Mesh(np.asarray(self._devices), (axis_name,))
        if want_hier:
            self.hierarchy = names
            self._hier_sizes = (int(self.mesh.shape[names[0]]),
                                int(self.mesh.shape[names[1]]))
            axis_name = names
        self.axis_name = axis_name
        # striped multi-path exchange (ISSUE 11): the DCN share of each
        # bucket's payload.  0 = the strict hierarchical schedule; the
        # env knob is read at CONSTRUCTION time (like bucket_mb) and
        # only where it can matter — a hierarchical mesh.  A flat
        # communicator has ONE fabric: an explicit ratio there is a
        # construction error, never a silent no-op.
        if stripe_ratio is None and want_hier:
            import os
            raw = os.environ.get("CHAINERMN_TPU_STRIPE_RATIO", "").strip()
            if raw:
                stripe_ratio = float(raw)
                self._hand_knobs["stripe_ratio"] = True
        if stripe_ratio is not None:
            stripe_ratio = float(stripe_ratio)
            if not 0.0 <= stripe_ratio <= 1.0:
                raise ValueError(
                    f"stripe_ratio must be in [0, 1], got {stripe_ratio}")
            if stripe_ratio > 0 and self.hierarchy is None:
                raise ValueError(
                    "stripe_ratio needs a hierarchical communicator "
                    "(name='hierarchical'/'two_dimensional' or an "
                    "intra_size/inter_size split): a flat mesh has one "
                    "fabric, there is nothing to stripe across")
        self.stripe_ratio = float(stripe_ratio or 0.0)
        self.dcn_grad_dtype = None
        self.error_feedback = bool(error_feedback)
        from ._memory_utility import is_quantized_dtype, resolve_grad_dtype
        if isinstance(allreduce_grad_dtype, dict):
            # per-hop compression (ISSUE 6): lossless ICI + compressed
            # DCN is the interesting point — the slow hop's bytes halve
            # while the fast hop keeps full precision
            if self.hierarchy is None:
                raise ValueError(
                    "per-hop allreduce_grad_dtype={'ici': ..., 'dcn': ...} "
                    "needs a hierarchical communicator "
                    "(name='hierarchical'/'two_dimensional' or an "
                    "intra_size/inter_size split)")
            unknown = set(allreduce_grad_dtype) - {"ici", "dcn"}
            if unknown:
                raise ValueError(
                    f"unknown per-hop dtype keys {sorted(unknown)} "
                    f"(hops are 'ici' and 'dcn')")
            ici_dt = allreduce_grad_dtype.get("ici")
            dcn_dt = allreduce_grad_dtype.get("dcn")
            if is_quantized_dtype(ici_dt):
                # the fast hop is lossless BY DESIGN (ISSUE 8): its
                # bytes are nearly free and a second quantization point
                # would need a second residual for no wire win
                raise ValueError(
                    f"quantized ici dtype {ici_dt!r}: the ICI hop is "
                    f"lossless by design — int8/fp8 compression is a "
                    f"slow-hop (dcn) knob")
            self.allreduce_grad_dtype = resolve_grad_dtype(ici_dt)
            self.dcn_grad_dtype = resolve_grad_dtype(dcn_dt)
        else:
            self.allreduce_grad_dtype = resolve_grad_dtype(
                allreduce_grad_dtype)
            if self.hierarchy is not None:
                self.dcn_grad_dtype = self.allreduce_grad_dtype
                if is_quantized_dtype(self.allreduce_grad_dtype):
                    # a scalar CAST dtype (bf16) compresses BOTH hops
                    # (flat-path parity), but a scalar QUANTIZED dtype
                    # compresses the DCN crossing only (ISSUE 8:
                    # lossless over ICI, compressed over DCN by
                    # default — int8 cannot ride a psum_scatter anyway)
                    self.allreduce_grad_dtype = None
        if self._compress_disabled():
            # CHAINERMN_TPU_COMPRESS=off — the factory-level escape
            # hatch (ISSUE 8): quantized wires fall back to LOSSLESS
            # (never to a silently different lossy dtype); plain cast
            # compression (bf16/fp16) is untouched — it predates the
            # quantized path and has its own knobs
            if is_quantized_dtype(self.allreduce_grad_dtype):
                self.allreduce_grad_dtype = None
            if is_quantized_dtype(self.dcn_grad_dtype):
                self.dcn_grad_dtype = None
        if batch_collectives not in (False, True, "bucketed"):
            raise ValueError(
                f"batch_collectives must be False (per-leaf collectives), "
                f"True (one flat bucket) or 'bucketed' (size-bounded "
                f"buckets); got {batch_collectives!r}")
        self.batch_collectives = batch_collectives
        # bucket bound for the "bucketed" exchange; the env knob is read
        # at CONSTRUCTION (not trace) time so every rank of a job traces
        # the same plan from the same communicator arguments.  Resolved
        # only when it can matter (explicit arg or bucketed exchange) —
        # a stray CHAINERMN_TPU_BUCKET_MB value must not break the
        # flavors that never plan buckets
        if bucket_mb is None and batch_collectives == "bucketed":
            import os
            from ._memory_utility import DEFAULT_BUCKET_MB
            raw = os.environ.get("CHAINERMN_TPU_BUCKET_MB")
            if raw:
                self._hand_knobs["bucket_mb"] = True
            bucket_mb = float(raw or DEFAULT_BUCKET_MB)
        if bucket_mb is not None:
            bucket_mb = float(bucket_mb)
            if bucket_mb <= 0:
                raise ValueError(
                    f"bucket_mb must be positive, got {bucket_mb}")
        self.bucket_mb = bucket_mb
        self._mailbox = {}
        self._obj_mailbox = {}
        self._lock = threading.Lock()
        self._jit_cache = {}
        # host topology (reference: init_ranks' hostname allgather at
        # communicator construction, SURVEY §2.1): with multiple
        # controller processes, intra_rank = this process's index among
        # the processes on the same host.  NOTE: under process_count > 1
        # communicator construction is a COLLECTIVE point — every process
        # must construct communicators (including from_mesh_axis /
        # split_all sub-communicators) in the same order, or peers block
        # in this allgather until the KV channel's timeout_ms expires
        # (the channel bounds every get/barrier, so a one-sided failure
        # surfaces as a timeout error on the peers, not a silent hang).
        # The except below only rescues THIS process (e.g. no object
        # channel at all); it cannot unblock peers already inside the
        # collective — they recover via the same timeout.
        self._intra = None
        if jax.process_count() > 1:
            try:
                import socket
                me = (socket.gethostname(), jax.process_index())
                peers = self._process_allgather_pickled(me)
                same = sorted(pi for host, pi in peers if host == me[0])
                self._intra = (same.index(me[1]), len(same))
            except Exception:
                self._intra = None  # no object channel: single-host default
        # observability (ISSUE 14): stamp the rank (and, on elastic
        # incarnations, the membership epoch) into the span tracer so
        # every subsequent event is rank/epoch-tagged — the merge tool
        # keys rank lanes off this.  No-op when tracing is off.
        from .. import observability
        if observability.enabled():
            observability.tracer().configure(
                rank=self.rank, epoch=getattr(self, "epoch", None))

    def __deepcopy__(self, memo):
        # communicators are process-global transport handles (mesh, device
        # list, mailboxes) — model deepcopies (create_mnbn_model) share them
        return self

    @staticmethod
    def _compress_disabled():
        import os
        return os.environ.get("CHAINERMN_TPU_COMPRESS", "") \
            .strip().lower() in ("off", "0", "none")

    @staticmethod
    def _resolve_hierarchy(n_devices, intra_size, inter_size):
        """``(inter, intra)`` of the two-level split: explicit sizes win
        (the simulated-multihost knob); otherwise the controller
        topology decides — one DCN group per controller process, ICI =
        the devices each drives.  Validated so a bad split fails at
        construction, not as a reshape error inside the first traced
        step."""
        if intra_size is not None and inter_size is not None:
            if intra_size * inter_size != n_devices:
                raise ValueError(
                    f"intra_size({intra_size}) × inter_size({inter_size})"
                    f" != device count {n_devices}")
            return int(inter_size), int(intra_size)
        if inter_size is not None:
            if inter_size < 1 or n_devices % inter_size:
                raise ValueError(
                    f"inter_size={inter_size} does not divide the "
                    f"device count {n_devices}")
            return int(inter_size), n_devices // int(inter_size)
        if intra_size is not None:
            if intra_size < 1 or n_devices % intra_size:
                raise ValueError(
                    f"intra_size={intra_size} does not divide the "
                    f"device count {n_devices}")
            return n_devices // int(intra_size), int(intra_size)
        inter = jax.process_count()
        if n_devices % inter:
            # ragged host layouts (devices= subsets) have no canonical
            # split; require the explicit knob rather than guessing
            raise ValueError(
                f"cannot infer a (dcn, ici) split: {n_devices} devices "
                f"over {inter} processes; pass intra_size=/inter_size=")
        return inter, n_devices // inter

    @classmethod
    def from_mesh_axis(cls, mesh: Mesh, axis_name, **kwargs):
        """Communicator over one named axis of an existing N-D mesh —
        or, with a ``(dcn, ici)`` 2-tuple of axis names, a HIERARCHICAL
        communicator over that two-level sub-topology (the ISSUE 6
        construction path for meshes that already carry the split)."""
        if isinstance(axis_name, (tuple, list)):
            dcn, ici = tuple(axis_name)
            sub = np.moveaxis(
                mesh.devices,
                (mesh.axis_names.index(dcn), mesh.axis_names.index(ici)),
                (0, 1))
            grid = sub.reshape(sub.shape[0], sub.shape[1], -1)[:, :, 0]
            comm = cls(devices=list(grid.reshape(-1)),
                       axis_name=(dcn, ici),
                       inter_size=int(grid.shape[0]),
                       intra_size=int(grid.shape[1]), **kwargs)
            comm.mesh = mesh  # collectives address the enclosing mesh's axes
            return comm
        sub = np.moveaxis(mesh.devices,
                          mesh.axis_names.index(axis_name), 0)
        comm = cls(devices=list(sub.reshape(sub.shape[0], -1)[:, 0]),
                   axis_name=axis_name, **kwargs)
        comm.mesh = mesh  # collectives run inside programs over the full mesh
        return comm

    # -- topology ------------------------------------------------------------
    @property
    def rank(self):
        return jax.process_index()

    @property
    def size(self):
        return len(self._devices)

    @property
    def intra_rank(self):
        """First device slot this controller drives on its host, in
        DEVICE-SLOT units — the same units as ``intra_size``, so the
        reference idiom ``intra_rank in range(0, intra_size)`` and
        slot arithmetic hold on every host layout.  0 for the common
        single-controller-per-host layout; ``local_proc_idx ×
        local_device_count`` when several controller processes share a
        host."""
        local_proc_idx = self._intra[0] if self._intra is not None else 0
        return local_proc_idx * jax.local_device_count()

    @property
    def intra_size(self):
        """Device slots this host contributes (DEVICE-SLOT units, like
        ``intra_rank``): local device count × co-located controller
        processes (reference: ranks per node).  On a hierarchical
        communicator this is the ICI axis size — the mesh's own view of
        "ranks per node", which equals the controller-derived figure on
        a real multihost run and stays correct under the simulated
        splits (``inter_size=`` on one controller)."""
        if self.hierarchy is not None:
            return self._hier_sizes[1]
        n_local_procs = self._intra[1] if self._intra is not None else 1
        return jax.local_device_count() * n_local_procs

    @property
    def inter_rank(self):
        return jax.process_index()

    @property
    def inter_size(self):
        """Number of controller PROCESSES — the host/object-channel view
        (scatter_dataset, checkpoint consensus, multi-node iterators key
        off this).  The device-mesh view of the two-level split lives on
        ``dcn_size``/``ici_size``; the two coincide on a real multihost
        run and deliberately differ under a single-controller simulated
        split (one controller still feeds the whole global batch)."""
        return jax.process_count()

    # -- two-level (ici × dcn) topology (ISSUE 6) --------------------------
    @property
    def dcn_axis(self):
        """Slow-hop mesh axis name (``None`` on flat communicators)."""
        return self.hierarchy[0] if self.hierarchy is not None else None

    @property
    def ici_axis(self):
        """Fast-hop mesh axis name (``None`` on flat communicators)."""
        return self.hierarchy[1] if self.hierarchy is not None else None

    @property
    def dcn_size(self):
        """Groups on the slow hop (1 on flat communicators)."""
        return self._hier_sizes[0] if self.hierarchy is not None else 1

    @property
    def ici_size(self):
        """Devices per slow-hop group (== ``size`` on flat
        communicators: the whole world is one fast-hop group)."""
        return self._hier_sizes[1] if self.hierarchy is not None \
            else self.size

    def chunk_axes(self):
        """Axis names of the gradient reduce-scatter chain, FAST hop
        first — the full buffer crosses the cheap wire, the slow hop
        only ever sees the 1/ici chunk.  ``(axis,)`` on flat
        communicators; ``(ici, dcn)`` on hierarchical ones.  The
        optimizer's sharded update chains ``psum_scatter`` in this
        order and ``all_gather`` in reverse."""
        if self.hierarchy is not None:
            return (self.ici_axis, self.dcn_axis)
        return (self.axis_name,)

    def flat_chunk_spec(self):
        """``PartitionSpec`` of a flat padded vector sharded one chunk
        per rank in the layout the chained reduce-scatter of
        :meth:`chunk_axes` produces (fast hop major) — what the sharded
        optimizer state and the reduce-scatter stale buffer use."""
        if self.hierarchy is not None:
            return P((self.ici_axis, self.dcn_axis))
        return P(self.axis_name)

    def striped_chunk_specs(self):
        """``(fast_major, slow_major)`` pair of chunk specs for the
        STRIPED sharded update (ISSUE 11): the ICI-path slice's chained
        reduce-scatter lands chunks fast-hop-major (== the
        :meth:`flat_chunk_spec` layout) while the DCN-path slice's
        transposed chain lands them slow-hop-major — the two flat
        state vectors of the striped ZeRO layout each carry their own
        spec."""
        if self.hierarchy is None:
            raise ValueError("striped chunk specs need a hierarchical "
                             "communicator")
        return (P((self.ici_axis, self.dcn_axis)),
                P((self.dcn_axis, self.ici_axis)))

    # -- mode dispatch ---------------------------------------------------------
    def _axis_index(self):
        return lax.axis_index(self.axis_name)

    # -- ndarray collectives ----------------------------------------------------
    def allreduce(self, data, op="sum"):
        """Traced: ``lax`` reduction over the axis.  Eager: reduce the
        stacked leading axis and return the (identical-on-all-ranks) value."""
        if _is_traced(data):
            if op == "sum":
                return lax.psum(data, self.axis_name)
            if op == "mean":
                return lax.pmean(data, self.axis_name)
            if op == "max":
                return lax.pmax(data, self.axis_name)
            if op == "min":
                return lax.pmin(data, self.axis_name)
            raise ValueError(f"unsupported op {op!r}")
        data = jnp.asarray(data)
        self._check_stacked(data, "allreduce")
        red = {"sum": jnp.sum, "mean": jnp.mean,
               "max": jnp.max, "min": jnp.min}[op]
        return red(data, axis=0)

    def multi_node_mean(self, data):
        """Reference ``CommunicatorBase.multi_node_mean``: allreduce ÷ size."""
        return self.allreduce(data, op="mean")

    def allgather(self, x):
        """Traced: ``lax.all_gather`` → leading ``size`` axis.  Eager: the
        stacked input *is* the gathered result; returned as a tuple for
        reference-shape parity."""
        if _is_traced(x):
            return lax.all_gather(x, self.axis_name)
        x = jnp.asarray(x)
        self._check_stacked(x, "allgather")
        return tuple(x[i] for i in range(self.size))

    def alltoall(self, xs):
        """Traced: ``lax.all_to_all`` on the leading (destination) axis.
        Eager: input [src, dst, ...] → output [dst, src, ...]."""
        if _is_traced(xs):
            if isinstance(xs, (tuple, list)):
                xs = jnp.stack(list(xs))
            return lax.all_to_all(xs, self.axis_name,
                                  split_axis=0, concat_axis=0, tiled=False)
        if isinstance(xs, (tuple, list)):
            xs = jnp.stack([jnp.stack(list(row)) for row in xs]) \
                if isinstance(xs[0], (tuple, list)) else jnp.stack(list(xs))
        self._check_stacked(xs, "alltoall")
        if xs.ndim < 2 or xs.shape[1] != self.size:
            raise ValueError(
                "eager alltoall expects [src, dst, ...] stacked input")
        return jnp.swapaxes(xs, 0, 1)

    def bcast(self, data, root=0):
        """Traced: every rank gets rank ``root``'s value.  Eager: stacked
        input → the root slice."""
        if _is_traced(data):
            masked = jnp.where(self._axis_index() == root, data,
                               jnp.zeros_like(data))
            return lax.psum(masked, self.axis_name)
        data = jnp.asarray(data)
        self._check_stacked(data, "bcast")
        return data[root]

    def gather(self, data, root=0):
        """Traced: ``all_gather`` (SPMD has no root asymmetry inside a
        compiled program).  Eager: tuple of per-rank slices."""
        if _is_traced(data):
            return lax.all_gather(data, self.axis_name)
        data = jnp.asarray(data)
        self._check_stacked(data, "gather")
        return tuple(data[i] for i in range(self.size))

    def scatter(self, xs, root=0):
        """Traced: rank ``root``'s stacked [size, ...] value, own slice out.
        Eager: identity on the stacked representation."""
        if isinstance(xs, (tuple, list)):
            xs = jnp.stack(list(xs))
        if _is_traced(xs):
            from_root = self.bcast(xs, root)
            return jnp.take(from_root, self._axis_index(), axis=0)
        self._check_stacked(xs, "scatter")
        return xs

    # -- point-to-point -----------------------------------------------------------
    def send(self, data, dest, tag=0, source=None):
        """Eager host-mode send.  Traced point-to-point lives in
        ``chainermn_tpu.functions`` (ppermute with static src/dst).

        Same controller: mailbox append.  Other controller process:
        pickled ndarray over the coordination KV channel.  ``source`` is
        optional sender attribution for MPI-style matched receives — the
        single controller acts for many ranks, so identity must be
        declared, not inferred; undeclared sends match any ``recv``.
        """
        if _is_traced(data):
            raise RuntimeError(
                "inside compiled steps use chainermn_tpu.functions.send "
                "(ppermute); Communicator.send is the host-mode channel")
        if dest != self.rank:
            ch = self._host_channel()
            if ch is not None:
                # attribution travels with the payload; cross-process
                # matching is already exact by (process, tag, seq)
                ch.send_obj((source, np.asarray(data)), dest,
                            tag=f"nd{tag}")
                return
        with self._lock:
            self._mailbox.setdefault((dest, tag), []).append(
                (source, jnp.asarray(data)))

    def recv(self, source, tag=0):
        """Matched receive: only messages sent with this ``source``
        attribution (or sent without one) are delivered — two pending
        senders with declared sources can no longer cross wires
        (MPI source-matching semantics)."""
        if source != self.rank:
            ch = self._host_channel()
            if ch is not None:
                _attr, data = ch.recv_obj(source, tag=f"nd{tag}")
                return jnp.asarray(data)
        with self._lock:
            for key in list(self._mailbox):
                if key[1] != tag:
                    continue
                box = self._mailbox[key]
                for i, (src, _) in enumerate(box):
                    if src is None or source is None or src == source:
                        return box.pop(i)[1]
        raise RuntimeError(
            f"recv with no matching message (host mode, source={source}, "
            f"tag={tag})")

    # -- object channel ---------------------------------------------------------
    # Same-controller: loopback mailbox (the controller holds the one copy).
    # Cross-process: chunked pickled transport over the jax.distributed
    # coordination KV store (reference: pickled MPI channel, SURVEY §2.7;
    # see ``_host_channel.HostChannel``).  In single-controller SPMD the
    # host-object unit is the controller process, so ``dest``/``source``
    # here are controller ranks (== ``inter_rank``/``jax.process_index()``).
    def _host_channel(self):
        from ._host_channel import get_host_channel
        return get_host_channel()

    def send_obj(self, obj, dest, tag=0):
        if dest != self.rank:
            ch = self._host_channel()
            if ch is not None:
                ch.send_obj(obj, dest, tag)
                return
        with self._lock:
            self._obj_mailbox.setdefault((dest, tag), []).append(obj)

    def recv_obj(self, source, tag=0):
        if source != self.rank:
            ch = self._host_channel()
            if ch is not None:
                return ch.recv_obj(source, tag)
        with self._lock:
            for key in list(self._obj_mailbox):
                if key[1] == tag and self._obj_mailbox[key]:
                    return self._obj_mailbox[key].pop(0)
        raise RuntimeError("recv_obj with empty mailbox (host mode)")

    def bcast_obj(self, obj, root=0):
        # root is a CONTROLLER rank (inter_rank) in every mode — the
        # single-controller collapse validates identically so a root that
        # would be rejected at scale fails in development too
        root = self._owning_process(root)
        if self.inter_size > 1:
            ch = self._host_channel()
            if ch is not None:
                return ch.bcast(obj, root=root)
            gathered = self._process_allgather_pickled(obj)
            return gathered[root]
        return obj

    def _owning_process(self, root):
        """Validate an object-channel root as a controller rank.

        Host-mode object ops consistently address CONTROLLER processes
        (``inter_rank`` — see ``_MultiNodeIterator._is_master``,
        ``scatter_dataset``).  A mis-addressed root raises instead of
        silently re-rooting to 0 (every process computes the same root
        from the same arguments, so the error is raised symmetrically —
        no one-sided collective hang)."""
        if not 0 <= root < self.inter_size:
            raise ValueError(
                f"object-channel root {root} out of range for "
                f"{self.inter_size} controller processes")
        return root

    def gather_obj(self, obj, root=0):
        return self.allgather_obj(obj)

    def allgather_obj(self, obj):
        """One entry per *rank* (device), independent of host layout.

        Each controlling process contributes one object on behalf of each
        device it drives (single-controller SPMD: all local ranks hold the
        same host-side object), so reductions over the result scale with
        ``size`` identically on 1×8 and 2×4 host layouts.
        """
        if self.inter_size > 1:
            per_process = self._process_allgather_pickled(obj)
            out = []
            local_counts = self._local_device_counts()
            for host_obj, count in zip(per_process, local_counts):
                out.extend([host_obj] * count)
            return out
        return [obj] * self.size

    def _local_device_counts(self):
        counts = [0] * jax.process_count()
        for d in self._devices:
            counts[getattr(d, "process_index", 0)] += 1
        return counts

    def _process_allgather_pickled(self, obj):
        """Allgather arbitrary Python objects across processes.

        Primary path: the coordination-service KV channel (host data never
        enters XLA — the reference's object channel was likewise pure MPI,
        SURVEY §2.7).  Fallback (no coordination service, e.g. some
        multi-host TPU runtimes bootstrapped externally): length-padded
        pickled byte arrays over ``multihost_utils.process_allgather``.
        """
        ch = self._host_channel()
        if ch is not None:
            return ch.allgather(obj)
        import pickle
        from jax.experimental import multihost_utils
        payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
        length = np.asarray([payload.size], dtype=np.int64)
        all_lengths = np.asarray(
            multihost_utils.process_allgather(length)).reshape(-1)
        max_len = int(all_lengths.max())
        padded = np.zeros(max_len, dtype=np.uint8)
        padded[: payload.size] = payload
        gathered = np.asarray(multihost_utils.process_allgather(padded))
        gathered = gathered.reshape(len(all_lengths), max_len)
        return [pickle.loads(gathered[i, : int(all_lengths[i])].tobytes())
                for i in range(len(all_lengths))]

    def allreduce_obj(self, obj):
        gathered = self.allgather_obj(obj)
        out = gathered[0]
        for other in gathered[1:]:
            out = jax.tree.map(lambda a, b: a + b, out, other)
        return out

    # -- model ops ------------------------------------------------------------------
    def bcast_data(self, model):
        """Make parameters explicitly replicated over the communicator mesh.

        In single-controller JAX, replication is a *sharding property*, not
        a message: this places every param/persistent array with a
        replicated ``NamedSharding`` so later sharded programs consume them
        without re-layout.  Multi-host agreement is handled by the runtime
        (same bytes on every host by construction of the program).
        """
        sharding = NamedSharding(self.mesh, P())
        for param in model.params():
            if param.array is not None:
                param.array = jax.device_put(param.array, sharding)
        from ..core.link import _persistent_slots
        for sublink, name, _ in _persistent_slots(model):
            value = getattr(sublink, name)
            if value is not None and not np.isscalar(value) \
                    and not isinstance(value, (int, float)):
                placed = jax.device_put(jnp.asarray(value), sharding)
                object.__setattr__(sublink, name, placed)
                sublink._persistent[name] = placed
        return model

    def multi_node_mean_grad(self, model, zero_fill=False):
        """Average per-rank gradients stored on the model (eager path).

        Grad layout contract (single-controller translation of "each rank
        holds its own grads"): a stacked gradient with leading axis ``size``
        (``grad.shape == (size,) + param.shape``) is averaged over that
        axis; an unstacked gradient is already global and is left as-is
        (÷1).  The *compiled* path — the one benchmarks use — is the
        ``grad_transform`` this communicator hands to the multi-node
        optimizer, where the same mean runs as an in-step ``pmean``.
        """
        named = [(path, p) for path, p in model.namedparams()
                 if p.array is not None]
        grads = {}
        for path, p in named:
            if p.grad is None:
                if zero_fill:
                    grads[path] = jnp.zeros((self.size,) + p.array.shape,
                                            p.array.dtype)
                else:
                    continue
            else:
                grads[path] = p.grad
        if not grads:
            return
        reduced = self._mean_grads_eager(grads, {path: p.array.shape
                                                 for path, p in named})
        for path, p in named:
            if path in reduced:
                p.grad = reduced[path]

    def _mean_grads_eager(self, grads, shapes):
        key = tuple(sorted((path, g.shape, str(g.dtype))
                           for path, g in grads.items()))
        fn = self._jit_cache.get(("mean_eager", key))
        if fn is None:
            size = self.size
            from ._memory_utility import is_quantized_dtype
            # quantization is a WIRE property (scale+codebook, not a
            # cast): the eager host-mode mean stays lossless
            dtype = None if is_quantized_dtype(self.allreduce_grad_dtype) \
                else self.allreduce_grad_dtype
            stacked = {path: (g.ndim == len(shapes[path]) + 1
                              and g.shape[0] == size
                              and tuple(g.shape[1:]) == tuple(shapes[path]))
                       for path, g in grads.items()}

            @jax.jit
            def fn(grads):
                out = {}
                for path, g in grads.items():
                    orig = g.dtype
                    if dtype is not None:
                        g = g.astype(dtype)
                    if stacked[path]:
                        g = jnp.mean(g, axis=0)
                    out[path] = g.astype(orig)
                return out

            self._jit_cache[("mean_eager", key)] = fn
        return fn(grads)

    # -- in-step gradient transform (the hot path) ---------------------------------
    @property
    def exchange(self):
        """Canonical name of this communicator's gradient-exchange
        structure: ``"per_leaf"`` | ``"flat"`` | ``"bucketed"`` (the
        vocabulary tools/comm_budgets.json and bench rows use)."""
        if self.batch_collectives == "bucketed":
            return "bucketed"
        return "flat" if self.batch_collectives else "per_leaf"

    @property
    def topology(self):
        """``"striped"`` (multi-path ici ∥ dcn exchange, ISSUE 11),
        ``"hierarchical"`` (strict two-level ici × dcn exchange) or
        ``"flat"`` (one mesh axis) — the topology column bench rows and
        the census carry, orthogonal to :attr:`exchange` (bucketing
        composes with any topology)."""
        if self.hierarchy is None:
            return "flat"
        return "striped" if self.striped else "hierarchical"

    @property
    def striped(self):
        """True when the gradient exchange stripes each bucket across
        BOTH fabrics concurrently (ISSUE 11): a hierarchical mesh with
        a nonzero :attr:`stripe_ratio`.  Ratio 0 is the strict
        hierarchical schedule — the degenerate collapse
        ``stripe_plan`` pins."""
        return self.hierarchy is not None and self.stripe_ratio > 0

    # -- self-tuning (ISSUE 19) --------------------------------------------
    def _clone_kwargs(self):
        """Constructor kwargs that rebuild THIS communicator (same
        devices, topology, knobs) — the base of :meth:`retuned`'s
        knob-override clone.  Subclasses extend (the elastic variant
        adds members/epoch/channel)."""
        kwargs = dict(devices=list(self._devices),
                      axis_name=self.axis_name,
                      batch_collectives=self.batch_collectives,
                      bucket_mb=self.bucket_mb,
                      name=self.name,
                      error_feedback=self.error_feedback)
        if self.hierarchy is not None:
            kwargs["axis_name"] = self.hierarchy
            kwargs["inter_size"], kwargs["intra_size"] = self._hier_sizes
            if self.allreduce_grad_dtype is not None \
                    or self.dcn_grad_dtype is not None:
                kwargs["allreduce_grad_dtype"] = {
                    "ici": self.allreduce_grad_dtype,
                    "dcn": self.dcn_grad_dtype}
            if self.stripe_ratio > 0:
                kwargs["stripe_ratio"] = self.stripe_ratio
        else:
            kwargs["allreduce_grad_dtype"] = self.allreduce_grad_dtype
        return kwargs

    def retuned(self, plan):
        """Apply an agreed autotune plan: a clone with the plan's knobs
        filled into every knob the caller did NOT hand-set (explicit
        argument or env var — the provenance ``_hand_knobs`` records at
        construction); hand knobs always win.  Returns ``self`` with
        the plan attached when nothing the plan proposes differs from
        the current knobs — the golden-trajectory contract: a plan that
        matches the hand knobs changes no compiled program.

        Collective when it rebuilds (communicator construction is a
        collective point) — safe because the plan itself is agreed
        (bcast from rank 0), so every rank takes the same branch.
        """
        hand = getattr(self, "_hand_knobs", {})
        kwargs = self._clone_kwargs()
        changed = False
        if plan.get("bucket_mb") is not None \
                and not hand.get("bucket_mb") \
                and self.batch_collectives == "bucketed":
            bucket = float(plan["bucket_mb"])
            if bucket != self.bucket_mb:
                kwargs["bucket_mb"] = bucket
                changed = True
        if plan.get("stripe_ratio") is not None \
                and not hand.get("stripe_ratio") \
                and self.hierarchy is not None:
            ratio = float(plan["stripe_ratio"])
            if ratio != self.stripe_ratio:
                kwargs["stripe_ratio"] = ratio
                changed = True
        if plan.get("grad_dtype") is not None \
                and not hand.get("grad_dtype") \
                and self.hierarchy is not None:
            from ._memory_utility import resolve_grad_dtype
            want = {hop: resolve_grad_dtype(dt)
                    for hop, dt in plan["grad_dtype"].items()}
            have = {"ici": self.allreduce_grad_dtype,
                    "dcn": self.dcn_grad_dtype}
            if want != have:
                kwargs["allreduce_grad_dtype"] = dict(plan["grad_dtype"])
                changed = True
        if not changed:
            self.autotune_plan = plan
            return self
        clone = type(self)(**kwargs)
        # provenance and plan CARRY FORWARD: the clone's constructor saw
        # explicit arguments (the applied plan values), which must not
        # read as hand-set on the next re-tune (elastic resizes re-tune
        # through the same path)
        clone._hand_knobs = dict(hand)
        clone._autotune_mode = self._autotune_mode
        clone.autotune_plan = plan
        return clone

    # -- quantized wire (ISSUE 8) ------------------------------------------
    @property
    def quantized(self):
        """True when any hop's wire dtype is a quantized (int8/fp8)
        codebook — the exchanges that carry a per-bucket symmetric
        scale and (with :attr:`error_feedback`) a residual buffer."""
        from ._memory_utility import is_quantized_dtype
        return (is_quantized_dtype(self.allreduce_grad_dtype)
                or is_quantized_dtype(self.dcn_grad_dtype))

    @property
    def quantized_wire_dtype(self):
        """The quantized wire dtype (the slow hop's on hierarchical
        communicators, the world wire on flat ones), or ``None``."""
        from ._memory_utility import is_quantized_dtype
        if self.hierarchy is not None:
            return self.dcn_grad_dtype \
                if is_quantized_dtype(self.dcn_grad_dtype) else None
        return self.allreduce_grad_dtype \
            if is_quantized_dtype(self.allreduce_grad_dtype) else None

    def grad_residual_len(self, shapes, dtypes):
        """LOCAL (per-device) length of the error-feedback residual the
        quantized ``grad_transform`` threads: per bucket, the quantized
        hop's per-device payload — the padded ``1/ici`` chunk on
        hierarchical communicators, the full bucket on flat ones —
        concatenated in plan order.  0 when the wire is not quantized.
        The global residual operand is this × ``size``, sharded by
        :meth:`flat_chunk_spec` (each device owns its slice — the same
        layout, donation, and resume plumbing as the reduce-scatter
        stale chunk)."""
        if self.quantized_wire_dtype is None:
            return 0
        total = 0
        from ._memory_utility import stripe_plan
        for idx in self.grad_buckets(shapes, dtypes):
            elems = sum(int(np.prod(shapes[i])) for i in idx)
            if self.striped:
                # per bucket: the DCN-path slice quantizes the full
                # pre-reduction slice per device, the ICI-path slice
                # quantizes its padded 1/ici chunk (layout: B then A —
                # the schedule's consumption order)
                n_i, n_d = stripe_plan(elems, self.stripe_ratio)
                total += n_d + (-(-n_i // self.ici_size) if n_i else 0)
            elif self.hierarchy is not None:
                intra = self.ici_size
                total += -(-elems // intra)
            else:
                total += elems
        return total

    def grad_residual_len_for(self, model):
        """:meth:`grad_residual_len` over ``model``'s gradient leaves,
        planned exactly like :meth:`grad_buckets_for` (post
        cast-compression, pre quantization) — the one length the hot
        path, the optimizer's zero-seed, and the resume template must
        agree on."""
        from ._memory_utility import is_quantized_dtype
        shapes, dtypes = self.grad_leaf_specs(model)
        if self.allreduce_grad_dtype is not None \
                and not is_quantized_dtype(self.allreduce_grad_dtype):
            dtypes = [self.allreduce_grad_dtype] * len(dtypes)
        return self.grad_residual_len(shapes, dtypes)

    def grad_dcn_stale_len_for(self, model):
        """Length of the DCN-slice-only stale buffer the
        ``double_buffering="dcn"`` variant threads (ISSUE 11): the
        DCN-path slice elements of every bucket, concatenated in plan
        order — the slow path's one-step-stale footprint, a
        ``stripe_ratio`` fraction of a full stale buffer.  0 on
        non-striped communicators."""
        if not self.striped:
            return 0
        from ._memory_utility import is_quantized_dtype, stripe_plan
        shapes, dtypes = self.grad_leaf_specs(model)
        if self.allreduce_grad_dtype is not None \
                and not is_quantized_dtype(self.allreduce_grad_dtype):
            dtypes = [self.allreduce_grad_dtype] * len(dtypes)
        total = 0
        for idx in self.grad_buckets(shapes, dtypes):
            elems = sum(int(np.prod(shapes[i])) for i in idx)
            total += stripe_plan(elems, self.stripe_ratio)[1]
        return total

    def grad_buckets(self, shapes, dtypes):
        """The bucket plan this communicator's ``grad_transform`` traces
        for leaves of the given shapes/dtypes (post dtype-compression):
        list of index lists in emission order.  Exposed so probes/tests
        census the SAME plan the hot path uses."""
        from ._memory_utility import plan_buckets
        if self.exchange == "per_leaf":
            return [[i] for i in reversed(range(len(shapes)))]
        if self.exchange == "flat":
            return [list(reversed(range(len(shapes))))] if shapes else []
        return plan_buckets(shapes, dtypes,
                            int(self.bucket_mb * 2 ** 20))

    @staticmethod
    def grad_leaf_specs(model):
        """``(shapes, dtypes)`` of ``model``'s params in the order
        ``grad_transform`` plans over: the params-tree FLATTEN order
        (sorted dict keys), NOT ``Link.params()`` registration order —
        the two orders yield different plans, so every bucket census
        must extract leaves through this one helper."""
        from ..core.link import extract_state
        leaves = jax.tree.leaves(extract_state(model)["params"])
        return [p.shape for p in leaves], [p.dtype for p in leaves]

    def grad_buckets_for(self, model):
        """The bucket plan ``grad_transform`` traces for ``model``'s
        gradients (leaves in hot-path order, post dtype-compression).
        A QUANTIZED wire dtype does not recast the leaves — quantization
        happens at the wire, so buckets are planned (and bounded) in the
        gradient's own dtype."""
        from ._memory_utility import is_quantized_dtype
        shapes, dtypes = self.grad_leaf_specs(model)
        if self.allreduce_grad_dtype is not None \
                and not is_quantized_dtype(self.allreduce_grad_dtype):
            dtypes = [self.allreduce_grad_dtype] * len(dtypes)
        return self.grad_buckets(shapes, dtypes)

    def grad_transform(self):
        """Return ``grads -> grads`` for use inside a compiled train step.

        Implements the reference's ``allreduce_grad`` data path (SURVEY
        §3.2): optional cast to the compressed dtype (N3), mean-``psum``
        over the communicator axis, cast back.  The collective structure
        follows ``batch_collectives``:

        * ``False`` — one ``pmean`` per leaf (the ``naive`` flavor).
        * ``True`` — gradients flatten into ONE contiguous bucket (the
          ``flat`` flavor, N2): one large transfer, but it cannot start
          until the LAST gradient exists and the update waits for the
          whole round trip.
        * ``"bucketed"`` — K size-bounded buckets (``bucket_mb``) in
          reverse parameter-registration order: the reference pure_nccl
          pipeline's schedulable units.  Early buckets' collectives
          cover late backward compute under XLA's async scheduler, and
          the update of late-registered params can begin before early
          buckets land.

        All three produce bitwise-identical results (``pmean`` is
        elementwise — packing changes the schedule, not the math;
        golden-pinned by tests/core_tests/test_exchange_equivalence.py).
        Packing goes through ``_memory_utility.tree_pack``/``tree_unpack``
        — the one pack/unpack implementation (shared with ZeRO and the
        reduce-scatter update).

        QUANTIZED wires (ISSUE 8): with an int8/fp8
        ``allreduce_grad_dtype`` the returned transform accepts an
        optional ``residual`` second argument (the error-feedback
        buffer) and, when given one, returns ``(grads, new_residual)``
        instead of bare grads — the multi-node optimizer threads it;
        legacy 1-arg callers get inline quantization with the residual
        discarded (error feedback off for that call).
        """
        if self.striped:
            return self._striped_grad_transform()
        if self.hierarchy is not None:
            return self._hierarchical_grad_transform()
        from ._memory_utility import is_quantized_dtype
        if is_quantized_dtype(self.allreduce_grad_dtype):
            return self._quantized_flat_grad_transform()
        axis = self.axis_name
        dtype = self.allreduce_grad_dtype
        comm = self

        def transform(grads):
            from ._memory_utility import tree_pack, tree_unpack
            leaves, treedef = jax.tree.flatten(grads)
            if not leaves:
                return grads
            orig_dtypes = [g.dtype for g in leaves]
            if dtype is not None:
                leaves = [g.astype(dtype) for g in leaves]
            buckets = comm.grad_buckets([g.shape for g in leaves],
                                        [g.dtype for g in leaves])
            out = [None] * len(leaves)
            for k, idx in enumerate(buckets):
                if len(idx) == 1:
                    # single-leaf bucket: skip the pack/unpack reshape
                    # noise (identical math, cleaner program)
                    with _bucket_scope("mn_leaf_pmean", k):
                        out[idx[0]] = lax.pmean(leaves[idx[0]], axis)
                    continue
                with _bucket_scope("mn_bucket_pmean", k):
                    flat, spec = tree_pack([leaves[i] for i in idx])
                    flat = lax.pmean(flat, axis)
                    for i, g in zip(idx, tree_unpack(flat, spec)):
                        out[i] = g
            leaves = [g.astype(d) for g, d in zip(out, orig_dtypes)]
            return jax.tree.unflatten(treedef, leaves)

        return transform

    def _quantized_flat_grad_transform(self):
        """The quantized one-hop exchange (ISSUE 8; also what the
        ``CHAINERMN_TPU_HIERARCHY=flat`` escape hatch collapses a
        quantized-DCN hierarchical communicator onto): per bucket,
        quantize ``v = grads (+ residual)`` with a per-bucket symmetric
        scale, ``all_gather`` the quantized payload + the scale scalar
        over the axis, and dequantize-sum — each rank reconstructs the
        mean from every rank's ``(q, scale)`` pair, so the wire carries
        the quantized fraction of the bytes while the accumulation
        stays f32 (an int8 ``psum`` would overflow at size 2, and ranks
        quantize with DIFFERENT scales — summing codewords is
        meaningless; DynamiQ's gather-then-dequantize shape).

        Error feedback: ``transform(grads, residual)`` adds the
        previous step's residual slice before quantizing and returns
        ``(grads, new_residual)`` with ``new_residual = v − Q(v)`` per
        bucket — the quantization error is carried, not lost, so the
        applied updates telescope to the true gradient sum
        (tests/communicator_tests/test_quantization.py).
        """
        axis = self.axis_name
        size = self.size
        wire = self.allreduce_grad_dtype
        comm = self

        def transform(grads, residual=None):
            from ._memory_utility import (dequantize_sum,
                                          quantize_with_feedback,
                                          tree_pack, tree_unpack)
            if residual is None and comm.error_feedback:
                _warn_inert_error_feedback()
            leaves, treedef = jax.tree.flatten(grads)
            if not leaves:
                return grads if residual is None else (grads, residual)
            orig_dtypes = [g.dtype for g in leaves]
            buckets = comm.grad_buckets([g.shape for g in leaves],
                                        [g.dtype for g in leaves])
            out = [None] * len(leaves)
            new_res = []
            offset = 0
            for k, idx in enumerate(buckets):
                with _bucket_scope("mn_q_bucket_exchange", k):
                    flat, spec = tree_pack([leaves[i] for i in idx])
                    n = flat.shape[0]
                    r = None
                    if residual is not None:
                        r = residual[offset:offset + n]
                        offset += n
                    q, scale, nr = quantize_with_feedback(flat, r, wire)
                    if nr is not None:
                        new_res.append(nr)
                    qg = lax.all_gather(q, axis)
                    sg = lax.all_gather(scale, axis)
                    mean = dequantize_sum(qg, sg) / size
                    for i, g in zip(idx, tree_unpack(mean, spec)):
                        out[i] = g
            leaves = [g.astype(d) for g, d in zip(out, orig_dtypes)]
            grads = jax.tree.unflatten(treedef, leaves)
            if residual is None:
                return grads
            return grads, jnp.concatenate(new_res)

        return transform

    def _hierarchical_grad_transform(self):
        """The two-level exchange (ISSUE 6): per bucket, intra-host
        ``psum_scatter`` over ICI → inter-host allreduce over DCN on the
        1/ici chunk → intra-host ``all_gather`` over ICI.  DCN — the hop
        that is an order of magnitude slower on a real pod — only ever
        carries ``1/ici_size`` of the gradient bytes.

        Emission follows ``_memory_utility.hop_schedule`` literally:
        each bucket's DCN collective is issued right after its ICI
        reduce-scatter (in reverse-registration plan order, so the
        first bucket backward closes reaches the slow wire first), and
        ALL DCN ops precede ALL ICI all-gathers — the slow hop starts
        as early as dataflow allows and the fast-hop rebuilds overlap
        the remaining DCN traffic (the hop-overlap schedule HiCCL and
        the multi-process-per-GPU allreduce paper measure; pinned by
        the ordered census in tests/test_comm_budget.py).

        Per-hop compression: ``allreduce_grad_dtype`` casts the leaves
        for the ICI hop (as on the flat path); ``dcn_grad_dtype`` —
        ``allreduce_grad_dtype={"dcn": ...}`` — additionally compresses
        only the chunk crossing DCN, so ICI stays lossless while the
        slow hop's bytes halve (the first brick of ROADMAP item 2).
        The mean divide happens once, on the 1/ici chunk (fewer flops,
        same math).

        QUANTIZED DCN (ISSUE 8, the second brick): an int8/fp8
        ``dcn_grad_dtype`` replaces the chunk ``psum`` with
        quantize → ``all_gather(q + scale)`` over DCN →
        dequantize-sum: ranks quantize with their OWN per-bucket scale
        (computed on the reduce-scattered chunk), so summing codewords
        is impossible — each rank reconstructs the sum from every
        group's ``(q, scale)`` instead, and the slow wire carries the
        quantized fraction of the bytes.  With ``transform(grads,
        residual)`` the quantization error is fed back (per bucket, per
        device) and the call returns ``(grads, new_residual)``.
        """
        ici, dcn = self.ici_axis, self.dcn_axis
        intra = self.ici_size
        size = self.size
        dtype = self.allreduce_grad_dtype
        dcn_dtype = self.dcn_grad_dtype
        from ._memory_utility import is_quantized_dtype
        q_dcn = is_quantized_dtype(dcn_dtype)
        comm = self

        def transform(grads, residual=None):
            from ._memory_utility import (dequantize_sum, hop_schedule,
                                          pad_to_multiple,
                                          quantize_with_feedback,
                                          tree_pack, tree_unpack)
            if residual is None and q_dcn and comm.error_feedback:
                _warn_inert_error_feedback()
            leaves, treedef = jax.tree.flatten(grads)
            if not leaves:
                return grads if residual is None else (grads, residual)
            orig_dtypes = [g.dtype for g in leaves]
            if dtype is not None:
                leaves = [g.astype(dtype) for g in leaves]
            buckets = comm.grad_buckets([g.shape for g in leaves],
                                        [g.dtype for g in leaves])
            out = [None] * len(leaves)
            specs = {}
            chunks = {}
            new_res = {}
            offset = 0
            for op, b in hop_schedule(len(buckets)):
                idx = buckets[b]
                if op == "ici_reduce_scatter":
                    with _bucket_scope("mn_hier_rs_ici", b):
                        flat, spec = tree_pack([leaves[i] for i in idx])
                        flat, n_true = pad_to_multiple(flat, intra)
                        specs[b] = (spec, n_true)
                        chunks[b] = lax.psum_scatter(
                            flat, ici, scatter_dimension=0, tiled=True)
                elif op == "dcn_exchange" and q_dcn:
                    with _bucket_scope("mn_hier_quantized_dcn", b):
                        c = chunks[b]
                        wire = c.dtype
                        n = c.shape[0]
                        r = None
                        if residual is not None:
                            r = residual[offset:offset + n]
                            offset += n
                        q, scale, nr = quantize_with_feedback(
                            c, r, dcn_dtype)
                        if nr is not None:
                            new_res[b] = nr
                        qg = lax.all_gather(q, dcn)
                        sg = lax.all_gather(scale, dcn)
                        chunks[b] = (dequantize_sum(qg, sg)
                                     / size).astype(wire)
                elif op == "dcn_exchange":
                    with _bucket_scope("mn_hier_allreduce_dcn", b):
                        c = chunks[b]
                        wire = c.dtype
                        if dcn_dtype is not None:
                            c = c.astype(dcn_dtype)
                        c = lax.psum(c, dcn)
                        chunks[b] = c.astype(wire) / size
                else:  # ici_all_gather
                    with _bucket_scope("mn_hier_ag_ici", b):
                        full = lax.all_gather(chunks[b], ici, tiled=True)
                    spec, n_true = specs[b]
                    for i, g in zip(idx, tree_unpack(full[:n_true], spec)):
                        out[i] = g
            leaves = [g.astype(d) for g, d in zip(out, orig_dtypes)]
            grads = jax.tree.unflatten(treedef, leaves)
            if residual is None:
                return grads
            return grads, jnp.concatenate(
                [new_res[b] for b in range(len(buckets))])

        return transform

    def _striped_grad_transform(self):
        """The multi-path striped exchange (ISSUE 11): each bucket's
        flat payload splits by ``stripe_plan(n, stripe_ratio)`` into an
        ICI-path slice and a DCN-path slice, and BOTH fabrics carry
        bulk traffic at once instead of hierarchically (FlexLink's
        use-every-link-simultaneously result; HiCCL-style compositional
        schedule — the plan is the pure function
        ``hop_schedule(k, mode="striped")`` and emission follows it
        literally).

        * **ICI path** (share ``1 − ratio``): the PR 6 fast-hop-major
          exchange — ``psum_scatter`` over ICI → chunk allreduce over
          DCN (per-hop dtype / int8+EF quantization apply here exactly
          as on the hierarchical exchange) → ``all_gather`` over ICI.
        * **DCN path** (share ``ratio``): the TRANSPOSED slow-hop-major
          exchange — ``psum_scatter`` over DCN (the bulk rides the slow
          wire, compressed under the per-hop dtype) → chunk allreduce
          over ICI (lossless by design: the chunk upcasts to f32 before
          the fast hop) → ``all_gather`` over DCN.  With a QUANTIZED
          ``dcn_grad_dtype`` the slow wire cannot carry a psum_scatter
          of codewords, so the path reshapes to lossless ``psum`` over
          ICI first, then quantize (+ error feedback) →
          ``all_gather(q + scale)`` over DCN → dequantize-sum — the
          DynamiQ gather shape on the slice's single slow crossing.

        Both paths' scatter+exchange ops are emitted before ANY
        bucket's gather epilogue (the generalized hop_schedule
        contract), so XLA's async scheduler can drain the two fabrics
        concurrently.

        ``stale_dcn`` (the DCN-slice-only double-buffering variant,
        ``double_buffering="dcn"``): the assembled gradient uses the
        PREVIOUS step's DCN-path results while this step's fresh
        DCN-path values are returned (appended last) to become the next
        stale buffer — the PR 5/6 one-step-stale contract applied
        per-path, hiding the slow path's latency entirely behind
        compute while the ICI path stays fresh.  Return shape:
        ``grads`` | ``(grads, new_residual)`` | ``(grads, fresh_dcn)``
        | ``(grads, new_residual, fresh_dcn)`` depending on which
        optional operands were threaded.
        """
        ici, dcn = self.ici_axis, self.dcn_axis
        intra, inter = self.ici_size, self.dcn_size
        size = self.size
        ratio = self.stripe_ratio
        dtype = self.allreduce_grad_dtype
        dcn_dtype = self.dcn_grad_dtype
        from ._memory_utility import is_quantized_dtype
        q_dcn = is_quantized_dtype(dcn_dtype)
        comm = self

        def transform(grads, residual=None, stale_dcn=None):
            from ._memory_utility import (dequantize_sum, hop_schedule,
                                          pad_to_multiple,
                                          quantize_with_feedback,
                                          stripe_plan, tree_pack,
                                          tree_unpack)
            if residual is None and q_dcn and comm.error_feedback:
                _warn_inert_error_feedback()
            leaves, treedef = jax.tree.flatten(grads)
            if not leaves:
                out = [grads]
                if residual is not None:
                    out.append(residual)
                if stale_dcn is not None:
                    out.append(stale_dcn)
                return out[0] if len(out) == 1 else tuple(out)
            orig_dtypes = [g.dtype for g in leaves]
            if dtype is not None:
                leaves = [g.astype(dtype) for g in leaves]
            buckets = comm.grad_buckets([g.shape for g in leaves],
                                        [g.dtype for g in leaves])
            # pre-pass: per-bucket split sizes and the residual /
            # stale-buffer offsets (pure python over the plan — the
            # schedule consumes buckets out of offset order, so a
            # running counter cannot work)
            n_i, n_d, chunk_a = [], [], []
            off_a, off_b, off_s = [], [], []
            r_off = s_off = 0
            for idx in buckets:
                n_b = sum(int(np.prod(leaves[i].shape)) for i in idx)
                a, d = stripe_plan(n_b, ratio)
                n_i.append(a)
                n_d.append(d)
                chunk_a.append(-(-a // intra) if a else 0)
                off_a.append(r_off + d)   # residual layout per bucket:
                off_b.append(r_off)       # [B slice, then A chunk] —
                r_off += d + chunk_a[-1]  # consumption order of the
                off_s.append(s_off)       # schedule (dcn path first)
                s_off += d
            out = [None] * len(leaves)
            specs = {}
            a_chunk = {}
            b_chunk = {}
            b_full = {}
            new_res = {}
            fresh_b = {}
            for op, b in hop_schedule(len(buckets), mode="striped"):
                idx = buckets[b]
                if op == "dcn_path_scatter":
                    with _bucket_scope("mn_stripe_pack_scatter_dcn", b):
                        flat, spec = tree_pack([leaves[i] for i in idx])
                        specs[b] = (spec, flat.dtype)
                        a_flat = flat[:n_i[b]]
                        b_slice = flat[n_i[b]:]
                        a_chunk[b] = a_flat  # scattered at ici_path_scatter
                        if not n_d[b]:
                            continue
                        if q_dcn:
                            # quantized slow wire: each device quantizes
                            # its OWN pre-reduction slice (+ its own
                            # error-feedback residual — quantizing after
                            # any cross-device reduce would mix distinct
                            # residuals into codewords that disagree
                            # across the ICI axis and de-replicate the
                            # params), and the slice's single DCN
                            # crossing is this gather of codewords —
                            # issued FIRST in the bucket, so the slow
                            # wire starts as early as possible
                            r = None
                            if residual is not None:
                                r = residual[off_b[b]:off_b[b] + n_d[b]]
                            q, scale, nr = quantize_with_feedback(
                                b_slice, r, dcn_dtype)
                            if nr is not None:
                                new_res[(b, "b")] = nr
                            b_chunk[b] = (lax.all_gather(q, dcn),
                                          lax.all_gather(scale, dcn))
                        else:
                            b_pad, _ = pad_to_multiple(b_slice, inter)
                            if dcn_dtype is not None:
                                b_pad = b_pad.astype(dcn_dtype)
                            b_chunk[b] = lax.psum_scatter(
                                b_pad, dcn, scatter_dimension=0,
                                tiled=True)
                elif op == "ici_path_scatter":
                    if not n_i[b]:
                        continue
                    with _bucket_scope("mn_stripe_rs_ici", b):
                        a_pad, _ = pad_to_multiple(a_chunk[b], intra)
                        a_chunk[b] = lax.psum_scatter(
                            a_pad, ici, scatter_dimension=0, tiled=True)
                elif op == "dcn_path_exchange":
                    if not n_d[b]:
                        continue
                    if q_dcn:
                        with _bucket_scope("mn_stripe_dequant_psum_ici", b):
                            # decode every DCN group's (q, scale) pair,
                            # then finish the reduction across ICI in
                            # f32 — the lossless fast hop, same
                            # contract as the hierarchical exchange
                            qg, sg = b_chunk[b]
                            s = dequantize_sum(qg, sg)
                            b_full[b] = lax.psum(s, ici) / size
                    else:
                        with _bucket_scope("mn_stripe_allreduce_ici", b):
                            # the DCN-path chunk's cross-fabric
                            # allreduce rides the LOSSLESS fast hop:
                            # upcast to f32 before accumulating
                            c = lax.psum(
                                b_chunk[b].astype(jnp.float32), ici)
                            b_chunk[b] = c / size
                elif op == "ici_path_exchange":
                    if not n_i[b]:
                        continue
                    c = a_chunk[b]
                    wire = c.dtype
                    if q_dcn:
                        with _bucket_scope("mn_stripe_quantized_chunk", b):
                            n = c.shape[0]
                            r = None
                            if residual is not None:
                                r = residual[off_a[b]:off_a[b] + n]
                            q, scale, nr = quantize_with_feedback(
                                c, r, dcn_dtype)
                            if nr is not None:
                                new_res[(b, "a")] = nr
                            qg = lax.all_gather(q, dcn)
                            sg = lax.all_gather(scale, dcn)
                            a_chunk[b] = (dequantize_sum(qg, sg)
                                          / size).astype(wire)
                    else:
                        with _bucket_scope("mn_stripe_allreduce_dcn", b):
                            if dcn_dtype is not None:
                                c = c.astype(dcn_dtype)
                            c = lax.psum(c, dcn)
                            a_chunk[b] = c.astype(wire) / size
                elif op == "dcn_path_gather":
                    if not n_d[b] or q_dcn:
                        continue  # quantized path is already full
                    with _bucket_scope("mn_stripe_ag_dcn", b):
                        c = b_chunk[b]
                        if dcn_dtype is not None:
                            c = c.astype(dcn_dtype)
                        full = lax.all_gather(c, dcn, tiled=True)
                        b_full[b] = full[:n_d[b]].astype(jnp.float32)
                else:  # ici_path_gather: rebuild + assemble the bucket
                    spec, wire = specs[b]
                    parts = []
                    if n_i[b]:
                        with _bucket_scope("mn_stripe_ag_ici", b):
                            full = lax.all_gather(a_chunk[b], ici,
                                                  tiled=True)
                        parts.append(full[:n_i[b]].astype(wire))
                    if n_d[b]:
                        fresh = b_full[b].astype(wire)
                        if stale_dcn is not None:
                            fresh_b[b] = fresh.astype(jnp.float32)
                            applied = stale_dcn[
                                off_s[b]:off_s[b] + n_d[b]].astype(wire)
                        else:
                            applied = fresh
                        parts.append(applied)
                    flat = parts[0] if len(parts) == 1 \
                        else jnp.concatenate(parts)
                    for i, g in zip(idx, tree_unpack(flat, spec)):
                        out[i] = g
            leaves = [g.astype(d) for g, d in zip(out, orig_dtypes)]
            grads = jax.tree.unflatten(treedef, leaves)
            ret = [grads]
            if residual is not None:
                res_parts = []
                for b in range(len(buckets)):
                    if (b, "b") in new_res:
                        res_parts.append(new_res[(b, "b")])
                    if (b, "a") in new_res:
                        res_parts.append(new_res[(b, "a")])
                ret.append(jnp.concatenate(res_parts) if res_parts
                           else residual)
            if stale_dcn is not None:
                ret.append(jnp.concatenate(
                    [fresh_b[b] for b in range(len(buckets))
                     if b in fresh_b]) if fresh_b else stale_dcn)
            return ret[0] if len(ret) == 1 else tuple(ret)

        return transform

    # -- SPMD launcher ----------------------------------------------------------------
    def run_spmd(self, fn, *args, in_specs=None, out_specs=None,
                 static_out=False):
        """Run ``fn`` as a ``shard_map``ped program over this communicator's
        axis: rank-local code with this communicator's methods emitting real
        collectives.  Default specs: every arg/result is stacked on its
        leading axis (one slice per rank); pass ``P()`` in ``in_specs``/
        ``out_specs`` for replicated values.
        """
        from chainermn_tpu.utils.compat import shard_map
        axis = self.axis_name
        if self._axis_in_scope():
            # already inside a shard_map binding this axis (e.g. the
            # plain optimizer's SPMD step wraps the whole train step):
            # args are rank-local; run the rank-local body directly —
            # nesting another shard_map over the same axis is an error
            return fn(*args)
        if in_specs is None:
            in_specs = tuple(P(axis) for _ in args)
        if out_specs is None:
            out_specs = P(axis)
        mapped = shard_map(fn, mesh=self.mesh, in_specs=in_specs,
                           out_specs=out_specs, check_vma=False)
        if _is_traced(args):
            # inside an outer jit/grad trace — inline the shard_mapped
            # computation.  NOTE: the outer jit must be mesh-aware for
            # this to lower (a single-device jit cannot host an N-device
            # shard_map); Optimizer._make_step handles that by making
            # the whole step a shard_map when the target is SPMD.
            return mapped(*args)
        return jax.jit(mapped)(*args)

    def axis_in_scope(self):
        """Public form of the axis-environment query: True when EVERY
        mesh axis this communicator's collectives address is bound by
        an enclosing ``shard_map`` of the current trace.  The dispatch
        guard model code uses (``models.transformer._axis_bound``,
        ``parallel.moe``) — a hierarchical communicator binds TWO axes
        and a bare ``axis_exists(self.axis_name)`` probe is False for
        the tuple, which is exactly how the MoE layer used to fall
        back to DENSE routing on a two-level mesh without a word
        (ISSUE 12 guard rail)."""
        return self._axis_in_scope()

    def _axis_in_scope(self):
        """True when this communicator's mesh axis is bound by an
        enclosing shard_map of the current trace — an explicit
        axis-environment query (``utils.compat.axis_env_contains``),
        NOT a probe-``lax.axis_index``-and-catch: this check dispatches
        between eager and traced collectives, and exception control
        flow here would silently flip modes under a jax behavior change
        (VERDICT open item 7; pinned by
        ``tests/communicator_tests/test_axis_in_scope.py``).  A
        hierarchical communicator binds TWO axes; both must be in scope
        (a partial binding cannot host the two-level exchange)."""
        from chainermn_tpu.utils.compat import axis_env_contains
        names = self.axis_name if isinstance(self.axis_name, tuple) \
            else (self.axis_name,)
        return all(axis_env_contains(n) for n in names)

    # -- split ------------------------------------------------------------------------
    def split(self, color, key):
        """Partition devices into sub-communicators (reference:
        ``MPI_Comm_Split`` semantics over device ranks).

        ``color``/``key`` follow the per-rank convention: sequences of
        length ``size`` (device rank i gets color[i]); scalars apply the
        same value to every rank (the common "all same group" case).
        Returns the sub-communicator containing the CALLING controller's
        devices (MPI semantics: rank r's ``MPI_Comm_Split`` returns r's
        group).  All of this controller's local devices must share one
        color — a straddling split has no single "my sub-communicator"
        under single-controller SPMD.  The full set is available as
        ``.split_all(color, key)``.
        """
        size = self.size
        colors = [color] * size if np.isscalar(color) else list(color)
        if len(colors) != size:
            raise ValueError("color/key must be scalars or length-size")
        local = [i for i, d in enumerate(self._devices)
                 if getattr(d, "process_index", 0) == jax.process_index()]
        my_colors = {colors[i] for i in (local or [0])}
        if len(my_colors) > 1:
            raise ValueError(
                f"this controller's devices straddle split colors "
                f"{sorted(my_colors)}; use split_all() for the full set")
        my_color = my_colors.pop()
        comms = self.split_all(color, key)
        return comms[sorted(set(colors)).index(my_color)]

    def split_all(self, color, key):
        """All sub-communicators of the split, ordered by sorted color.

        Sub-communicators are FLAT (one axis): an arbitrary color
        partition has no canonical two-level structure, so a
        hierarchical parent's split members drop the (dcn, ici) split —
        rebuild one with ``intra_size=``/``inter_size=`` if a subgroup
        spans hosts and needs it.  A hierarchical parent's per-hop
        compression degrades onto the subgroup's single hop — the DCN
        entry wins (slow-hop intent), else the ICI entry (the same
        keep-the-bytes-low convention as the
        ``CHAINERMN_TPU_HIERARCHY=flat`` escape hatch) — never silently
        to lossless."""
        size = self.size
        colors = [color] * size if np.isscalar(color) else list(color)
        keys = [key] * size if np.isscalar(key) else list(key)
        if len(colors) != size or len(keys) != size:
            raise ValueError("color/key must be scalars or length-size")
        base = self.axis_name if isinstance(self.axis_name, str) \
            else "_".join(self.axis_name)
        groups = {}
        for i, (c, k) in enumerate(zip(colors, keys)):
            groups.setdefault(c, []).append((k, i))
        comms = []
        for c in sorted(groups):
            members = [i for _, i in sorted(groups[c])]
            comms.append(MeshCommunicator(
                devices=[self._devices[i] for i in members],
                axis_name=f"{base}_s{c}",
                allreduce_grad_dtype=(
                    self.dcn_grad_dtype or self.allreduce_grad_dtype
                    if self.hierarchy is not None
                    else self.allreduce_grad_dtype),
                batch_collectives=self.batch_collectives,
                bucket_mb=self.bucket_mb,
                error_feedback=self.error_feedback,
                # a hierarchical name would re-trigger the two-level
                # split on the subgroup's arbitrary device subset
                name="jax_ici" if self.hierarchy is not None
                else self.name))
        return comms

    # -- diagnostics --------------------------------------------------------------------
    def __repr__(self):
        topo = (f" hierarchy={self.dcn_size}x{self.ici_size}"
                if self.hierarchy is not None else "")
        if self.striped:
            topo += f" stripe_ratio={self.stripe_ratio}"
        return (f"<{type(self).__name__} name={self.name!r} size={self.size} "
                f"axis={self.axis_name!r}{topo} "
                f"grad_dtype={self.allreduce_grad_dtype}>")

    def _check_stacked(self, x, what):
        if x.ndim == 0 or x.shape[0] != self.size:
            raise ValueError(
                f"eager {what} expects a stacked array with leading axis "
                f"size={self.size} (one slice per rank); got shape {x.shape}. "
                f"Inside compiled steps (run_spmd) pass the rank-local value.")


class ElasticMeshCommunicator(MeshCommunicator):
    """A :class:`MeshCommunicator` over the LIVE subset of controller
    processes (ISSUE 10 — the rebuilt transport after an elastic
    shrink/grow).

    ``members`` are GLOBAL controller ranks (the stable process
    identities membership decides over); the communicator maps them to
    dense slots 0..n-1 for collective addressing — ``rank`` /
    ``inter_rank`` are the SLOT, ``stable_rank`` keeps the global
    identity (checkpoint filenames key off it, so a process re-reads
    its OWN snapshots across any number of resizes).  ``epoch`` is the
    membership epoch the member set was decided at; the mesh axis name
    and the object-channel namespace are both epoch-suffixed, so a
    rebuilt incarnation can never match a dead one's compiled programs
    or stranded KV keys.

    Construction is COLLECTIVE over the members (every live member
    builds the communicator for the same view, lock-step — the elastic
    supervisor's rebuild step guarantees this); a dead peer is, by
    definition of the view, not required.

    ``channel`` (optional): the previous incarnation's
    :class:`~._host_channel.HostChannel`, donated as a template — its
    client and timeout/retry knobs carry over to the members-only
    sub-channel.  ``devices``: explicit device list override (the
    single-controller simulated-elasticity knob tier-1 uses — shrink a
    world of local devices without any real process leaving).
    """

    def __init__(self, members, epoch=0, channel=None, devices=None,
                 axis_name=None, **kwargs):
        members = tuple(sorted(int(m) for m in members))
        if not members:
            raise ValueError("an elastic communicator needs >= 1 member")
        self.members = members
        self.epoch = int(epoch)
        me = jax.process_index()
        if jax.process_count() > 1 and me not in members:
            raise ValueError(
                f"process {me} is not in the elastic view {members}; "
                f"non-members must re-join through the membership "
                f"protocol before constructing the communicator")
        self._member_slot = members.index(me) if me in members else 0
        self._stable_rank = me
        # the members-only object channel must exist BEFORE the base
        # constructor runs (its intra-topology allgather is the first
        # collective of the new incarnation)
        self._elastic_channel = self._derive_channel(channel)
        if devices is None:
            by_proc = {}
            for d in jax.devices():
                by_proc.setdefault(getattr(d, "process_index", 0),
                                   []).append(d)
            devices = [d for m in members
                       for d in sorted(by_proc.get(m, ()),
                                       key=lambda d: d.id)]
            if not devices:
                raise ValueError(
                    f"no devices owned by members {members}")
        if axis_name is None:
            axis_name = f"elastic_e{self.epoch}"
        super().__init__(devices=devices, axis_name=axis_name, **kwargs)

    def _derive_channel(self, template):
        """Members-only sub-channel: same client and tolerance knobs as
        the template, namespace scoped by membership epoch (keys of any
        other incarnation can never match), process ids remapped to the
        view's dense slots."""
        from ._host_channel import HostChannel, get_host_channel
        if template is None:
            template = get_host_channel()
        if template is None or len(self.members) <= 1:
            # single live controller (or no coordination service): the
            # object channel degenerates to loopback like any
            # single-process run
            return None
        ns_root = template._ns.split("/el", 1)[0]
        return HostChannel(
            namespace=f"{ns_root}/el{self.epoch}",
            client=template._client,
            chunk_bytes=template._chunk,
            timeout_ms=template._timeout_ms,
            op_timeouts=dict(template._op_timeouts),
            max_retries=template.max_retries,
            backoff_base_s=template.backoff_base_s,
            backoff_max_s=template.backoff_max_s,
            clock=template._clock, sleep=template._sleep,
            process_id=self._member_slot,
            num_processes=len(self.members))

    def _host_channel(self):
        return self._elastic_channel

    def _clone_kwargs(self):
        # a retuned elastic clone is the SAME incarnation (same members,
        # same epoch, same channel template) with different exchange
        # knobs — the epoch-suffixed axis name already rides in via the
        # base kwargs, so the re-tuned plan artifact is per-epoch
        kwargs = super()._clone_kwargs()
        kwargs["members"] = self.members
        kwargs["epoch"] = self.epoch
        kwargs["channel"] = self._elastic_channel
        return kwargs

    # -- topology: slots for collectives, stable ids for identity ----------
    @property
    def rank(self):
        return self._member_slot

    @property
    def inter_rank(self):
        return self._member_slot

    @property
    def inter_size(self):
        return len(self.members)

    @property
    def stable_rank(self):
        """This process's GLOBAL controller rank — invariant across
        resizes (snapshot filenames and membership announcements key
        off it, never off the per-view slot)."""
        return self._stable_rank

    def _local_device_counts(self):
        # base indexes by jax process id over process_count slots; the
        # elastic view has len(members) slots keyed by member order
        slot = {m: i for i, m in enumerate(self.members)}
        counts = [0] * len(self.members)
        for d in self._devices:
            counts[slot[getattr(d, "process_index", 0)]] += 1
        return counts

    def _process_allgather_pickled(self, obj):
        # NEVER fall back to multihost_utils.process_allgather: that
        # path spans every BOOT process, and an elastic world exists
        # precisely because some of them are gone — the fallback would
        # hang on the dead peers.  Members-only channel, or loopback.
        ch = self._host_channel()
        if ch is not None:
            return ch.allgather(obj)
        return [obj]

    def __repr__(self):
        return (f"<ElasticMeshCommunicator epoch={self.epoch} "
                f"members={self.members} size={self.size} "
                f"axis={self.axis_name!r}>")
