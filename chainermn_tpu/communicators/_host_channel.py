"""Cross-process host-side object channel over the coordinator KV store.

Reference: the pickled-object MPI transport in
``chainermn/communicators/mpi_communicator_base.py · send_obj/recv_obj/
bcast_obj/allgather_obj`` (SURVEY.md §2.7 "object channel: pickle over
MPI, chunked at ~256 MiB").  The TPU-native control plane is
``jax.distributed``'s coordination service; its key-value store plays
MPI's host-data role (SURVEY §2.5 N4).  Tensors never travel here — the
data plane is XLA collectives over ICI/DCN.

Design:

* Values are pickled bytes, chunked (default 1 MiB — the coordination
  service rides gRPC, whose default message cap is 4 MiB; the chunk size
  is a knob for parity with the reference's ``max_buf_len``).
* Point-to-point messages are sequenced per ``(src, dst, tag)`` on both
  ends, so repeated sends match repeated recvs in order, exactly like
  matched MPI send/recv pairs.
* Collective-style helpers (``allgather``/``bcast``/``barrier``) are
  epoch-counted: SPMD lock-step call order is the correctness contract,
  the same invariant the reference inherits from MPI.
* Keys are deleted by their *reader(s)* once consumed (last reader for
  collectives) — and, since the resilience pass, by their *writer* in a
  ``finally`` when a collective fails partway, so an exception can never
  strand chunk/seq keys that would poison the next matched op.

Failure semantics (see ``docs/resilience.md``):

* Every op runs under a **per-op deadline** (``op_timeouts``) with
  **bounded retry + exponential backoff** for transient transport
  errors; exhaustion raises :class:`ChannelTimeoutError` (typed, carries
  op + key) instead of a bare runtime error after one flat 600 s wait.
* An optional **heartbeat monitor** posts this process's liveness to the
  store and audits peers' beats while blocked in a get, converting a
  peer-stall hang into :class:`PeerLostError` carrying the suspected
  rank — the detection half of the fail-stop contract.
* All keys live under a **generation** prefix; ``bump_generation()``
  (called by the recovery supervisor) rotates it and re-arms sequence/
  epoch counters, so keys stranded by a fault can never match ops issued
  by the recovered incarnation.
* **Fault hook points** (``set_fault_hook``) let the chaos harness
  inject transport faults — lost chunk, stale meta key, straggle,
  transient raise — at the exact put/get/barrier sites a real multi-host
  failure would hit, without a real multi-host run.
"""

from __future__ import annotations

import pickle
import threading
import time

__all__ = ["HostChannel", "HeartbeatMonitor", "get_host_channel",
           "reset_host_channel", "ChannelError", "ChannelTimeoutError",
           "PeerLostError"]

_DEFAULT_CHUNK = 1 << 20  # 1 MiB
_DEFAULT_TIMEOUT_MS = 600_000


class ChannelError(RuntimeError):
    """Base class for typed host-channel transport failures."""


class ChannelTimeoutError(ChannelError):
    """An op exhausted its deadline/retry budget.  Carries op and key."""

    def __init__(self, op, key, timeout_ms, attempts):
        self.op = op
        self.key = key
        self.timeout_ms = timeout_ms
        self.attempts = attempts
        super().__init__(
            f"host-channel {op!r} timed out on {key!r} after "
            f"{attempts} attempt(s) within {timeout_ms} ms")


class PeerLostError(ChannelError):
    """A peer's heartbeat went stale while we were blocked on it."""

    def __init__(self, rank, stale_s):
        self.rank = rank
        self.stale_s = stale_s
        super().__init__(
            f"peer process {rank} presumed lost: heartbeat stale for "
            f"{stale_s:.1f}s")


def _kv_client():
    """The process's coordination-service client, or None single-process."""
    try:
        from jax._src import distributed
        return distributed.global_state.client
    except Exception:
        return None


class HeartbeatMonitor:
    """Liveness over the KV store: each process posts a beat token under
    its rank; ``check()`` raises :class:`PeerLostError` for a peer whose
    token has not *changed* for longer than ``stall_s``.

    Staleness is measured entirely on the observer's clock — the time
    since this process last saw the peer's token change — never by
    differencing two hosts' wall clocks, so cross-host clock skew cannot
    fabricate a lost peer.

    A peer that has *never* beaten is not accused — processes may enable
    heartbeats at different times, and absence of the key is
    indistinguishable from "not enabled".  Detection therefore needs one
    observed beat from the peer, after which frozen silence is evidence.

    Without the background ``thread``, beats are only posted from inside
    blocked channel gets — a peer busy in a long compile/compute stretch
    would go stale and be falsely accused.  Production use should keep
    the daemon beater (the ``enable_heartbeat`` default); thread-less
    mode exists for deterministic fake-clock tests, where ``stall_s``
    must exceed the longest legitimate beat gap.
    """

    def __init__(self, channel, interval_s=2.0, stall_s=None,
                 wall=time.time):
        self._ch = channel
        self.interval_s = float(interval_s)
        self.stall_s = float(stall_s) if stall_s is not None \
            else 5.0 * self.interval_s
        self._wall = wall
        self._last_beat = float("-inf")
        self._beat_counter = 0
        self._seen = {}  # rank -> (token, observer-local first-seen time)
        self._thread = None
        self._stop = threading.Event()

    def start_thread(self):
        """Daemon beater: posts liveness every ``interval_s`` regardless
        of what the main thread is doing (compiles, compute), so only a
        truly dead/hung *process* ever goes stale."""
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval_s):
                self.beat(force=True)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="cmn-heartbeat")
        self._thread.start()

    def stop_thread(self):
        if self._thread is not None:
            self._stop.set()
            self._thread.join(timeout=self.interval_s + 1.0)
            self._thread = None

    def _key(self, rank):
        return f"{self._ch._prefix()}/hb/{rank}"

    def beat(self, force=False):
        now = self._wall()
        if not force and now - self._last_beat < self.interval_s:
            return
        self._last_beat = now
        self._beat_counter += 1
        try:
            # the value is an opaque change-token, never compared to any
            # clock: the counter guarantees every beat is a fresh value
            self._ch._client.key_value_set(
                self._key(self._ch.process_id),
                f"{self._beat_counter}:{now!r}")
        except Exception:
            pass  # liveness posting must never take the poster down

    def check(self):
        now = self._wall()
        for rank in range(self._ch.num_processes):
            if rank == self._ch.process_id:
                continue
            try:
                raw = self._ch._client.key_value_try_get(self._key(rank))
            except Exception:
                raw = None
            if raw is None:
                continue
            prev = self._seen.get(rank)
            if prev is None or prev[0] != raw:
                self._seen[rank] = (raw, now)  # fresh token: alive
                continue
            stale = now - prev[1]
            if stale > self.stall_s:
                raise PeerLostError(rank, stale)


class HostChannel:
    """Pickled-object transport between controller processes.

    One instance per (communicator, namespace).  All methods are
    host-side and blocking; they must be called in SPMD lock-step where
    documented (allgather/bcast/barrier), mirroring MPI semantics.

    ``op_timeouts`` maps op families (``"p2p"``, ``"allgather"``,
    ``"bcast"``, ``"barrier"``) to per-op deadlines in ms (default:
    ``timeout_ms``).  ``max_retries``/``backoff_base_s``/``backoff_max_s``
    bound the transient-error retry loop.  ``clock``/``sleep`` are
    injectable for deterministic tests (fake clock).
    """

    def __init__(self, namespace="cmn", client=None,
                 chunk_bytes=_DEFAULT_CHUNK,
                 timeout_ms=_DEFAULT_TIMEOUT_MS,
                 op_timeouts=None, max_retries=3,
                 backoff_base_s=0.05, backoff_max_s=2.0,
                 clock=time.monotonic, sleep=time.sleep,
                 process_id=None, num_processes=None):
        self._client = client if client is not None else _kv_client()
        self._ns = namespace
        self._chunk = int(chunk_bytes)
        self._timeout_ms = int(timeout_ms)
        self._op_timeouts = dict(op_timeouts or {})
        self.max_retries = int(max_retries)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self._clock = clock
        self._sleep = sleep
        self._send_seq = {}
        self._recv_seq = {}
        self._epoch = 0
        self._generation = 0
        self._lock = threading.Lock()
        self._fault_hook = None
        self.monitor = None
        self.stats = {"retries": 0, "timeouts": 0, "cleaned_keys": 0}
        if process_id is not None and num_processes is not None:
            self.process_id = int(process_id)
            self.num_processes = int(num_processes)
        else:
            import jax
            self.process_id = jax.process_index()
            self.num_processes = jax.process_count()

    @property
    def available(self):
        return self._client is not None and self.num_processes > 1

    # -- resilience plumbing -------------------------------------------------
    def _prefix(self):
        return f"{self._ns}/g{self._generation}"

    @property
    def generation(self):
        return self._generation

    def bump_generation(self):
        """Rotate the key namespace after a failure: sequence and epoch
        counters re-arm and stranded keys from the failed incarnation can
        never match ops issued by the recovered one.  Lock-step: every
        surviving process must bump together (the recovery supervisor
        does this before its consensus resume)."""
        with self._lock:
            self._generation += 1
            self._send_seq = {}
            self._recv_seq = {}
            self._epoch = 0
        return self._generation

    def set_fault_hook(self, hook):
        """Install ``hook(event, ctx)`` called at transport hook points
        (``hc.put``, ``hc.chunk``, ``hc.get``, ``hc.barrier``).  The hook
        may raise (transient transport error — exercised against the
        retry loop) or mutate the store through ``ctx['client']``
        (lost-chunk / stale-key faults).  ``None`` uninstalls."""
        self._fault_hook = hook

    def _fault(self, event, **ctx):
        if self._fault_hook is not None:
            ctx.setdefault("client", self._client)
            self._fault_hook(event, ctx)

    def enable_heartbeat(self, interval_s=2.0, stall_s=None, wall=time.time,
                         thread=True):
        """Attach a :class:`HeartbeatMonitor`; blocked gets then audit
        peers' liveness, raising :class:`PeerLostError` on a stalled peer
        instead of hanging to the full deadline.  ``thread=True``
        (default) starts the daemon beater so our own liveness survives
        long compute/compile stretches; pass ``thread=False`` only in
        deterministic fake-clock tests."""
        if self.monitor is not None:  # re-arm: never leak the old beater
            self.monitor.stop_thread()
        self.monitor = HeartbeatMonitor(self, interval_s=interval_s,
                                        stall_s=stall_s, wall=wall)
        self.monitor.beat(force=True)
        if thread:
            self.monitor.start_thread()
        return self.monitor

    def _op_timeout_ms(self, op):
        return int(self._op_timeouts.get(op, self._timeout_ms))

    def _n_chunks(self, payload):
        """Chunk count _put will write for this payload — cleanup paths
        compute it from the bytes in hand (never probed from the meta
        key, which a pre-publish failure never wrote)."""
        return max(1, (len(payload) + self._chunk - 1) // self._chunk)

    def _retrying(self, op, key, fn):
        """Run one transport attempt under the op deadline, absorbing
        transient errors with exponential backoff up to ``max_retries``.

        Non-retriable: :class:`PeerLostError` (the peer is gone — more
        attempts cannot help), the posted-abort RuntimeError (fail-stop
        must win), and the injected
        :class:`~.fault_schedule.RankPreempted` (a reclaimed host does
        not come back within a backoff — the elastic supervisor must
        see it immediately).  Everything else is treated as transient
        until the retry/deadline budget runs out, then surfaces as
        :class:`ChannelTimeoutError` chained to the last failure.
        """
        from .fault_schedule import RankPreempted
        timeout_ms = self._op_timeout_ms(op)
        deadline = self._clock() + timeout_ms / 1000.0
        attempts = 0
        last_exc = None
        while True:
            remaining_ms = int((deadline - self._clock()) * 1000)
            if remaining_ms <= 0 or attempts > self.max_retries:
                self.stats["timeouts"] += 1
                raise ChannelTimeoutError(op, key, timeout_ms,
                                          attempts) from last_exc
            attempts += 1
            try:
                return fn(remaining_ms)
            except (PeerLostError, _AbortedError, RankPreempted):
                raise
            except Exception as e:
                last_exc = e
                if attempts > self.max_retries \
                        or self._clock() >= deadline:
                    continue  # decided: raise above without a dead pause
                self.stats["retries"] += 1
                pause = min(self.backoff_base_s * (2 ** (attempts - 1)),
                            self.backoff_max_s)
                self._sleep(pause)

    # -- low-level chunked put/get ------------------------------------------
    def _put(self, key, payload: bytes, published=None):
        """Chunked write; ``published`` (a mutable list, optional) gains
        an entry the moment the meta key — the publish point — lands, so
        callers can tell a pre-publish failure (rollback safe) from a
        post-publish one (message live; a consumer may already have it)
        WITHOUT probing the store, where a fast reader's key deletion
        would masquerade as never-published."""
        c = self._client
        n_chunks = self._n_chunks(payload)
        for i in range(n_chunks):
            self._fault("hc.chunk", key=key, chunk=i)
            c.key_value_set_bytes(
                f"{key}/c{i}", payload[i * self._chunk:(i + 1) * self._chunk])
        # meta last: its presence means every chunk is readable
        c.key_value_set(f"{key}/meta", f"{n_chunks}:{len(payload)}")
        if published is not None:
            published.append(True)
        self._fault("hc.put", key=key)

    def _blocking_get_or_abort(self, key, timeout_ms):
        """Blocking get that polls the job-abort flag and the heartbeat
        monitor: when a peer's except hook posts an abort (fail-stop,
        SURVEY §5) waiting ranks raise instead of hanging until the full
        timeout — the KV analog of MPI_Abort killing ranks blocked in a
        recv — and a peer whose heartbeat stalls raises
        :class:`PeerLostError` with the suspected rank."""
        c = self._client
        deadline = self._clock() + timeout_ms / 1000.0
        while True:
            reason = None
            try:
                reason = c.key_value_try_get(f"{self._ns}/abort")
            except Exception:
                pass  # no abort posted
            if reason is not None:
                raise _AbortedError(
                    f"distributed job aborted by a peer: {reason}")
            if self.monitor is not None:
                self.monitor.beat()
                self.monitor.check()
            slice_ms = int(min(2000, max(1, (deadline - self._clock())
                                         * 1000)))
            try:
                return c.blocking_key_value_get(key, slice_ms)
            except Exception:
                if self._clock() >= deadline:
                    raise

    def post_abort(self, reason="unknown"):
        """Fail-stop broadcast: unblocks every peer waiting in a channel
        get (they raise) — called by the global except hook.  Posted at
        the namespace root (generation-independent) so it reaches peers
        regardless of which incarnation they are blocked in."""
        try:
            self._client.key_value_set(f"{self._ns}/abort", str(reason))
        except Exception:
            pass

    def clear_abort(self):
        """Recovery-side reset of a posted abort flag (lock-step with
        ``bump_generation`` in the supervisor)."""
        try:
            self._client.key_value_delete(f"{self._ns}/abort")
        except Exception:
            pass

    def _get_once(self, key, timeout_ms):
        c = self._client
        self._fault("hc.get", key=key)
        meta = self._blocking_get_or_abort(f"{key}/meta", timeout_ms)
        n_chunks, total = (int(v) for v in meta.split(":"))
        parts = [c.blocking_key_value_get_bytes(f"{key}/c{i}", timeout_ms)
                 for i in range(n_chunks)]
        return b"".join(parts)[:total], n_chunks

    def _get(self, key, delete=True, op="p2p"):
        payload, n_chunks = self._retrying(op, key, lambda rem:
                                           self._get_once(key, rem))
        if delete:
            self.delete(key, n_chunks)
        return payload

    def delete(self, key, n_chunks=None):
        c = self._client
        try:
            if n_chunks is None:
                meta = c.key_value_try_get(f"{key}/meta")
                n_chunks, _ = (int(v) for v in meta.split(":"))
            for i in range(n_chunks):
                c.key_value_delete(f"{key}/c{i}")
            c.key_value_delete(f"{key}/meta")
            self.stats["cleaned_keys"] += 1
        except Exception:
            pass  # best-effort GC; unread keys die with the coordinator

    # -- point-to-point ------------------------------------------------------
    def send_obj(self, obj, dest_process, tag=0):
        """Chunked pickled send to another controller process (reference:
        ``MpiCommunicatorBase.send_obj``).  Non-blocking wrt the receiver
        (the store buffers), like MPI's eager protocol for small messages."""
        if not 0 <= dest_process < self.num_processes:
            raise ValueError(
                f"dest={dest_process} is not a controller-process rank "
                f"(num_processes={self.num_processes}); host-mode object "
                f"p2p addresses controller processes")
        with self._lock:
            seq = self._send_seq.get((dest_process, tag), 0)
            self._send_seq[(dest_process, tag)] = seq + 1
        key = (f"{self._prefix()}/p2p/{self.process_id}-{dest_process}"
               f"/t{tag}/s{seq}")
        payload = pickle.dumps(obj)
        n_chunks = self._n_chunks(payload)
        published = []
        try:
            self._put(key, payload, published=published)
        except Exception:
            # Rollback ONLY if the message never became visible.  A
            # fault after publish (e.g. an injected hc.put raise) must
            # leave the message alone — the receiver may already have
            # consumed it (deleting the keys, so probing the store here
            # would lie) and advanced its sequence; deleting and
            # re-sequencing would desync the matched stream.
            # Unpublished: scrub the chunks and roll the send sequence
            # back so a retried send re-matches.
            if not published:
                self.delete(key, n_chunks)
                with self._lock:
                    if self._send_seq.get((dest_process, tag)) == seq + 1:
                        self._send_seq[(dest_process, tag)] = seq
            raise

    def recv_obj(self, source_process, tag=0):
        """Blocking matched receive (reference: ``recv_obj``): order per
        (source, tag) is preserved by sequence numbers.  The sequence slot
        is consumed only on success, so a timed-out/aborted receive can be
        retried without desynchronizing the stream."""
        if not 0 <= source_process < self.num_processes:
            raise ValueError(
                f"source={source_process} is not a controller-process rank "
                f"(num_processes={self.num_processes}); host-mode object "
                f"p2p addresses controller processes")
        with self._lock:
            seq = self._recv_seq.get((source_process, tag), 0)
        key = (f"{self._prefix()}/p2p/{source_process}-{self.process_id}"
               f"/t{tag}/s{seq}")
        obj = pickle.loads(self._get(key, op="p2p"))
        with self._lock:
            self._recv_seq[(source_process, tag)] = seq + 1
        return obj

    # -- collectives (SPMD lock-step) ---------------------------------------
    def _next_epoch(self):
        with self._lock:
            self._epoch += 1
            return self._epoch

    def allgather(self, obj):
        """All processes contribute one object; everyone gets the list in
        process order.  Must be entered by every process (lock-step).

        Cleanup contract: this process's contribution (and, best-effort,
        the ``done`` barrier key) is deleted in a ``finally`` — on the
        success path only after the all-read barrier, on the failure path
        immediately, so an exception cannot strand keys that would poison
        the next epoch (or the next generation after recovery)."""
        e = self._next_epoch()
        me = self.process_id
        n = self.num_processes
        prefix = f"{self._prefix()}/ag/{e}"
        payload = pickle.dumps(obj)
        # chunk count computed from the payload, NOT probed from the
        # meta key: a pre-publish put failure never wrote meta, and the
        # cleanup below must still reach the chunks already written
        my_chunks = self._n_chunks(payload)
        try:
            self._put(f"{prefix}/{me}", payload)
            out = [pickle.loads(self._get(f"{prefix}/{i}", delete=False,
                                          op="allgather"))
                   for i in range(n)]
            # all processes must finish reading before anyone deletes
            self._barrier_wait(f"{prefix}/done", op="allgather")
            return out
        finally:
            self.delete(f"{prefix}/{me}", my_chunks)
            self._delete_barrier_key(f"{prefix}/done")

    def bcast(self, obj, root=0):
        """Root's object on every process (lock-step entry).  Root-side
        cleanup of the value key runs in a ``finally`` (see
        :meth:`allgather` for the contract)."""
        e = self._next_epoch()
        prefix = f"{self._prefix()}/bc/{e}"
        if self.process_id == root:
            payload = pickle.dumps(obj)
            my_chunks = self._n_chunks(payload)
            try:
                self._put(f"{prefix}/v", payload)
                out = obj
                self._barrier_wait(f"{prefix}/done", op="bcast")
            finally:
                # chunk count from the payload: cleanup must work even
                # when the put failed before publishing meta
                self.delete(f"{prefix}/v", my_chunks)
                self._delete_barrier_key(f"{prefix}/done")
            return out
        out = pickle.loads(self._get(f"{prefix}/v", delete=False,
                                     op="bcast"))
        self._barrier_wait(f"{prefix}/done", op="bcast")
        return out

    def _barrier_wait(self, barrier_id, op="barrier"):
        self._fault("hc.barrier", key=barrier_id)
        try:
            self._client.wait_at_barrier(barrier_id,
                                         self._op_timeout_ms(op))
        except (_AbortedError, PeerLostError):
            raise
        except Exception as e:
            self.stats["timeouts"] += 1
            raise ChannelTimeoutError(op, barrier_id,
                                      self._op_timeout_ms(op), 1) from e

    def _delete_barrier_key(self, barrier_id):
        # coordination-service barriers are opaque server state; some
        # backends (and the test fake) expose them as plain keys — scrub
        # best-effort so a failed epoch leaves nothing matchable behind
        try:
            self._client.key_value_delete(barrier_id)
        except Exception:
            pass

    def barrier(self, name=None):
        e = self._next_epoch()
        barrier_id = name or f"{self._prefix()}/bar/{e}"
        try:
            self._barrier_wait(barrier_id, op="barrier")
        finally:
            self._delete_barrier_key(barrier_id)


class _AbortedError(RuntimeError):
    """A peer posted the fail-stop abort flag (not retriable)."""


_channel = None
_channel_lock = threading.Lock()


def get_host_channel():
    """Process-global channel (lazy; None when single-process or no
    coordination service)."""
    global _channel
    with _channel_lock:
        if _channel is None:
            ch = HostChannel()
            if not ch.available:
                return None
            _channel = ch
        return _channel


def reset_host_channel():
    """Drop the process-global channel (tests / full teardown), stopping
    its heartbeat beater so the dead incarnation stops posting liveness."""
    global _channel
    with _channel_lock:
        if _channel is not None and _channel.monitor is not None:
            _channel.monitor.stop_thread()
        _channel = None
