"""Cross-process host-side object channel over the coordinator KV store.

Reference: the pickled-object MPI transport in
``chainermn/communicators/mpi_communicator_base.py · send_obj/recv_obj/
bcast_obj/allgather_obj`` (SURVEY.md §2.7 "object channel: pickle over
MPI, chunked at ~256 MiB").  The TPU-native control plane is
``jax.distributed``'s coordination service; its key-value store plays
MPI's host-data role (SURVEY §2.5 N4).  Tensors never travel here — the
data plane is XLA collectives over ICI/DCN.

Design:

* Values are pickled bytes, chunked (default 1 MiB — the coordination
  service rides gRPC, whose default message cap is 4 MiB; the chunk size
  is a knob for parity with the reference's ``max_buf_len``).
* Point-to-point messages are sequenced per ``(src, dst, tag)`` on both
  ends, so repeated sends match repeated recvs in order, exactly like
  matched MPI send/recv pairs.
* Collective-style helpers (``allgather``/``bcast``/``barrier``) are
  epoch-counted: SPMD lock-step call order is the correctness contract,
  the same invariant the reference inherits from MPI.
* Keys are deleted by their *reader(s)* once consumed (last reader for
  collectives), so the store does not grow with training time.
"""

from __future__ import annotations

import pickle
import threading

__all__ = ["HostChannel", "get_host_channel"]

_DEFAULT_CHUNK = 1 << 20  # 1 MiB
_DEFAULT_TIMEOUT_MS = 600_000


def _kv_client():
    """The process's coordination-service client, or None single-process."""
    try:
        from jax._src import distributed
        return distributed.global_state.client
    except Exception:
        return None


class HostChannel:
    """Pickled-object transport between controller processes.

    One instance per (communicator, namespace).  All methods are
    host-side and blocking; they must be called in SPMD lock-step where
    documented (allgather/bcast/barrier), mirroring MPI semantics.
    """

    def __init__(self, namespace="cmn", client=None,
                 chunk_bytes=_DEFAULT_CHUNK,
                 timeout_ms=_DEFAULT_TIMEOUT_MS):
        import jax
        self._client = client if client is not None else _kv_client()
        self._ns = namespace
        self._chunk = int(chunk_bytes)
        self._timeout_ms = int(timeout_ms)
        self._send_seq = {}
        self._recv_seq = {}
        self._epoch = 0
        self._lock = threading.Lock()
        self.process_id = jax.process_index()
        self.num_processes = jax.process_count()

    @property
    def available(self):
        return self._client is not None and self.num_processes > 1

    # -- low-level chunked put/get ------------------------------------------
    def _put(self, key, payload: bytes):
        c = self._client
        n_chunks = max(1, (len(payload) + self._chunk - 1) // self._chunk)
        for i in range(n_chunks):
            c.key_value_set_bytes(
                f"{key}/c{i}", payload[i * self._chunk:(i + 1) * self._chunk])
        # meta last: its presence means every chunk is readable
        c.key_value_set(f"{key}/meta", f"{n_chunks}:{len(payload)}")

    def _blocking_get_or_abort(self, key):
        """Blocking get that polls the job-abort flag: when a peer's
        except hook posts an abort (fail-stop, SURVEY §5), waiting ranks
        raise instead of hanging until the full timeout — the KV analog
        of MPI_Abort killing ranks blocked in a recv."""
        import time
        c = self._client
        deadline = time.monotonic() + self._timeout_ms / 1000.0
        while True:
            reason = None
            try:
                reason = c.key_value_try_get(f"{self._ns}/abort")
            except Exception:
                pass  # no abort posted
            if reason is not None:
                raise RuntimeError(
                    f"distributed job aborted by a peer: {reason}")
            slice_ms = int(min(2000, max(1, (deadline - time.monotonic())
                                         * 1000)))
            try:
                return c.blocking_key_value_get(key, slice_ms)
            except Exception:
                if time.monotonic() >= deadline:
                    raise

    def post_abort(self, reason="unknown"):
        """Fail-stop broadcast: unblocks every peer waiting in a channel
        get (they raise) — called by the global except hook."""
        try:
            self._client.key_value_set(f"{self._ns}/abort", str(reason))
        except Exception:
            pass

    def _get(self, key, delete=True):
        c = self._client
        meta = self._blocking_get_or_abort(f"{key}/meta")
        n_chunks, total = (int(v) for v in meta.split(":"))
        parts = [c.blocking_key_value_get_bytes(f"{key}/c{i}",
                                                self._timeout_ms)
                 for i in range(n_chunks)]
        payload = b"".join(parts)[:total]
        if delete:
            self.delete(key, n_chunks)
        return payload

    def delete(self, key, n_chunks=None):
        c = self._client
        try:
            if n_chunks is None:
                meta = c.key_value_try_get(f"{key}/meta")
                n_chunks, _ = (int(v) for v in meta.split(":"))
            for i in range(n_chunks):
                c.key_value_delete(f"{key}/c{i}")
            c.key_value_delete(f"{key}/meta")
        except Exception:
            pass  # best-effort GC; unread keys die with the coordinator

    # -- point-to-point ------------------------------------------------------
    def send_obj(self, obj, dest_process, tag=0):
        """Chunked pickled send to another controller process (reference:
        ``MpiCommunicatorBase.send_obj``).  Non-blocking wrt the receiver
        (the store buffers), like MPI's eager protocol for small messages."""
        if not 0 <= dest_process < self.num_processes:
            raise ValueError(
                f"dest={dest_process} is not a controller-process rank "
                f"(num_processes={self.num_processes}); host-mode object "
                f"p2p addresses controller processes")
        with self._lock:
            seq = self._send_seq.get((dest_process, tag), 0)
            self._send_seq[(dest_process, tag)] = seq + 1
        key = (f"{self._ns}/p2p/{self.process_id}-{dest_process}"
               f"/t{tag}/s{seq}")
        self._put(key, pickle.dumps(obj))

    def recv_obj(self, source_process, tag=0):
        """Blocking matched receive (reference: ``recv_obj``): order per
        (source, tag) is preserved by sequence numbers.  The sequence slot
        is consumed only on success, so a timed-out/aborted receive can be
        retried without desynchronizing the stream."""
        if not 0 <= source_process < self.num_processes:
            raise ValueError(
                f"source={source_process} is not a controller-process rank "
                f"(num_processes={self.num_processes}); host-mode object "
                f"p2p addresses controller processes")
        with self._lock:
            seq = self._recv_seq.get((source_process, tag), 0)
        key = (f"{self._ns}/p2p/{source_process}-{self.process_id}"
               f"/t{tag}/s{seq}")
        obj = pickle.loads(self._get(key))
        with self._lock:
            self._recv_seq[(source_process, tag)] = seq + 1
        return obj

    # -- collectives (SPMD lock-step) ---------------------------------------
    def _next_epoch(self):
        with self._lock:
            self._epoch += 1
            return self._epoch

    def allgather(self, obj):
        """All processes contribute one object; everyone gets the list in
        process order.  Must be entered by every process (lock-step)."""
        e = self._next_epoch()
        c = self._client
        me = self.process_id
        n = self.num_processes
        prefix = f"{self._ns}/ag/{e}"
        self._put(f"{prefix}/{me}", pickle.dumps(obj))
        out = [pickle.loads(self._get(f"{prefix}/{i}", delete=False))
               for i in range(n)]
        # all processes must finish reading before anyone deletes
        c.wait_at_barrier(f"{prefix}/done", self._timeout_ms)
        self.delete(f"{prefix}/{me}")
        return out

    def bcast(self, obj, root=0):
        """Root's object on every process (lock-step entry)."""
        e = self._next_epoch()
        prefix = f"{self._ns}/bc/{e}"
        c = self._client
        if self.process_id == root:
            self._put(f"{prefix}/v", pickle.dumps(obj))
            out = obj
            c.wait_at_barrier(f"{prefix}/done", self._timeout_ms)
            self.delete(f"{prefix}/v")
        else:
            out = pickle.loads(self._get(f"{prefix}/v", delete=False))
            c.wait_at_barrier(f"{prefix}/done", self._timeout_ms)
        return out

    def barrier(self, name=None):
        e = self._next_epoch()
        self._client.wait_at_barrier(name or f"{self._ns}/bar/{e}",
                                     self._timeout_ms)


_channel = None
_channel_lock = threading.Lock()


def get_host_channel():
    """Process-global channel (lazy; None when single-process or no
    coordination service)."""
    global _channel
    with _channel_lock:
        if _channel is None:
            ch = HostChannel()
            if not ch.available:
                return None
            _channel = ch
        return _channel
