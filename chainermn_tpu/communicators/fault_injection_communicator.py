"""Fault-injection communicator — deterministic chaos at the API surface.

Sibling of ``debug_communicator`` (SURVEY.md §5's structural-mitigation
family): wraps *any* :class:`CommunicatorBase` implementation and
consults a :class:`~.fault_schedule.FaultSchedule` before every named
operation, so a test (or a ``make chaos`` run) can make the Nth
``allreduce`` raise, the 3rd ``send_obj`` vanish, or every ``bcast_obj``
straggle — without a real multi-host failure.

Under multi-controller SPMD the schedule is shared state: every process
builds the same schedule (same specs, same seed) and the lock-step call
order guarantees all processes hit an injected collective fault at the
same call site, which is exactly what a real collective failure looks
like from the trainer (everyone raises, everyone recovers via the
checkpointer's consensus resume — see ``docs/resilience.md``).

Host-side transport faults (lost chunk, stale key, timeout) are injected
one level lower through :func:`bind_host_channel`, which installs a
schedule-driven hook at ``HostChannel``'s put/get/barrier hook points.
"""

from __future__ import annotations

import time

from .communicator_base import CommunicatorBase
from .fault_schedule import FaultSchedule

__all__ = ["FaultInjectionCommunicator", "bind_host_channel"]

# ops consulted against the schedule (everything stateful or collective
# on the CommunicatorBase vocabulary)
_INTERCEPTED = (
    "send", "recv", "bcast", "gather", "allgather", "alltoall", "scatter",
    "allreduce", "multi_node_mean",
    "send_obj", "recv_obj", "bcast_obj", "gather_obj", "allgather_obj",
    "allreduce_obj",
    "bcast_data", "multi_node_mean_grad", "allreduce_grad",
)
# "drop" semantics by op family:
#   value-preserving collectives -> input returned unchanged (a silently
#     no-op collective);
#   sends -> message lost, returns None (the peer's matched receive then
#     exercises the timeout path);
#   everything else (scatter/gather/allgather/alltoall/recv*) has no
#     well-defined silent result -> drop degrades to raise, modeling a
#     failed collective rather than fabricating a wrong-shaped value.
_DROP_RETURNS_INPUT = {
    "bcast", "allreduce", "multi_node_mean", "bcast_obj", "allreduce_obj",
    "bcast_data",
}
_DROP_LOSES_MESSAGE = {"send", "send_obj"}
# payload parameter name per drop-returns-input op, for keyword-invoked
# calls (kwargs insertion order is NOT the signature order)
_PAYLOAD_KW = {"bcast": "data", "allreduce": "data",
               "multi_node_mean": "data", "bcast_obj": "obj",
               "allreduce_obj": "obj", "bcast_data": "model"}


class FaultInjectionCommunicator(CommunicatorBase):
    """Transparent communicator wrapper driven by a fault schedule.

    ``base``: the real communicator.  ``schedule``: a
    :class:`FaultSchedule` (or spec-dict accepted by
    ``FaultSchedule.from_dict``).  ``sleep``: injectable clock for tests
    (``delay`` actions call it).
    """

    def __init__(self, base, schedule, sleep=time.sleep):
        if isinstance(schedule, dict):
            schedule = FaultSchedule.from_dict(schedule)
        self.base = base
        self.schedule = schedule
        if schedule.rank is None:
            # rank-restricted specs (the elastic preempt shape) address
            # communicator ranks; bind the wrapped communicator's rank
            # so the shared schedule fires only on its target.  An
            # explicit pre-bound rank wins (tests drive several ranks'
            # schedules from one process).
            schedule.bind_rank(getattr(base, "rank", None))
        self.hc_schedule = None  # transport-layer clone (factory-bound)
        self._sleep = sleep
        self.injected = 0

    # -- interception core ---------------------------------------------------
    def _maybe_inject(self, op, first_arg=None):
        """Returns (handled, value): handled=True means the op was
        consumed by the fault (value is its replacement result)."""
        fault = self.schedule.on_call(op)
        if fault is None:
            return False, None
        if fault.action == "delay":
            self._sleep(fault.spec.delay_s)
            return False, None  # delayed, then executes normally
        self.injected += 1
        if fault.action == "drop":
            if op in _DROP_RETURNS_INPUT:
                return True, first_arg
            if op in _DROP_LOSES_MESSAGE:
                return True, None
        # raise, preempt (a typed RankPreempted — the elastic
        # supervisor's leave cue, hard fail-stop otherwise),
        # drop-without-a-well-defined-silent-result, and the
        # transport-flavored actions (lost_chunk/stale_key only have
        # meaning inside the host channel — bind_host_channel) all
        # surface as the injected exception
        raise fault.make_exception()

    # -- topology (pure delegation) -----------------------------------------
    rank = property(lambda self: self.base.rank)
    size = property(lambda self: self.base.size)
    intra_rank = property(lambda self: self.base.intra_rank)
    intra_size = property(lambda self: self.base.intra_size)
    inter_rank = property(lambda self: self.base.inter_rank)
    inter_size = property(lambda self: self.base.inter_size)

    # -- everything else delegates (mesh, run_spmd, grad_transform, ...) ----
    def __getattr__(self, name):
        # only called for attributes not found on this class; keeps the
        # wrapper transparent for backend-specific surface (mesh,
        # axis_name, _host_channel, split_all, ...).  'base' itself must
        # fail plainly or a half-constructed instance recurses forever
        if name == "base":
            raise AttributeError(name)
        return getattr(self.base, name)

    # base-class concrete methods shadow __getattr__, so delegate explicitly
    def split(self, color, key):
        return self.base.split(color, key)

    def _axis_in_scope(self):
        return self.base._axis_in_scope()

    def finalize(self):
        # unbind OUR schedule's transport hook from the (process-global)
        # host channel, so injected faults cannot outlive this
        # communicator into supposedly fault-free later runs; another
        # owner's hook is left alone
        try:
            ch = self.base._host_channel()
        except Exception:
            ch = None
        tag = getattr(ch, "_fault_hook", None) and \
            getattr(ch._fault_hook, "_schedule", None)
        if tag is not None and (tag is self.schedule
                                or tag is self.hc_schedule):
            ch.set_fault_hook(None)
        return self.base.finalize()


def _make_intercepted(op):
    payload_kw = _PAYLOAD_KW.get(op)

    def method(self, *args, **kwargs):
        if args:
            first = args[0]
        else:  # keyword-invoked: resolve the payload by PARAMETER name
            first = kwargs.get(payload_kw) if payload_kw else None
        handled, value = self._maybe_inject(op, first_arg=first)
        if handled:
            return value
        return getattr(self.base, op)(*args, **kwargs)
    method.__name__ = op
    method.__qualname__ = f"FaultInjectionCommunicator.{op}"
    method.__doc__ = (f"Schedule-checked ``{op}`` "
                      f"(delegates to the wrapped communicator).")
    return method


for _op in _INTERCEPTED:
    setattr(FaultInjectionCommunicator, _op, _make_intercepted(_op))
del _op


def bind_host_channel(channel, schedule, sleep=time.sleep):
    """Install a schedule-driven fault hook at a HostChannel's hook points.

    The channel calls ``hook(event, ctx)`` at ``hc.put`` / ``hc.chunk`` /
    ``hc.get`` / ``hc.barrier`` sites (see ``_host_channel.HostChannel``).
    Actions:

    ``raise``      raise at the hook site (a transport error the
                   channel's bounded retry may absorb — ``hc.get`` raises
                   surface as transient failures of one attempt).
    ``delay``      straggle (drives deadline/backoff paths).
    ``lost_chunk`` after a put, delete one chunk key from the store —
                   the reader sees a torn message and must time out or
                   retry (ctx supplies the key and the client).
    ``stale_key``  corrupt the meta key so the reader sees a stale/
                   malformed entry (exercises key-cleanup paths).
    ``preempt``    raise :class:`RankPreempted` at the hook site.  The
                   channel's retry loop treats it as NON-transient (a
                   reclaimed host cannot come back within a backoff),
                   so it surfaces immediately instead of burning the
                   retry budget.
    """
    if isinstance(schedule, dict):
        schedule = FaultSchedule.from_dict(schedule)

    def hook(event, ctx):
        fault = schedule.on_call(event)
        if fault is None:
            return
        if fault.action == "delay":
            sleep(fault.spec.delay_s)
            return
        if fault.action == "raise":
            raise fault.make_exception()
        if fault.action == "lost_chunk":
            try:
                ctx["client"].key_value_delete(ctx["key"] + "/c0")
            except Exception:
                pass
            return
        if fault.action == "stale_key":
            try:
                ctx["client"].key_value_set(ctx["key"] + "/meta", "stale:0")
            except Exception:
                pass
            return
        raise fault.make_exception()

    hook._schedule = schedule  # ownership tag: lets the schedule's
    # communicator wrapper unbind exactly this hook in finalize()
    channel.set_fault_hook(hook)
    return schedule
