"""Declarative, seeded fault schedules — the injection layer's brain.

The reference stack's fail-stop contract (SURVEY.md §2.4/§3.5: crash →
relaunch → converge on the newest checkpoint present on *all* ranks) is
only trustworthy if it can be *exercised*.  A :class:`FaultSchedule` is a
deterministic oracle consulted once per named operation call: given the
op name it answers "nothing", or one of the fault actions below.  Two
schedules built from the same specs and seed, driven through the same
sequence of op calls, fire at exactly the same call sites — that replay
property is itself under test (``tests/resilience_tests``).

Fault actions
-------------
``raise``  raise :class:`InjectedFault` (default) or a caller-supplied
           exception type — models a crashed collective / transport error.
``drop``   skip the operation.  For sends this loses the message (the
           peer's matched receive then exercises the timeout path); for
           value-preserving collectives (bcast/allreduce flavors) the
           wrapper returns the input unchanged (a no-op collective —
           models silent data-plane loss); ops with no well-defined
           silent result (scatter/gather/recv…) degrade to ``raise``.
``delay``  sleep ``delay_s`` before executing — models stragglers and
           exercises deadline/backoff paths without a real slow host.
``preempt`` raise :class:`RankPreempted` — the rank exits hard at this
           call (spot/preemptible capacity reclaiming the host, ISSUE
           10).  Unhandled, the exception fail-stops the process like
           any crash; under :class:`~..extensions.ElasticRecovery` the
           rank announces ``leave`` and the survivors shrink the
           communicator instead (``docs/resilience.md`` §7).

Spec matching
-------------
A spec names an ``op`` (exact name, or ``"*"`` wildcard) and fires either
on the ``nth`` call of that op (1-based, counted per schedule instance)
or probabilistically with ``prob`` drawn from the schedule's seeded RNG —
one shared stream, consumed in op-call order, so probabilistic schedules
replay deterministically too.  ``count`` bounds how many times a spec
fires (default 1; ``None`` = unbounded).  ``rank`` restricts a spec to
ONE rank of a shared schedule (the elastic chaos shape: every process
builds the same schedule, only the targeted rank is preempted).  Rank
filtering happens *after* the probabilistic draw, so a rank-restricted
spec consumes identical RNG stream positions on every rank — the
cross-rank replay property survives targeting.

``step`` restricts a spec to a NAMED protocol step: interception sites
that are themselves multi-step protocols (the capacity-transfer
conversion, ISSUE 16) pass ``on_call(op, step=...)`` and a
step-restricted spec only fires when the names agree (the chaos shape
"preempt exactly at CONVERTING").  Like ``rank``, step filtering
happens after the draw, so cross-rank streams stay call-site-aligned
regardless of which step each rank is currently executing.

Host-channel ops are namespaced ``hc.<op>`` (``hc.put``, ``hc.get``,
``hc.barrier``, ``hc.chunk``) and carry transport-flavored actions
(``lost_chunk``, ``stale_key``) interpreted by the host-channel fault
hook — see ``fault_injection_communicator.bind_host_channel``.

See ``docs/resilience.md`` for the schedule file format and the recovery
state machine it feeds.
"""

from __future__ import annotations

import json
import os
import random

__all__ = ["InjectedFault", "RankPreempted", "FaultSpec", "FaultSchedule",
           "schedule_from_env"]

_ACTIONS = ("raise", "drop", "delay", "lost_chunk", "stale_key", "preempt")


class InjectedFault(RuntimeError):
    """A deliberately injected fault (carries the op and call index)."""

    def __init__(self, op, call_index, note=""):
        self.op = op
        self.call_index = call_index
        super().__init__(
            f"injected fault at {op!r} call #{call_index}"
            + (f" ({note})" if note else ""))


class RankPreempted(RuntimeError):
    """This rank's capacity was reclaimed (the ``preempt`` action).

    Deliberately NOT an :class:`InjectedFault` subclass: the fixed-size
    :class:`~..extensions.FailureRecovery` must fail-stop on it (an
    in-place retry cannot bring back a reclaimed host), while
    :class:`~..extensions.ElasticRecovery` treats it as this rank's cue
    to leave the membership.  Carries the op, call index, and the
    targeted rank (``None`` when the spec was rank-unrestricted)."""

    def __init__(self, op, call_index, rank=None, note=""):
        self.op = op
        self.call_index = call_index
        self.rank = rank
        super().__init__(
            f"rank{'' if rank is None else f' {rank}'} preempted at "
            f"{op!r} call #{call_index}"
            + (f" ({note})" if note else ""))


class FaultSpec:
    """One declarative fault: *when* (op + nth/prob) and *what* (action)."""

    def __init__(self, op, action="raise", nth=None, prob=None,
                 delay_s=0.0, exc=None, count=1, note="", rank=None,
                 step=None):
        if action not in _ACTIONS:
            raise ValueError(f"unknown fault action {action!r}; "
                             f"choose from {_ACTIONS}")
        if (nth is None) == (prob is None):
            raise ValueError("exactly one of nth=/prob= must be given")
        if nth is not None and nth < 1:
            raise ValueError("nth is 1-based (first call is nth=1)")
        if rank is not None and int(rank) < 0:
            raise ValueError(f"rank must be a non-negative rank id, "
                             f"got {rank}")
        if step is not None and (not isinstance(step, str) or not step):
            raise ValueError(f"step must be a non-empty protocol-step "
                             f"name, got {step!r}")
        self.op = op
        self.action = action
        self.nth = nth
        self.prob = prob
        self.delay_s = float(delay_s)
        self.exc = exc
        self.count = count  # None = unbounded
        self.note = note
        self.rank = None if rank is None else int(rank)
        self.step = step
        self.fired = 0

    def to_dict(self):
        d = {"op": self.op, "action": self.action}
        if self.nth is not None:
            d["nth"] = self.nth
        if self.prob is not None:
            d["prob"] = self.prob
        if self.delay_s:
            d["delay_s"] = self.delay_s
        if self.count != 1:
            d["count"] = self.count
        if self.note:
            d["note"] = self.note
        if self.rank is not None:
            d["rank"] = self.rank
        if self.step is not None:
            d["step"] = self.step
        return d

    def __repr__(self):
        return f"FaultSpec({self.to_dict()!r})"


class _Fault:
    """A resolved injection decision handed back to the interception site."""

    def __init__(self, spec, op, call_index):
        self.spec = spec
        self.action = spec.action
        self.op = op
        self.call_index = call_index

    def make_exception(self):
        if self.action == "preempt":
            # the preempt action owns its exception type: a caller-
            # supplied exc= would hide the RankPreempted contract the
            # elastic supervisor dispatches on
            return RankPreempted(self.op, self.call_index,
                                 rank=self.spec.rank, note=self.spec.note)
        if self.spec.exc is not None:
            return self.spec.exc(
                f"injected fault at {self.op!r} call #{self.call_index}")
        return InjectedFault(self.op, self.call_index, self.spec.note)


class FaultSchedule:
    """Seeded oracle: ``on_call(op)`` → :class:`_Fault` or ``None``.

    Deterministic by construction: per-op call counters plus one seeded
    RNG stream consumed in call order.  ``fired`` records every injection
    as ``(op, call_index, action)`` — the replay log the determinism
    tests compare.
    """

    def __init__(self, specs=(), seed=0, rank=None):
        self.seed = int(seed)
        self._rng = random.Random(self.seed)
        self.specs = [s if isinstance(s, FaultSpec) else FaultSpec(**s)
                      for s in specs]
        self._counters = {}
        self.fired = []
        # the rank this schedule instance is driving (bound by the
        # fault-injection communicator at wrap time; settable up front
        # for host-channel-only schedules).  None = unbound: rank-
        # restricted specs never fire, rank-free specs always can.
        self.rank = None if rank is None else int(rank)

    def bind_rank(self, rank):
        """Bind the schedule to the rank it is driving — rank-restricted
        specs (``FaultSpec(rank=k)``) only fire on the bound rank.  The
        RNG stream is unaffected (rank filtering happens after the
        draw), so bound and unbound instances of the same schedule stay
        call-site-aligned."""
        self.rank = None if rank is None else int(rank)
        return self

    # -- construction --------------------------------------------------------
    @classmethod
    def from_dict(cls, d):
        """``{"seed": int, "faults": [spec-dict, ...]}``."""
        return cls(specs=d.get("faults", ()), seed=d.get("seed", 0))

    @classmethod
    def from_json(cls, text):
        return cls.from_dict(json.loads(text))

    def to_dict(self):
        return {"seed": self.seed,
                "faults": [s.to_dict() for s in self.specs]}

    # -- the oracle ----------------------------------------------------------
    def on_call(self, op, step=None):
        """Consult the schedule for one call of ``op``.

        Increments the op's call counter, then returns the first matching
        armed spec's decision (or None).  The RNG stream is advanced for
        every probabilistic spec naming this op — match or not — so the
        draw sequence depends only on the op-call sequence.  ``step``
        names the protocol step the caller is executing (capacity
        conversion sites pass it); step-restricted specs only fire when
        the names agree.
        """
        n = self._counters.get(op, 0) + 1
        self._counters[op] = n
        hit = None
        for spec in self.specs:
            if spec.op != "*" and spec.op != op:
                continue
            if spec.count is not None and spec.fired >= spec.count:
                # exhausted probabilistic specs must still consume their
                # draw, or exhaustion would shift later specs' sites
                if spec.prob is not None:
                    self._rng.random()
                continue
            if spec.nth is not None:
                matched = (n == spec.nth)
            else:
                matched = (self._rng.random() < spec.prob)
            if matched and spec.rank is not None \
                    and spec.rank != self.rank:
                # targeted at another rank (or unbound schedule): the
                # draw above is already consumed, so every rank's
                # stream stays aligned — the spec just doesn't fire here
                matched = False
            if matched and spec.step is not None and spec.step != step:
                # step filtering mirrors rank filtering: post-draw, so
                # ranks at different protocol steps stay stream-aligned
                matched = False
            if matched and hit is None:
                spec.fired += 1
                hit = _Fault(spec, op, n)
        if hit is not None:
            self.fired.append((hit.op, hit.call_index, hit.action))
        return hit

    def calls(self, op):
        """How many times ``op`` has been consulted."""
        return self._counters.get(op, 0)

    def reset(self):
        """Re-arm: counters, RNG stream, and spec budgets back to t=0."""
        self._rng = random.Random(self.seed)
        self._counters = {}
        self.fired = []
        for spec in self.specs:
            spec.fired = 0

    def __repr__(self):
        return (f"<FaultSchedule seed={self.seed} specs={len(self.specs)} "
                f"fired={len(self.fired)}>")


def schedule_from_env(env="CHAINERMN_TPU_FAULT_SCHEDULE"):
    """Build a schedule from a JSON env var (CI/chaos entry point).

    The value is either inline JSON or an ``@/path/to/file.json``
    reference.  Returns None when unset — injection stays zero-cost for
    normal runs.
    """
    raw = os.environ.get(env)
    if not raw:
        return None
    if raw.startswith("@"):
        with open(raw[1:]) as f:
            raw = f.read()
    return FaultSchedule.from_json(raw)
