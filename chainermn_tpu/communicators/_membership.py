"""Elastic membership — who is in the job, decided over the KV store.

ISSUE 10 (ROADMAP item 5): PR 1's resilience subsystem recovers faults
at a FIXED world size; spot/preemptible capacity means controller
processes leave and join mid-run.  This module is the control-plane
half of elasticity: a generation-keyed (``epoch``-keyed) membership
protocol over the same coordination-service KV client the
:class:`~._host_channel.HostChannel` rides, so survivors can agree on
the new rank set without any participation from a dead peer — the one
thing the channel's lock-step collectives can never do.

Protocol (see ``docs/resilience.md`` §7):

* Membership is a monotonically increasing **epoch** counter plus, per
  epoch, a decided **view** (the sorted tuple of live controller
  ranks).  Keys live under ``<ns>/<role>`` (``<ns>/elastic`` for the
  training group; the serving fleet binds ``role="fleet"`` →
  ``<ns>/fleet``) — OUTSIDE the host channel's per-generation prefix,
  so a ``bump_generation`` (the fixed-size recovery quiesce) never
  strands a membership decision.  Role groups are fully disjoint key
  namespaces: a fleet group and a training elastic group sharing one
  KV store never see each other's presence/candidate/intent keys, and
  every decided view carries its ``role`` so downstream diagnostics
  (``RecoveryGivingUp``) name the right group.
* Each decision also publishes a **multicast tree plan**
  (:func:`multicast_tree_plan`, a pure function of the member set):
  the O(log N)-round binomial broadcast schedule bulk state transfers
  (serving-fleet weight sync, ISSUE 15) ride instead of N sequential
  root bcasts.  The plan key is informational — every member computes
  the identical plan from the identical view.
* ``announce_leave()`` / ``announce_join()`` are non-blocking,
  generation-keyed intents a rank posts before it departs / when it
  wants back in.  A standing ``leave`` excludes its rank from the next
  decision even if stale presence keys linger; ``announce_join``
  retracts any standing leave.
* :meth:`ElasticMembership.resolve` is the consensus: every candidate
  posts (and keeps refreshing) a presence beat under the NEXT epoch,
  the lowest-ranked *live* candidate acts as leader, and the leader
  publishes the view once the candidate set is **complete** (every
  rank in ``expect`` present) or **settled** (unchanged for
  ``settle_s`` — the typed timeout that drops unresponsive peers).
  Everyone else adopts the published view.  Candidates whose beat
  freezes for ``stall_s`` mid-resolve are excluded (and skipped for
  leadership), measured on the observer's clock like
  :class:`~._host_channel.HeartbeatMonitor` — a peer that died INSIDE
  the consensus cannot wedge it.
* A resolve that exhausts its deadline without any published view
  raises :class:`~._host_channel.ChannelTimeoutError` (op
  ``"membership.resolve"``) — typed, never a hang.

Split-brain note: the leader rule (minimum live candidate decides) has
the usual asynchronous-consensus caveat — a candidate so slow that the
leader settles without it finds itself EXCLUDED from the published
view.  That is surfaced, not hidden: :meth:`resolve` returns the view
it adopted, and :class:`~..extensions.ElasticRecovery` treats
"view without me" exactly like a preemption (announce join, wait for
re-admission), so a late rank degrades to a rejoin instead of a
second, disjoint world.
"""

from __future__ import annotations

import time

from ._host_channel import ChannelTimeoutError

__all__ = ["MembershipView", "ElasticMembership", "multicast_tree_plan"]


def multicast_tree_plan(members, root=None):
    """Binomial broadcast-tree schedule over ``members`` — the O(log N)
    replacement for the lowest-survivor O(N) sequential bcast.

    Returns a tuple of ROUNDS; round ``k`` is a tuple of ``(src, dst)``
    member pairs whose transfers can all run concurrently (every ``src``
    already holds the payload: the root before round 0, plus every
    ``dst`` of an earlier round).  Pure function of ``(members, root)``
    — every member computes the identical plan, so no coordination
    beyond the decided view is needed.  Properties (pinned by test):

    * every non-root member appears EXACTLY once as a ``dst``;
    * every ``src`` of round ``k`` is the root or a ``dst`` of a round
      ``< k`` (no transfer from an empty holder);
    * depth ``== ceil(log2 N)`` (``0`` rounds for a single member).

    ``root`` defaults to the lowest member (the serving fleet's lowest
    survivor; the elastic snapshot root).
    """
    members = tuple(sorted(int(m) for m in members))
    if not members:
        raise ValueError("multicast_tree_plan needs at least one member")
    if len(set(members)) != len(members):
        raise ValueError(f"duplicate members: {members!r}")
    root = members[0] if root is None else int(root)
    if root not in members:
        raise ValueError(f"root {root} is not a member of {members!r}")
    order = (root,) + tuple(m for m in members if m != root)
    n = len(order)
    rounds = []
    have = 1  # holders so far: order[:have]
    while have < n:
        rounds.append(tuple((order[i], order[i + have])
                            for i in range(have) if i + have < n))
        have *= 2
    return tuple(rounds)


def _serialize_tree_plan(plan):
    return ";".join(",".join(f"{s}>{d}" for s, d in rnd) for rnd in plan)


def _parse_tree_plan(raw):
    plan = []
    for rnd in str(raw).split(";"):
        if not rnd:
            continue
        plan.append(tuple(tuple(int(x) for x in pair.split(">"))
                          for pair in rnd.split(",") if pair))
    return tuple(plan)


class MembershipView:
    """One decided membership generation: ``epoch`` + sorted ``members``
    (global controller ranks) + the ``role`` of the group that decided
    it (``"elastic"`` for the training group, ``"fleet"`` for the
    serving fleet — views from different role groups never compare
    equal).  Immutable value object."""

    def __init__(self, epoch, members, role="elastic"):
        self.epoch = int(epoch)
        self.role = str(role)
        self.members = tuple(sorted(int(m) for m in members))
        if len(set(self.members)) != len(self.members):
            raise ValueError(f"duplicate members in view: {members!r}")

    @property
    def size(self):
        return len(self.members)

    def slot(self, rank):
        """This member's dense 0-based slot in the view (collective
        addressing), or None for a non-member."""
        return self.members.index(rank) if rank in self.members else None

    def __contains__(self, rank):
        return rank in self.members

    def __eq__(self, other):
        return (isinstance(other, MembershipView)
                and (self.epoch, self.members, self.role)
                == (other.epoch, other.members, other.role))

    def __hash__(self):
        return hash((self.epoch, self.members, self.role))

    def tree_plan(self, root=None):
        """The view's multicast tree plan (pure; see
        :func:`multicast_tree_plan`)."""
        return multicast_tree_plan(self.members, root=root)

    def __repr__(self):
        return (f"<MembershipView role={self.role!r} epoch={self.epoch} "
                f"members={self.members}>")


class ElasticMembership:
    """The membership protocol bound to one process (see module doc).

    ``client``: the coordination-service KV client (or the test fake).
    ``rank``/``world``: this process's GLOBAL controller rank and the
    boot-time process count — membership ranks are stable process
    identities; a resized communicator maps them to dense slots.
    ``role``: the group namespace suffix — ``"elastic"`` (default, the
    training group) or ``"fleet"`` (the serving fleet); groups of
    different roles in the same KV store are fully key-disjoint.
    ``settle_s``: how long the candidate set must be unchanged before
    the leader decides without the full ``expect`` set (the per-peer
    timeout).  ``stall_s``: a candidate whose presence beat freezes
    this long mid-resolve is presumed dead and excluded.  ``clock``/
    ``sleep`` are injectable for deterministic tests.
    """

    def __init__(self, client, rank, world, namespace="cmn",
                 role="elastic", settle_s=1.0, stall_s=10.0, poll_s=0.05,
                 timeout_ms=60_000, clock=time.monotonic,
                 sleep=time.sleep):
        self._client = client
        self.rank = int(rank)
        self.world = int(world)
        self.role = str(role)
        if not self.role or "/" in self.role:
            raise ValueError(f"membership role must be a single path "
                             f"segment, got {role!r}")
        self._ns = str(namespace)
        self._base = f"{namespace}/{self.role}"
        self.settle_s = float(settle_s)
        self.stall_s = float(stall_s)
        self.poll_s = float(poll_s)
        self.timeout_ms = int(timeout_ms)
        self._clock = clock
        self._sleep = sleep
        self._epoch_cache = 0  # monotone last-known decided epoch
        self.stats = {"resolves": 0, "led": 0, "adopted": 0}

    # -- KV primitives -------------------------------------------------------
    # The real coordination-service client is narrower than the test
    # fakes: it has NO ``key_value_try_get`` (non-blocking probes ride a
    # short ``blocking_key_value_get``) and its ``key_value_set``
    # REFUSES overwrites (ALREADY_EXISTS) — re-announcements, presence
    # beats, and the epoch pointer all need delete-then-set.  These
    # wrappers absorb both shapes, so the protocol runs identically
    # against jax's client and the in-memory fakes.

    #: probe window for the emulated non-blocking get (ms): long enough
    #: for the server round-trip, short enough that a full world scan
    #: stays well under one poll interval
    PROBE_MS = 5

    def _try_get(self, key):
        c = self._client
        fn = getattr(c, "key_value_try_get", None)
        try:
            if fn is not None:
                return fn(key)
            return c.blocking_key_value_get(key, self.PROBE_MS)
        except Exception:
            return None

    def _set(self, key, value):
        c = self._client
        try:
            c.key_value_set(key, str(value))
            return
        except Exception:
            pass
        try:
            # ALREADY_EXISTS (the real client's overwrite refusal):
            # last-writer-wins via delete-then-set.  Every such key has
            # a single writer by protocol (own presence/announce keys;
            # the epoch pointer is leader-only), so the window is benign
            c.key_value_delete(key)
            c.key_value_set(key, str(value))
        except Exception:
            pass

    def _delete(self, key):
        try:
            self._client.key_value_delete(key)
        except Exception:
            pass

    def _scan(self, prefix, ranks):
        """``{rank: value}`` of ``<prefix>/<rank>`` keys.  One
        ``key_value_dir_get`` round-trip on the real client; per-rank
        probes on fakes that lack it."""
        c = self._client
        fn = getattr(c, "key_value_dir_get", None)
        if fn is not None:
            out = {}
            try:
                for key, value in fn(prefix):
                    tail = str(key).rsplit("/", 1)[-1]
                    if tail.isdigit():
                        out[int(tail)] = value
            except Exception:
                pass
            return {r: out[r] for r in ranks if r in out}
        return {r: v for r in ranks
                if (v := self._try_get(f"{prefix}/{r}")) is not None}

    # -- epochs and views ----------------------------------------------------
    def current_epoch(self):
        """The newest DECIDED epoch (0 = boot, nothing decided yet).

        Decided epochs are APPEND-ONLY keys (``epochs/<k>``, one per
        decision, never overwritten or deleted): a single mutable
        pointer would need the real client's delete-then-set overwrite
        emulation, whose missing-key window lets a concurrent reader
        observe epoch 0 and adopt a long-stale early view.  Discovery
        probes upward from the instance's cached last-known epoch —
        monotone, so it can never regress through any write gap."""
        e = self._epoch_cache
        while self._try_get(f"{self._base}/epochs/{e + 1}") is not None:
            e += 1
        self._epoch_cache = e
        return e

    def bootstrap_view(self):
        """Epoch-0 view: every boot-time controller rank (the world
        before any elasticity event)."""
        return MembershipView(0, range(self.world), role=self.role)

    def current_view(self):
        """The newest decided view, or the bootstrap view when no
        decision has been published yet."""
        epoch = self.current_epoch()
        if epoch == 0:
            return self.bootstrap_view()
        view = self._read_view(epoch)
        return view if view is not None else self.bootstrap_view()

    def _read_view(self, epoch):
        raw = self._try_get(f"{self._base}/e{epoch}/view")
        if raw is None:
            return None
        try:
            members = [int(tok) for tok in str(raw).split(",") if tok != ""]
        except ValueError:
            return None
        return MembershipView(epoch, members, role=self.role)

    # -- announcements (generation-keyed intents) ---------------------------
    def announce_leave(self, note="", rank=None):
        """Post a departure (non-blocking, best-effort): the next
        resolve excludes the rank without waiting out a timeout.  A
        standing join intent is retracted.  ``rank`` defaults to this
        process; a survivor passes a DEAD rank's id when aborting an
        orphaned capacity conversion (the journal proves the intent —
        posting it merely spares everyone the timeout)."""
        rank = self.rank if rank is None else int(rank)
        self._delete(f"{self._base}/join/{rank}")
        self._set(f"{self._base}/leave/{rank}",
                  f"{self.current_epoch()}:{note}")

    def announce_join(self, note="", rank=None):
        """Post a wish to (re-)enter: survivors' join polls see it and
        initiate a grow resolve.  Retracts any standing leave (the
        spot host came back).  ``rank`` defaults to this process."""
        rank = self.rank if rank is None else int(rank)
        self._delete(f"{self._base}/leave/{rank}")
        self._set(f"{self._base}/join/{rank}",
                  f"{self.current_epoch()}:{note}")

    def retract_join(self, rank=None):
        """Scrub a standing join intent without posting a leave — this
        rank's own retraction, or a survivor scrubbing a DEAD rank's
        intent while aborting an orphaned capacity conversion (a rank
        that died at ``REJOINING`` must never be admitted)."""
        rank = self.rank if rank is None else int(rank)
        self._delete(f"{self._base}/join/{rank}")

    def pending_joins(self, view=None):
        """Ranks with a standing join announcement that are NOT in the
        (given or current) view — the survivors' per-iteration poll."""
        view = view if view is not None else self.current_view()
        joins = self._scan(f"{self._base}/join", range(self.world))
        return tuple(r for r in sorted(joins) if r not in view)

    # -- capacity-conversion journal (ISSUE 16) ------------------------------
    # A rank changing ROLE (training <-> fleet, the capacity-transfer
    # protocol in chainermn_tpu/elastic/capacity.py) journals each
    # conversion step here BEFORE executing it, so a preempt landing
    # mid-conversion leaves a typed record survivors can roll forward
    # or abort.  The journal lives under ``<ns>/capacity`` — OUTSIDE
    # both role groups' key prefixes, because a conversion by
    # definition spans two groups: members of EITHER group must see
    # the same journal through their own membership object.  Values
    # are ``step:beat:note`` — the beat increments on every write by
    # the converting rank, so an observer can distinguish a LIVE
    # conversion (beat advancing) from an orphaned one (beat frozen,
    # the stall_s idiom measured on the observer's clock).

    def journal_conversion(self, step, note="", rank=None, beat=None):
        """Write (or advance) the conversion-journal entry for ``rank``
        (default: this rank).  ``beat`` defaults to previous+1."""
        rank = self.rank if rank is None else int(rank)
        if beat is None:
            prev = self.read_conversion(rank)
            beat = (prev[1] + 1) if prev is not None else 1
        self._set(f"{self._ns}/capacity/{rank}", f"{step}:{int(beat)}:{note}")

    def read_conversion(self, rank):
        """``(step, beat, note)`` of ``rank``'s journal entry, or None."""
        raw = self._try_get(f"{self._ns}/capacity/{int(rank)}")
        if raw is None:
            return None
        parts = str(raw).split(":", 2)
        if len(parts) != 3:
            return None
        try:
            return (parts[0], int(parts[1]), parts[2])
        except ValueError:
            return None

    def scan_conversions(self):
        """``{rank: (step, beat, note)}`` of every standing journal
        entry — the survivors' orphan-detection scan."""
        found = self._scan(f"{self._ns}/capacity", range(self.world))
        out = {}
        for r in sorted(found):
            entry = self.read_conversion(r)
            if entry is not None:
                out[r] = entry
        return out

    def clear_conversion(self, rank=None):
        """Scrub the journal entry (conversion completed or aborted)."""
        rank = self.rank if rank is None else int(rank)
        self._delete(f"{self._ns}/capacity/{rank}")

    # -- consensus -----------------------------------------------------------
    def resolve(self, expect=None, require=None, timeout_ms=None):
        """Agree on the next epoch's member set; returns the decided
        :class:`MembershipView` (which may EXCLUDE this rank — see the
        module docstring's split-brain note).

        ``expect``: ranks the caller believes alive; the leader decides
        as soon as all of them are present (fast path), or once the
        candidate set has settled for ``settle_s`` (the typed per-peer
        timeout path that drops unresponsive ranks).

        ``require``: ranks that MUST be present before this caller may
        publish ANY decision — the settle path cannot drop them.  A
        JOINER passes the current survivors here: without it, a joiner
        whose resolve never overlaps the survivors' would settle alone
        and decide a second, disjoint world.  Unsatisfiable ``require``
        ends in the typed timeout, never a wrong view.

        Raises :class:`ChannelTimeoutError` when no view lands within
        the deadline."""
        self.stats["resolves"] += 1
        timeout_ms = self.timeout_ms if timeout_ms is None else timeout_ms
        epoch = self.current_epoch() + 1
        prefix = f"{self._base}/e{epoch}"
        deadline = self._clock() + timeout_ms / 1000.0
        beat = 0
        seen = {}  # rank -> (token, observer-local last-change time)
        prev_candidates = None
        last_change = self._clock()
        while True:
            decided = self._read_view(epoch)
            if decided is not None:
                self.stats["adopted"] += 1
                return decided
            if self._clock() >= deadline:
                raise ChannelTimeoutError("membership.resolve",
                                          f"{prefix}/view", timeout_ms,
                                          beat)
            beat += 1
            self._set(f"{prefix}/present/{self.rank}", str(beat))
            present = self._scan(f"{prefix}/present", range(self.world))
            leaves = self._scan(f"{self._base}/leave", range(self.world))
            candidates = []
            for r, tok in sorted(present.items()):
                if r in leaves:
                    continue  # announced departure: never a candidate
                prev = seen.get(r)
                now = self._clock()
                if prev is None:
                    seen[r] = (tok, now, 0)
                elif prev[0] != tok:
                    seen[r] = (tok, now, prev[2] + 1)
                elif r != self.rank and now - prev[1] > self.stall_s:
                    continue  # beat frozen mid-resolve: presumed dead
                candidates.append(r)
            cand = tuple(sorted(candidates))
            if cand != prev_candidates:
                prev_candidates = cand
                last_change = self._clock()
            complete = expect is not None \
                and set(int(e) for e in expect) <= set(cand)
            settled = self._clock() - last_change >= self.settle_s
            required_ok = require is None \
                or set(int(r) for r in require) <= set(cand)
            if cand and cand[0] == self.rank and required_ok \
                    and (complete or settled):
                if not complete:
                    # settle-path zombie screen: a presence key whose
                    # token NEVER changed during this resolve is a
                    # leftover from a dead rank's earlier attempt (live
                    # candidates rebeat every poll loop) — deciding it
                    # into the view would seed the next failure
                    cand = tuple(r for r in cand
                                 if r == self.rank or seen[r][2] >= 1)
                if cand and cand[0] == self.rank:
                    view = MembershipView(epoch, cand, role=self.role)
                    self._publish(view)
                    self.stats["led"] += 1
                    return view
            self._sleep(self.poll_s)

    def read_tree_plan(self, epoch=None):
        """The leader-published multicast tree plan of the (given or
        newest) epoch, or the locally computed plan when the key is
        absent (the plan is a pure function of the view, so the two can
        never disagree — the published key exists for operators and
        cross-version readers)."""
        epoch = self.current_epoch() if epoch is None else int(epoch)
        raw = self._try_get(f"{self._base}/e{epoch}/tree")
        if raw is not None:
            return _parse_tree_plan(raw)
        view = self._read_view(epoch) if epoch else self.bootstrap_view()
        if view is None:
            return None
        return multicast_tree_plan(view.members)

    def _publish(self, view):
        """Leader-side decision write: the view key first, then the
        epoch's append-only marker (a reader that discovers the new
        epoch always finds its view), then the consumed join/leave
        intents are scrubbed (admitted ranks' joins, departed ranks'
        leaves).  The view's multicast tree plan (rooted at the lowest
        member) is published next to it — informational, every member
        recomputes the identical plan."""
        prefix = f"{self._base}/e{view.epoch}"
        self._set(f"{prefix}/view", ",".join(str(m) for m in view.members))
        self._set(f"{prefix}/tree",
                  _serialize_tree_plan(view.tree_plan()))
        self._set(f"{self._base}/epochs/{view.epoch}", "1")
        for r in view.members:
            self._delete(f"{self._base}/join/{r}")
        for r in range(self.world):
            if r not in view:
                self._delete(f"{self._base}/leave/{r}")
        # presence keys of PAST epochs are dead weight: scrub the
        # previous epoch's (best-effort; the current epoch's stay for
        # late adopters still polling them)
        for r in range(self.world):
            self._delete(f"{self._base}/e{view.epoch - 1}/present/{r}")

    def __repr__(self):
        return (f"<ElasticMembership rank={self.rank} world={self.world} "
                f"epoch={self.current_epoch()}>")
