"""Self-tuning exchange planner (ISSUE 19, ROADMAP item 2).

Every topology knob the comm stack grew — ``bucket_mb``, ``stripe_ratio``,
the per-hop dtype ladder — used to be a static constructor argument the
operator guessed per topology.  This module closes the loop the way
HiCCL composes collectives from a machine description and FlexLink
picks its multi-path split from measured link bandwidths:

1. **measure** — :func:`measure_fabric` runs a seconds-scale startup
   micro-bench (one ``psum`` per mesh hop: a large probe for bandwidth,
   a tiny probe for launch latency) over the REAL fabric; the optional
   online mode (:func:`measurements_from_trace`) instead reads the
   ISSUE 14 span tracer's ``train/grad_exchange*`` spans, whose
   payload-bytes attributes make bandwidth = Σbytes/Σduration directly
   readable off a trace.
2. **agree** — :func:`agree_exchange_plan` all-gathers the per-rank
   measurements over the object channel, reduces them DETERMINISTICALLY
   (sorted median, fixed tie-break, 6-significant-digit rounding — no
   rank-local floating-point divergence), derives the plan locally, and
   broadcasts rank 0's plan so every rank executes the identical
   exchange even if a rank's derivation somehow diverged (divergence is
   counted and warned, never silently absorbed).
3. **plan** — :func:`derive_exchange_plan` is a PURE function of the
   agreed measurements + the (collectively identical) topology summary:
   ``bucket_mb`` from the slowest measured hop's bandwidth×latency
   (:func:`~._memory_utility.derived_bucket_bytes`), ``stripe_ratio``
   from docs/performance.md §10's finish-together split
   (:func:`~._memory_utility.derived_stripe_ratio`), and a bfloat16 DCN
   crossing when the slow hop is < half the fast hop's bandwidth.
   Unmeasurable hops (axis size 1, missing latency) fall back to the
   documented defaults WITH a derivation note — the plan always says
   why it chose what it chose.

The derived plan only fills knobs the caller did NOT hand-set
(explicit constructor argument or env var — provenance recorded at
construction, carried across clones and elastic rebuilds): hand knobs
always win, which is what makes the golden-trajectory gate exact — an
``autotune=`` run whose derived plan matches the hand knobs compiles
the identical program.  The agreed plan is recorded as an artifact
(``CHAINERMN_TPU_AUTOTUNE_DIR``) mirroring ``tools/autotune_plan.json``,
whose committed numeric fields stay null until the recovery queue's
FIRST-CHIP-CONTACT item 11 stamps them on real hardware.
"""

from __future__ import annotations

import hashlib
import json
import time
import warnings

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ._memory_utility import (DEFAULT_BUCKET_MB, DEFAULT_STRIPE_RATIO,
                              derived_bucket_bytes, derived_stripe_ratio,
                              exchanged_bytes)

__all__ = ["measure_fabric", "measurements_from_trace",
           "reduce_measurements", "derive_exchange_plan",
           "agree_exchange_plan", "retune_communicator",
           "topology_summary", "plan_fingerprint", "record_plan",
           "PLAN_VERSION"]

#: plan schema version — bumped when the derivation rules change, so a
#: recorded artifact can never be replayed against different rules
PLAN_VERSION = 1

#: per-collective launch-overhead budget the bucket rule amortizes
OVERHEAD_FRAC = 0.125


def _round6(x):
    """Canonical 6-significant-digit rounding — every number that
    enters the plan passes through here, so two ranks deriving from the
    same agreed measurements produce byte-identical JSON."""
    return float(f"{float(x):.6g}")


# -- measurement ------------------------------------------------------------
def _hop_list(comm):
    """``(hop_name, mesh_axis, axis_size)`` per fabric hop: ``ici`` +
    ``dcn`` on a hierarchical communicator, the single ``world`` hop on
    a flat one."""
    if comm.hierarchy is not None:
        return [("ici", comm.ici_axis, comm.ici_size),
                ("dcn", comm.dcn_axis, comm.dcn_size)]
    return [("world", comm.axis_name, comm.size)]


def measure_fabric(comm, probe_mb=1.0, iters=4):
    """Startup micro-bench: per mesh hop, one replicated ``psum`` timed
    at two sizes — a ``probe_mb`` buffer for bandwidth (wire bytes per
    call = :func:`~._memory_utility.exchanged_bytes` of a psum over the
    hop) and an 8-element buffer for launch latency (min over iters).

    Seconds-scale by construction: 2 compiles + ``2×iters`` executions
    per hop.  A size-1 hop is UNMEASURABLE (nothing crosses a wire) and
    reports ``{"size": 1, "gbps": None, "lat_us": None}`` — the planner
    falls back for it explicitly.  Collective: every rank must enter
    (the probes are real collectives over the shared mesh).
    """
    from .. import observability
    from chainermn_tpu.utils.compat import shard_map
    measurement = {"source": "startup", "probe_mb": _round6(probe_mb),
                   "iters": int(iters), "hops": {}}
    with observability.span("autotune/measure",
                            tags={"mode": "startup",
                                  "probe_mb": float(probe_mb)}):
        for hop, axis, axis_size in _hop_list(comm):
            if axis_size <= 1:
                measurement["hops"][hop] = {"size": 1, "gbps": None,
                                            "lat_us": None}
                continue
            inv = 1.0 / float(axis_size)

            def probe(x, _axis=axis, _inv=inv):
                # /size keeps the replicated value stable across iters
                return lax.psum(x, _axis) * _inv

            mapped = jax.jit(shard_map(
                probe, mesh=comm.mesh, in_specs=(P(),), out_specs=P(),
                check_vma=False))
            n_big = max(1, int(float(probe_mb) * (1 << 20)) // 4)
            big = jnp.ones((n_big,), jnp.float32)
            mapped(big).block_until_ready()          # compile + warm
            t0 = time.perf_counter()
            out = big
            for _ in range(int(iters)):
                out = mapped(out)
            out.block_until_ready()
            elapsed = max(time.perf_counter() - t0, 1e-9)
            wire = exchanged_bytes(n_big * 4, axis_size, "psum")
            gbps = wire * int(iters) / elapsed / 1e9

            small = jnp.ones((8,), jnp.float32)
            mapped(small).block_until_ready()
            lat_s = float("inf")
            for _ in range(int(iters)):
                t0 = time.perf_counter()
                mapped(small).block_until_ready()
                lat_s = min(lat_s, time.perf_counter() - t0)
            measurement["hops"][hop] = {"size": int(axis_size),
                                        "gbps": float(gbps),
                                        "lat_us": float(lat_s * 1e6)}
    return measurement


def measurements_from_trace(events, payload_key="payload_bytes"):
    """Online mode: bandwidth read directly off the ISSUE 14 tracer's
    ``train/grad_exchange*`` spans.  B/E pairs are matched LIFO per
    ``(pid, tid, name)`` track; each pair contributes its
    ``args.payload_bytes`` (the ISSUE 19 small-fix attribute) over its
    duration, grouped by the span's ``args.hop`` tag when present
    (``world`` otherwise).  Spans without a payload attribute are
    skipped — timing alone is not a bandwidth sample.

    No latency field comes out of a trace (a full-exchange span bounds
    launch overhead only loosely), so plans derived from online
    measurements keep the committed ``bucket_mb`` fallback unless a
    startup micro-bench also ran.
    """
    open_spans = {}
    totals = {}     # hop -> [bytes, seconds, samples]
    for ev in events or []:
        name = ev.get("name", "")
        if not name.startswith("train/grad_exchange"):
            continue
        key = (ev.get("pid"), ev.get("tid"), name)
        if ev.get("ph") == "B":
            open_spans.setdefault(key, []).append(ev)
        elif ev.get("ph") == "E" and open_spans.get(key):
            b = open_spans[key].pop()
            args = b.get("args") or {}
            payload = args.get(payload_key)
            if payload is None:
                continue
            dur_s = max(ev.get("ts", 0) - b.get("ts", 0), 0) * 1e-6
            if dur_s <= 0:
                continue
            hop = args.get("hop", "world")
            acc = totals.setdefault(hop, [0.0, 0.0, 0])
            acc[0] += float(payload)
            acc[1] += dur_s
            acc[2] += 1
    hops = {}
    for hop, (nbytes, secs, samples) in sorted(totals.items()):
        hops[hop] = {"size": None,
                     "gbps": nbytes / secs / 1e9 if secs > 0 else None,
                     "lat_us": None, "samples": samples}
    return {"source": "online", "hops": hops}


# -- deterministic agreement -------------------------------------------------
def reduce_measurements(gathered):
    """Reduce the all-gathered per-rank measurements to ONE agreed set:
    per hop and field, the sorted median with a FIXED tie-break
    (element ``(n-1)//2``), rounded to 6 significant digits.  A pure,
    order-insensitive function of the gathered list — every rank holds
    the same list after the allgather, so every rank computes the same
    agreed measurements (the determinism the plan fingerprint gates).
    """
    gathered = [g for g in gathered if g]
    if not gathered:
        raise ValueError("no fabric measurements to reduce")
    base = gathered[0]
    out = {"source": base.get("source", "startup"), "ranks": len(gathered)}
    for k in ("probe_mb", "iters"):
        if base.get(k) is not None:
            out[k] = base[k]
    hop_names = sorted({h for g in gathered for h in (g.get("hops") or {})})
    hops = {}
    for h in hop_names:
        entries = [g["hops"][h] for g in gathered
                   if h in (g.get("hops") or {})]
        agg = {}
        for field in ("size", "gbps", "lat_us"):
            vals = sorted(float(e[field]) for e in entries
                          if e.get(field) is not None)
            if not vals:
                agg[field] = None
            else:
                v = vals[(len(vals) - 1) // 2]
                agg[field] = int(v) if field == "size" else _round6(v)
        hops[h] = agg
    out["hops"] = hops
    return out


def topology_summary(comm):
    """The collectively-identical topology facts the planner keys off —
    every field is a pure function of the communicator's construction
    arguments, which are themselves collective."""
    axis = comm.axis_name
    label = "x".join(axis) if isinstance(axis, (tuple, list)) else str(axis)
    summary = {"axis": label,
               "kind": "hierarchical" if comm.hierarchy is not None
               else "flat",
               "size": int(comm.size),
               "exchange": comm.exchange}
    if comm.hierarchy is not None:
        summary["inter"], summary["intra"] = (int(s)
                                              for s in comm._hier_sizes)
    return summary


def plan_fingerprint(plan):
    """16-hex-char sha256 of the plan's canonical JSON (sorted keys,
    no whitespace, ``fingerprint`` excluded) — the identity the
    cross-rank determinism gate and the plan gauge carry."""
    body = {k: v for k, v in plan.items() if k != "fingerprint"}
    blob = json.dumps(body, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:16]


def derive_exchange_plan(measurements, topology):
    """PURE planner: agreed measurements + topology summary → the
    ``{bucket_mb, stripe_ratio, grad_dtype}`` plan.  Deterministic and
    byte-identical across ranks (every number passes through 6-digit
    canonical rounding; the fingerprint is over canonical JSON).

    Derivation rules, each with an explicit fallback note when a hop is
    unmeasurable:

    * ``bucket_mb`` — from the SLOWEST measured hop's (bandwidth,
      latency) via :func:`~._memory_utility.derived_bucket_bytes` (the
      slow hop's launch overhead is the one worth amortizing); ``None``
      (= keep the committed default) when no hop has both fields.
    * ``stripe_ratio`` — hierarchical topologies only:
      :func:`~._memory_utility.derived_stripe_ratio` (§10's
      ``r* = B_dcn/(B_ici+B_dcn)``) when BOTH hops measured, else the
      documented :data:`~._memory_utility.DEFAULT_STRIPE_RATIO`
      fallback; ``None`` on flat topologies (one fabric — nothing to
      stripe).
    * ``grad_dtype`` — ``{"ici": None, "dcn": "bfloat16"}`` when the
      measured DCN bandwidth is under half the ICI bandwidth (the slow
      crossing is worth halving; ICI stays lossless by design), else
      ``None``.
    """
    notes = []
    hops = dict(measurements.get("hops") or {})
    measured = {h: v for h, v in hops.items()
                if (v or {}).get("gbps") is not None}

    bucket_mb = None
    if measured:
        slowest = min(sorted(measured), key=lambda h: measured[h]["gbps"])
        lat = measured[slowest].get("lat_us")
        if lat is not None:
            bucket_mb = _round6(
                derived_bucket_bytes(measured[slowest]["gbps"], lat,
                                     overhead_frac=OVERHEAD_FRAC)
                / (1 << 20))
            notes.append(f"bucket_mb from slowest measured hop "
                         f"'{slowest}' (bandwidth x latency / "
                         f"{OVERHEAD_FRAC})")
        else:
            notes.append(f"hop '{slowest}' has bandwidth but no latency "
                         f"sample (online trace): bucket_mb keeps the "
                         f"committed default {DEFAULT_BUCKET_MB} MB")
    else:
        notes.append(f"no measurable hop: bucket_mb keeps the committed "
                     f"default {DEFAULT_BUCKET_MB} MB")

    stripe_ratio = None
    grad_dtype = None
    if topology.get("kind") == "hierarchical":
        gi = (hops.get("ici") or {}).get("gbps")
        gd = (hops.get("dcn") or {}).get("gbps")
        if gi is not None and gd is not None:
            stripe_ratio = _round6(derived_stripe_ratio(gi, gd))
            notes.append("stripe_ratio = r* = B_dcn / (B_ici + B_dcn) "
                         "(docs/performance.md S10 finish-together split)")
            if gd < 0.5 * gi:
                grad_dtype = {"ici": None, "dcn": "bfloat16"}
                notes.append("B_dcn < B_ici/2: bfloat16 DCN crossing "
                             "(ICI stays lossless by design)")
        else:
            missing = "+".join(h for h in ("ici", "dcn")
                               if (hops.get(h) or {}).get("gbps") is None)
            stripe_ratio = _round6(DEFAULT_STRIPE_RATIO)
            notes.append(f"{missing} unmeasured: stripe_ratio falls back "
                         f"to DEFAULT_STRIPE_RATIO "
                         f"({DEFAULT_STRIPE_RATIO})")

    plan = {
        "version": PLAN_VERSION,
        "axis": topology.get("axis"),
        "topology": dict(topology),
        "bucket_mb": bucket_mb,
        "stripe_ratio": stripe_ratio,
        "grad_dtype": grad_dtype,
        "measurements": measurements,
        "derivation": {
            "formula": "r* = B_dcn / (B_ici + B_dcn)",
            "bucket_rule": f"bytes = bandwidth x latency / "
                           f"{OVERHEAD_FRAC}, clamped [1, 32] MB",
            "fallbacks": {"stripe_ratio": DEFAULT_STRIPE_RATIO,
                          "bucket_mb": DEFAULT_BUCKET_MB},
            "notes": notes,
        },
    }
    plan["fingerprint"] = plan_fingerprint(plan)
    return plan


def agree_exchange_plan(comm, measurement):
    """Allgather the per-rank measurements, reduce deterministically,
    derive locally, then take RANK 0's plan by broadcast — the agreed
    plan every rank applies.  The local derivation *should* already be
    byte-identical (pure function of agreed inputs — the tier-1
    determinism gate); if a rank's fingerprint still diverges the
    broadcast wins, a warning fires, and the divergence counter bumps —
    never a silent split-brain exchange."""
    from .. import observability
    with observability.span("autotune/agree"):
        gathered = comm.allgather_obj(measurement)
        reduced = reduce_measurements(gathered)
        with observability.span("autotune/derive"):
            local = derive_exchange_plan(reduced, topology_summary(comm))
        plan = comm.bcast_obj(local, root=0)
    if plan.get("fingerprint") != local.get("fingerprint"):
        from ..observability import registry
        registry().counter(
            "chainermn_tpu_autotune_plan_divergence_total",
            help="ranks whose locally derived plan differed from the "
                 "broadcast rank-0 plan (should be 0: the planner is a "
                 "pure function of agreed measurements)").inc(
            axis=str(plan.get("axis")))
        warnings.warn(
            f"autotune plan derivation diverged from rank 0 "
            f"(local {local.get('fingerprint')} != broadcast "
            f"{plan.get('fingerprint')}); executing rank 0's plan",
            RuntimeWarning, stacklevel=2)
    from ..observability import registry
    registry().gauge(
        "chainermn_tpu_autotune_plan_fingerprint",
        help="numeric prefix of the agreed exchange plan's fingerprint "
             "(identical on every rank of a healthy job)").set(
        float(int(plan["fingerprint"][:12], 16)),
        axis=str(plan.get("axis")))
    observability.instant(
        "autotune/plan",
        tags={"fingerprint": plan["fingerprint"],
              "bucket_mb": plan.get("bucket_mb"),
              "stripe_ratio": plan.get("stripe_ratio")})
    if comm.rank == 0:
        record_plan(plan)
    return plan


def record_plan(plan, path=None):
    """Write the agreed plan as a JSON artifact.  Default location:
    ``$CHAINERMN_TPU_AUTOTUNE_DIR/autotune_plan_<axis>.json`` (one file
    per mesh axis — an elastic resize's epoch-suffixed axis gets a
    FRESH artifact, the per-epoch trail the re-tune tests pin); no env
    var, no write.  Returns the path written, or ``None``."""
    import os
    if path is None:
        out_dir = os.environ.get("CHAINERMN_TPU_AUTOTUNE_DIR", "").strip()
        if not out_dir:
            return None
        safe_axis = "".join(c if c.isalnum() or c in "-_" else "_"
                            for c in str(plan.get("axis", "world")))
        path = os.path.join(out_dir, f"autotune_plan_{safe_axis}.json")
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w") as f:
        json.dump(plan, f, indent=2, sort_keys=True)
        f.write("\n")
    return path


def retune_communicator(comm, mode="startup", events=None):
    """measure → agree → apply: returns the communicator to actually
    train with (a retuned clone, or ``comm`` itself with the plan
    attached when the derived plan changes nothing the caller left
    free).  ``mode="online"`` derives from tracer events (``events`` or
    the live tracer ring) instead of running the startup micro-bench.
    Collective under multi-process execution — every rank must call
    with the same arguments, like communicator construction itself."""
    if mode in (True, "startup"):
        measurement = measure_fabric(comm)
    elif mode == "online":
        if events is None:
            from .. import observability
            events = observability.tracer().events()
        measurement = measurements_from_trace(events)
    else:
        raise ValueError(
            f"autotune mode must be 'startup' (True) or 'online', "
            f"got {mode!r}")
    plan = agree_exchange_plan(comm, measurement)
    return comm.retuned(plan)
