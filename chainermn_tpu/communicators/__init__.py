"""Communicator factory.

Reference: ``chainermn/communicators/__init__.py · create_communicator``
(SURVEY.md §2.1) — maps a name string to a communicator.  All reference
names are accepted; on TPU they are flavors of one mesh-backed
implementation (SURVEY §2.7: the taxonomy collapses to mesh-axis +
dtype + bucketing choices):

===================  ========================================================
name                 TPU realization
===================  ========================================================
``naive``            per-parameter mean collectives (correctness baseline)
``flat``             single flat-bucket collective (``batch_collectives``)
``pure_nccl``        fused bucket + optional compressed-dtype gradient psum
                     (``batch_collectives="bucketed"`` restores the
                     reference's SIZE-BOUNDED bucket pipeline — K
                     ``bucket_mb``-bounded collectives in reverse
                     registration order, overlappable with backward)
``hierarchical``     REAL two-level (dcn × ici) exchange (ISSUE 6, no
                     longer an alias): intra-host reduce-scatter over
                     ICI → inter-host allreduce over DCN on the 1/intra
                     chunk → intra-host all-gather, so DCN only ever
                     carries ``1/ici_size`` of the gradient bytes.  The
                     split is inferred from process_count × local
                     devices, forced with ``intra_size=``/
                     ``inter_size=``, or taken from a 2-axis mesh via
                     ``MeshCommunicator.from_mesh_axis(mesh, (dcn,
                     ici))``.  Pays off whenever the mesh spans >1 DCN
                     hop (multi-host pods/slices); on one host it
                     degenerates to a size-1 DCN axis (measure — the
                     schedule is free there, not harmful).  Per-hop
                     compression: ``allreduce_grad_dtype={"dcn":
                     "bfloat16"}``.  ``CHAINERMN_TPU_HIERARCHY=flat``
                     is the escape hatch back to the flat alias.
``two_dimensional``  same two-level exchange as ``hierarchical`` (the
                     reference's leader-staged vs chunked-2D variants
                     collapse on TPU: every chip is DCN-attached, so
                     the chunked form strictly dominates — kept as a
                     distinct name for reference parity)
``single_node``      asserts one host, otherwise ``pure_nccl``
``non_cuda_aware``   alias of ``naive`` (host staging has no TPU analog)
``jax_ici``          canonical native name (= ``pure_nccl`` defaults)
``dummy``            no-op loopback
===================  ========================================================
"""

from __future__ import annotations

import jax

from ._autotune import (agree_exchange_plan, derive_exchange_plan,
                        measure_fabric, measurements_from_trace,
                        plan_fingerprint, record_plan, reduce_measurements,
                        retune_communicator, topology_summary)
from ._host_channel import (ChannelError, ChannelTimeoutError, PeerLostError,
                            HostChannel, HeartbeatMonitor)
from ._membership import (ElasticMembership, MembershipView,
                          multicast_tree_plan)
from .communicator_base import CommunicatorBase
from .debug_communicator import DebugCommunicator
from .dummy_communicator import DummyCommunicator
from .fault_injection_communicator import (FaultInjectionCommunicator,
                                           bind_host_channel)
from .fault_schedule import (FaultSchedule, FaultSpec, InjectedFault,
                             RankPreempted, schedule_from_env)
from .mesh_communicator import ElasticMeshCommunicator, MeshCommunicator

__all__ = ["create_communicator", "CommunicatorBase", "MeshCommunicator",
           "ElasticMeshCommunicator", "DummyCommunicator",
           "DebugCommunicator",
           "FaultInjectionCommunicator", "FaultSchedule", "FaultSpec",
           "InjectedFault", "RankPreempted", "bind_host_channel",
           "schedule_from_env",
           "ChannelError", "ChannelTimeoutError", "PeerLostError",
           "HostChannel", "HeartbeatMonitor",
           "ElasticMembership", "MembershipView", "multicast_tree_plan",
           "EXCHANGES", "exchange_knobs",
           "agree_exchange_plan", "derive_exchange_plan", "measure_fabric",
           "measurements_from_trace", "plan_fingerprint", "record_plan",
           "reduce_measurements", "retune_communicator",
           "topology_summary"]

_NAMES = ("naive", "flat", "hierarchical", "two_dimensional", "single_node",
          "non_cuda_aware", "pure_nccl", "jax_ici", "dummy", "debug",
          "fault")

#: gradient-exchange vocabulary shared by bench rows, the gloo A/B, and
#: tools/comm_budgets.json configs
EXCHANGES = ("per_leaf", "flat", "bucketed", "reduce_scatter",
             "hierarchical", "hierarchical_rs", "striped", "striped_rs")


def exchange_knobs(exchange):
    """``(communicator name, batch_collectives, optimizer exchange=)``
    triple for a named gradient-exchange structure — the ONE mapping
    bench.py's on-chip rows and bench_scaling.py's gloo A/B share, so
    the same name always measures the same collective structure on both
    surfaces.  ``reduce_scatter`` keeps a flat communicator: the
    optimizer-level step variant owns its collective structure (the
    communicator's packing only affects eager-mode collectives there).
    ``hierarchical`` is the two-level (ici × dcn) allreduce exchange;
    ``hierarchical_rs`` composes it with the reduce-scatter DP update
    (both hops reduce-scatter the gradient, both all-gather the
    params).  ``striped``/``striped_rs`` (ISSUE 11) are the multi-path
    variants of those two: same communicator name, but the caller must
    additionally pass a nonzero ``stripe_ratio`` to
    ``create_communicator`` (bench surfaces default it to
    ``DEFAULT_STRIPE_RATIO`` / the ``BENCH_STRIPE_RATIO`` /
    ``CHAINERMN_TPU_STRIPE_RATIO`` knobs) — a zero ratio would silently
    measure the strict hierarchical schedule under the striped name."""
    try:
        name, bc = {
            "per_leaf": ("jax_ici", False),
            "flat": ("jax_ici", True),
            "bucketed": ("jax_ici", "bucketed"),
            "reduce_scatter": ("jax_ici", True),
            "hierarchical": ("hierarchical", True),
            "hierarchical_rs": ("hierarchical", True),
            "striped": ("hierarchical", True),
            "striped_rs": ("hierarchical", True),
        }[exchange]
    except KeyError:
        raise ValueError(f"unknown exchange {exchange!r} "
                         f"({'|'.join(EXCHANGES)})") from None
    return name, bc, ("reduce_scatter"
                      if exchange in ("reduce_scatter", "hierarchical_rs",
                                      "striped_rs")
                      else "allreduce")


def create_communicator(communicator_name="jax_ici", devices=None,
                        axis_name="mn_world", allreduce_grad_dtype=None,
                        batch_collectives=None, bucket_mb=None,
                        fault_schedule=None, intra_size=None,
                        inter_size=None, error_feedback=True,
                        stripe_ratio=None, autotune=None, **kwargs):
    """Create a communicator by reference name.

    ``allreduce_grad_dtype``: gradient-compression dtype for the collective
    (reference fp16 path; bf16 recommended on TPU).  On the hierarchical
    flavors a ``{"ici": ..., "dcn": ...}`` dict compresses per hop
    (lossless ICI + bf16 DCN is the interesting point).  ISSUE 8 adds
    the QUANTIZED wires ``"int8"`` / ``"float8_e4m3"`` /
    ``"float8_e5m2"``: per-bucket symmetric-scale quantization of the
    slow hop (the DCN crossing on hierarchical flavors — a scalar
    quantized dtype compresses DCN only, ICI stays lossless; the whole
    exchange on flat ones), with ``error_feedback=True`` (default)
    carrying the quantization residual in a persistent buffer so the
    error telescopes instead of accumulating (docs/performance.md §9;
    convergence is parity-gated, not bit-exact).
    ``CHAINERMN_TPU_COMPRESS=off`` is the factory-level escape hatch:
    quantized wires fall back to lossless (bf16 casts untouched).
    ISSUE 12: on hierarchical flavors the ``dcn`` entry ALSO
    compresses the MoE token dispatch's slow crossing
    (``parallel.moe`` two-stage exchange: bf16 cast, or int8/fp8
    codewords with per-segment scales) — one knob, every slow-hop
    traffic class; the ICI stage of the dispatch is lossless by
    design like every fast hop.
    ``devices``:
    subset of ``jax.devices()`` (default all).  ``batch_collectives``:
    ``False`` (per-leaf collectives), ``True`` (one flat bucket — the
    per-name default for the fused flavors) or ``"bucketed"`` (K
    size-bounded buckets, the reference pure_nccl pipeline; ``bucket_mb``
    / ``CHAINERMN_TPU_BUCKET_MB`` bounds each bucket, default ~4 MB —
    composes with the hierarchical flavors: each bucket runs the
    two-level rs/allreduce/ag).  ``intra_size``/``inter_size``: force
    the (dcn, ici) split of the hierarchical flavors instead of
    inferring it from the controller topology (the simulated-multihost
    knob tier-1 uses).  ``stripe_ratio`` (ISSUE 11, hierarchical
    flavors only; ``CHAINERMN_TPU_STRIPE_RATIO`` is the no-code-change
    env knob): the DCN share of each bucket's payload in the STRIPED
    multi-path exchange — that slice runs the transposed slow-hop-major
    exchange concurrently with the fast-hop-major remainder, so both
    fabrics carry bulk traffic at once instead of hierarchically
    (docs/performance.md §10; 0 = the strict hierarchical schedule;
    the committed per-topology value comes from the ``bench_scaling``
    striped ratio sweep).  ``CHAINERMN_TPU_HIERARCHY=flat`` collapses
    ``hierarchical``/``two_dimensional`` back to the flat one-axis
    alias (sizes ignored, striping dropped — one fabric has no second
    path) — the no-code-change escape hatch.
    ``fault_schedule`` (``fault`` name only): a :class:`FaultSchedule` or
    spec dict; defaults to ``CHAINERMN_TPU_FAULT_SCHEDULE`` from the
    environment — the chaos harness's entry point (see
    ``docs/resilience.md``).
    ``autotune`` (ISSUE 19, docs/performance.md §12): self-tune the
    exchange knobs from MEASURED fabric numbers instead of guesses.
    ``True``/``"startup"`` runs the seconds-scale startup micro-bench
    now (collective — every rank enters), agrees the plan (measurements
    all-gathered + reduced deterministically, plan broadcast from rank
    0) and returns the retuned communicator; ``"online"`` defers — the
    multi-node optimizer re-tunes after its first N steps from the span
    tracer's ``train/grad_exchange*`` payload-tagged spans; a dict is a
    RECORDED plan (e.g. the committed ``tools/autotune_plan.json``
    ``plan`` object) applied directly with no measurement.  The plan
    only fills knobs not hand-set here (explicit argument or env var) —
    hand knobs always win, so pinning ``bucket_mb=``/``stripe_ratio=``
    alongside ``autotune=`` keeps those knobs yours and derives the
    rest.
    """
    name = communicator_name
    if name not in _NAMES:
        raise ValueError(
            f"unknown communicator {name!r}; choose from {_NAMES}")
    if fault_schedule is not None and name != "fault":
        raise ValueError(
            f"fault_schedule= is only honored by the 'fault' "
            f"communicator, not {name!r} — a silently dropped schedule "
            f"would make a chaos run pass vacuously")
    if autotune not in (None, False, True, "startup", "online") \
            and not isinstance(autotune, dict):
        raise ValueError(
            f"autotune must be True/'startup' (micro-bench now), "
            f"'online' (re-tune from the first N steps' trace), or a "
            f"recorded plan dict; got {autotune!r}")
    if autotune and name in ("dummy", "debug"):
        raise ValueError(
            f"autotune= is a mesh-communicator knob, not {name!r} — a "
            f"silently dropped plan would make an autotune run pass "
            f"vacuously")
    if name == "dummy":
        return DummyCommunicator()
    if name == "fault":
        schedule = fault_schedule if fault_schedule is not None \
            else schedule_from_env()
        if schedule is None:
            raise ValueError(
                "communicator 'fault' needs fault_schedule= or the "
                "CHAINERMN_TPU_FAULT_SCHEDULE env var")
        if isinstance(schedule, dict):
            schedule = FaultSchedule.from_dict(schedule)
        base = create_communicator(
            "jax_ici", devices=devices, axis_name=axis_name,
            allreduce_grad_dtype=allreduce_grad_dtype,
            batch_collectives=batch_collectives, bucket_mb=bucket_mb,
            intra_size=intra_size, inter_size=inter_size,
            error_feedback=error_feedback, stripe_ratio=stripe_ratio,
            autotune=autotune, **kwargs)
        # the hc.* transport hook gets its own schedule CLONE (same
        # specs + seed, separate RNG stream/counters): transport call
        # counts are inherently per-rank asymmetric (root puts,
        # non-root gets, retries), and sharing one RNG stream would let
        # that asymmetry desync the communicator-surface draws across
        # ranks — breaking the lock-step same-call-site guarantee the
        # wrapper documents.  hc faults are recorded on the clone.
        comm = FaultInjectionCommunicator(base, schedule)
        channel = base._host_channel()
        if channel is not None:
            # the clone re-binds the wrapper's rank: to_dict carries the
            # specs' rank targeting but a schedule's OWN binding is
            # process-local state
            comm.hc_schedule = bind_host_channel(
                channel, FaultSchedule.from_dict(schedule.to_dict())
                .bind_rank(schedule.rank))
        return comm
    if name == "debug":
        return DebugCommunicator(devices=devices, axis_name=axis_name,
                                 allreduce_grad_dtype=allreduce_grad_dtype,
                                 batch_collectives=batch_collectives or False,
                                 bucket_mb=bucket_mb)
    if name == "single_node" and jax.process_count() != 1:
        raise ValueError("single_node communicator requires one host "
                         f"(process_count={jax.process_count()})")
    if allreduce_grad_dtype is not None and name not in (
            "pure_nccl", "jax_ici", "hierarchical", "two_dimensional"):
        raise ValueError(
            f"allreduce_grad_dtype is supported by the fused-bucket "
            f"communicators, not {name!r} (reference: pure_nccl-only)")
    if isinstance(allreduce_grad_dtype, dict) \
            and name not in ("hierarchical", "two_dimensional") \
            and intra_size is None and inter_size is None:
        # an explicit intra/inter split makes ANY fused flavor
        # hierarchical (MeshCommunicator's own contract), so the dict
        # is only nonsense when the result will be a flat one-hop mesh
        raise ValueError(
            f"per-hop allreduce_grad_dtype dicts are a hierarchical-"
            f"communicator knob, not {name!r} without an intra_size/"
            f"inter_size split (a flat exchange has one hop)")
    if batch_collectives is None:
        batch_collectives = name in ("flat", "pure_nccl", "jax_ici",
                                     "hierarchical", "two_dimensional",
                                     "single_node")
    if name in ("hierarchical", "two_dimensional"):
        import os
        if os.environ.get("CHAINERMN_TPU_HIERARCHY", "") \
                .strip().lower() in ("flat", "off", "0"):
            # escape hatch (docs/performance.md §8): flat one-axis alias,
            # split knobs dropped — one env var, zero call-site edits
            intra_size = inter_size = None
            if isinstance(axis_name, (tuple, list)):
                # a (dcn, ici) tuple would re-trigger the two-level
                # split inside MeshCommunicator — flatten the name too
                axis_name = "_".join(axis_name)
            if isinstance(allreduce_grad_dtype, dict):
                # the flat alias has one hop; keep whatever compression
                # the dict asked for on it — the DCN entry wins (the
                # slow-hop intent), else the ICI entry — never a silent
                # drop to lossless (wire bytes must not silently grow).
                # The degradation is NOT silent (ISSUE 8 satellite): the
                # per-hop intent cannot survive a one-hop mesh, so name
                # what was kept and what was dropped, once per distinct
                # dict
                chosen_key = "dcn" if allreduce_grad_dtype.get("dcn") \
                    is not None else "ici"
                dropped = sorted(k for k, v in allreduce_grad_dtype.items()
                                 if k != chosen_key and v is not None)
                _warn_hierarchy_flat_dict_degraded(
                    allreduce_grad_dtype, chosen_key, dropped)
                allreduce_grad_dtype = (allreduce_grad_dtype.get("dcn")
                                        or allreduce_grad_dtype.get("ici"))
            try:
                eff_stripe = stripe_ratio if stripe_ratio is not None \
                    else float(os.environ.get(
                        "CHAINERMN_TPU_STRIPE_RATIO", "") or 0)
            except ValueError:
                eff_stripe = 0
            if eff_stripe:
                # striping needs two fabrics; the flat alias has one.
                # NOT silent (same contract as the per-hop dict
                # degradation): the caller asked for multi-path wire
                # use and gets the flat single-path exchange instead
                _warn_hierarchy_flat_stripe_dropped(eff_stripe)
            comm = MeshCommunicator(
                devices=devices, axis_name=axis_name,
                allreduce_grad_dtype=allreduce_grad_dtype,
                batch_collectives=batch_collectives,
                bucket_mb=bucket_mb, name="jax_ici",
                error_feedback=error_feedback)
            # the hatch DEGRADED a requested hierarchy to one axis:
            # record it, so downstream topology-aware consumers (the
            # MoE two-stage dispatch) can warn precisely — a comm that
            # was never hierarchical must not trigger hatch warnings
            comm._hierarchy_flattened_by_env = True
            return _apply_autotune(comm, autotune)
    comm = MeshCommunicator(devices=devices, axis_name=axis_name,
                            allreduce_grad_dtype=allreduce_grad_dtype,
                            batch_collectives=batch_collectives,
                            bucket_mb=bucket_mb, name=name,
                            intra_size=intra_size, inter_size=inter_size,
                            error_feedback=error_feedback,
                            stripe_ratio=stripe_ratio)
    return _apply_autotune(comm, autotune)


def _apply_autotune(comm, autotune):
    """Resolve the factory's ``autotune=`` knob against a freshly built
    mesh communicator: measure+agree+apply now (``"startup"``), defer
    to the optimizer face (``"online"`` — the mode rides on the comm),
    or apply a RECORDED plan dict directly.  Both the retune and the
    clone it may build are collective, lock-step on every rank — the
    plan is agreed before anyone rebuilds."""
    if autotune in (None, False):
        return comm
    if isinstance(autotune, dict):
        return comm.retuned(autotune)
    if autotune == "online":
        comm._autotune_mode = "online"
        return comm
    comm._autotune_mode = "startup"
    from ._autotune import retune_communicator
    return retune_communicator(comm, mode="startup")


#: distinct degraded dicts already warned about (one-time per intent —
#: a training loop constructing communicators repeatedly must not spam)
_WARNED_FLAT_DICTS = set()

#: stripe ratios already warned about under the flat escape hatch
_WARNED_FLAT_STRIPES = set()

#: one-time latch for the MoE two-stage drop under the flat hatch
#: (ISSUE 12 satellite — same not-silent pattern as striping: the
#: caller asked for multi-fabric wire use and gets the single-axis
#: exchange instead)
_WARNED_FLAT_TWO_STAGE = set()


def _warn_hierarchy_flat_two_stage_dropped():
    """CHAINERMN_TPU_HIERARCHY=flat is active and an MoE dispatch that
    would have run the two-stage (ici → dcn) token exchange is running
    the flat single-axis ``all_to_all`` instead.  Warn once per process
    (``parallel.moe`` calls this at dispatch resolution time — the
    factory cannot know at construction that a communicator will carry
    MoE traffic)."""
    import warnings
    if _WARNED_FLAT_TWO_STAGE:
        return
    _WARNED_FLAT_TWO_STAGE.add(True)
    warnings.warn(
        "CHAINERMN_TPU_HIERARCHY=flat drops two-stage MoE routing: the "
        "flat one-axis alias has a single fabric, so token dispatch "
        "runs the flat single-axis all_to_all (on-host tokens ride the "
        "same collective as off-host ones and the DCN crossing cannot "
        "be compressed separately).  Unset CHAINERMN_TPU_HIERARCHY to "
        "restore the two-stage ici × dcn dispatch.",
        UserWarning, stacklevel=4)


def _warn_hierarchy_flat_stripe_dropped(stripe_ratio):
    import warnings
    if stripe_ratio in _WARNED_FLAT_STRIPES:
        return
    _WARNED_FLAT_STRIPES.add(stripe_ratio)
    warnings.warn(
        f"CHAINERMN_TPU_HIERARCHY=flat drops stripe_ratio="
        f"{stripe_ratio}: the flat one-axis alias has a single fabric, "
        f"so the multi-path striped exchange degrades to the flat "
        f"single-path allreduce.  Unset CHAINERMN_TPU_HIERARCHY to "
        f"restore the striped two-fabric schedule.",
        UserWarning, stacklevel=3)


def _warn_hierarchy_flat_dict_degraded(dtype_dict, chosen_key, dropped):
    import warnings
    key = tuple(sorted((k, str(v)) for k, v in dtype_dict.items()))
    if key in _WARNED_FLAT_DICTS:
        return
    _WARNED_FLAT_DICTS.add(key)
    kept = dtype_dict.get(chosen_key)
    detail = (f"dropped per-hop entries {dropped} "
              if dropped else "per-hop structure dropped ")
    warnings.warn(
        f"CHAINERMN_TPU_HIERARCHY=flat degrades per-hop "
        f"allreduce_grad_dtype={dtype_dict!r} to its {chosen_key!r} "
        f"entry ({kept!r}) on the ONE flat hop: {detail}— the full "
        f"gradient now rides the {chosen_key} compression instead of "
        f"only that hop's chunk.  Unset CHAINERMN_TPU_HIERARCHY to "
        f"restore the two-level exchange.",
        UserWarning, stacklevel=3)
