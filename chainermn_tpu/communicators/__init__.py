"""Communicator factory.

Reference: ``chainermn/communicators/__init__.py · create_communicator``
(SURVEY.md §2.1) — maps a name string to a communicator.  All reference
names are accepted; on TPU they are flavors of one mesh-backed
implementation (SURVEY §2.7: the taxonomy collapses to mesh-axis +
dtype + bucketing choices):

===================  ========================================================
name                 TPU realization
===================  ========================================================
``naive``            per-parameter mean collectives (correctness baseline)
``flat``             single flat-bucket collective (``batch_collectives``)
``pure_nccl``        fused bucket + optional compressed-dtype gradient psum
                     (``batch_collectives="bucketed"`` restores the
                     reference's SIZE-BOUNDED bucket pipeline — K
                     ``bucket_mb``-bounded collectives in reverse
                     registration order, overlappable with backward)
``hierarchical``     alias of ``pure_nccl`` (XLA handles torus hierarchy)
``two_dimensional``  alias of ``pure_nccl``
``single_node``      asserts one host, otherwise ``pure_nccl``
``non_cuda_aware``   alias of ``naive`` (host staging has no TPU analog)
``jax_ici``          canonical native name (= ``pure_nccl`` defaults)
``dummy``            no-op loopback
===================  ========================================================
"""

from __future__ import annotations

import jax

from ._host_channel import (ChannelError, ChannelTimeoutError, PeerLostError,
                            HostChannel, HeartbeatMonitor)
from .communicator_base import CommunicatorBase
from .debug_communicator import DebugCommunicator
from .dummy_communicator import DummyCommunicator
from .fault_injection_communicator import (FaultInjectionCommunicator,
                                           bind_host_channel)
from .fault_schedule import (FaultSchedule, FaultSpec, InjectedFault,
                             schedule_from_env)
from .mesh_communicator import MeshCommunicator

__all__ = ["create_communicator", "CommunicatorBase", "MeshCommunicator",
           "DummyCommunicator", "DebugCommunicator",
           "FaultInjectionCommunicator", "FaultSchedule", "FaultSpec",
           "InjectedFault", "bind_host_channel", "schedule_from_env",
           "ChannelError", "ChannelTimeoutError", "PeerLostError",
           "HostChannel", "HeartbeatMonitor",
           "EXCHANGES", "exchange_knobs"]

_NAMES = ("naive", "flat", "hierarchical", "two_dimensional", "single_node",
          "non_cuda_aware", "pure_nccl", "jax_ici", "dummy", "debug",
          "fault")

#: gradient-exchange vocabulary shared by bench rows, the gloo A/B, and
#: tools/comm_budgets.json configs
EXCHANGES = ("per_leaf", "flat", "bucketed", "reduce_scatter")


def exchange_knobs(exchange):
    """``(batch_collectives, optimizer exchange=)`` pair for a named
    gradient-exchange structure — the ONE mapping bench.py's on-chip
    rows and bench_scaling.py's gloo A/B share, so the same name always
    measures the same collective structure on both surfaces.
    ``reduce_scatter`` keeps a flat communicator: the optimizer-level
    step variant owns its collective structure (the communicator's
    packing only affects eager-mode collectives there)."""
    try:
        bc = {"per_leaf": False, "flat": True, "bucketed": "bucketed",
              "reduce_scatter": True}[exchange]
    except KeyError:
        raise ValueError(f"unknown exchange {exchange!r} "
                         f"({'|'.join(EXCHANGES)})") from None
    return bc, ("reduce_scatter" if exchange == "reduce_scatter"
                else "allreduce")


def create_communicator(communicator_name="jax_ici", devices=None,
                        axis_name="mn_world", allreduce_grad_dtype=None,
                        batch_collectives=None, bucket_mb=None,
                        fault_schedule=None, **kwargs):
    """Create a communicator by reference name.

    ``allreduce_grad_dtype``: gradient-compression dtype for the collective
    (reference fp16 path; bf16 recommended on TPU).  ``devices``: subset of
    ``jax.devices()`` (default all).  ``batch_collectives``: ``False``
    (per-leaf collectives), ``True`` (one flat bucket — the per-name
    default for the fused flavors) or ``"bucketed"`` (K size-bounded
    buckets, the reference pure_nccl pipeline; ``bucket_mb`` /
    ``CHAINERMN_TPU_BUCKET_MB`` bounds each bucket, default ~4 MB).
    ``fault_schedule`` (``fault`` name only): a :class:`FaultSchedule` or
    spec dict; defaults to ``CHAINERMN_TPU_FAULT_SCHEDULE`` from the
    environment — the chaos harness's entry point (see
    ``docs/resilience.md``).
    """
    name = communicator_name
    if name not in _NAMES:
        raise ValueError(
            f"unknown communicator {name!r}; choose from {_NAMES}")
    if fault_schedule is not None and name != "fault":
        raise ValueError(
            f"fault_schedule= is only honored by the 'fault' "
            f"communicator, not {name!r} — a silently dropped schedule "
            f"would make a chaos run pass vacuously")
    if name == "dummy":
        return DummyCommunicator()
    if name == "fault":
        schedule = fault_schedule if fault_schedule is not None \
            else schedule_from_env()
        if schedule is None:
            raise ValueError(
                "communicator 'fault' needs fault_schedule= or the "
                "CHAINERMN_TPU_FAULT_SCHEDULE env var")
        if isinstance(schedule, dict):
            schedule = FaultSchedule.from_dict(schedule)
        base = create_communicator(
            "jax_ici", devices=devices, axis_name=axis_name,
            allreduce_grad_dtype=allreduce_grad_dtype,
            batch_collectives=batch_collectives, bucket_mb=bucket_mb,
            **kwargs)
        # the hc.* transport hook gets its own schedule CLONE (same
        # specs + seed, separate RNG stream/counters): transport call
        # counts are inherently per-rank asymmetric (root puts,
        # non-root gets, retries), and sharing one RNG stream would let
        # that asymmetry desync the communicator-surface draws across
        # ranks — breaking the lock-step same-call-site guarantee the
        # wrapper documents.  hc faults are recorded on the clone.
        comm = FaultInjectionCommunicator(base, schedule)
        channel = base._host_channel()
        if channel is not None:
            comm.hc_schedule = bind_host_channel(
                channel, FaultSchedule.from_dict(schedule.to_dict()))
        return comm
    if name == "debug":
        return DebugCommunicator(devices=devices, axis_name=axis_name,
                                 allreduce_grad_dtype=allreduce_grad_dtype,
                                 batch_collectives=batch_collectives or False,
                                 bucket_mb=bucket_mb)
    if name == "single_node" and jax.process_count() != 1:
        raise ValueError("single_node communicator requires one host "
                         f"(process_count={jax.process_count()})")
    if allreduce_grad_dtype is not None and name not in (
            "pure_nccl", "jax_ici", "hierarchical", "two_dimensional"):
        raise ValueError(
            f"allreduce_grad_dtype is supported by the fused-bucket "
            f"communicators, not {name!r} (reference: pure_nccl-only)")
    if batch_collectives is None:
        batch_collectives = name in ("flat", "pure_nccl", "jax_ici",
                                     "hierarchical", "two_dimensional",
                                     "single_node")
    return MeshCommunicator(devices=devices, axis_name=axis_name,
                            allreduce_grad_dtype=allreduce_grad_dtype,
                            batch_collectives=batch_collectives,
                            bucket_mb=bucket_mb, name=name)
