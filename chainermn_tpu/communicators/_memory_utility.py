"""Parameter packing utilities.

Reference: ``chainermn/communicators/_memory_utility.py · DeviceMemory,
pack_params, unpack_params`` (SURVEY.md §2.1, N2 in §2.5) — there, CUDA
arenas and batched-copy kernels gather scattered grads into one buffer.
On TPU, packing is a ``concatenate`` *inside* the compiled step (XLA fuses
the copies); no arena management exists because XLA owns HBM.  These
helpers provide the same pack/unpack contract for the ``flat``-flavor
communicator, the bucketed gradient exchange, and flat-buffer
checkpointing.

Bucket planning (reference: pure_nccl's size-bounded gradient buckets,
SURVEY §2.5 N2): :func:`plan_buckets` partitions a leaf list into
contiguous size-bounded groups in REVERSE leaf order — backward produces
the LAST-registered parameters' gradients first, so the first emitted
bucket closes (and its collective can start) while earlier layers'
gradients are still being computed.  The plan is a pure function of
(shapes, dtypes, bound): every process traces the identical partition,
which is what makes the per-bucket collectives line up across ranks.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

__all__ = ["pack_params", "unpack_params", "tree_pack", "tree_unpack",
           "plan_buckets", "bucket_table", "hop_schedule", "stripe_plan",
           "derived_stripe_ratio", "derived_bucket_bytes",
           "plan_buckets_from_measurement", "stripe_plan_from_measurement",
           "exchanged_bytes", "hierarchical_exchanged_bytes",
           "striped_exchanged_bytes", "moe_dispatch_exchanged_bytes",
           "pad_to_multiple", "QUANTIZED_DTYPES", "resolve_grad_dtype",
           "is_quantized_dtype", "quantize_symmetric",
           "quantize_symmetric_segments",
           "dequantize_symmetric", "quantization_residual",
           "quantized_hop_bytes"]

#: default bucket bound (MB) for the bucketed exchange —
#: ``CHAINERMN_TPU_BUCKET_MB`` overrides (reference: pure_nccl's
#: allreduce chunking; ~4 MB keeps each collective large enough to hit
#: ring bandwidth while leaving several schedulable units per step)
DEFAULT_BUCKET_MB = 4.0

#: the DOCUMENTED FALLBACK stripe ratio (ISSUE 19), used only when no
#: fabric measurement exists — NOT a silent always-answer.  The right
#: value is the slow fabric's share of the mesh's aggregate bandwidth,
#: ``derived_stripe_ratio(b_ici, b_dcn)`` (docs/performance.md §10's
#: finish-together split r* = B_dcn / (B_ici + B_dcn)); ``autotune=``
#: measures the two hops at startup and derives it per topology.  When
#: a hop is unmeasurable (axis size 1, no measurement yet) the planner
#: falls back HERE and records why in the plan's derivation notes.
#: 0.25 is the 1:3 DCN:ICI seed ratio (DCN is the narrow fabric);
#: ``CHAINERMN_TPU_STRIPE_RATIO`` / ``create_communicator(stripe_ratio=)``
#: hand-pin it and win over any derived plan.
DEFAULT_STRIPE_RATIO = 0.25


def tree_pack(tree, dtype=None):
    """Flatten a pytree of arrays into (flat_vector, spec)."""
    leaves, treedef = jax.tree.flatten(tree)
    shapes = [l.shape for l in leaves]
    dtypes = [l.dtype for l in leaves]
    flat = jnp.concatenate(
        [l.reshape(-1).astype(dtype or l.dtype) for l in leaves]) \
        if leaves else jnp.zeros((0,), dtype or jnp.float32)
    return flat, (treedef, shapes, dtypes)


def tree_unpack(flat, spec):
    treedef, shapes, dtypes = spec
    leaves = []
    offset = 0
    for shape, dt in zip(shapes, dtypes):
        n = int(np.prod(shape))
        leaves.append(flat[offset:offset + n].reshape(shape).astype(dt))
        offset += n
    return jax.tree.unflatten(treedef, leaves)


def plan_buckets(shapes, dtypes, bucket_bytes):
    """Partition leaves into size-bounded buckets of leaf INDICES.

    Deterministic pure function of the arguments (identical on every
    rank — the cross-process contract the per-bucket collectives rely
    on).  Properties, pinned by tests/communicator_tests:

    * every leaf index appears in exactly one bucket;
    * buckets are emitted in REVERSE leaf order (last-registered
      parameter first — its gradient exists first in the backward);
    * a bucket never exceeds ``bucket_bytes`` unless a single leaf does
      (an oversize leaf gets a bucket of its own);
    * a bucket never mixes dtypes: the pack is a ``concatenate``, and a
      mixed bucket would silently promote (and mis-size) the transfer.
    """
    if bucket_bytes <= 0:
        raise ValueError(f"bucket_bytes must be positive, got {bucket_bytes}")
    buckets = []
    current = []
    current_bytes = 0
    current_dtype = None
    for i in reversed(range(len(shapes))):
        dt = jnp.dtype(dtypes[i])
        nbytes = int(np.prod(shapes[i])) * dt.itemsize
        if current and (current_bytes + nbytes > bucket_bytes
                        or dt != current_dtype):
            buckets.append(current)
            current, current_bytes = [], 0
        current.append(i)
        current_bytes += nbytes
        current_dtype = dt
    if current:
        buckets.append(current)
    return buckets


def hop_schedule(n_buckets, mode="hierarchical"):
    """Emission schedule of the two-level (ici × dcn) bucketed exchange:
    ordered ``(op, bucket)`` pairs the hierarchical/striped
    ``grad_transform`` follows literally, so the ordering properties are
    a tested pure function rather than an accident of loop structure.

    ``mode="hierarchical"`` (the strict two-level exchange, ISSUE 6) —
    ops per bucket: ``"ici_reduce_scatter"`` (fast hop, full bucket) →
    ``"dcn_exchange"`` (slow hop, the 1/intra chunk) →
    ``"ici_all_gather"`` (fast hop, rebuild).  Ordering contract
    (HiCCL / the multi-process-per-GPU allreduce paper's hop-overlap
    result — ROADMAP item 1):

    * within a bucket: reduce_scatter < dcn_exchange < all_gather
      (dataflow);
    * buckets enter the schedule in PLAN order (reverse registration —
      the first bucket to close in backward reaches the wire first);
    * EVERY slow-hop op precedes EVERY fast-hop all_gather: all DCN
      transfers are issued before any ICI rebuild, so the slow hop
      starts as early as dataflow allows and the ICI all-gathers
      overlap the remaining DCN traffic instead of serializing ahead
      of it.

    ``mode="striped"`` (ISSUE 11, the multi-path exchange) — each
    bucket's payload is split by :func:`stripe_plan` into an ICI-path
    slice (fast-hop-major exchange: rs over ICI → chunk crossing over
    DCN → ag over ICI) and a DCN-path slice (the TRANSPOSED, slow-hop-
    major exchange: rs over DCN → chunk crossing over ICI → ag over
    DCN), so both fabrics carry bulk traffic at the same time instead
    of hierarchically (FlexLink's use-every-link-at-once result).  Ops
    per bucket: ``dcn_path_scatter`` → ``ici_path_scatter`` →
    ``dcn_path_exchange`` → ``ici_path_exchange``, then per-bucket
    epilogue ``dcn_path_gather`` → ``ici_path_gather``.  Ordering
    contract, generalized from the hierarchical one:

    * within a bucket and phase, the SLOW path's op is issued first
      (its wire is the long pole);
    * per path, dataflow order holds (scatter < exchange < gather);
    * BOTH paths' scatter+exchange ops of every bucket precede ANY
      bucket's gather epilogue — the two paths are concurrently
      eligible end to end, and the rebuilds overlap whatever bulk
      traffic is still draining on either fabric.  This is the
      per-path ordering the generalized census ``hop_ordered`` gate
      validates.

    ``mode="moe"`` (ISSUE 12, the two-stage expert-parallel token
    exchange) — one "bucket" is one MoE layer's dispatch buffer.  Ops
    per bucket: ``ici_dispatch`` (fast hop: tokens regroup by
    destination SLOT within the host, so tokens whose expert lives
    on-host finish here) → ``dcn_dispatch`` (slow hop: only the
    off-host remainder crosses — issued immediately after its fast
    stage, as early as dataflow allows), then the combine epilogue
    ``dcn_combine`` → ``ici_combine`` — the TRANSPOSED reverse, slow
    hop first again so the combine's DCN crossing starts the moment
    the expert compute closes.  The two stages commute as index
    permutations (they act on disjoint buffer dims), so this order is
    a schedule CHOICE with the same result content — pinned here as a
    pure function the dispatch follows literally, like every other
    exchange.
    """
    if n_buckets < 0:
        raise ValueError(f"n_buckets must be >= 0, got {n_buckets}")
    if mode not in ("hierarchical", "striped", "moe"):
        raise ValueError(f"unknown hop_schedule mode {mode!r}")
    schedule = []
    if mode == "moe":
        for b in range(n_buckets):
            schedule.append(("ici_dispatch", b))
            schedule.append(("dcn_dispatch", b))
        for b in range(n_buckets):
            schedule.append(("dcn_combine", b))
            schedule.append(("ici_combine", b))
        return schedule
    if mode == "striped":
        for b in range(n_buckets):
            schedule.append(("dcn_path_scatter", b))
            schedule.append(("ici_path_scatter", b))
            schedule.append(("dcn_path_exchange", b))
            schedule.append(("ici_path_exchange", b))
        for b in range(n_buckets):
            schedule.append(("dcn_path_gather", b))
            schedule.append(("ici_path_gather", b))
        return schedule
    for b in range(n_buckets):
        schedule.append(("ici_reduce_scatter", b))
        schedule.append(("dcn_exchange", b))
    for b in range(n_buckets):
        schedule.append(("ici_all_gather", b))
    return schedule


def stripe_plan(n_elems, ratio):
    """Contiguous two-slice split of a bucket's flat payload for the
    striped exchange: ``(ici_elems, dcn_elems)`` with the ICI-path slice
    at ``flat[:ici_elems]`` and the DCN-path slice at
    ``flat[ici_elems:]``.

    Deterministic pure function of ``(n_elems, ratio)`` — every rank
    traces the identical split, the same cross-process contract
    :func:`plan_buckets` carries.  Properties, pinned by
    tests/communicator_tests:

    * every element lands in exactly one slice
      (``ici_elems + dcn_elems == n_elems``);
    * both slices are contiguous (one split point — the pack stays two
      cheap dynamic slices, never a gather);
    * the DCN share is the committed ratio rounded to whole elements
      (``dcn_elems == round(ratio * n_elems)``);
    * degenerate ratios collapse to a single path: ``ratio == 0`` is
      the strict hierarchical exchange (everything fast-hop-major),
      ``ratio == 1`` routes the whole payload over the slow-hop-major
      path (the flat-one-fabric shape with DCN as the bulk wire).

    The ratio itself is a committed per-topology constant (like
    ``bucket_mb``): the ``bench_scaling --gloo-exchange striped`` ratio
    sweep measures the real bandwidth split on ≥2 hosts and first chip
    contact commits the winner.
    """
    if not 0.0 <= ratio <= 1.0:
        raise ValueError(f"stripe ratio must be in [0, 1], got {ratio}")
    if n_elems < 0:
        raise ValueError(f"n_elems must be >= 0, got {n_elems}")
    dcn_elems = int(round(ratio * n_elems))
    return n_elems - dcn_elems, dcn_elems


# -- measurement-driven planning (ISSUE 19) ----------------------------------
def derived_stripe_ratio(b_ici, b_dcn):
    """The finish-together DCN share from MEASURED per-hop bandwidths —
    docs/performance.md §10's ``r* = B_dcn / (B_ici + B_dcn)``: both
    paths of the striped exchange drain at the same instant when each
    fabric carries bytes in proportion to its bandwidth.

    Deterministic pure function of the two bandwidths (any consistent
    unit — only the ratio matters).  Properties, pinned by
    tests/communicator_tests/test_autotune.py:

    * monotone non-decreasing in ``b_dcn`` (a faster slow fabric earns
      a larger share) and non-increasing in ``b_ici``;
    * recovers :data:`DEFAULT_STRIPE_RATIO` (0.25) exactly at the 1:3
      DCN:ICI seed ratio;
    * clamped to the OPEN interval (0, 1): a derived plan never
      collapses the striped exchange to a degenerate single path —
      hand knobs may pin 0 or 1, the planner never does;
    * non-finite or non-positive bandwidths raise (an unmeasured hop is
      the caller's fallback branch, never a silent 0-bandwidth input).
    """
    b_ici, b_dcn = float(b_ici), float(b_dcn)
    if not (np.isfinite(b_ici) and np.isfinite(b_dcn)) \
            or b_ici <= 0 or b_dcn <= 0:
        raise ValueError(
            f"derived_stripe_ratio needs positive finite per-hop "
            f"bandwidths, got b_ici={b_ici!r} b_dcn={b_dcn!r}; an "
            f"unmeasured hop falls back to DEFAULT_STRIPE_RATIO "
            f"explicitly at the call site")
    ratio = b_dcn / (b_ici + b_dcn)
    eps = 1e-6
    return min(1.0 - eps, max(eps, ratio))


def derived_bucket_bytes(gbps, lat_us, overhead_frac=0.125,
                         floor_mb=1.0, cap_mb=32.0):
    """Bucket bound (BYTES) from a measured hop's (bandwidth, latency):
    the smallest bucket whose wire time keeps per-collective launch
    overhead under ``overhead_frac`` of the transfer —
    ``bytes = bandwidth × latency / overhead_frac`` — clamped to
    [``floor_mb``, ``cap_mb``] MB and rounded to 2 significant digits
    so the derived knob is a stable, human-readable census value
    rather than a noisy float.

    Deterministic pure function; small buckets stay schedulable (the
    overlap property §7 measures), huge buckets would serialize the
    exchange behind backward, hence the cap.
    """
    gbps, lat_us = float(gbps), float(lat_us)
    if not (np.isfinite(gbps) and np.isfinite(lat_us)) \
            or gbps <= 0 or lat_us < 0:
        raise ValueError(
            f"derived_bucket_bytes needs a positive finite bandwidth "
            f"and a non-negative latency, got gbps={gbps!r} "
            f"lat_us={lat_us!r}")
    raw = gbps * 1e9 * (lat_us * 1e-6) / float(overhead_frac)
    mb = min(float(cap_mb), max(float(floor_mb), raw / (1 << 20)))
    if mb > 0:
        from math import floor, log10
        digits = 1 - int(floor(log10(abs(mb))))
        mb = round(mb, digits)
    return int(round(min(float(cap_mb), max(float(floor_mb), mb))
                     * (1 << 20)))


def plan_buckets_from_measurement(shapes, dtypes, gbps, lat_us,
                                  overhead_frac=0.125):
    """:func:`plan_buckets` with the bound DERIVED from a measured hop
    (the measurement-driven entry point ``autotune=`` calls) — the
    partition properties are exactly :func:`plan_buckets`'s."""
    return plan_buckets(shapes, dtypes,
                        derived_bucket_bytes(gbps, lat_us,
                                             overhead_frac=overhead_frac))


def stripe_plan_from_measurement(n_elems, b_ici, b_dcn):
    """:func:`stripe_plan` with the ratio DERIVED from measured per-hop
    bandwidths (the measurement-driven entry point ``autotune=``
    calls) — the split properties are exactly :func:`stripe_plan`'s."""
    return stripe_plan(n_elems, derived_stripe_ratio(b_ici, b_dcn))


def pad_to_multiple(flat, multiple):
    """Zero-pad a 1-D vector up to the next multiple (a tiled
    ``psum_scatter``/``all_gather`` needs the scattered dim divisible by
    the axis size).  Returns ``(padded, true_length)``."""
    n = flat.shape[0]
    n_pad = -(-n // multiple) * multiple
    if n_pad == n:
        return flat, n
    return jnp.pad(flat, (0, n_pad - n)), n


# -- quantized wire dtypes (ISSUE 8) ----------------------------------------
#: wire dtypes the compressed gradient exchange quantizes to, mapped to
#: the largest magnitude each can represent (the symmetric-scale
#: target).  int8 uses the symmetric range ±127 (−128 is never emitted
#: — a symmetric codebook keeps Q(−v) == −Q(v), so the residual math
#: telescopes without a sign bias).  The fp8 names follow the ISSUE's
#: spelling; jax's dtype is the OCP ``e4m3fn`` variant (finite-only,
#: max 448) and ``e5m2`` (max 57344).
QUANTIZED_DTYPES = {
    "int8": 127.0,
    "float8_e4m3": 448.0,
    "float8_e5m2": 57344.0,
}

def resolve_grad_dtype(dtype):
    """``allreduce_grad_dtype`` entry → jnp dtype, accepting the
    quantized wire names (``"float8_e4m3"`` resolves to jax's
    ``float8_e4m3fn``).  ``None`` passes through (lossless)."""
    if dtype is None:
        return None
    name = str(dtype)
    if name in ("float8_e4m3", "float8_e4m3fn"):
        return jnp.dtype(jnp.float8_e4m3fn)
    if name == "float8_e5m2":
        return jnp.dtype(jnp.float8_e5m2)
    return jnp.dtype(dtype)


def _quant_key(dtype):
    """Canonical QUANTIZED_DTYPES key of a dtype, or ``None``."""
    if dtype is None:
        return None
    name = str(jnp.dtype(dtype) if not isinstance(dtype, str) else dtype)
    name = {"float8_e4m3fn": "float8_e4m3"}.get(name, name)
    return name if name in QUANTIZED_DTYPES else None


def is_quantized_dtype(dtype):
    """True for the int8/fp8 wire dtypes the quantized exchange owns
    (bf16/fp16 are plain casts — they ride the lossy-cast path, not the
    scale+residual machinery)."""
    return _quant_key(dtype) is not None


def quantize_symmetric(v, wire_dtype):
    """Per-bucket symmetric quantization: ``(q, scale)`` with
    ``q ≈ v / scale`` stored in ``wire_dtype`` and
    ``scale = absmax(v) / qmax``.

    Contract (pinned by tests/communicator_tests/test_quantization.py):

    * **deterministic** — a pure elementwise function of ``v``; every
      rank quantizing the same buffer computes the same ``(q, scale)``
      (the cross-rank agreement the dequantize-sum relies on);
    * **zero-safe** — an all-zero (or empty) bucket quantizes to zeros
      with ``scale = 1`` (never a 0/0);
    * **non-finite-safe** — ``±inf`` saturates to ``±qmax`` (the scale
      is computed over the FINITE values only, so one overflowed
      gradient cannot zero out the rest of the bucket); ``NaN`` encodes
      as 0.  The residual for non-finite inputs is defined as 0 by
      :func:`quantization_residual` — error feedback must not turn one
      bad step into a permanently poisoned buffer.

    Round-trip bound: for finite ``v``, ``|v − q·scale| ≤ scale/2``
    per element for int8 (round-to-nearest on a uniform codebook) and
    ``≤ absmax · 2^−m`` relative for fp8 with ``m`` mantissa bits.
    """
    wire = resolve_grad_dtype(wire_dtype)
    qmax = QUANTIZED_DTYPES[_quant_key(wire)]
    v = v.astype(jnp.float32)
    finite = jnp.isfinite(v)
    absmax = jnp.max(jnp.abs(jnp.where(finite, v, 0.0))) \
        if v.size else jnp.float32(0.0)
    scale = jnp.where(absmax > 0, absmax / qmax, 1.0).astype(jnp.float32)
    scaled = jnp.clip(jnp.where(jnp.isnan(v), 0.0, v) / scale,
                      -qmax, qmax)
    if jnp.issubdtype(wire, jnp.integer):
        q = jnp.round(scaled).astype(wire)
    else:
        q = scaled.astype(wire)
    return q, scale


def dequantize_symmetric(q, scale):
    """Inverse of :func:`quantize_symmetric`: ``q·scale`` in f32."""
    return q.astype(jnp.float32) * scale


def quantize_symmetric_segments(v, wire_dtype):
    """Per-SEGMENT symmetric quantization along the leading axis: one
    ``(q, scale)`` pair per segment, via :func:`quantize_symmetric`
    vmapped over ``v[0]`` — the MoE dispatch's slow-crossing codebook
    (ISSUE 12).  Each destination group's block quantizes with its OWN
    scale (one absmax per segment, so a hot expert's activations cannot
    flatten a quiet one's codewords), and the ``[segments]`` scale
    vector ships alongside the codewords on its own tiny collective.
    Inherits quantize_symmetric's determinism/zero/non-finite
    contracts per segment.  Returns ``(q [S, ...], scales [S])``."""
    return jax.vmap(lambda seg: quantize_symmetric(seg, wire_dtype))(v)


def quantization_residual(v, q, scale):
    """Error-feedback residual ``v − Q(v)``, sanitized: positions where
    ``v`` was non-finite carry 0 (their information is unrepresentable —
    carrying ±inf/NaN forward would poison every later step)."""
    v = v.astype(jnp.float32)
    r = v - dequantize_symmetric(q, scale)
    return jnp.where(jnp.isfinite(v) & jnp.isfinite(r), r, 0.0)


def quantize_with_feedback(v, residual, wire_dtype):
    """The one quantization prologue every compressed hop shares (flat
    transform, hierarchical DCN branch, sharded-update slow hop):
    ``v`` is accumulated in f32, the carried ``residual`` (or ``None``
    when error feedback is off) is added before quantizing, and the new
    residual ``v − Q(v)`` is returned (``None`` without feedback).
    Returns ``(q, scale, new_residual)``."""
    v = v.astype(jnp.float32)
    if residual is not None:
        v = v + residual
    q, scale = quantize_symmetric(v, wire_dtype)
    new_residual = quantization_residual(v, q, scale) \
        if residual is not None else None
    return q, scale, new_residual


def dequantize_sum(q_stacked, scales):
    """Sum of per-rank dequantized buffers: ``q_stacked`` is the
    gathered ``(size, n)`` codewords, ``scales`` the gathered ``(size,)``
    per-rank scales — each rank's codewords decode with ITS OWN scale
    before the f32 accumulation (summing codewords directly would be
    meaningless across scales)."""
    return jnp.sum(dequantize_symmetric(q_stacked, scales[:, None]),
                   axis=0)


def quantized_hop_bytes(chunk_elems, size, collective, wire_dtype):
    """Per-replica wire bytes of the QUANTIZED slow-hop exchange on a
    ``chunk_elems`` per-rank chunk over ``size`` ranks, priced at the
    wire dtype's itemsize (the packed buffer that actually crosses —
    never the gradient dtype's):

    * ``"psum"`` (the hierarchical allreduce's DCN hop): implemented as
      an ``all_gather`` of the quantized chunk + dequantize-sum —
      ``chunk_q · (size−1)`` per replica.  vs the f32 chunk allreduce's
      ``8 · chunk · (size−1)/size`` this is ``itemsize·size/8`` of the
      lossless crossing: exactly the quantized fraction at ``size=2``
      (1/4 for int8), break-even at ``size = 8/itemsize`` — the
      decision table in docs/performance.md §9.
    * ``"reduce_scatter"`` (the sharded-update DCN hop): an
      ``all_to_all`` of the quantized chunk's segments —
      ``chunk_q · (size−1)/size``: exactly the quantized fraction of
      the f32 reduce-scatter crossing at ANY ``size``.

    The per-bucket scale scalars also cross (one f32 ``all_gather`` per
    bucket) — O(buckets), excluded here as they are from the census's
    gradient rows (below ``GRAD_ELEMS_FLOOR``).
    """
    if size <= 1:
        return 0
    itemsize = resolve_grad_dtype(wire_dtype).itemsize
    n_bytes = chunk_elems * itemsize
    if collective == "psum":
        return int(n_bytes * (size - 1))
    if collective == "reduce_scatter":
        return int(n_bytes * (size - 1) / size)
    raise ValueError(f"unknown quantized collective {collective!r}")


def bucket_table(shapes, dtypes, bucket_bytes):
    """Human/probe-facing accounting of a bucket plan: one row per
    bucket with its leaf count, element count, bytes, and dtype."""
    rows = []
    for b, idx in enumerate(plan_buckets(shapes, dtypes, bucket_bytes)):
        dt = jnp.dtype(dtypes[idx[0]])
        elems = sum(int(np.prod(shapes[i])) for i in idx)
        rows.append({"bucket": b, "n_leaves": len(idx),
                     "elems": elems, "bytes": elems * dt.itemsize,
                     "dtype": str(dt)})
    return rows


def exchanged_bytes(n_bytes, size, collective):
    """Per-replica wire bytes of one collective on an ``n_bytes`` FULL
    buffer (for ``all_gather``, the gathered result — chunk × size)
    over ``size`` ranks, under the standard ring/bandwidth-optimal
    decomposition (the accounting tools/comm_budgets.json commits):

    * ``psum`` (allreduce)   → ``2 · n · (size-1)/size``
      (reduce-scatter phase + all-gather phase)
    * ``reduce_scatter``     → ``n · (size-1)/size``
    * ``all_gather``         → ``n · (size-1)/size``
    * ``all_to_all``         → ``n · (size-1)/size``
      (each rank keeps its own segment; the quantized reduce-scatter
      rides this — every segment crosses once, priced at the operand's
      own wire dtype)

    This is why the reduce-scatter update halves per-replica exchanged
    GRADIENT bytes vs allreduce: the gradient crosses the wire once
    (reduce-scatter) instead of twice; the step's other transfer — the
    params all-gather — is parameter bytes, accounted separately.
    """
    if size <= 1:
        return 0
    frac = (size - 1) / size
    if collective == "psum":
        return int(2 * n_bytes * frac)
    if collective in ("reduce_scatter", "all_gather", "all_to_all"):
        return int(n_bytes * frac)
    raise ValueError(f"unknown collective {collective!r}")


def hierarchical_exchanged_bytes(n_bytes, intra_size, inter_size,
                                 collective="psum", dcn_n_bytes=None):
    """Per-replica wire bytes of the two-level (ici × dcn) exchange on an
    ``n_bytes`` FULL buffer, split by hop: ``{"ici": ..., "dcn": ...}``.

    The slow hop only ever sees the 1/intra chunk the ICI reduce-scatter
    leaves on each device — the tentpole's byte contract (DCN payload =
    ``n_bytes / intra_size``).  ``dcn_n_bytes`` overrides that chunk's
    byte count for the per-hop-dtype variant (bf16 over DCN while ICI
    stays lossless: half the chunk bytes on the slow hop only).

    * ``"psum"`` (the hierarchical allreduce exchange):
      ICI carries the reduce-scatter AND the all-gather phase
      (``2·n·(intra-1)/intra``); DCN carries a chunk allreduce
      (``2·chunk·(inter-1)/inter``).
    * ``"reduce_scatter"`` / ``"all_gather"`` (the hierarchical DP
      update's gradient / params-rebuild halves): one crossing per hop
      (``n·(intra-1)/intra`` over ICI, ``chunk·(inter-1)/inter`` over
      DCN).

    Identity, pinned by tests: with matching dtypes the hop totals sum
    to the flat ring figure over ``intra·inter`` ranks —
    ``2n(intra-1)/intra + 2(n/intra)(inter-1)/inter =
    2n(intra·inter-1)/(intra·inter)`` — the hierarchy relocates bytes
    onto the fast wires, it does not add any.
    """
    if intra_size < 1 or inter_size < 1:
        raise ValueError(
            f"intra_size/inter_size must be >= 1, got "
            f"{intra_size}/{inter_size}")
    if n_bytes % intra_size:
        # callers pad buckets to a multiple of intra before the wire
        raise ValueError(
            f"n_bytes={n_bytes} not divisible by intra_size={intra_size} "
            f"(pad_to_multiple the bucket first — the accounting must "
            f"match the traced buffer)")
    chunk = n_bytes // intra_size if dcn_n_bytes is None else dcn_n_bytes
    ici = exchanged_bytes(n_bytes, intra_size, "reduce_scatter")
    dcn = exchanged_bytes(chunk, inter_size, "reduce_scatter")
    if collective == "psum":
        return {"ici": 2 * ici, "dcn": 2 * dcn}
    if collective in ("reduce_scatter", "all_gather"):
        return {"ici": ici, "dcn": dcn}
    raise ValueError(f"unknown collective {collective!r}")


def striped_exchanged_bytes(n_bytes, intra_size, inter_size, ratio,
                            itemsize=4, dcn_itemsize=None):
    """Per-replica wire bytes of the STRIPED exchange (ISSUE 11) on an
    ``n_bytes`` full buffer, split by PATH and by FABRIC::

        {"ici_path": {"ici": ..., "dcn": ..., "total": ...},
         "dcn_path": {"ici": ..., "dcn": ..., "total": ...}}

    The ICI-path slice (share ``1 - ratio``) runs the fast-hop-major
    exchange — its bulk (rs + ag) rides ICI, only its ``1/intra`` chunk
    allreduce crosses DCN.  The DCN-path slice (share ``ratio``) runs
    the TRANSPOSED slow-hop-major exchange — its bulk rides DCN, only
    its ``1/inter`` chunk allreduce crosses ICI.  Each path is priced by
    :func:`hierarchical_exchanged_bytes` with its own (fast, slow)
    orientation.

    Identities, pinned by tests (exact when the split divides cleanly;
    each slice otherwise pads to its ring multiple exactly like the
    wire does — ``pad_to_multiple`` before the bulk scatter — so the
    figures track the traced program, with the usual pad slack):

    * **conservation**: ``ici_path.total + dcn_path.total`` equals the
      flat allreduce's per-replica figure over ``intra × inter`` ranks
      (each path's hop totals already telescope to the flat ring figure
      for its slice — striping relocates bytes, it adds none);
    * **committed share**: ``dcn_path.total / grand total == ratio`` —
      per-path totals are proportional to slice sizes, so the DCN
      path's byte share IS the committed split ratio.

    ``dcn_itemsize`` prices only the DCN-fabric crossings at a
    different wire dtype (the per-hop-dtype variant: the ICI-path
    chunk's DCN allreduce AND the DCN-path slice's bulk rs/ag both ride
    the compressed wire, ICI stays lossless).  The DCN-path slice's ICI
    chunk crossing is always priced at f32 — the transform upcasts it
    before the fast-hop allreduce (lossless-over-ICI by design).

    This is the ONE per-path pricing surface: bench.py's striped rows
    route through it, so the committed identities and the bench
    columns cannot drift apart.
    """
    elems = n_bytes // itemsize
    if elems * itemsize != n_bytes:
        raise ValueError(
            f"n_bytes={n_bytes} is not a multiple of itemsize={itemsize}")
    ici_elems, dcn_elems = stripe_plan(elems, ratio)
    n_i = -(-ici_elems // intra_size) * intra_size * itemsize
    n_d = -(-dcn_elems // inter_size) * inter_size * itemsize
    dcn_scale = (dcn_itemsize / itemsize) if dcn_itemsize else 1.0
    # fast-hop-major path: hierarchical_exchanged_bytes as-is (the
    # per-hop-dtype override compresses only its DCN chunk crossing)
    a = hierarchical_exchanged_bytes(
        n_i, intra_size, inter_size, "psum",
        dcn_n_bytes=int(n_i // intra_size * dcn_scale)
        if dcn_itemsize else None) if n_i else {"ici": 0, "dcn": 0}
    # slow-hop-major path: the same formula with the hops TRANSPOSED —
    # its "intra" ring is the DCN axis (bulk rs+ag, compressed under the
    # per-hop dtype) and its chunk crossing rides ICI (lossless by
    # design: the chunk upcasts to f32 before the fast-hop allreduce);
    # relabel the returned hops back to fabrics
    b = hierarchical_exchanged_bytes(
        int(n_d * dcn_scale), inter_size, intra_size, "psum",
        dcn_n_bytes=n_d // itemsize // inter_size * 4) \
        if n_d else {"ici": 0, "dcn": 0}
    ici_path = {"ici": a["ici"], "dcn": a["dcn"]}
    dcn_path = {"dcn": b["ici"], "ici": b["dcn"]}
    for p in (ici_path, dcn_path):
        p["total"] = p["ici"] + p["dcn"]
    return {"ici_path": ici_path, "dcn_path": dcn_path}


def moe_dispatch_exchanged_bytes(n_bytes, intra_size, inter_size,
                                 two_stage=True, dcn_n_bytes=None):
    """Per-replica wire bytes of ONE MoE layer's token exchange — the
    dispatch + combine round trip on an ``n_bytes`` capacity buffer
    (``[E, C, D]`` at the compute wire dtype) — split by fabric
    (ISSUE 12):

    * ``two_stage=True``: an ``all_to_all`` over ICI each way
      (``n·(intra−1)/intra``) plus an ``all_to_all`` over DCN each way
      carrying only the off-host remainder (``n·(inter−1)/inter`` —
      the ring keeps the own-host segment local, so the slow-fabric
      bill IS the ``off_host_dispatch_ratio`` share of the buffer).
      ``dcn_n_bytes`` overrides the slow crossing's buffer bytes for
      the compressed variants (bf16 halves it, int8/fp8 quarter it;
      the per-segment scale vectors are O(inter) — excluded, like the
      gradient census's scale gathers).  Returns ``{"ici", "dcn"}``.
    * ``two_stage=False``: the flat single collective — one
      ``all_to_all`` each way over the JOINT ``intra·inter`` ring
      (``n·(E−1)/E``), one fabric label, unsplittable and
      uncompressible per hop.  Returns ``{"world": ...}``.

    This is the ONE pricing surface bench.py's MoE rows and the
    committed MoE census identities share.
    """
    if two_stage:
        ici = exchanged_bytes(n_bytes, intra_size, "all_to_all")
        dcn = exchanged_bytes(
            n_bytes if dcn_n_bytes is None else dcn_n_bytes,
            inter_size, "all_to_all")
        return {"ici": 2 * ici, "dcn": 2 * dcn}
    world = exchanged_bytes(n_bytes, intra_size * inter_size,
                            "all_to_all")
    return {"world": 2 * world}


def pack_params(params, attr="grad", dtype=None):
    """Pack ``param.<attr>`` of a parameter list into one flat vector.

    Reference-shaped API (``pack_params(params, 'grad', buffer)``); returns
    (flat, spec) instead of filling a caller-owned arena.
    """
    arrays = [getattr(p, attr) for p in params]
    return tree_pack(arrays, dtype=dtype)


def unpack_params(params, flat, spec, attr="grad"):
    arrays = tree_unpack(flat, spec)
    for p, a in zip(params, arrays):
        setattr(p, attr, a)
