"""Capacity transfer — one pool of chips following the traffic.

ISSUE 16 (ROADMAP item 5, the elastic story finished): PR 15's serving
fleet deliberately stopped at "scale decisions are surfaced, not
auto-applied" — :class:`~chainermn_tpu.serving.fleet
.QueueDepthScalePolicy` reads the queue-depth gauges and emits +1/-1
but nothing moves.  This module is the EXECUTOR: a
:class:`CapacityBroker` that answers sustained queue pressure by
moving a training rank into the serving fleet (clean leave → the PR 10
shrink preserves the global batch → re-register under the ``fleet``
role → adopt serving weights over the PR 15 multicast tree) and moves
it back when the queues drain (retire → re-join training through the
snapshot-sync grow path).

The conversion is a typed multi-step state machine::

    LEAVE_ANNOUNCED → CONVERTING → SERVING → RETIRING → REJOINING

journaled in the KV store (``<ns>/capacity/<rank>``, shared by BOTH
role groups — see
:meth:`~chainermn_tpu.communicators.ElasticMembership
.journal_conversion`) BEFORE each step executes, so a preempt landing
at ANY step leaves a record survivors can act on:
:meth:`CapacityBroker.recover_orphans` detects a journal entry whose
beat has frozen past ``stale_s`` (the observer-clock staleness idiom
the membership protocol's ``stall_s`` screen uses) and rolls the world
forward — completing the step when its effects already landed,
aborting it (scrubbing half-admitted replicas and standing join
intents) when they did not.  The failure matrix is pinned step by step
in ``tests/resilience_tests/test_capacity.py`` and documented in
``docs/resilience.md`` §8.

Safety rails:

* **hysteresis** — the policy's high/low water marks + per-direction
  re-arm collapse a sustained spike to one decision, and the broker
  adds per-direction COOLDOWNS (``convert_cooldown_s`` /
  ``retire_cooldown_s``) so oscillating load cannot thrash
  conversions;
* **floors for BOTH roles** — training never shrinks below
  ``min_world``, the fleet never below one replica; a violating
  request refuses with a typed :class:`CapacityFloorError` carrying
  both role views;
* **chaos hooks** — every conversion step consults the
  :class:`~chainermn_tpu.communicators.fault_schedule.FaultSchedule`
  (op ``"capacity.convert"``, ``step=`` the state name), so the chaos
  suite kills mid-conversion deterministically
  (``FaultSpec(op="capacity.convert", action="preempt",
  step="CONVERTING", ...)``).

Observability: spans ``capacity/leave`` / ``capacity/convert`` /
``capacity/retire`` and the per-role world-size gauge
``chainermn_tpu_role_world_size{role=...}``.
"""

from __future__ import annotations

import time

from .. import observability
from ..communicators._membership import MembershipView

__all__ = ["CONVERSION_STEPS", "CapacityFloorError",
           "CapacityProtocolError", "CapacityBroker", "LocalTrainGroup"]

#: the conversion state machine, in order.  ``LEAVE_ANNOUNCED`` /
#: ``CONVERTING`` / ``SERVING`` belong to the training→fleet leg,
#: ``RETIRING`` / ``REJOINING`` to the way back; ``SERVING`` is the
#: steady state a converted rank parks in between the two legs.
CONVERSION_STEPS = ("LEAVE_ANNOUNCED", "CONVERTING", "SERVING",
                    "RETIRING", "REJOINING")

#: legal journal transitions (``None`` = no standing entry)
_NEXT = {None: ("LEAVE_ANNOUNCED",),
         "LEAVE_ANNOUNCED": ("CONVERTING",),
         "CONVERTING": ("SERVING",),
         "SERVING": ("RETIRING",),
         "RETIRING": ("REJOINING",),
         "REJOINING": ()}

#: the fault-schedule op every conversion step consults
FAULT_OP = "capacity.convert"


class CapacityFloorError(RuntimeError):
    """A capacity transfer would breach a role's floor (training below
    ``min_world``, or the fleet below one live replica).  Refused, not
    clamped — carries BOTH role views so the operator reads the whole
    world in one exception."""

    def __init__(self, message, training_view=None, fleet_view=None):
        self.training_view = training_view
        self.fleet_view = fleet_view
        detail = []
        if training_view is not None:
            detail.append(f"training={list(training_view.members)}")
        if fleet_view is not None:
            detail.append(f"fleet={list(fleet_view.members)}")
        super().__init__(message + (f" ({', '.join(detail)})"
                                    if detail else ""))


class CapacityProtocolError(RuntimeError):
    """An illegal conversion-state transition (a journal write that
    skips or rewinds the state machine) — always a caller bug, never a
    runtime condition, so it is typed separately from the floor
    refusal."""


class LocalTrainGroup:
    """Single-controller stand-in for the TRAINING side of a capacity
    transfer (the analog of the fleet's ``_LocalConsensus``): leaves
    and joins apply immediately, the epoch bumps on every change, and
    the conversion journal lives in a dict.  The bench's diurnal
    scenario and the tier-1 broker tests drive this; the gloo leg
    swaps in a real :class:`~chainermn_tpu.communicators
    .ElasticMembership` pair sharing one KV store."""

    role = "elastic"

    def __init__(self, world=2, rank=0):
        self.rank = int(rank)
        self.world = int(world)
        self._epoch = 0
        self._members = tuple(range(self.world))
        self._journal = {}

    def current_epoch(self):
        return self._epoch

    def current_view(self):
        return MembershipView(self._epoch, self._members, role=self.role)

    def announce_leave(self, note="", rank=None):
        r = self.rank if rank is None else int(rank)
        if r in self._members:
            self._members = tuple(m for m in self._members if m != r)
            self._epoch += 1

    def announce_join(self, note="", rank=None):
        r = self.rank if rank is None else int(rank)
        if r not in self._members:
            self._members = tuple(sorted(self._members + (r,)))
            self._epoch += 1

    def retract_join(self, rank=None):
        pass

    def pending_joins(self, view=None):
        return ()

    # -- conversion journal (dict-backed mirror of the KV protocol) ----------
    def journal_conversion(self, step, note="", rank=None, beat=None):
        r = self.rank if rank is None else int(rank)
        prev = self._journal.get(r)
        if beat is None:
            beat = (prev[1] + 1) if prev is not None else 1
        self._journal[r] = (str(step), int(beat), str(note))

    def read_conversion(self, rank):
        return self._journal.get(int(rank))

    def scan_conversions(self):
        return dict(self._journal)

    def clear_conversion(self, rank=None):
        self._journal.pop(self.rank if rank is None else int(rank), None)


class CapacityBroker:
    """The capacity-transfer executor over one training group and one
    serving fleet (see module docstring).

    ``train``: the training side's membership — a real
    :class:`~chainermn_tpu.communicators.ElasticMembership` (the
    broker acts for its own rank, or for another rank when the
    membership accepts ``rank=``) or the single-controller
    :class:`LocalTrainGroup`.  Must expose the conversion-journal
    surface (``journal_conversion`` / ``scan_conversions`` / ...).
    ``fleet``: the :class:`~chainermn_tpu.serving.fleet.ReplicaFleet`
    (held by reference; the broker uses only its public
    join/retire/preempt/discard surface).
    ``engine_factory``: ``factory(rank) -> ServingEngine`` for a
    converting rank the caller hands no engine (the tree sync
    overwrites its weights bit-identically from the fleet root).
    ``recovery``: optional
    :class:`~chainermn_tpu.extensions.ElasticRecovery` — when the
    broker runs ON the converting rank, leaves/rejoins ride the
    supervisor's own protocol helpers (``capacity_leave`` /
    ``capacity_rejoin``) so the training side shrinks and grows
    through the PR 10 paths.
    ``min_world``: the training floor; the fleet floor is
    ``max(1, fleet.min_replicas)``.
    ``convert_cooldown_s`` / ``retire_cooldown_s``: per-direction
    cooldowns :meth:`apply` enforces on top of the policy's own
    hysteresis.
    ``stale_s``: how long a journal entry's beat must be frozen (on
    THIS observer's clock) before :meth:`recover_orphans` treats the
    conversion as orphaned.
    ``schedule``: optional fault schedule consulted at every step.
    ``auto_apply``: ``False`` preserves PR 15's surfaced-only behavior
    — :meth:`apply` records the decision and moves nothing.
    """

    def __init__(self, train, fleet, engine_factory=None, recovery=None,
                 min_world=1, convert_cooldown_s=0.0,
                 retire_cooldown_s=0.0, stale_s=2.0, schedule=None,
                 auto_apply=True, donor=None, clock=time.monotonic,
                 sleep=time.sleep):
        self.train = train
        self.fleet = fleet
        self.engine_factory = engine_factory
        self.recovery = recovery
        self.min_world = int(min_world)
        self.fleet_floor = max(1, getattr(fleet, "min_replicas", 1))
        self.convert_cooldown_s = float(convert_cooldown_s)
        self.retire_cooldown_s = float(retire_cooldown_s)
        self.stale_s = float(stale_s)
        self.schedule = schedule
        self.auto_apply = bool(auto_apply)
        self._donor = donor
        self._clock = clock
        self._sleep = sleep
        self.converted = {}          # training rank -> fleet rid
        self._last_convert = None
        self._last_retire = None
        self._orphan_seen = {}       # rank -> ((step, beat), first-seen t)
        self.stats = {"conversions": 0, "retires": 0,
                      "role_transfers": 0, "convert_s": 0.0,
                      "floor_refusals": 0, "surfaced": 0,
                      "aborted": 0, "rolled_forward": 0}
        self._publish_gauges()

    # -- plumbing ------------------------------------------------------------

    @property
    def train_role(self):
        return getattr(self.train, "role", "elastic")

    def _fleet_view(self):
        view = getattr(self.fleet, "view", None)
        if view is not None:
            return view
        return MembershipView(0, [r.rid for r in
                                  self.fleet.live_replicas()],
                              role="fleet")

    def _hook(self, step):
        """Fault-schedule hook: one consult per conversion step.  A
        ``delay`` fault sleeps in place; everything else raises its
        typed exception HERE — after the step was journaled, before it
        executed — which is exactly the mid-conversion crash the
        recovery matrix handles."""
        if self.schedule is None:
            return
        fault = self.schedule.on_call(FAULT_OP, step=step)
        if fault is None:
            return
        if fault.action == "delay":
            self._sleep(fault.spec.delay_s)
            return
        raise fault.make_exception()

    def _journal(self, rank, step, note=""):
        prev = self.train.read_conversion(rank)
        prev_step = prev[0] if prev is not None else None
        if step not in _NEXT.get(prev_step, ()):
            raise CapacityProtocolError(
                f"illegal conversion transition {prev_step!r} -> "
                f"{step!r} for rank {rank} (order: "
                f"{' -> '.join(CONVERSION_STEPS)})")
        self.train.journal_conversion(step, note=note, rank=rank)

    def _train_leave(self, rank, note):
        if self.recovery is not None \
                and rank == self.recovery.stable_rank:
            self.recovery.capacity_leave(note=note)
        elif getattr(self.train, "rank", None) == rank:
            self.train.announce_leave(note=note)
        else:
            self.train.announce_leave(note=note, rank=rank)

    def _train_join(self, rank, note):
        if self.recovery is not None \
                and rank == self.recovery.stable_rank:
            self.recovery.capacity_rejoin(note=note)
        elif getattr(self.train, "rank", None) == rank:
            self.train.announce_join(note=note)
        else:
            self.train.announce_join(note=note, rank=rank)

    def _publish_gauges(self):
        reg = observability.registry()
        gauge = reg.gauge(
            "chainermn_tpu_role_world_size",
            help="controller ranks per role group (the capacity "
                 "broker's two-role world view)")
        gauge.set(self.train.current_view().size, role=self.train_role)
        gauge.set(len(self.fleet.live_replicas()), role="fleet")

    # -- the two legs --------------------------------------------------------

    def convert_to_serving(self, rank=None, engine=None, now=None):
        """training → fleet: clean leave, fleet admission, tree weight
        sync.  Returns the converted training rank.  Raises
        :class:`CapacityFloorError` when training would shrink below
        ``min_world``; a fault-schedule preempt mid-way leaves the
        journal at the step it reached (the recovery matrix's input).
        """
        t0 = self._clock()
        train_view = self.train.current_view()
        if rank is None:
            rank = (self._donor(train_view) if self._donor is not None
                    else max(train_view.members))
        rank = int(rank)
        fleet_view = self._fleet_view()
        if rank not in train_view:
            raise CapacityFloorError(
                f"rank {rank} is not a training member",
                training_view=train_view, fleet_view=fleet_view)
        if train_view.size - 1 < self.min_world:
            self.stats["floor_refusals"] += 1
            raise CapacityFloorError(
                f"converting rank {rank} would shrink training below "
                f"min_world={self.min_world}",
                training_view=train_view, fleet_view=fleet_view)
        with observability.span("capacity/leave", tags={"rank": rank}):
            self._journal(rank, "LEAVE_ANNOUNCED",
                          note="queue pressure")
            self._hook("LEAVE_ANNOUNCED")
            self._train_leave(
                rank, note="capacity transfer: converting to serving")
        with observability.span("capacity/convert", tags={"rank": rank}):
            self._journal(rank, "CONVERTING")
            self._hook("CONVERTING")
            if engine is None:
                if self.engine_factory is None:
                    raise ValueError("convert_to_serving needs engine= "
                                     "or a broker engine_factory")
                engine = self.engine_factory(rank)
            rid = rank if rank not in self.fleet.replicas \
                else max(self.fleet.replicas) + 1
            self.fleet.join(engines={rid: engine})
            self._journal(rank, "SERVING")
            self._hook("SERVING")
        self.converted[rank] = rid
        self.stats["conversions"] += 1
        self.stats["role_transfers"] += 1
        self.stats["convert_s"] += self._clock() - t0
        self._last_convert = now if now is not None else self._clock()
        self._publish_gauges()
        return rank

    def retire_to_training(self, rank=None, now=None):
        """fleet → training: graceful retire (in-flight work reroutes
        first), then re-join through the training grow path.  Returns
        the returned rank.  Raises :class:`CapacityFloorError` when
        the retire would leave the fleet below one live replica."""
        t0 = self._clock()
        if rank is None:
            if not self.converted:
                raise CapacityFloorError(
                    "no converted rank to retire",
                    training_view=self.train.current_view(),
                    fleet_view=self._fleet_view())
            rank = next(reversed(self.converted))   # LIFO: newest
            #                                         stint ends first
        rank = int(rank)
        rid = self.converted.get(rank, rank)
        live = {r.rid for r in self.fleet.live_replicas()}
        if rid in live and len(live) - 1 < self.fleet_floor:
            self.stats["floor_refusals"] += 1
            raise CapacityFloorError(
                f"retiring replica {rid} would shrink the fleet below "
                f"its floor of {self.fleet_floor}",
                training_view=self.train.current_view(),
                fleet_view=self._fleet_view())
        with observability.span("capacity/retire",
                                tags={"rank": rank, "rid": rid}):
            self._journal(rank, "RETIRING")
            self._hook("RETIRING")
            if rid in live:
                self.fleet.retire(rid, now=now)
            self._journal(rank, "REJOINING")
            self._hook("REJOINING")
            self._train_join(
                rank, note="capacity transfer: rejoining training")
            self.train.clear_conversion(rank)
        self.converted.pop(rank, None)
        self.stats["retires"] += 1
        self.stats["role_transfers"] += 1
        self.stats["convert_s"] += self._clock() - t0
        self._last_retire = now if now is not None else self._clock()
        self._publish_gauges()
        return rank

    # -- auto-apply ----------------------------------------------------------

    def apply(self, decision, now=None):
        """Execute one scale decision (the policy's +1/-1/0).  Returns
        ``("convert", rank)`` / ``("retire", rank)`` / ``None``.

        ``auto_apply=False`` preserves PR 15: the decision is counted
        (``stats["surfaced"]``) and nothing moves.  Per-direction
        cooldowns and floor refusals also answer ``None`` — the broker
        never half-applies; floors raise only on DIRECT calls where
        the caller asked for that specific transfer."""
        if not decision:
            return None
        if not self.auto_apply:
            self.stats["surfaced"] += 1
            return None
        t = now if now is not None else self._clock()
        if decision > 0:
            if self._last_convert is not None \
                    and t - self._last_convert < self.convert_cooldown_s:
                return None
            train_view = self.train.current_view()
            if train_view.size - 1 < self.min_world:
                self.stats["floor_refusals"] += 1
                return None
            rank = self.convert_to_serving(now=now)
            return ("convert", rank)
        if not self.converted:
            return None   # nothing of ours to give back
        if self._last_retire is not None \
                and t - self._last_retire < self.retire_cooldown_s:
            return None
        try:
            rank = self.retire_to_training(now=now)
        except CapacityFloorError:
            self.stats["floor_refusals"] += 1
            return None
        return ("retire", rank)

    # -- orphan recovery -----------------------------------------------------

    def recover_orphans(self, now=None):
        """Survivor-side sweep: detect conversions whose journal beat
        froze for ``stale_s`` and roll the world forward without them.
        Returns a tuple of ``(rank, step, action)`` where ``action`` is
        ``"roll-forward"`` (the step's effects landed; complete it) or
        ``"abort"`` (they did not; scrub every trace).  A healthy
        ``SERVING`` stint (rank live in the fleet) is never treated as
        orphaned — that journal entry parks on purpose."""
        t = now if now is not None else self._clock()
        actions = []
        standing = self.train.scan_conversions()
        for rank in list(self._orphan_seen):
            if rank not in standing:
                del self._orphan_seen[rank]    # journal cleared: done
        for rank, (step, beat, note) in sorted(standing.items()):
            live = {r.rid for r in self.fleet.live_replicas()}
            rid = self.converted.get(rank, rank)
            if step == "SERVING" and rid in live:
                self._orphan_seen.pop(rank, None)
                continue                       # healthy stint, parked
            prev = self._orphan_seen.get(rank)
            if prev is None or prev[0] != (step, beat):
                self._orphan_seen[rank] = ((step, beat), t)
                continue                       # first sight / advancing
            if t - prev[1] < self.stale_s:
                continue                       # not stale yet
            action = self._roll(rank, step, rid, live, now=now)
            actions.append((rank, step, action))
            self._orphan_seen.pop(rank, None)
        if actions:
            self._publish_gauges()
        return tuple(actions)

    def _roll(self, rank, step, rid, live, now=None):
        """One orphaned conversion resolved — the failure matrix
        (``docs/resilience.md`` §8): complete a step whose effects
        already landed, abort one whose effects did not, and never
        leave the rank present in either role group."""
        observability.instant("capacity/orphan",
                              tags={"rank": rank, "step": step})
        if step == "LEAVE_ANNOUNCED":
            # died before touching the fleet — and possibly before its
            # own leave landed: post it on the dead rank's behalf
            # (idempotent; the announced-leave fast path spares the
            # survivors a timeout) and scrub
            self._train_leave(rank, note="orphaned conversion abort")
            action = "abort"
        elif step == "CONVERTING":
            if rid in live:
                # the join fully landed, only the SERVING journal
                # write was lost: complete the record and keep serving
                self._journal(rank, "SERVING", note="rolled forward")
                self.converted[rank] = rid
                self.stats["rolled_forward"] += 1
                return "roll-forward"
            # half-admitted carcass (never went live): evict it
            self.fleet.discard(rid)
            action = "abort"
        elif step == "SERVING":
            # (rid not live here — live stints were skipped above) the
            # replica died while serving: the fleet's shed already
            # rerouted its work or will give up typed; nothing returns
            # to training
            if rid in self.fleet.replicas and rid in live:
                self.fleet.preempt(rid, now=now)
            action = "roll-forward"
        elif step == "RETIRING":
            # the retire stalled mid-flight: complete it (rerouting
            # whatever the replica still held); the rank is dead, so
            # NO training rejoin
            if rid in live:
                self.fleet.preempt(rid, now=now)
            elif rid in self.fleet.replicas:
                self.fleet.discard(rid)
            action = "roll-forward"
        elif step == "REJOINING":
            # died between the retire and the training admission:
            # scrub the standing join intent so a dead rank is never
            # admitted
            retract = getattr(self.train, "retract_join", None)
            if retract is not None:
                retract(rank=rank)
            action = "abort"
        else:
            action = "abort"   # unknown step (future writer): scrub
        self.converted.pop(rank, None)
        self.train.clear_conversion(rank)
        self.stats["aborted" if action == "abort"
                   else "rolled_forward"] += 1
        return action

    def __repr__(self):
        return (f"<CapacityBroker converted={sorted(self.converted)} "
                f"transfers={self.stats['role_transfers']}>")
