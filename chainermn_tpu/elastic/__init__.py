"""Elastic capacity transfer (ISSUE 16): the broker that lets one
pool of chips follow the traffic between the training ``elastic``
group and the serving ``fleet`` group.  See :mod:`.capacity` for the
protocol and ``docs/resilience.md`` §8 for the design."""

from .capacity import (CONVERSION_STEPS, CapacityBroker,
                       CapacityFloorError, CapacityProtocolError,
                       LocalTrainGroup)

__all__ = ["CONVERSION_STEPS", "CapacityBroker", "CapacityFloorError",
           "CapacityProtocolError", "LocalTrainGroup"]
