"""Mergeable runtime metrics registry (ISSUE 14 tentpole, part b).

Counters, gauges, and fixed-bucket histograms as PURE HOST OBJECTS —
no device arrays, no jit interaction — updated from the instrumentation
sites (trainer step phases, serving scheduler, elastic supervisor) and:

* **mergeable across ranks** over the existing object collectives
  (:meth:`MetricsRegistry.merge_across` rides ``comm.allgather_obj`` —
  the same transport scatter_dataset/checkpoint consensus use, so a
  metrics merge needs no new wire machinery).  Counters and histograms
  SUM (they are rank-additive by construction); gauges are point-in-
  time per-rank facts and merge under an added ``rank`` label instead
  of a lossy reduction;
* **dumped in Prometheus text exposition format**
  (:meth:`to_prometheus` — ``# HELP``/``# TYPE`` + samples, histograms
  as cumulative ``_bucket{le=...}`` / ``_sum`` / ``_count``), which is
  what ``PROBE=obs`` renders and what a real deployment's scraper
  ingests unchanged.

Histograms use FIXED bucket bounds chosen at construction (the
Prometheus discipline): merging is then bucket-wise addition, exact —
no quantile sketch, no approximation surprises across ranks.

All mutation paths are thread-safe (one registry lock — these are
bookkeeping counters, not a hot loop; the serving engine touches them
a handful of times per decode step and only when observability is
enabled).
"""

from __future__ import annotations

import threading

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "registry", "reset_registry", "DEFAULT_TIME_BUCKETS_MS"]

# Default latency bucket ladder (milliseconds): spans queue waits from
# sub-ms scheduler passes to multi-second preemption stalls.
DEFAULT_TIME_BUCKETS_MS = (1.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                           500.0, 1000.0, 2500.0, 5000.0, 10000.0)


def _label_key(labels):
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _escape_label_value(v):
    """Prometheus text-format label-value escaping (backslash, quote,
    newline) — label values are caller-supplied (tenant names), and one
    stray quote must not forge or break the whole exposition."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _fmt_labels(key):
    if not key:
        return ""
    return "{" + ",".join(f'{k}="{_escape_label_value(v)}"'
                          for k, v in key) + "}"


class _Metric:
    kind = None

    def __init__(self, name, help=""):
        self.name = name
        self.help = help
        self._values = {}          # label key tuple -> value
        self._lock = threading.Lock()

    def labels(self):
        with self._lock:
            return list(self._values)

    def value(self, **labels):
        with self._lock:
            return self._values.get(_label_key(labels))


class Counter(_Metric):
    """Monotonic accumulator (``inc`` only — a decrement is a bug)."""

    kind = "counter"

    def inc(self, amount=1, **labels):
        if amount < 0:
            raise ValueError(f"counter {self.name}: negative increment "
                             f"{amount}")
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0) + amount

    def _merge(self, values):
        with self._lock:
            for key, v in values.items():
                self._values[key] = self._values.get(key, 0) + v

    def _samples(self):
        with self._lock:
            return [(self.name, key, v)
                    for key, v in sorted(self._values.items())]


class Gauge(_Metric):
    """Point-in-time value (``set``); per-rank under merge."""

    kind = "gauge"

    def set(self, value, **labels):
        with self._lock:
            self._values[_label_key(labels)] = value

    def inc(self, amount=1, **labels):
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0) + amount

    def _merge(self, values):
        # rank label is appended by the registry merge BEFORE this is
        # called, so distinct ranks can never collide here
        with self._lock:
            self._values.update(values)

    def _samples(self):
        with self._lock:
            return [(self.name, key, v)
                    for key, v in sorted(self._values.items())]


class Histogram(_Metric):
    """Fixed-bucket histogram (Prometheus shape: cumulative ``le``
    buckets + ``_sum`` + ``_count``).  Bucket bounds are part of the
    metric's identity — merging with mismatched bounds is a hard error,
    never a silent re-bin."""

    kind = "histogram"

    def __init__(self, name, help="", buckets=DEFAULT_TIME_BUCKETS_MS):
        super().__init__(name, help)
        self.buckets = tuple(float(b) for b in buckets)
        if list(self.buckets) != sorted(self.buckets):
            raise ValueError(f"histogram {name}: bucket bounds must be "
                             f"sorted, got {buckets}")

    def observe(self, value, **labels):
        key = _label_key(labels)
        with self._lock:
            counts, total, n = self._values.get(
                key, ([0] * (len(self.buckets) + 1), 0.0, 0))
            counts = list(counts)
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1   # +Inf bucket
            self._values[key] = (counts, total + value, n + 1)

    def percentile(self, q, **labels):
        """Bucket-resolution percentile estimate (upper bound of the
        bucket holding the q-th observation) — what the serving bench
        reports as p50/p99 queue wait when only the merged histogram
        survives.  None when empty."""
        v = self.value(**labels)
        if v is None or v[2] == 0:
            return None
        counts, _, n = v
        target = q / 100.0 * n
        seen = 0
        for i, c in enumerate(counts):
            seen += c
            if seen >= target and c:
                return (self.buckets[i] if i < len(self.buckets)
                        else float("inf"))
        return float("inf")

    def _merge(self, values):
        with self._lock:
            for key, (counts, total, n) in values.items():
                if key in self._values:
                    mc, mt, mn = self._values[key]
                    if len(mc) != len(counts):
                        raise ValueError(
                            f"histogram {self.name}: merging mismatched "
                            f"bucket counts ({len(mc)} vs {len(counts)})")
                    self._values[key] = (
                        [a + b for a, b in zip(mc, counts)],
                        mt + total, mn + n)
                else:
                    self._values[key] = (list(counts), total, n)

    def _samples(self):
        out = []
        with self._lock:
            for key, (counts, total, n) in sorted(self._values.items()):
                cum = 0
                for bound, c in zip(self.buckets, counts):
                    cum += c
                    out.append((f"{self.name}_bucket",
                                key + (("le", repr(bound)),), cum))
                out.append((f"{self.name}_bucket",
                            key + (("le", "+Inf"),), cum + counts[-1]))
                out.append((f"{self.name}_sum", key, total))
                out.append((f"{self.name}_count", key, n))
        return out


class MetricsRegistry:
    """Name -> metric, with get-or-create accessors (idempotent: the
    same name returns the same object; a name re-used across metric
    kinds is a hard error)."""

    def __init__(self):
        self._metrics = {}
        self._lock = threading.Lock()

    def _get(self, cls, name, help, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help=help, **kw)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(f"metric {name!r} already registered as "
                                f"{m.kind}, requested {cls.kind}")
            return m

    def counter(self, name, help=""):
        return self._get(Counter, name, help)

    def gauge(self, name, help=""):
        return self._get(Gauge, name, help)

    def histogram(self, name, help="", buckets=DEFAULT_TIME_BUCKETS_MS):
        return self._get(Histogram, name, help, buckets=buckets)

    def metrics(self):
        with self._lock:
            return dict(self._metrics)

    def get(self, name):
        with self._lock:
            return self._metrics.get(name)

    # -- merge ---------------------------------------------------------------

    def to_dict(self):
        """Plain JSON-able snapshot (what rides ``allgather_obj``)."""
        out = {}
        for name, m in self.metrics().items():
            entry = {"kind": m.kind, "help": m.help,
                     "values": {json_key(k): v
                                for k, v in m._values.items()}}
            if m.kind == "histogram":
                entry["buckets"] = list(m.buckets)
            out[name] = entry
        return out

    def merge_dict(self, snapshot, rank=None):
        """Fold one rank's snapshot in: counters/histograms ADD, gauges
        keep per-rank identity via an appended ``rank`` label (when
        ``rank`` is given)."""
        for name, entry in snapshot.items():
            kind = entry["kind"]
            if kind == "counter":
                m = self.counter(name, entry.get("help", ""))
            elif kind == "gauge":
                m = self.gauge(name, entry.get("help", ""))
            elif kind == "histogram":
                m = self.histogram(name, entry.get("help", ""),
                                   buckets=tuple(entry["buckets"]))
                if tuple(entry["buckets"]) != m.buckets:
                    raise ValueError(
                        f"histogram {name!r}: bucket bounds differ "
                        f"across ranks ({entry['buckets']} vs "
                        f"{list(m.buckets)})")
            else:
                raise ValueError(f"metric {name!r}: unknown kind "
                                 f"{kind!r}")
            values = {unjson_key(k): v
                      for k, v in entry["values"].items()}
            if kind == "gauge" and rank is not None:
                # keys stay in _label_key's sorted order so lookups
                # through value(**labels) keep working after the merge
                values = {tuple(sorted(key + (("rank", str(rank)),))): v
                          for key, v in values.items()}
            if kind == "histogram":
                values = {k: tuple(v) for k, v in values.items()}
            m._merge(values)

    def merge_across(self, comm):
        """Every rank contributes its snapshot over the existing object
        collectives; every rank returns the SAME merged registry (the
        allgather is symmetric).  Counters/histograms sum; gauges gain
        a ``rank`` label."""
        shards = comm.allgather_obj(self.to_dict())
        merged = MetricsRegistry()
        for r, shard in enumerate(shards):
            merged.merge_dict(shard, rank=r)
        return merged

    # -- export --------------------------------------------------------------

    def to_prometheus(self):
        """Text exposition format (the scrape payload / PROBE=obs
        rendering)."""
        lines = []
        for name, m in sorted(self.metrics().items()):
            if m.help:
                lines.append(f"# HELP {name} {m.help}")
            lines.append(f"# TYPE {name} {m.kind}")
            for sample_name, key, v in m._samples():
                if isinstance(v, float) and v == int(v):
                    v = int(v)
                lines.append(f"{sample_name}{_fmt_labels(key)} {v}")
        return "\n".join(lines) + ("\n" if lines else "")


def json_key(key):
    """Label key tuple -> a JSON-object-safe string."""
    return "\x1f".join(f"{k}\x1e{v}" for k, v in key)


def unjson_key(s):
    if not s:
        return ()
    return tuple(tuple(part.split("\x1e", 1))
                 for part in s.split("\x1f"))


_REGISTRY = None
_REGISTRY_LOCK = threading.Lock()


def registry():
    """The process-global registry (created on first use)."""
    global _REGISTRY
    if _REGISTRY is None:
        with _REGISTRY_LOCK:
            if _REGISTRY is None:
                _REGISTRY = MetricsRegistry()
    return _REGISTRY


def reset_registry():
    """Drop the global registry (tests)."""
    global _REGISTRY
    _REGISTRY = None
