"""Unified runtime observability (ISSUE 14).

Two host-side pieces every subsystem shares:

* :mod:`~chainermn_tpu.observability.tracing` — structured span
  tracing into a bounded ring, exported as Chrome-trace-event JSONL
  (Perfetto-loadable; rank shards merge via ``tools/trace_merge.py``),
  gated by ``CHAINERMN_TPU_TRACE=off|events|full``;
* :mod:`~chainermn_tpu.observability.metrics` — a mergeable registry
  of counters/gauges/fixed-bucket histograms, joined across ranks over
  the object collectives and rendered in Prometheus text format
  (``PROBE=obs`` / ``make probe-obs``).

Span taxonomy, knob ladder, and the merge workflow:
``docs/observability.md``.
"""

from .tracing import (MODES, TRACE_ENV, Span, SpanTracer, enabled,
                      instant, mode, named_scopes_enabled, read_jsonl,
                      repair_balance, reset_tracer, set_mode, span,
                      tracer, validate_events)
from .metrics import (DEFAULT_TIME_BUCKETS_MS, Counter, Gauge, Histogram,
                      MetricsRegistry, registry, reset_registry)

__all__ = [
    "Span", "SpanTracer", "tracer", "span", "instant", "mode", "enabled",
    "named_scopes_enabled", "set_mode", "reset_tracer", "validate_events",
    "repair_balance",
    "read_jsonl", "TRACE_ENV", "MODES",
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "registry",
    "reset_registry", "DEFAULT_TIME_BUCKETS_MS",
]
