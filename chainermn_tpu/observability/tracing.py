"""Host-side structured span tracing (ISSUE 14 tentpole, part a).

The reference's runtime measurement story was hook-based — TimerHook /
CupyMemoryProfileHook wrapping function calls, nvprof wrapping the
process (PAPER.md §5).  The TPU rebuild's equivalent must attribute
time across THREE subsystems (training step phases, serving request
lifecycles, elastic resize timelines) and across RANKS, and it must
cost nothing when off — every numeric gate armed behind first chip
contact will need exactly this attribution the day it fires.

Design:

* a :class:`Span` is a named interval on a (pid, tid) track, recorded
  with ``time.monotonic()`` (never wall clock — NTP steps would break
  the balance invariant) into a BOUNDED ring buffer (old events fall
  off; a trainer cannot leak memory by tracing forever);
* export is Chrome-trace-event JSONL — one event object per line,
  ``B``/``E`` pairs per track plus ``i`` instants and ``M`` metadata —
  which Perfetto / ``chrome://tracing`` open directly
  (``tools/trace_merge.py`` joins rank shards into one file);
* ``pid`` is the RANK (so a merged multi-rank trace shows one process
  lane per rank), ``tid`` is the host thread — or a synthetic
  per-request track for serving lifecycles;
* the knob ladder is ``CHAINERMN_TPU_TRACE=off|events|full``: ``off``
  (default) makes every call site a no-op returning a module-level
  singleton (zero allocations — pinned by test), ``events`` records
  host spans, ``full`` additionally opens ``jax.named_scope`` around
  each span so XProf/jax.profiler timelines carry the SAME vocabulary
  (the two tools join on span names).

The mode is resolved ONCE at import (the documented near-zero-cost
contract: the hot path is one module-global truthiness check);
:func:`set_mode` exists for tests and tools that flip it in-process.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time

__all__ = ["Span", "SpanTracer", "tracer", "span", "instant", "mode",
           "enabled", "named_scopes_enabled", "set_mode", "reset_tracer",
           "validate_events", "repair_balance", "read_jsonl",
           "TRACE_ENV", "MODES"]

TRACE_ENV = "CHAINERMN_TPU_TRACE"
MODES = ("off", "events", "full")

_REQUIRED_KEYS = ("name", "ph", "ts", "pid", "tid")


def _resolve_mode(value=None):
    v = (value if value is not None
         else os.environ.get(TRACE_ENV, "off")).strip().lower() or "off"
    if v not in MODES:
        raise ValueError(f"{TRACE_ENV}={v!r}: expected one of {MODES}")
    return v


# Resolved at import: the disabled hot path is `if not _ENABLED` on a
# module global — no env read, no object construction, per call site.
_MODE = _resolve_mode()
_ENABLED = _MODE != "off"
_FULL = _MODE == "full"


def mode():
    """The resolved ``CHAINERMN_TPU_TRACE`` mode (off|events|full)."""
    return _MODE


def enabled():
    """True when spans are recorded (``events`` or ``full``)."""
    return _ENABLED


def named_scopes_enabled():
    """True only under ``full``: span names also open
    ``jax.named_scope`` so XProf timelines share the vocabulary."""
    return _FULL


def set_mode(value):
    """Re-resolve the trace mode in-process (tests / tools; production
    runs set the env var before import).  Returns the previous mode."""
    global _MODE, _ENABLED, _FULL
    prev = _MODE
    _MODE = _resolve_mode(value)
    _ENABLED = _MODE != "off"
    _FULL = _MODE == "full"
    return prev


class _NoopSpan:
    """The off-path singleton: every disabled ``span()`` call returns
    THIS object — no allocation, no clock read (pinned by the
    zero-allocation smoke in tests/observability_tests)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


class Span:
    """An open interval: ``B`` recorded at construction, ``E`` at
    ``__exit__``/``close()``.  Context-manager use guarantees balance;
    an unclosed span is repaired at export (synthetic ``E``)."""

    __slots__ = ("_tracer", "name", "tid")

    def __init__(self, tracer, name, tags=None, tid=None):
        self._tracer = tracer
        self.name = name
        self.tid = tid if tid is not None else threading.get_ident()
        tracer._emit("B", name, tracer._now_us(), self.tid, tags)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def close(self):
        if self._tracer is not None:
            self._tracer._emit("E", self.name, self._tracer._now_us(),
                               self.tid, None)
            self._tracer = None


class SpanTracer:
    """Rank-tagged span recorder over a bounded ring buffer.

    ``capacity``: ring bound (``CHAINERMN_TPU_TRACE_CAPACITY``, default
    65536 events) — the oldest events fall off; export repairs any
    B/E pairs the eviction unbalanced so the written file is always
    schema-valid.
    """

    def __init__(self, rank=0, capacity=None):
        if capacity is None:
            capacity = int(os.environ.get(
                "CHAINERMN_TPU_TRACE_CAPACITY", "65536"))
        from collections import deque
        self._events = deque(maxlen=int(capacity))
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        self.rank = int(rank)
        self.epoch = None
        self._dropped = 0
        self._track_ts = {}   # tid -> last emitted ts (complete() clamp)

    # -- configuration -------------------------------------------------------

    def configure(self, rank=None, epoch=None):
        """Stamp the rank (Chrome ``pid`` — one lane per rank in a
        merged trace) and, on elastic runs, the current membership
        epoch (tagged into every subsequent event's args)."""
        if rank is not None:
            self.rank = int(rank)
        if epoch is not None:
            self.epoch = int(epoch)

    # -- recording -----------------------------------------------------------

    def _now_us(self):
        return int((time.monotonic() - self._t0) * 1e6)

    def _emit(self, ph, name, ts, tid, tags):
        ev = {"name": name, "ph": ph, "ts": ts, "pid": self.rank,
              "tid": tid}
        args = dict(tags) if tags else None
        if self.epoch is not None:
            args = args or {}
            args["epoch"] = self.epoch
        if args:
            ev["args"] = args
        if ph == "i":
            ev["s"] = "t"   # thread-scoped instant (Perfetto marker)
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self._dropped += 1
            self._events.append(ev)
            if ts > self._track_ts.get(tid, -1):
                self._track_ts[tid] = ts

    def span(self, name, tags=None, tid=None):
        """Open a span (use as a context manager)."""
        return Span(self, name, tags=tags, tid=tid)

    def instant(self, name, tags=None, tid=None):
        """A point event on the track (eviction, fork, detection...)."""
        self._emit("i", name, self._now_us(),
                   tid if tid is not None else threading.get_ident(),
                   tags)

    def complete(self, name, duration_s, tags=None, tid=None, end_us=None):
        """Record a span RETROACTIVELY: an interval of ``duration_s``
        seconds ending now (or at ``end_us``).  Used where the start
        was observed on a different clock — e.g. a serving request's
        queue wait, measured on the engine's (possibly SIMULATED)
        clock: the EXACT duration is stamped into ``args.duration_ms``,
        and the drawn interval is clamped so its start never reaches
        back past the track's last event — a foreign-clock duration
        larger than the real elapsed tracer time would otherwise
        overlap earlier spans on the lane and cross-pair their B/E
        under LIFO pairing (wrong durations in Perfetto even though
        the file stays balanced)."""
        end = self._now_us() if end_us is None else int(end_us)
        t = tid if tid is not None else threading.get_ident()
        start = max(0, end - int(duration_s * 1e6),
                    self._track_ts.get(t, 0))
        end = max(end, start)
        args = dict(tags) if tags else {}
        args["duration_ms"] = round(duration_s * 1e3, 3)
        self._emit("B", name, start, t, args)
        self._emit("E", name, end, t, None)

    # -- export --------------------------------------------------------------

    def events(self):
        """Snapshot of the ring (metadata events NOT included)."""
        with self._lock:
            return list(self._events)

    def clear(self):
        with self._lock:
            self._events.clear()
            self._track_ts.clear()
            self._dropped = 0

    def export(self, path):
        """Write the ring as Chrome-trace-event JSONL, sanitized to the
        committed schema: events ts-sorted, per-track B/E balanced
        (orphan ``E`` whose ``B`` fell off the ring are dropped,
        unclosed ``B`` get a synthetic ``E`` at the track's last ts),
        prefixed with ``M`` metadata naming the rank lane.  Returns the
        number of NON-metadata events written (0 = nothing recorded;
        callers use that to skip empty shards)."""
        evs = sorted(self.events(), key=lambda e: e["ts"])
        evs = repair_balance(evs)
        meta = [{"name": "process_name", "ph": "M", "ts": 0,
                 "pid": self.rank, "tid": 0,
                 "args": {"name": f"rank{self.rank}"}}]
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "w") as f:
            for ev in meta + evs:
                f.write(json.dumps(ev) + "\n")
        return len(evs)


def repair_balance(events):
    """Repair B/E damage in a ts-sorted event stream: drop ``E`` events
    whose ``B`` is gone (ring eviction; a checkpoint export's synthetic
    close followed by the exit export's real ``E``), close still-open
    ``B`` with synthetic ``E`` at the track's final ts.  Used by both
    :meth:`SpanTracer.export` and ``tools/trace_merge.py`` — output
    satisfies :func:`validate_events`."""
    out = []
    stacks = {}   # (pid, tid) -> [names]
    last_ts = {}
    for ev in events:
        key = (ev["pid"], ev["tid"])
        ph = ev["ph"]
        if ph == "B":
            stacks.setdefault(key, []).append(ev["name"])
            out.append(ev)
        elif ph == "E":
            stack = stacks.get(key)
            if stack and stack[-1] == ev["name"]:
                stack.pop()
                out.append(ev)
            # else: orphan E (its B was evicted) — dropped
        else:
            out.append(ev)
        last_ts[key] = ev["ts"]
    for (pid, tid), stack in stacks.items():
        while stack:
            out.append({"name": stack.pop(), "ph": "E",
                        "ts": last_ts[(pid, tid)], "pid": pid,
                        "tid": tid})
    return out


def validate_events(events):
    """The committed trace schema, machine-checked (tier-1 gate in
    tests/observability_tests/test_tracing.py; ``tools/trace_merge.py``
    refuses to write a merge that fails it).

    Every event: the required keys, ``ph`` in {B,E,i,M}, integer
    ``ts >= 0``.  Per (pid, tid) track: ``ts`` monotonically
    non-decreasing in file order, and B/E strictly balanced with
    E matching the innermost open B (proper nesting).  Raises
    ``ValueError`` naming the first offending event; returns the event
    count on success."""
    cursors = {}
    stacks = {}
    for i, ev in enumerate(events):
        for k in _REQUIRED_KEYS:
            if k not in ev:
                raise ValueError(f"event {i} missing key {k!r}: {ev}")
        if ev["ph"] not in ("B", "E", "i", "M"):
            raise ValueError(f"event {i}: unknown ph {ev['ph']!r}")
        if not isinstance(ev["ts"], int) or ev["ts"] < 0:
            raise ValueError(f"event {i}: ts must be a non-negative "
                             f"integer, got {ev['ts']!r}")
        if ev["ph"] == "M":
            continue
        key = (ev["pid"], ev["tid"])
        if ev["ts"] < cursors.get(key, 0):
            raise ValueError(
                f"event {i}: ts {ev['ts']} goes backwards on track "
                f"{key} (last {cursors[key]})")
        cursors[key] = ev["ts"]
        if ev["ph"] == "B":
            stacks.setdefault(key, []).append(ev["name"])
        elif ev["ph"] == "E":
            stack = stacks.get(key)
            if not stack:
                raise ValueError(f"event {i}: E {ev['name']!r} with no "
                                 f"open B on track {key}")
            if stack[-1] != ev["name"]:
                raise ValueError(
                    f"event {i}: E {ev['name']!r} does not match "
                    f"innermost open B {stack[-1]!r} on track {key}")
            stack.pop()
    for key, stack in stacks.items():
        if stack:
            raise ValueError(f"track {key}: unclosed B spans {stack}")
    return len(events)


def read_jsonl(path):
    """Read a JSONL trace shard (blank lines skipped)."""
    events = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                events.append(json.loads(line))
    return events


# -- module-level convenience surface ---------------------------------------

_TRACER = None
_TRACER_LOCK = threading.Lock()


def tracer():
    """The process-global tracer (created on first use)."""
    global _TRACER
    if _TRACER is None:
        with _TRACER_LOCK:
            if _TRACER is None:
                _TRACER = SpanTracer()
    return _TRACER


def reset_tracer():
    """Drop the global tracer (tests; the next ``tracer()`` call builds
    a fresh one re-reading the capacity env knob)."""
    global _TRACER
    _TRACER = None


@contextlib.contextmanager
def _full_span(name, tags, tid):
    import jax
    with jax.named_scope(name.replace("/", ".")):
        with tracer().span(name, tags=tags, tid=tid):
            yield


def span(name, tags=None, tid=None):
    """Open a span on the global tracer — THE instrumentation call site.

    Off (default): returns the no-op singleton — no allocation, no
    clock read.  ``events``: records B/E on the ring.  ``full``:
    additionally opens ``jax.named_scope`` so any surrounding
    jax.profiler trace carries the same name."""
    if not _ENABLED:
        return _NOOP
    if _FULL:
        return _full_span(name, tags, tid)
    return tracer().span(name, tags=tags, tid=tid)


def instant(name, tags=None, tid=None):
    """Record a point event on the global tracer (no-op when off)."""
    if _ENABLED:
        tracer().instant(name, tags=tags, tid=tid)
