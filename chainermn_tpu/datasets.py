"""Dataset scattering.

Reference: ``chainermn/datasets.py · scatter_dataset, create_empty_dataset``
(SURVEY.md §2.4, call stack §3.4).  The reference pickles per-rank
``SubDataset`` specs over MPI (chunked at ~256 MiB).  Single-controller
translation: ranks are devices driven by this process, so "scattering"
ships no bytes — it returns an index-remapped view (permuted, padded by
wrap-around to a multiple of ``comm.size`` so every rank's shard is equal
length: the lock-step invariant that keeps collectives deadlock-free,
SURVEY §7 hard-parts).  Multi-host, each controller gets its contiguous
slice of the padded order; the order is agreed via the object channel.
"""

from __future__ import annotations

import numpy as np

from .dataset.datasets import SubDataset

__all__ = ["scatter_dataset", "rescatter_dataset", "create_empty_dataset",
           "scatter_index", "get_n_iterations_for_one_epoch"]


def scatter_dataset(dataset, comm, root=0, shuffle=False, seed=None,
                    max_buf_len=256 * 1024 * 1024, force_equal_length=True):
    """Return this host's equal-length shard of ``dataset``.

    Reference signature preserved (``max_buf_len`` kept for parity; no
    pickled transport exists to chunk on a single controller).  The shard
    covers all devices this host drives — per-device slicing happens
    inside the compiled step (shard_map splits the batch dimension), so
    iterate with ``batchsize = per_rank_bs * comm.size``.
    """
    if comm.inter_size > 1:
        # Reference §3.4: the root owns the dataset and ships it to peers
        # over the chunked pickled object channel (peers pass None).  The
        # broadcast only happens when some peer actually lacks the data —
        # hosts that already loaded the dataset locally ship nothing.
        # The ImageNet pattern — scatter file *paths*, not tensors —
        # keeps the shipped case cheap for large corpora.
        if comm.inter_rank == root and dataset is None:
            raise ValueError("root must pass the dataset to scatter")
        haves = comm.allgather_obj(dataset is not None)
        if not all(haves):
            dataset = comm.bcast_obj(dataset if comm.inter_rank == root
                                     else None, root=root)
    if dataset is None:
        raise ValueError("non-root dataset=None requires a multi-host "
                         "communicator (inter_size > 1)")
    n = len(dataset)
    if n == 0:
        raise ValueError("cannot scatter an empty dataset")
    size = comm.size
    if shuffle:
        if seed is None:
            order = np.random.permutation(n)
            order = comm.bcast_obj(order, root=root)
        else:
            order = np.random.RandomState(seed).permutation(n)
    else:
        order = np.arange(n)
    if force_equal_length:
        per_rank = -(-n // size)  # ceil
        total = per_rank * size
        if total > n:
            # wrap-around padding (reference behavior) keeps shards equal
            order = np.concatenate([order, order[: total - n]])
    else:
        total = (n // size) * size
        order = order[:total]
    n_hosts = max(comm.inter_size, 1)
    host = comm.inter_rank
    per_host = total // n_hosts
    start, finish = host * per_host, (host + 1) * per_host
    return SubDataset(dataset, start, finish, order=order)


def rescatter_dataset(shard, comm):
    """Deterministically re-slice an already-scattered shard for a
    RESIZED communicator (elastic shrink/grow, ISSUE 10).

    ``shard`` is a :class:`SubDataset` a previous ``scatter_dataset``
    produced (its ``order`` is the seeded permutation every member
    agreed on); ``comm`` is the REBUILT communicator.  The SAME order
    is re-padded by wrap-around to the new ``comm.size`` multiple and
    re-sliced contiguously over the new ``comm.inter_size`` hosts — a
    pure function of (order, new topology), so every surviving member
    computes the identical partition with no collective, and the union
    of the new shards equals the union of the old ones: within an
    epoch no sample is dropped, and none is counted twice beyond the
    equal-length wrap-around padding ``scatter_dataset`` itself
    documents.  Iterator position (which samples of the epoch are
    already consumed) is trainer state and rides the checkpoint, not
    this function.
    """
    if not isinstance(shard, SubDataset):
        raise TypeError(
            f"rescatter_dataset re-slices a SubDataset produced by "
            f"scatter_dataset, got {type(shard).__name__}; for a raw "
            f"dataset call scatter_dataset with the same seed instead")
    base = shard._dataset
    order = shard._order
    n = len(base) if order is None else len(np.unique(order))
    if order is not None:
        # strip the previous wrap-around padding: the agreed permutation
        # is the first n entries (scatter_dataset appends the pad AFTER
        # the permutation)
        order = np.asarray(order)[:n]
    else:
        order = np.arange(n)
    size = comm.size
    per_rank = -(-n // size)
    total = per_rank * size
    if total > n:
        order = np.concatenate([order, order[: total - n]])
    n_hosts = max(comm.inter_size, 1)
    host = comm.inter_rank
    per_host = total // n_hosts
    start, finish = host * per_host, (host + 1) * per_host
    return SubDataset(base, start, finish, order=order)


def scatter_index(n_total, comm, root=0):
    """Reference ``chainermn.datasets.scatter_index``: evenly split
    ``range(n_total)``; returns this host's (start, stop)."""
    n_hosts = max(comm.inter_size, 1)
    host = comm.inter_rank
    per = -(-n_total // n_hosts)
    return host * per, min((host + 1) * per, n_total)


class _EmptyDataset:
    def __init__(self, length):
        self._length = length

    def __len__(self):
        return self._length

    def __getitem__(self, index):
        if isinstance(index, slice):
            return [None] * len(range(*index.indices(self._length)))
        if isinstance(index, (list, np.ndarray)):
            return [None] * len(index)
        if index < 0 or index >= self._length:
            raise IndexError("dataset index out of range")
        return None


def create_empty_dataset(dataset):
    """Same-length dataset of ``None``s (reference: ranks that feed no
    data in model-parallel configurations still iterate in lock-step)."""
    return _EmptyDataset(len(dataset))


def get_n_iterations_for_one_epoch(dataset, local_batch_size, comm):
    per_rank = -(-len(dataset) // comm.size)
    return -(-per_rank // local_batch_size)
