"""Process-pool batch iterator (reference: ``chainer.iterators.
MultiprocessIterator``, SURVEY.md §2.8).

The escape hatch for GIL-bound per-example transforms: a pool of worker
*processes* runs ``dataset[i]`` and assembles each batch directly into a
``multiprocessing.shared_memory`` ring-buffer slot — array payloads
cross the process boundary as one shared-memory write plus one parent-
side memcpy, never a pickle.  Control traffic (index lists, slot ids,
completion records) stays on small queues.

Layered like the thread iterator:

* a scheduler (`SerialIterator` bookkeeping) decides each batch's
  indices up front — workers are stateless executors, so delivery can be
  deterministic (``ordered=True``, default) regardless of which worker
  finishes first, or arrival-ordered (``ordered=False``) when latency
  matters more than reproducibility;
* a consumer-side state shadow advances only when the consumer takes a
  batch, so ``serialize`` records a resumable position with the same
  consumer-granularity contract the thread and native iterators honor
  (snapshots are interchangeable between the three — shared key names);
* worker death is detected (liveness poll while waiting on results) and
  surfaced as a typed :class:`IteratorWorkerCrashed`; a transform
  exception crosses back as :class:`IteratorWorkerError` carrying the
  worker-side traceback text.

Slot layout is probed from ``dataset[0]`` at construction: each slot
holds ``batch_size`` examples' arrays field-by-field, contiguously.  A
batch whose example shapes don't match the probe (ragged datasets), or
a dataset whose examples aren't arrays/scalars at all, falls back to
pickling that batch through the result queue — correct, just not on the
fast path.  ``shared_mem`` caps the per-slot byte size (reference knob);
0 forces the pickle path.

Worker processes only ever touch numpy + the dataset — never jax — so
forking from a parent with an initialized JAX backend is safe (the
same contract PyTorch's DataLoader relies on).
"""

from __future__ import annotations

import os
import queue as _queue_mod
import traceback

import numpy as np

from .iterators import (Iterator, _make_shadow_pair,
                        _serialize_consumer_shadow)

__all__ = ["MultiprocessIterator", "IteratorError", "IteratorWorkerError",
           "IteratorWorkerCrashed"]


class IteratorError(RuntimeError):
    """Base class for iterator pipeline failures."""


class IteratorWorkerError(IteratorError):
    """The per-example transform raised inside a worker process; carries
    the worker-side traceback text."""

    def __init__(self, exc_type, message, tb_text):
        super().__init__(
            f"{exc_type} in MultiprocessIterator worker: {message}\n"
            f"--- worker traceback ---\n{tb_text}")
        self.exc_type = exc_type
        self.worker_traceback = tb_text


class IteratorWorkerCrashed(IteratorError):
    """A worker process died without reporting a result (segfault,
    os._exit, OOM-kill): the pipeline cannot make progress."""

    def __init__(self, pid, exitcode):
        super().__init__(
            f"MultiprocessIterator worker pid={pid} died with "
            f"exitcode={exitcode} (segfault/os._exit/OOM-kill?); "
            "the iterator cannot continue — rebuild it (reset()) or fix "
            "the transform")
        self.pid = pid
        self.exitcode = exitcode


class _SlotLayout:
    """Per-slot shared-memory layout: ``batch_size`` examples, each a
    tuple of fixed-shape arrays, stored field-by-field as contiguous
    ``[batch_size, *shape]`` blocks.  Picklable (shipped to spawn-started
    workers)."""

    def __init__(self, tuple_mode, shapes, dtypes, batch_size):
        self.tuple_mode = tuple_mode
        self.shapes = shapes
        self.dtypes = [np.dtype(d) for d in dtypes]
        self.batch_size = batch_size
        self.offsets = []
        off = 0
        for shape, dtype in zip(shapes, self.dtypes):
            self.offsets.append(off)
            nbytes = batch_size * int(np.prod(shape, dtype=np.int64)) \
                * dtype.itemsize
            # 64-byte-align every field block (cheap, keeps memcpy fast)
            off += (nbytes + 63) & ~63
        self.slot_bytes = off

    def field_views(self, buf, slot_off):
        """One writable ndarray view per field over ``buf`` at the slot."""
        return [np.ndarray((self.batch_size,) + shape, dtype=dtype,
                           buffer=buf, offset=slot_off + off)
                for shape, dtype, off
                in zip(self.shapes, self.dtypes, self.offsets)]


def _probe_layout(dataset, batch_size, shared_mem):
    """Build a :class:`_SlotLayout` from ``dataset[0]``, or None when the
    dataset can't use the shared-memory path (ragged/object examples, or
    a slot that would exceed the ``shared_mem`` cap)."""
    try:
        example = dataset[0]
    except Exception:
        return None
    fields = example if isinstance(example, (tuple, list)) else (example,)
    shapes, dtypes = [], []
    for f in fields:
        try:
            a = np.asarray(f)
        except Exception:
            return None
        if a.dtype == object or a.dtype.hasobject:
            return None
        shapes.append(a.shape)
        dtypes.append(a.dtype)
    layout = _SlotLayout(isinstance(example, (tuple, list)),
                         shapes, dtypes, batch_size)
    if shared_mem is not None and layout.slot_bytes > shared_mem:
        return None
    if layout.slot_bytes == 0:
        return None
    return layout


class _LayoutMismatch(Exception):
    """A batch's example shapes/dtypes don't match the probed layout —
    internal signal for the per-batch pickle fallback."""


def _assemble_into_slot(layout, buf, slot_off, examples):
    """Write ``examples`` into the slot's field blocks.  Raises
    :class:`_LayoutMismatch` when an example disagrees with the probe."""
    views = layout.field_views(buf, slot_off)
    for j, example in enumerate(examples):
        fields = example if layout.tuple_mode else (example,)
        if len(fields) != len(views):
            raise _LayoutMismatch
        for view, shape, dtype, f in zip(views, layout.shapes,
                                         layout.dtypes, fields):
            fa = np.asarray(f)
            if fa.shape != shape or fa.dtype != dtype:
                raise _LayoutMismatch
            view[j] = fa


def _worker_loop(dataset, shm_name, layout, task_q, result_q):
    """Worker process body: pull (seq, slot, indices) tasks, run the
    per-example transform, assemble into the shared slot (pickle
    fallback on layout mismatch), report completion.  Exits on the None
    sentinel.  Top-level so spawn-started workers can import it."""
    shm = None
    if shm_name is not None:
        from multiprocessing import shared_memory
        # The parent owns the segment.  On 3.10 attaching ALSO registers
        # with the resource tracker (bpo-39959), and with fork the
        # tracker process is shared — a per-child unregister would strip
        # the parent's registration (and later ones KeyError in the
        # tracker).  Suppress the attach-side registration instead.
        try:
            from multiprocessing import resource_tracker
            _orig_register = resource_tracker.register
            resource_tracker.register = lambda *a, **k: None
        except Exception:
            resource_tracker = None
        try:
            shm = shared_memory.SharedMemory(name=shm_name)
        finally:
            if resource_tracker is not None:
                resource_tracker.register = _orig_register
    try:
        while True:
            try:
                task = task_q.get(timeout=5.0)
            except _queue_mod.Empty:
                # orphan guard: a SIGKILLed parent never sends the
                # sentinel (daemon cleanup only runs on clean exit) —
                # without this check the worker would block in get()
                # forever, pinning inherited fds (e.g. a pipe a
                # supervisor is waiting to see EOF on)
                import multiprocessing as _mp
                parent = _mp.parent_process()
                if parent is not None and not parent.is_alive():
                    return
                continue
            if task is None:
                return
            seq, slot, indices = task
            try:
                examples = [dataset[int(i)] for i in indices]
                if shm is not None:
                    try:
                        _assemble_into_slot(
                            layout, shm.buf, slot * layout.slot_bytes,
                            examples)
                        result_q.put((seq, slot, "shm", len(examples)))
                        continue
                    except _LayoutMismatch:
                        pass
                result_q.put((seq, slot, "pickle", examples))
            except Exception as e:
                result_q.put((seq, slot, "error",
                              (type(e).__name__, str(e),
                               traceback.format_exc())))
    except (KeyboardInterrupt, EOFError, OSError):
        pass  # parent tore the queues down first: silent exit
    finally:
        if shm is not None:
            try:
                shm.close()
            except Exception:
                pass


class _PoolResources:
    """Everything `finalize` must tear down, detached from the iterator
    object so a ``weakref.finalize`` can run the teardown at GC time
    without resurrecting it."""

    def __init__(self):
        self.procs = []
        self.task_q = None
        self.result_q = None
        self.shm = None
        self.closed = False

    def close(self):
        if self.closed:
            return
        self.closed = True
        try:
            for _ in self.procs:
                try:
                    self.task_q.put_nowait(None)
                except Exception:
                    break
            for p in self.procs:
                p.join(timeout=2.0)
            for p in self.procs:
                if p.is_alive():
                    p.terminate()
                    p.join(timeout=2.0)
        except Exception:
            pass
        for q in (self.task_q, self.result_q):
            try:
                q.cancel_join_thread()
                q.close()
            except Exception:
                pass
        if self.shm is not None:
            try:
                self.shm.close()
            except Exception:
                pass
            try:
                self.shm.unlink()
            except Exception:
                pass
            self.shm = None


class MultiprocessIterator(Iterator):
    """Process-pool prefetching iterator (the reference's namesake).

    Args:
        dataset: indexable dataset; ``dataset[i]`` (the per-example
            transform) runs in the worker processes.  With the default
            ``fork`` start method it is inherited copy-on-write; with
            ``spawn`` it must pickle.
        batch_size: examples per batch.
        repeat / shuffle / seed: `SerialIterator` semantics.
        n_processes: worker count (default ``os.cpu_count()``).
        n_prefetch: completed batches kept ready ahead of the consumer.
        shared_mem: per-slot byte cap (reference knob).  None = size
            from probing ``dataset[0]``; 0 disables shared memory (all
            batches pickle through the result queue).
        ordered: True (default) delivers batches in schedule order —
            identical stream to `SerialIterator`; False delivers in
            completion order (same multiset per epoch, lower latency
            under skewed transform cost).
        as_arrays: True returns the batch as a tuple of stacked
            ``[n, *shape]`` arrays (`NativeBatchIterator` convention,
            pair with ``identity_converter``); False (default) returns
            the reference's list-of-examples (views into the stacked
            arrays — `concat_examples` compatible).
        start_method: multiprocessing start method; default ``fork``
            where available (no dataset pickling) else ``spawn``.
        worker_timeout: seconds to wait on a dead pipeline before
            declaring it crashed (liveness is polled much faster; this
            only bounds the no-progress-no-corpse case).
    """

    def __init__(self, dataset, batch_size, repeat=True, shuffle=None,
                 n_processes=None, n_prefetch=2, shared_mem=None,
                 seed=None, ordered=True, as_arrays=False,
                 start_method=None, worker_timeout=60.0):
        self.dataset = dataset
        self.batch_size = batch_size
        self._repeat = repeat
        self._shuffle = shuffle
        self._seed = seed
        self._n_processes = max(1, n_processes or os.cpu_count() or 2)
        self._n_prefetch = max(1, n_prefetch)
        self._shared_mem = shared_mem
        self._ordered = ordered
        self._as_arrays = as_arrays
        self._start_method = start_method
        self._worker_timeout = worker_timeout
        self._res = None
        self._finalized = False
        # probe once: the layout depends only on constructor-fixed
        # inputs, and dataset[0] runs the (possibly expensive) transform
        # in the parent — reset()/resume rebuilds must not re-pay it
        self._layout = None if shared_mem == 0 else _probe_layout(
            dataset, batch_size, shared_mem)
        self._setup()

    # -- pipeline lifecycle -------------------------------------------------
    def _setup(self, from_state=None):
        import multiprocessing as mp
        import weakref

        # scheduler decides batch indices ahead of the workers;
        # consumer shadow advances per delivered batch (serialize source)
        self._sched, self._state = _make_shadow_pair(
            self.dataset, self.batch_size, self._repeat, self._shuffle,
            self._seed, from_state)

        method = self._start_method or (
            "fork" if "fork" in mp.get_all_start_methods() else "spawn")
        ctx = mp.get_context(method)

        res = _PoolResources()
        self._n_slots = self._n_prefetch + self._n_processes
        if self._layout is not None:
            from multiprocessing import shared_memory
            res.shm = shared_memory.SharedMemory(
                create=True,
                size=self._n_slots * self._layout.slot_bytes)
        res.task_q = ctx.Queue()
        res.result_q = ctx.Queue()
        shm_name = res.shm.name if res.shm is not None else None
        import warnings
        with warnings.catch_warnings():
            # CPython warns on fork-under-threads because the child
            # could deadlock in an inherited lock; these workers run
            # only numpy + the dataset (never jax/XLA) and take no
            # parent locks before exec'ing their loop — the
            # PyTorch-DataLoader contract.  Silence the per-worker
            # noise rather than train users to ignore warnings.
            warnings.filterwarnings(
                "ignore", message=".*os.fork.*", category=RuntimeWarning)
            warnings.filterwarnings(
                "ignore", message=".*fork.*multithreaded.*",
                category=DeprecationWarning)
            for _ in range(self._n_processes):
                p = ctx.Process(
                    target=_worker_loop,
                    args=(self.dataset, shm_name, self._layout,
                          res.task_q, res.result_q),
                    daemon=True)
                p.start()
                res.procs.append(p)
        self._res = res
        # GC-time teardown must not keep the iterator alive
        self._gc_guard = weakref.finalize(self, res.close)

        self._free_slots = list(range(self._n_slots))
        self._pending = {}        # seq -> completed-but-undelivered result
        self._seq_epoch = {}      # seq -> epoch the batch was scheduled in
        self._undelivered = set()
        self._seq_submitted = 0
        self._seq_delivered = 0
        self._exhausted = False
        self._broken = None       # sticky pipeline error
        self._finalized = False
        self.epoch = self._state.epoch
        self.is_new_epoch = self._state.is_new_epoch
        self._submit_tasks()

    def _submit_tasks(self):
        while self._free_slots and not self._exhausted:
            sched_epoch = self._sched.epoch  # epoch the batch STARTS in
            try:
                indices = self._sched._next_indices()
            except StopIteration:
                self._exhausted = True
                return
            slot = self._free_slots.pop()
            self._res.task_q.put(
                (self._seq_submitted, slot,
                 np.asarray(indices, dtype=np.int64)))
            self._seq_epoch[self._seq_submitted] = sched_epoch
            self._undelivered.add(self._seq_submitted)
            self._seq_submitted += 1

    def _check_workers_alive(self):
        for p in self._res.procs:
            if not p.is_alive():
                self._broken = IteratorWorkerCrashed(p.pid, p.exitcode)
                raise self._broken

    def _take_result(self):
        """Next deliverable result: the exact next seq when ordered; any
        completed batch of the OLDEST undelivered epoch when unordered
        (the scheduler runs ahead across epoch boundaries, but epochs
        must still deliver in order or the per-epoch example multiset
        breaks).  Polls worker liveness while waiting so a crashed pool
        raises instead of hanging."""
        import time
        deadline = time.monotonic() + self._worker_timeout
        while True:
            if self._ordered:
                want = self._seq_delivered
                if want in self._pending:
                    self._undelivered.discard(want)
                    self._seq_epoch.pop(want, None)
                    return self._pending.pop(want)
            elif self._pending:
                gate = self._seq_epoch[min(self._undelivered)]
                for seq in self._pending:
                    if self._seq_epoch[seq] == gate:
                        self._undelivered.discard(seq)
                        self._seq_epoch.pop(seq, None)
                        return self._pending.pop(seq)
            try:
                seq, slot, kind, payload = \
                    self._res.result_q.get(timeout=0.05)
            except _queue_mod.Empty:
                self._check_workers_alive()
                if time.monotonic() > deadline:
                    self._broken = IteratorError(
                        f"no batch completed within worker_timeout="
                        f"{self._worker_timeout}s (workers alive but "
                        "not progressing)")
                    raise self._broken
                continue
            # progress: ANY completed batch resets the no-progress
            # deadline — a single legitimately slow batch must not
            # break a pipeline whose other workers keep delivering
            deadline = time.monotonic() + self._worker_timeout
            self._pending[seq] = (slot, kind, payload)

    def _materialize(self, slot, kind, payload):
        """Copy the batch out of its ring slot (one memcpy per field),
        free the slot, and shape the output per ``as_arrays``."""
        if kind == "error":
            self._free_slots.append(slot)
            exc_type, message, tb_text = payload
            self._broken = IteratorWorkerError(exc_type, message, tb_text)
            raise self._broken
        if kind == "shm":
            n = payload
            views = self._layout.field_views(
                self._res.shm.buf, slot * self._layout.slot_bytes)
            arrays = [np.array(v[:n]) for v in views]  # memcpy out
            self._free_slots.append(slot)
            if self._as_arrays:
                return tuple(arrays) if self._layout.tuple_mode \
                    else arrays[0]
            if self._layout.tuple_mode:
                return [tuple(a[j] for a in arrays) for j in range(n)]
            return [arrays[0][j] for j in range(n)]
        # pickle fallback: payload IS the example list
        self._free_slots.append(slot)
        if not self._as_arrays:
            return payload
        first = payload[0]
        if isinstance(first, (tuple, list)):
            return tuple(np.stack([np.asarray(ex[k]) for ex in payload])
                         for k in range(len(first)))
        return np.stack([np.asarray(ex) for ex in payload])

    # -- iterator protocol --------------------------------------------------
    def __next__(self):
        if self._finalized:
            raise RuntimeError("MultiprocessIterator is finalized")
        if self._broken is not None:
            raise self._broken
        if self._exhausted and self._seq_delivered >= self._seq_submitted:
            raise StopIteration
        slot, kind, payload = self._take_result()
        batch = self._materialize(slot, kind, payload)
        self._seq_delivered += 1
        self._submit_tasks()
        # consumer shadow advances in lock-step (index bookkeeping only)
        self._state._next_indices()
        self.epoch = self._state.epoch
        self.is_new_epoch = self._state.is_new_epoch
        return batch

    next = __next__

    @property
    def epoch_detail(self):
        return self._state.epoch_detail

    @property
    def previous_epoch_detail(self):
        return self._state.previous_epoch_detail

    def reset(self):
        """Tear the pool down and restart from a fresh epoch."""
        self.finalize()
        self._setup()

    def serialize(self, serializer):
        """Consumer-granularity snapshot (reference contract; same keys
        as `SerialIterator`/`MultithreadIterator`, so snapshots are
        interchangeable across iterator classes).  On load the pool is
        rebuilt from the restored position.

        ``ordered=False`` refuses to WRITE a mid-stream snapshot: the
        consumer shadow tracks schedule order, but unordered delivery
        hands out an arbitrary completion-ordered subset — a resumed
        stream would duplicate the batches delivered out of schedule
        order and permanently drop the ones skipped.  Failing loudly
        beats silently corrupting the epoch multiset; reading INTO an
        unordered iterator is fine (scheduling restarts at the restored
        position)."""
        if serializer.is_writer and not self._ordered \
                and self._seq_delivered:
            raise RuntimeError(
                "MultiprocessIterator(ordered=False) cannot snapshot "
                "a mid-stream position: completion-order delivery "
                "diverges from the schedule-order shadow, so resume "
                "would duplicate/drop examples.  Use ordered=True "
                "for checkpointed training")
        _serialize_consumer_shadow(self, serializer)

    def finalize(self):
        """Stop workers, release queues and the shared-memory ring.
        Idempotent — double-finalize (trainer teardown after an explicit
        close) is a no-op."""
        if self._finalized or self._res is None:
            return
        self._finalized = True
        self._gc_guard.detach()
        self._res.close()
