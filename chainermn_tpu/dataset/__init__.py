from .datasets import (DatasetMixin, TupleDataset, DictDataset, SubDataset,
                       TransformDataset, ConcatenatedDataset, split_dataset,
                       split_dataset_random, get_mnist, get_cifar10,
                       get_synthetic_imagenet)
from .iterators import (Iterator, SerialIterator, MultiprocessIterator,
                        MultithreadIterator, DevicePrefetchIterator,
                        IteratorError, IteratorWorkerError,
                        IteratorWorkerCrashed)
from .convert import concat_examples, to_device, identity_converter
from .image_dataset import ImageDataset, LabeledImageDataset

try:
    from .native_iterator import NativeBatchIterator
except Exception:  # pragma: no cover - no toolchain
    NativeBatchIterator = None
