"""Datasets (consumed-Chainer surface: ``chainer.dataset`` / ``chainer.datasets``).

Reference anchors: ``chainer/datasets/tuple_dataset.py · TupleDataset``,
``sub_dataset.py · SubDataset/split_dataset``, ``transform_dataset.py``,
``dict_dataset.py``, ``concatenated_dataset.py`` (SURVEY.md §2.8).
``SubDataset`` is the type ``chainermn_tpu.datasets.scatter_dataset`` returns
(SURVEY §3.4): an index-remapped view, so scattering ships only index specs,
never tensor copies, and every shard has *equal length* — the lock-step
invariant that keeps collectives deadlock-free.

``get_mnist``/``get_cifar10`` return deterministic synthetic datasets (this
machine has no network); the generated classification tasks are genuinely
learnable so convergence tests are meaningful.
"""

from __future__ import annotations

import numpy as np

__all__ = ["DatasetMixin", "TupleDataset", "DictDataset", "SubDataset",
           "TransformDataset", "ConcatenatedDataset", "split_dataset",
           "split_dataset_random", "get_mnist", "get_cifar10",
           "get_synthetic_imagenet"]


class DatasetMixin:
    """Minimal dataset protocol: ``__len__`` + ``get_example``."""

    def __getitem__(self, index):
        if isinstance(index, slice):
            start, stop, step = index.indices(len(self))
            return [self.get_example(i) for i in range(start, stop, step)]
        if isinstance(index, (list, np.ndarray)):
            return [self.get_example(int(i)) for i in index]
        return self.get_example(int(index))

    def __len__(self):
        raise NotImplementedError

    def get_example(self, i):
        raise NotImplementedError


class TupleDataset:
    def __init__(self, *datasets):
        if not datasets:
            raise ValueError("no datasets given")
        length = len(datasets[0])
        for d in datasets[1:]:
            if len(d) != length:
                raise ValueError("all datasets must have the same length")
        self._datasets = datasets
        self._length = length

    def __getitem__(self, index):
        batches = [d[index] for d in self._datasets]
        if isinstance(index, (slice, list, np.ndarray)):
            length = len(batches[0])
            return [tuple(b[i] for b in batches) for i in range(length)]
        return tuple(batches)

    def __len__(self):
        return self._length


class DictDataset:
    def __init__(self, **datasets):
        if not datasets:
            raise ValueError("no datasets given")
        length = None
        for key, d in datasets.items():
            if length is None:
                length = len(d)
            elif len(d) != length:
                raise ValueError("all datasets must have the same length")
        self._datasets = datasets
        self._length = length

    def __getitem__(self, index):
        batches = {k: d[index] for k, d in self._datasets.items()}
        if isinstance(index, (slice, list, np.ndarray)):
            length = len(next(iter(batches.values())))
            return [{k: batch[i] for k, batch in batches.items()}
                    for i in range(length)]
        return batches

    def __len__(self):
        return self._length


class SubDataset(DatasetMixin):
    """View of ``dataset[start:finish]`` through an optional index ``order``.

    Reference: ``chainer/datasets/sub_dataset.py · SubDataset``.  Used by
    ``scatter_dataset`` to give each rank an equal-length shard (with
    wrap-around padding applied by the scatterer).
    """

    def __init__(self, dataset, start, finish, order=None):
        if start < 0 or finish > (len(order) if order is not None else len(dataset)):
            raise ValueError("subset overruns the base dataset")
        self._dataset = dataset
        self._start = start
        self._finish = finish
        self._size = finish - start
        self._order = order

    def __len__(self):
        return self._size

    def get_example(self, i):
        if i < 0 or i >= self._size:
            raise IndexError("dataset index out of range")
        index = self._start + i
        if self._order is not None:
            index = self._order[index]
        return self._dataset[int(index)]


class TransformDataset(DatasetMixin):
    def __init__(self, dataset, transform):
        self._dataset = dataset
        self._transform = transform

    def __len__(self):
        return len(self._dataset)

    def get_example(self, i):
        return self._transform(self._dataset[i])


class ConcatenatedDataset(DatasetMixin):
    def __init__(self, *datasets):
        self._datasets = datasets
        self._lengths = [len(d) for d in datasets]
        self._total = sum(self._lengths)

    def __len__(self):
        return self._total

    def get_example(self, i):
        for d, n in zip(self._datasets, self._lengths):
            if i < n:
                return d[i]
            i -= n
        raise IndexError("dataset index out of range")


def split_dataset(dataset, split_at, order=None):
    return (SubDataset(dataset, 0, split_at, order),
            SubDataset(dataset, split_at,
                       len(order) if order is not None else len(dataset), order))


def split_dataset_random(dataset, first_size, seed=None):
    order = np.random.RandomState(seed).permutation(len(dataset))
    return split_dataset(dataset, first_size, order)


# ---------------------------------------------------------------------------
# Synthetic stand-ins for the reference example datasets (no network access)
# ---------------------------------------------------------------------------

def _synthetic_classification(n, shape, n_classes, template_seed, sample_seed):
    """Learnable synthetic task: class-dependent template + noise.

    ``template_seed`` fixes the class structure (shared between train and
    test splits so they are the *same* task); ``sample_seed`` varies the
    drawn examples.
    """
    dim = int(np.prod(shape))
    templates = np.random.RandomState(template_seed).normal(
        0, 1.0, size=(n_classes, dim)).astype(np.float32)
    rng = np.random.RandomState(sample_seed)
    labels = rng.randint(0, n_classes, size=n).astype(np.int32)
    x = templates[labels] + rng.normal(0, 0.8, size=(n, dim)).astype(np.float32)
    x = (x - x.mean()) / (x.std() + 1e-8)
    x = x.reshape((n,) + shape)
    return x.astype(np.float32), labels


def get_mnist(withlabel=True, ndim=1, n_train=6000, n_test=1000, seed=1701):
    """Synthetic MNIST-shaped dataset (28×28, 10 classes).

    Mirrors ``chainer.datasets.get_mnist`` signature subset.  ``ndim=1`` →
    flat 784 vectors, ``ndim=3`` → (1, 28, 28).
    """
    shape = (784,) if ndim == 1 else (1, 28, 28)
    xtr, ytr = _synthetic_classification(n_train, shape, 10, seed, seed + 1)
    xte, yte = _synthetic_classification(n_test, shape, 10, seed, seed + 2)
    if withlabel:
        return TupleDataset(xtr, ytr), TupleDataset(xte, yte)
    return xtr, xte


def get_cifar10(withlabel=True, n_train=5000, n_test=1000, seed=1702):
    xtr, ytr = _synthetic_classification(n_train, (3, 32, 32), 10, seed, seed + 1)
    xte, yte = _synthetic_classification(n_test, (3, 32, 32), 10, seed, seed + 2)
    if withlabel:
        return TupleDataset(xtr, ytr), TupleDataset(xte, yte)
    return xtr, xte


def get_synthetic_imagenet(n=256, size=224, n_classes=1000, seed=1703,
                           dtype="float32"):
    """ImageNet-shaped synthetic data for the ResNet-50 benchmark vertical.

    ``dtype="uint8"`` emits raw 0-255 pixels — the TPU-idiomatic input
    pipeline (pair with ``ResNet50(input_norm="imagenet")``: the cast +
    standardize run in-graph on device, 4× less host→HBM traffic)."""
    rng = np.random.RandomState(seed)
    if dtype == "uint8":
        x = rng.randint(0, 256, size=(n, 3, size, size), dtype=np.uint8)
    else:
        x = rng.normal(0, 1, size=(n, 3, size, size)).astype(dtype)
    y = rng.randint(0, n_classes, size=n).astype(np.int32)
    return TupleDataset(x, y)
