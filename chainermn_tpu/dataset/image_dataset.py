"""File-based image datasets.

Reference: ``chainer/datasets/image_dataset.py · ImageDataset,
LabeledImageDataset`` (SURVEY.md §2.8; the reference's ImageNet example
scatters file *paths*, not tensors — §3.4 note).  Files are read lazily
per example (PIL for standard formats, ``.npy`` natively), decoded to
float32 NCHW; combine with ``scatter_dataset`` (which ships index specs)
and ``MultithreadIterator`` for a prefetching input pipeline.
"""

from __future__ import annotations

import os

import numpy as np

from .datasets import DatasetMixin

__all__ = ["ImageDataset", "LabeledImageDataset"]


def _read_image(path, dtype=np.float32):
    if path.endswith(".npy"):
        arr = np.load(path)
        if arr.ndim == 2:
            arr = arr[None]
        elif arr.ndim == 3 and arr.shape[0] not in (1, 3, 4):
            arr = arr.transpose(2, 0, 1)  # HWC → CHW
        return arr.astype(dtype)
    from PIL import Image
    with Image.open(path) as img:
        arr = np.asarray(img, dtype=dtype)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return arr


class ImageDataset(DatasetMixin):
    """Dataset of image file paths → float32 CHW arrays.

    ``paths``: list of paths or a text file with one path per line.
    """

    def __init__(self, paths, root=".", dtype=np.float32):
        if isinstance(paths, str):
            with open(paths) as f:
                paths = [line.strip() for line in f if line.strip()]
        self._paths = list(paths)
        self._root = root
        self._dtype = dtype

    def __len__(self):
        return len(self._paths)

    def get_example(self, i):
        return _read_image(os.path.join(self._root, self._paths[i]),
                           self._dtype)


class LabeledImageDataset(DatasetMixin):
    """(image, label) pairs from files.

    ``pairs``: list of (path, int) tuples or a text file of
    ``<path> <label>`` lines (the reference's ImageNet list format).
    """

    def __init__(self, pairs, root=".", dtype=np.float32,
                 label_dtype=np.int32):
        if isinstance(pairs, str):
            parsed = []
            with open(pairs) as f:
                for line in f:
                    parts = line.split()
                    if len(parts) == 2:
                        parsed.append((parts[0], int(parts[1])))
            pairs = parsed
        self._pairs = list(pairs)
        self._root = root
        self._dtype = dtype
        self._label_dtype = label_dtype

    def __len__(self):
        return len(self._pairs)

    def get_example(self, i):
        path, label = self._pairs[i]
        image = _read_image(os.path.join(self._root, path), self._dtype)
        return image, np.asarray(label, self._label_dtype)
