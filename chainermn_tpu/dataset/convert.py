"""Batch assembly (consumed-Chainer surface: ``chainer.dataset.convert``).

Reference: ``chainer/dataset/convert.py · concat_examples/to_device``.
Batches are stacked on host with numpy; device placement happens once, at
the jitted-step boundary (minimizing host↔HBM transfers — SURVEY §7 design
stance).
"""

from __future__ import annotations

import numpy as np

import jax

__all__ = ["concat_examples", "to_device", "identity_converter"]


def identity_converter(batch, device=None):
    """Pass-through converter for iterators that already emit stacked
    arrays (``NativeBatchIterator``)."""
    if device is not None:
        return to_device(batch, device)
    return batch


def _stack(xs, padding=None):
    first = xs[0]
    if padding is None:
        return np.stack([np.asarray(x) for x in xs])
    shape = np.array(np.asarray(first).shape, dtype=int)
    for x in xs[1:]:
        shape = np.maximum(shape, np.asarray(x).shape)
    out = np.full((len(xs),) + tuple(shape), padding,
                  dtype=np.asarray(first).dtype)
    for i, x in enumerate(xs):
        x = np.asarray(x)
        slices = tuple(slice(0, s) for s in x.shape)
        out[(i,) + slices] = x
    return out


def concat_examples(batch, device=None, padding=None):
    if not batch:
        raise ValueError("batch is empty")
    first = batch[0]
    if isinstance(first, tuple):
        result = tuple(
            _stack([ex[i] for ex in batch],
                   padding[i] if isinstance(padding, tuple) else padding)
            for i in range(len(first)))
    elif isinstance(first, dict):
        result = {
            key: _stack([ex[key] for ex in batch],
                        padding[key] if isinstance(padding, dict) else padding)
            for key in first}
    else:
        result = _stack(batch, padding)
    if device is not None:
        # the stacks above are freshly allocated and owned by the result:
        # safe for the zero-copy bridge
        result = _to_device_owned(result, device)
    return result


def to_device(x, device=None):
    """Place a pytree of host arrays on device (COPY semantics, like the
    reference's ``to_device``: callers may freely mutate the source
    afterwards).  Freshly-owned internal arrays take the zero-copy DLPack
    bridge via ``_to_device_owned`` instead."""
    dev = None if device in (None, -1, "@jax") else device
    return jax.tree.map(lambda a: jax.device_put(a, dev), x)


def _to_device_owned(x, device=None):
    """DLPack-bridge placement for arrays whose ownership transfers to
    the result (nothing else will mutate them) — ``concat_examples``'
    fresh stacks and the native iterator's held ring views.  On the CPU
    backend the ``jax.Array`` may alias the buffer (zero-copy)."""
    from ..utils.dlpack import from_numpy
    dev = None if device in (None, -1, "@jax") else device

    def place(a):
        if dev is None and isinstance(a, np.ndarray):
            return from_numpy(a)
        return jax.device_put(a, dev)

    return jax.tree.map(place, x)
