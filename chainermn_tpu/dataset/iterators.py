"""Iterators (consumed-Chainer surface: ``chainer.iterators``).

Reference anchors: ``chainer/iterators/serial_iterator.py · SerialIterator``,
``multiprocess_iterator.py · MultiprocessIterator`` (SURVEY.md §2.8).
Three prefetch tiers share one consumer contract:

* ``MultithreadIterator`` — background-thread prefetch; right when the
  per-example work releases the GIL (numpy decode/augment) and
  fork+pickle overhead isn't worth paying;
* ``MultiprocessIterator`` (``multiprocess_iterator.py``) — a real
  process pool assembling batches into shared-memory ring slots; the
  escape hatch for GIL-bound Python transforms;
* ``NativeBatchIterator`` (``native_iterator.py``) — the C++ gather
  engine for plain-array datasets.

``DevicePrefetchIterator`` stacks over any of them and keeps batches
already placed in device HBM, with the host-side convert + ``device_put``
issued from a feeder thread so the H2D path overlaps device compute.
"""

from __future__ import annotations

import queue
import threading
import time

import numpy as np

__all__ = ["Iterator", "SerialIterator", "MultiprocessIterator",
           "MultithreadIterator", "DevicePrefetchIterator"]


def serialize_rng(serializer, rng):
    """Write a ``np.random.RandomState``'s MT19937 state under the
    shared key names every iterator uses (``rng_keys``/``rng_pos``/...)
    — post-resume reshuffles then match the uninterrupted run exactly."""
    _, keys, pos, has_gauss, cached = rng.get_state()
    serializer("rng_keys", np.asarray(keys))
    serializer("rng_pos", int(pos))
    serializer("rng_has_gauss", int(has_gauss))
    serializer("rng_cached_gaussian", float(cached))


def deserialize_rng(serializer, rng):
    """Restore :func:`serialize_rng`'s state; tolerates snapshots that
    lack the keys (pre-feature, or written by an iterator class that
    didn't save RNG state) by keeping the current state.  Returns True
    when a state was restored."""
    try:
        keys = serializer("rng_keys", None)
    except KeyError:
        return False
    if keys is None:
        return False
    rng.set_state(("MT19937", np.asarray(keys, np.uint32),
                   int(serializer("rng_pos", 0)),
                   int(serializer("rng_has_gauss", 0)),
                   float(serializer("rng_cached_gaussian", 0.0))))
    return True


class Iterator:
    """Iterator protocol: ``__next__``, ``epoch``, ``is_new_epoch``, ``reset``."""

    def __iter__(self):
        return self

    def __next__(self):
        raise NotImplementedError

    next = __next__

    def finalize(self):
        pass

    def serialize(self, serializer):
        pass


class SerialIterator(Iterator):
    """Single-thread batch iterator (reference: ``SerialIterator``)."""

    def __init__(self, dataset, batch_size, repeat=True, shuffle=None,
                 order_sampler=None, seed=None):
        self.dataset = dataset
        self.batch_size = batch_size
        self._repeat = repeat
        self._shuffle = True if shuffle is None else shuffle
        self._rng = np.random.RandomState(seed)
        self._order_sampler = order_sampler
        self.reset()

    def reset(self):
        self.current_position = 0
        self.epoch = 0
        self.is_new_epoch = False
        self._previous_epoch_detail = -1.0
        self._order = self._new_order()

    def _new_order(self):
        n = len(self.dataset)
        if self._order_sampler is not None:
            return np.asarray(self._order_sampler(np.arange(n), 0))
        if self._shuffle:
            return self._rng.permutation(n)
        return np.arange(n)

    @property
    def epoch_detail(self):
        return self.epoch + self.current_position / len(self.dataset)

    @property
    def previous_epoch_detail(self):
        return self._previous_epoch_detail

    def _next_indices(self):
        """Advance position/epoch bookkeeping and return the batch's dataset
        indices WITHOUT touching the data (lets a prefetching wrapper keep a
        cheap consumer-side state shadow for serialization)."""
        n = len(self.dataset)
        if not self._repeat and self.current_position >= n:
            raise StopIteration
        self._previous_epoch_detail = self.epoch_detail
        i = self.current_position
        i_end = i + self.batch_size
        indices = [int(idx) for idx in self._order[i:i_end]]
        if i_end >= n:
            if self._repeat:
                rest = i_end - n
                self._order = self._new_order()
                if rest > 0:
                    indices.extend(int(idx) for idx in self._order[:rest])
                self.current_position = rest
            else:
                self.current_position = n
            self.epoch += 1
            self.is_new_epoch = True
        else:
            self.is_new_epoch = False
            self.current_position = i_end
        return indices

    def __next__(self):
        return [self.dataset[i] for i in self._next_indices()]

    next = __next__

    def _copy_state_from(self, other):
        """Clone another SerialIterator's position/order/RNG state."""
        self.current_position = other.current_position
        self.epoch = other.epoch
        self.is_new_epoch = other.is_new_epoch
        self._previous_epoch_detail = other._previous_epoch_detail
        self._order = np.array(other._order)
        self._rng.set_state(other._rng.get_state())

    def serialize(self, serializer):
        self.current_position = int(serializer("current_position",
                                               self.current_position))
        self.epoch = int(serializer("epoch", self.epoch))
        self.is_new_epoch = bool(serializer("is_new_epoch", self.is_new_epoch))
        order = serializer("order", np.asarray(self._order))
        if order is not None and not serializer.is_writer:
            self._order = np.asarray(order)
        self._previous_epoch_detail = float(serializer(
            "previous_epoch_detail", self._previous_epoch_detail))
        # RNG state too (beyond the reference): checkpoint fidelity is
        # bit-exact, not just epoch-aligned (shared helpers so every
        # iterator class reads/writes the same keys with the same
        # missing-key tolerance)
        if serializer.is_writer:
            serialize_rng(serializer, self._rng)
        else:
            deserialize_rng(serializer, self._rng)


def _make_shadow_pair(dataset, batch_size, repeat, shuffle, seed,
                      from_state=None):
    """(lead, shadow) `SerialIterator` pair shared by the prefetching
    iterators: the lead runs ahead feeding the pipeline, the shadow
    advances once per CONSUMED batch — the serializable consumer
    position.  Both start from ``from_state`` when resuming."""
    lead = SerialIterator(dataset, batch_size, repeat=repeat,
                          shuffle=shuffle, seed=seed)
    shadow = SerialIterator(dataset, batch_size, repeat=repeat,
                            shuffle=shuffle, seed=seed)
    if from_state is not None:
        shadow._copy_state_from(from_state)
        lead._copy_state_from(shadow)
    else:
        shadow._copy_state_from(lead)
    return lead, shadow


def _serialize_consumer_shadow(it, serializer):
    """ONE copy of the consumer-shadow resume contract
    (`MultithreadIterator` / `MultiprocessIterator` — their snapshots
    stay interchangeable because this is the same code): the writer
    snapshots the shadow; the reader restores it, then tears the
    pipeline down and rebuilds from the restored position.  Snapshots
    from before iterators serialized anything (KeyError) keep the
    fresh stream."""
    if serializer.is_writer:
        it._state.serialize(serializer)
        return
    try:
        it._state.serialize(serializer)
    except KeyError:
        return
    it.finalize()
    it._setup(from_state=it._state)


class MultithreadIterator(Iterator):
    """Background-thread prefetching iterator.

    API-parity stand-in for the reference ``MultiprocessIterator`` /
    ``MultithreadIterator``: a worker thread keeps ``n_prefetch`` batches
    ready so host input prep overlaps device compute.
    """

    def __init__(self, dataset, batch_size, repeat=True, shuffle=None,
                 n_threads=1, n_prefetch=2, seed=None):
        self.dataset = dataset
        self.batch_size = batch_size
        self._repeat = repeat
        self._shuffle = shuffle
        self._seed = seed
        self._n_prefetch = max(1, n_prefetch)
        self._setup()

    def _setup(self, from_state=None):
        # worker-side lead + consumer-side shadow (the worker's `_base`
        # runs ahead by up to n_prefetch batches; `serialize` records
        # the shadow's resumable position)
        self._base, self._state = _make_shadow_pair(
            self.dataset, self.batch_size, self._repeat, self._shuffle,
            self._seed, from_state)
        self._queue: queue.Queue = queue.Queue(maxsize=self._n_prefetch)
        self._stop = threading.Event()
        # worker state is bound as arguments: a not-yet-stopped old worker
        # can only ever touch its OWN (discarded) base/queue/stop, never a
        # rebuilt pipeline's
        self._thread = threading.Thread(
            target=self._worker, args=(self._base, self._queue, self._stop),
            daemon=True)
        self._started = False
        self._exhausted = False
        self._error = None
        self.epoch = self._state.epoch
        self.is_new_epoch = self._state.is_new_epoch

    def reset(self):
        """Stop the worker and restart from a fresh epoch (Evaluator reuse)."""
        self.finalize()
        self._setup()

    @staticmethod
    def _worker(base, q, stop):
        try:
            while not stop.is_set():
                try:
                    batch = base.next()
                except StopIteration:
                    q.put(StopIteration)
                    return
                q.put(batch)
        except Exception as e:  # surface worker errors to the consumer
            q.put(e)

    def __next__(self):
        if self._exhausted:
            # sticky: the worker's one StopIteration sentinel is gone —
            # blocking on the dead queue again would hang forever
            raise StopIteration
        if self._error is not None:
            raise self._error
        if not self._started:
            self._thread.start()
            self._started = True
        item = self._queue.get()
        if item is StopIteration:
            self._exhausted = True
            raise StopIteration
        if isinstance(item, Exception):
            # sticky, like exhaustion: the worker died delivering this —
            # a later next() would block forever on its dead queue
            self._error = item
            raise item
        # advance the consumer shadow in lock-step (index bookkeeping only)
        self._state._next_indices()
        self.epoch = self._state.epoch
        self.is_new_epoch = self._state.is_new_epoch
        return item

    next = __next__

    @property
    def epoch_detail(self):
        return self._state.epoch_detail

    @property
    def previous_epoch_detail(self):
        return self._state.previous_epoch_detail

    def serialize(self, serializer):
        """Snapshot/restore the CONSUMER position (reference contract:
        resume continues the stream where training saw it, regardless of
        prefetch depth).  On load, the prefetch pipeline is rebuilt from
        the restored position."""
        _serialize_consumer_shadow(self, serializer)

    def finalize(self):
        self._stop.set()
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        if self._started:  # drained queue unblocks a pending put → quick exit
            self._thread.join(timeout=5.0)


class _FeedDone:
    """Sentinel: the feeder drained a non-repeating base iterator."""


class _FeedError:
    """Feeder-thread exception carrier (re-raised on the consumer)."""

    def __init__(self, error):
        self.error = error


class DevicePrefetchIterator(Iterator):
    """Device-feed stage: keeps up to ``size`` batches already PLACED in
    device HBM (optionally under a ``jax.sharding.Sharding``) before the
    consumer asks for them — the TPU analog of the CUDA-stream prefetch
    inside the reference's ``MultiprocessIterator`` (SURVEY §2.8
    iterators row), composed as a separate stage so it stacks over ANY
    host iterator (Serial / Multithread / Multiprocess / NativeBatch).

    With ``overlap=True`` (default) a feeder thread pulls from the base
    iterator, runs ``converter``, and issues ``jax.device_put`` — i.e.
    the whole host-side feed (batch assembly handoff, converter, H2D
    dispatch) is double-buffered behind the current step's compute; the
    consumer's ``next()`` only blocks when the feed can't keep up, and
    that blocked time is accounted in :attr:`input_stall_ms`.
    ``overlap=False`` keeps the synchronous fill (no extra thread; the
    async ``device_put`` dispatch still overlaps the DMA itself).

    ``converter`` (e.g. ``dataset.concat_examples``) runs on host before
    placement; give the downstream updater ``identity_converter`` since
    batches arrive as device arrays.

    Resume contract (same as ``MultithreadIterator``): ``serialize``
    records the CONSUMER position — the base iterator's state from just
    before fetching the oldest unconsumed batch — so snapshot/resume is
    bit-exact regardless of prefetch depth.
    """

    def __init__(self, base_iterator, size=2, sharding=None,
                 converter=None, overlap=True):
        self.base = base_iterator
        self._size = max(1, size)
        self._sharding = sharding
        self._converter = converter
        self._overlap = overlap
        self._stall_s = 0.0  # cumulative consumer wait on the feed
        self._setup_feed()

    def _setup_feed(self):
        self._buf = []       # sync mode: device batches in flight
        self._meta = []      # sync mode: per-batch epoch bookkeeping
        self._states = []    # base snapshot BEFORE fetching each batch
        self._consumer_state = None  # base snapshot at consumer position
        self._detail = None
        self._prev_detail = None
        self.epoch = getattr(self.base, "epoch", 0)
        self.is_new_epoch = getattr(self.base, "is_new_epoch", False)
        if self._overlap:
            self._q: queue.Queue = queue.Queue(maxsize=self._size)
            self._stop = threading.Event()
            self._base_lock = threading.Lock()
            self._states_lock = threading.Lock()
            # ALL feeder-touched state is bound as args (queue, stop,
            # states list, both locks): an old feeder that outlived
            # _teardown_feed's join timeout (base.next() blocked >5s)
            # can only ever touch its OWN discarded objects — its stale
            # state snapshot lands in the old list, never the rebuilt
            # pipeline's resume bookkeeping
            self._thread = threading.Thread(
                target=self._feeder,
                args=(self.base, self._q, self._stop, self._states,
                      self._states_lock, self._base_lock), daemon=True)
            self._started = False
            self._drained = False
            self._feed_error = None

    @staticmethod
    def _snap(base):
        from ..serializers.npz import DictionarySerializer
        s = DictionarySerializer()
        base.serialize(s)
        return s.target

    def _place(self, batch):
        import jax
        if self._converter is not None:
            batch = self._converter(batch)
        return jax.tree.map(
            lambda a: jax.device_put(a, self._sharding), batch)

    # -- overlapped feed ----------------------------------------------------
    def _feeder(self, base, q, stop, states, states_lock, base_lock):
        try:
            while not stop.is_set():
                with base_lock:
                    # snapshot + fetch + state-append are one atomic unit:
                    # serialize's writer takes the same lock, so it can
                    # never observe a fetched-but-unregistered batch (that
                    # batch would be skipped on resume)
                    state = self._snap(base)
                    try:
                        batch = base.next()
                    except StopIteration:
                        q.put(_FeedDone)
                        return
                    meta = (getattr(base, "epoch", 0),
                            getattr(base, "is_new_epoch", False),
                            getattr(base, "epoch_detail", None),
                            getattr(base, "previous_epoch_detail", None))
                    with states_lock:
                        states.append(state)
                placed = self._place(batch)  # H2D dispatched off-thread
                q.put((placed, meta))
        except Exception as e:  # surface feeder errors to the consumer
            q.put(_FeedError(e))

    def _teardown_feed(self):
        """Stop the feeder thread (overlap mode) and drop buffered
        batches; the base iterator is left untouched.  The feeder's
        queue/stop/states are its own (bound as args), but ``base`` is
        shared with whatever comes next — so wait for the feeder to
        actually exit (draining the queue so a pending put can't wedge
        it), bounded at ~30s; a feeder still inside a pathologically
        blocked ``base.next()`` after that is reported, not silently
        raced."""
        if not self._overlap:
            return
        self._stop.set()
        if self._started:
            deadline = time.monotonic() + 30.0
            while self._thread.is_alive() \
                    and time.monotonic() < deadline:
                try:
                    while True:
                        self._q.get_nowait()
                except queue.Empty:
                    pass
                self._thread.join(timeout=0.5)
            if self._thread.is_alive():
                import sys
                print("chainermn_tpu: DevicePrefetchIterator feeder "
                      "still blocked in base.next() after 30s teardown "
                      "wait; proceeding — the old feeder may consume "
                      "one batch from the shared base iterator",
                      file=sys.stderr)
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass

    # -- sync feed ----------------------------------------------------------
    def _fill(self):
        while len(self._buf) < self._size:
            state = self._snap(self.base)
            try:
                batch = self.base.next()
            except StopIteration:
                return  # drain what's already in flight
            self._buf.append(self._place(batch))
            self._states.append(state)
            self._meta.append((
                getattr(self.base, "epoch", 0),
                getattr(self.base, "is_new_epoch", False),
                getattr(self.base, "epoch_detail", None),
                getattr(self.base, "previous_epoch_detail", None)))

    def __next__(self):
        if not self._overlap:
            t0 = time.perf_counter()
            self._fill()
            self._stall_s += time.perf_counter() - t0
            if not self._buf:
                raise StopIteration
            batch = self._buf.pop(0)
            self._consumer_state = self._states.pop(0)
            (self.epoch, self.is_new_epoch, self._detail,
             self._prev_detail) = self._meta.pop(0)
            return batch
        if self._drained:
            raise StopIteration
        if self._feed_error is not None:
            raise self._feed_error
        if not self._started:
            self._thread.start()
            self._started = True
        t0 = time.perf_counter()
        item = self._q.get()
        self._stall_s += time.perf_counter() - t0
        if item is _FeedDone:
            self._drained = True
            raise StopIteration
        if isinstance(item, _FeedError):
            # sticky: the feeder thread exited delivering this — a later
            # next() would block forever on its dead queue
            self._feed_error = item.error
            raise item.error
        placed, meta = item
        with self._states_lock:
            self._consumer_state = self._states.pop(0)
        (self.epoch, self.is_new_epoch, self._detail,
         self._prev_detail) = meta
        return placed

    next = __next__

    @property
    def epoch_detail(self):
        return self._detail if self._detail is not None \
            else getattr(self.base, "epoch_detail", None)

    @property
    def previous_epoch_detail(self):
        return self._prev_detail if self._detail is not None \
            else getattr(self.base, "previous_epoch_detail", None)

    @property
    def input_stall_ms(self):
        """Cumulative milliseconds ``next()`` spent blocked waiting for
        the feed — the exposed (un-overlapped) input cost."""
        return self._stall_s * 1e3

    def reset(self):
        self._teardown_feed()
        if hasattr(self.base, "reset"):
            self.base.reset()
        self._setup_feed()

    def serialize(self, serializer):
        if serializer.is_writer:
            # consumer position: state before the oldest unconsumed
            # batch; if nothing is buffered, the base's current state.
            # In overlap mode the base lock excludes a mid-fetch feeder
            # (see _feeder) so the fallback snapshot is consistent.
            if self._overlap and self._started:
                with self._base_lock:
                    with self._states_lock:
                        state = dict(self._states[0]) if self._states \
                            else None
                    if state is None:
                        state = self._snap(self.base)
            else:
                state = (self._states[0] if self._states
                         else self._snap(self.base))
            for key, value in state.items():
                serializer(key, value)
            return
        # read: the stored keys are exactly what base.serialize reads
        self._teardown_feed()
        self.base.serialize(serializer)
        self._setup_feed()

    def finalize(self):
        self._teardown_feed()
        self._buf, self._meta, self._states = [], [], []
        if hasattr(self.base, "finalize"):
            self.base.finalize()


# The real process-pool implementation (shared-memory ring slots, typed
# worker-error propagation) lives in multiprocess_iterator.py; re-export
# under the reference import path (`dataset.iterators`).
from .multiprocess_iterator import (  # noqa: E402  (after base classes)
    IteratorError, IteratorWorkerCrashed, IteratorWorkerError,
    MultiprocessIterator)
